"""PCK-style keypoint-transfer metric on `SyntheticPairDataset` pairs.

The synthetic target is the source cyclically rolled by a known per-pair
horizontal ``shift``: source pixel (x, y) appears at target
(x + shift mod W, y). That known dense correspondence gives a ground-truth
keypoint-transfer metric with zero annotation — the synthetic analog of
the PF-Pascal PCK protocol (reference eval_pf_pascal.py:69-89), used to
demonstrate end-to-end learning without any dataset on disk.

Query points are placed on a grid in the RIGHT half of the target image;
since ``shift < W/2``, their true source positions ``x - shift`` never
wrap, so the cyclic seam does not contaminate the metric.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import immatchnet_apply
from ncnet_tpu.ops.coords import points_to_pixel_coords, points_to_unit_coords
from ncnet_tpu.ops.matches import bilinear_point_transfer, corr_to_matches
from ncnet_tpu.ops.metrics import pck


def _query_grid(h, w, n_side=4):
    """[2, n_side^2] pixel points in the right half of a (h, w) image."""
    xs = np.linspace(w * 0.55, w * 0.95, n_side)
    ys = np.linspace(h * 0.1, h * 0.9, n_side)
    gx, gy = np.meshgrid(xs, ys)
    return np.stack([gx.ravel(), gy.ravel()]).astype(np.float32)


def make_synthetic_pck_step(config, alpha=0.1, n_side=4):
    """Returns jitted ``step(params, batch) -> [b] per-pair PCK`` where
    ``batch`` additionally carries the per-pair ``shift`` (pixels)."""

    def step(params, batch):
        src = batch["source_image"]
        b, h, w = src.shape[0], src.shape[1], src.shape[2]
        corr = immatchnet_apply(params, config, src, batch["target_image"])
        x_a, y_a, x_b, y_b, _ = corr_to_matches(corr, do_softmax=True)

        tgt_px = jnp.broadcast_to(
            jnp.asarray(_query_grid(h, w, n_side))[None], (b, 2, n_side**2)
        )
        im_size = jnp.broadcast_to(
            jnp.asarray([h, w, 3], jnp.float32)[None], (b, 3)
        )
        tgt_norm = points_to_unit_coords(tgt_px, im_size)
        warped_norm = bilinear_point_transfer((x_a, y_a, x_b, y_b), tgt_norm)
        warped_px = points_to_pixel_coords(warped_norm, im_size)

        # ground truth: x_src = x_tgt - shift (never wraps for these points)
        gt = tgt_px.at[:, 0, :].add(-batch["shift"][:, None])
        l_pck = jnp.full((b, 1), w, jnp.float32)
        return pck(gt, warped_px, l_pck, alpha=alpha)

    return jax.jit(step)


def evaluate_synthetic(params, config, loader, alpha=0.1, n_side=4):
    """Mean synthetic-transfer PCK over a loader of shift-annotated batches."""
    step = make_synthetic_pck_step(config, alpha, n_side)
    scores = []
    for batch in loader:
        jb = {
            "source_image": jnp.asarray(batch["source_image"]),
            "target_image": jnp.asarray(batch["target_image"]),
            "shift": jnp.asarray(batch["shift"]),
        }
        scores.extend(np.asarray(step(params, jb)).tolist())
    arr = np.asarray(scores)
    valid = ~np.isnan(arr)
    return float(arr[valid].mean()) if valid.any() else float("nan")


def synthetic_pck_vs_topk(params, config, batches, ks, alpha=0.1, n_side=4):
    """Synthetic-transfer PCK across sparse band widths (accuracy/compute
    sweep for the sparse NC path, ncnet_tpu.sparse).

    Args:
      batches: a list (or reusable loader) of shift-annotated batches —
        the SAME pairs are scored at every K so the sweep isolates the
        band width.
      ks: iterable of ``nc_topk`` values; 0 = the dense path.

    Returns:
      ``{k: mean_pck}``. With ``k >= hB*wB`` the band is complete and the
      entry must equal the dense one — the sanity anchor of the sweep.
    """
    cached = list(batches)
    return {
        int(k): evaluate_synthetic(
            params, config.replace(nc_topk=int(k)), cached, alpha, n_side
        )
        for k in ks
    }


def synthetic_pck_vs_refine(
    params, config, batches, factors, ks, radius=0, alpha=0.1, n_side=4
):
    """Synthetic-transfer PCK across (pool factor, coarse band width)
    pairs — the accuracy/compute surface of coarse-to-fine refinement
    (ncnet_tpu.refine), same protocol as `synthetic_pck_vs_topk`.

    Args:
      batches: a list (or reusable loader) of shift-annotated batches —
        the SAME pairs are scored at every cell so the sweep isolates
        the refinement geometry.
      factors: iterable of ``refine_factor`` pool factors; 0 = the dense
        baseline (scored once, keyed ``(0, 0)``).
      ks: iterable of ``refine_topk`` coarse-band widths (ignored for
        factor 0).

    Returns:
      ``{(factor, k): mean_pck}``. The factor-1 row at ``k >= hB*wB``
      re-scores a complete band through a single-entry window, so it
      must equal the dense entry — the sweep's sanity anchor.
    """
    cached = list(batches)
    results = {}
    for factor in factors:
        if int(factor) == 0:
            results[(0, 0)] = evaluate_synthetic(
                params, config.replace(refine_factor=0), cached, alpha,
                n_side,
            )
            continue
        for k in ks:
            results[(int(factor), int(k))] = evaluate_synthetic(
                params,
                config.replace(
                    refine_factor=int(factor),
                    refine_topk=int(k),
                    refine_radius=int(radius),
                ),
                cached, alpha, n_side,
            )
    return results
