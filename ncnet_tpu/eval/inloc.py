"""InLoc dense-match dump.

Reproduces the Python-side contract of the reference's eval_inloc.py so the
downstream MATLAB PnP-RANSAC + pose-verification pipeline runs unmodified:
one ``matches/<experiment>/<q+1>.mat`` per query containing a ``matches``
array ``[1, Npanos, N, 5]`` of ``(xA, yA, xB, yB, score)`` rows in
normalized [0, 1] coordinates (eval_inloc.py:126,199-203,221).

Pipeline per (query, pano) pair (eval_inloc.py:124-203):
  aspect-preserving resize with the feature grid quantized to multiples of
  ``k_size`` (so 4D max-pool relocalization divides evenly)
  -> bf16 forward with fused correlation+maxpool4d
  -> `corr_to_matches` in both directions (scale='positive', softmax)
  -> concatenate, sort by descending score, coordinate-level dedup
  -> recenter normalized coords to feature-cell centers.

XLA note: every distinct image shape compiles once; the k_size·stride
quantization (shared with the serving engine via
`ncnet_tpu.serve.buckets`) already buckets shapes to a small set, so the
jit cache acts as the shape-bucketing layer.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp

from ncnet_tpu.data.images import (
    load_image,
    normalize_image_np,
    resize_bilinear_np,
    to_uint8_image,
)
from ncnet_tpu.models.feature_extraction import backbone_stride
from ncnet_tpu.models.immatchnet import immatchnet_apply
from ncnet_tpu.ops.matches import corr_to_matches

# the resize-quantization rule now lives in the shared shape-bucketing
# module (ncnet_tpu.serve.buckets) so the serving engine and this dump
# agree on the bucket set; re-exported here for existing callers
from ncnet_tpu.serve.buckets import SCALE_FACTOR, quantized_resize_shape
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import default_registry

__all__ = [
    "SCALE_FACTOR",
    "quantized_resize_shape",
    "load_and_preprocess",
    "make_match_fn",
    "match_pair",
    "dump_matches",
    "n_match_slots",
    "recenter",
]


def _to_str(x):
    """Unwrap scipy-loaded MATLAB cell/char nesting to a plain str."""
    while isinstance(x, np.ndarray):
        x = x.ravel()[0]
    return str(x)


def load_and_preprocess(path, image_size, k_size, grid_multiple=None,
                        device_normalize=False, device_resize=False):
    """Load -> quantized resize -> ImageNet-normalize.

    ``device_normalize=True`` returns the resized image as uint8 and
    leaves normalization to the device (`make_match_fn`'s
    ``device_preprocess``): the tunneled host<->device link of this
    platform moves ~25 MB/s, so shipping a (2400, 3200) image as fp32
    costs ~3.7 s against ~0.9 s as uint8 — measured round 4; on directly-
    attached TPU hosts both are microseconds and the paths are
    numerically equivalent to within the uint8 rounding of the resized
    pixels (<=0.2% of the dynamic range, far below matching tolerance).

    ``device_resize=True`` (requires ``device_normalize``) changes the
    RETURN TYPE to ``(uint8 [1,h,w,3], target_hw_or_None)``: when the
    quantized resize would UPSCALE the image (InLoc's 1600x1200 panos
    blow up 4x to the (2400, 3200) bucket, reference eval_inloc.py:84-89),
    the ORIGINAL pixels are returned with the target shape and the
    bilinear resize happens on device (`device_resize_uint8`), cutting
    the dominant per-pair host->device transfer from ~23 MB to ~5.8 MB.
    Downscales keep the host resize (the resized image is the smaller
    wire format there) and return ``(resized uint8, None)``.

    Two scope notes on the device_resize path (ADVICE r5):

    * Compile cost scales with DISTINCT ORIGINAL shapes: upscaled
      originals ship at their raw, unquantized size, so each new
      original shape jit-compiles `device_resize_uint8` once — the
      resize-quantization bucketing only caps the RESIZED shapes. Free
      on real InLoc (panos are uniformly 1600x1200 -> one compile), but
      a dataset of heterogeneous originals would thrash the jit cache;
      pad such originals to a few buckets first, or keep
      ``device_resize=False`` there.
    * The upscale test is total-AREA based (``h*w`` grows), which
      assumes the aspect-preserving resize rule: both axes then scale by
      the same factor and area growth implies per-axis growth. A caller
      feeding shapes that upscale one axis while downscaling the other
      (impossible under `quantized_resize_shape`) would ship an original
      larger than needed on the downscaled axis — compare per-axis
      before reusing this helper outside the InLoc resize rule.
    """
    img = load_image(path)
    h, w = quantized_resize_shape(
        img.shape[0], img.shape[1], image_size, k_size, grid_multiple
    )
    if device_resize:
        if not device_normalize:
            raise ValueError("device_resize requires device_normalize")
        if h * w > img.shape[0] * img.shape[1]:  # upscale: ship original
            return to_uint8_image(img)[None], (h, w)
        return to_uint8_image(resize_bilinear_np(img, h, w))[None], None
    img = resize_bilinear_np(img, h, w)
    if device_normalize:
        return to_uint8_image(img)[None]
    return normalize_image_np(img)[None]  # [1, h, w, 3]


def _device_resize_uint8(img, out_h, out_w):
    from ncnet_tpu.ops.image import resize_bilinear_align_corners

    out = resize_bilinear_align_corners(img.astype(jnp.float32), out_h, out_w)
    return jnp.rint(jnp.clip(out, 0.0, 255.0)).astype(jnp.uint8)


# jitted with static output shape; uint8 in -> uint8 out so downstream
# (on-device ImageNet normalize) is identical to the host-resize path —
# the only numerics delta is float-order rounding at rint boundaries
# (<=1 gray level on a vanishing fraction of pixels, tested)
device_resize_uint8 = jax.jit(_device_resize_uint8, static_argnums=(1, 2))


def make_match_fn(config, mesh=None, softmax=True, device_preprocess=False,
                  concat_directions=False, from_features=False):
    """(params, src, tgt) -> (fwd, rev) match tuples for one pair (jittable).

    ``from_features=True`` consumes PRECOMPUTED trunk features instead of
    images: ``src``/``tgt`` are ``[1, fh, fw, c]`` feature maps (e.g. from
    the gallery feature store) and the forward contains zero backbone ops
    — the correlation/NC pipeline is identical. Incompatible with
    ``device_preprocess`` (there is no image to normalize) and ``mesh``
    (the sharded pipeline manages its own extraction).

    ``concat_directions=True`` (the both-directions dump's mode) returns
    ONE ``[5, b, n_fwd + n_rev]`` array instead of the (fwd, rev) pair —
    the direction concat moves inside the jit, saving a separate device
    dispatch per pair (each costs ~80 ms over this platform's tunnel).

    With ``mesh`` (a Mesh with a 'spatial' axis), the correlation/NC
    pipeline runs sharded over the A-grid rows via
    `parallel.spatial.make_sharded_match_pipeline` — the high-res path for
    grids whose corr4d exceeds a single chip's HBM (BASELINE config 5).
    Feature grids must divide k_size x the shard count (use
    ``grid_multiple`` in `load_and_preprocess`).

    ``device_preprocess=True`` accepts uint8 images and ImageNet-
    normalizes them ON DEVICE (pair with `load_and_preprocess
    (device_normalize=True)`) — a 4x host->device transfer saving.
    """
    from ncnet_tpu.ops.image import imagenet_normalize

    k = config.relocalization_k_size

    if from_features:
        if device_preprocess:
            raise ValueError(
                "from_features match fns take feature maps, not images; "
                "device_preprocess does not apply"
            )
        if mesh is not None:
            raise ValueError(
                "from_features is not supported with a spatial mesh (the "
                "sharded pipeline manages its own feature extraction)"
            )
        from ncnet_tpu.models.immatchnet import match_pipeline

        def forward(params, src, tgt):
            return match_pipeline(
                params["neigh_consensus"], config, src, tgt
            )
    elif mesh is None:
        def forward(params, src, tgt):
            return immatchnet_apply(params, config, src, tgt)
    else:
        from ncnet_tpu.models.immatchnet import extract_features
        from ncnet_tpu.parallel.spatial import make_sharded_match_pipeline

        pipeline = make_sharded_match_pipeline(config, mesh)

        def forward(params, src, tgt):
            feat_a = extract_features(params, config, src)
            feat_b = extract_features(params, config, tgt)
            return pipeline(params["neigh_consensus"], feat_a, feat_b)

    def fn(params, src, tgt):
        if device_preprocess:
            src = imagenet_normalize(src.astype(jnp.float32))
            tgt = imagenet_normalize(tgt.astype(jnp.float32))
        out = forward(params, src, tgt)
        corr, delta4d = out if k > 1 else (out, None)
        kw = dict(
            scale="positive", do_softmax=softmax, delta4d=delta4d,
            k_size=max(k, 1),
        )
        fwd = corr_to_matches(corr, **kw)
        rev = corr_to_matches(corr, invert_matching_direction=True, **kw)
        # one device buffer per direction (not 5): each D2H transfer pays
        # this platform's ~80 ms dispatch latency, so the dump loop reads
        # ONE stacked [5, b, n] array per direction instead of five
        if concat_directions:
            return jnp.concatenate([jnp.stack(fwd), jnp.stack(rev)], axis=2)
        return jnp.stack(fwd), jnp.stack(rev)

    return fn


def recenter(coord, n_cells):
    """Normalized [0,1] grid coords -> feature-cell centers
    (eval_inloc.py:179-189)."""
    return coord * (n_cells - 1) / n_cells + 0.5 / n_cells


def match_pair(match_fn, params, src, tgt, k_size, stride=16,
               both_directions=True, flip_direction=False, dedup=True,
               precomputed=None, shapes=None):
    """Returns (xA, yA, xB, yB, score) numpy arrays for one image pair.

    ``precomputed``: optionally the device output of an earlier
    (asynchronously dispatched) ``match_fn`` call — lets callers overlap
    the next pair's host->device transfer (and, with the pipelined dump
    loop, the next pair's whole compute) with this pair's readout. Either
    the (fwd, rev) tuple or, from a ``concat_directions`` match fn, the
    single combined ``[5, b, n]`` array (implies ``both_directions``).

    ``shapes``: optional ``(src_shape, tgt_shape)`` standing in for
    ``src.shape``/``tgt.shape`` — lets a pipelined caller drop the device
    image references while the pair's readout is still in flight.
    """
    src_shape, tgt_shape = shapes if shapes else (src.shape, tgt.shape)
    k = max(k_size, 1)
    # pooled correlation grid dims, derived from the image shapes
    fs1 = src_shape[1] // stride // k
    fs2 = src_shape[2] // stride // k
    fs3 = tgt_shape[1] // stride // k
    fs4 = tgt_shape[2] // stride // k
    out = (
        precomputed if precomputed is not None
        else match_fn(params, src, tgt)
    )
    if isinstance(out, (tuple, list)):
        fwd, rev = out
        # each direction is ONE stacked [5, b, n] device array
        # (make_match_fn); concatenating on device keeps the host sync to
        # a single transfer
        if both_directions:
            parts = np.asarray(jnp.concatenate([fwd, rev], axis=2))
        elif flip_direction:
            parts = np.asarray(rev)
        else:
            parts = np.asarray(fwd)
    else:
        # a `concat_directions` match fn (live or precomputed): already
        # the combined [5, b, n] array. A contract check, not an assert:
        # under python -O an assert would silently treat the [5, b, n]
        # concat as a single-direction result (ADVICE r5).
        if not both_directions:
            raise ValueError(
                "combined [5, b, n] match output implies both_directions; "
                "pass both_directions=True or use a non-concat match fn"
            )
        parts = np.asarray(out)
    xa, ya, xb, yb, score = parts[:, 0]

    if both_directions:
        order = np.argsort(-score)  # descending; keeps max-score dup first
        xa, ya, xb, yb, score = (v[order] for v in (xa, ya, xb, yb, score))
        if dedup:
            coords = np.stack([xa, ya, xb, yb])
            _, uniq = np.unique(coords, axis=1, return_index=True)
            xa, ya, xb, yb, score = (v[uniq] for v in (xa, ya, xb, yb, score))

    ya = recenter(ya, fs1 * k)
    xa = recenter(xa, fs2 * k)
    yb = recenter(yb, fs3 * k)
    xb = recenter(xb, fs4 * k)
    return xa, ya, xb, yb, score


def _atomic_savemat(out_path, payload):
    """savemat into a temp name + atomic rename: resume treats any
    existing ``<q+1>.mat`` as complete, so a crash mid-write must never
    leave a file under the final name."""
    from scipy.io import savemat

    tmp = f"{out_path}.tmp.{os.getpid()}"
    try:
        savemat(tmp, payload, do_compression=True)
        os.replace(tmp, out_path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _clean_stale_temps(output_dir):
    """Remove torn ``.mat.tmp.<pid>`` files left by a killed run — but
    NOT temps owned by a still-running dump sharing this directory (a
    second resume process must not delete the first's in-flight file)."""
    for stale in os.listdir(output_dir):
        if ".mat.tmp." not in stale:
            continue
        try:
            owner = int(stale.rsplit(".", 1)[-1])
            os.kill(owner, 0)  # raises if no such process
            continue  # owner alive: leave its temp alone
        except (ValueError, ProcessLookupError):
            pass
        except PermissionError:
            continue  # pid exists under another uid: leave it
        try:
            os.unlink(os.path.join(output_dir, stale))
        except FileNotFoundError:
            pass  # a concurrent starter already cleaned it


def n_match_slots(image_size, k_size, both_directions):
    """Fixed slot count of the .mat contract (eval_inloc.py:116-118)."""
    g = image_size * SCALE_FACTOR / k_size
    n = int(g * np.floor(g * (3 / 4)))
    return 2 * n if both_directions else n


def dump_matches(
    params,
    config,
    shortlist_path,
    query_path,
    pano_path,
    output_dir,
    image_size=3200,
    n_queries=356,
    n_panos=10,
    both_directions=True,
    flip_direction=False,
    verbose=True,
    mesh=None,
    softmax=True,
    device_preprocess=False,
    device_resize=False,
    feature_store_dir=None,
):
    """Run the full dump. Writes ``<output_dir>/<q+1>.mat`` per query.

    ``feature_store_dir``: directory of a
    :class:`ncnet_tpu.features.GalleryFeatureStore` (created on first
    use, digest-guarded — a store extracted under different trunk
    weights/config is REJECTED, never silently matched against).
    Database-pano trunk features are then read from the store instead of
    re-running the trunk per query-pano pair: each pano's backbone
    forward runs once EVER (across queries AND dump restarts), and each
    query's once per query — the reference re-extracts both images for
    every pair, so at the standard 10-pano shortlist the trunk work
    drops ~10x per query visit and to ~zero on re-runs. Incompatible
    with ``mesh``/``device_preprocess``/``device_resize`` (the store
    path has its own host pipeline; panos ship as features — 1.28 MB
    bf16 at the (2400, 3200) bucket vs 5.8 MB uint8 original — so the
    transfer engineering of the image path does not apply).

    ``mesh``: optional Mesh with a 'spatial' axis — shards the correlation
    pipeline over A-grid rows for resolutions beyond single-chip HBM. The
    resize quantization is widened so feature grids divide the shard count.

    Crash safety: each ``.mat`` is written to a temp name in the output
    dir and atomically renamed into place, so resume (which skips
    existing files) can never trust a torn write; stale temp files from a
    killed run are removed on start.

    Host pipeline engineering (rounds 4-5, measured): the per-pair wall
    clock started at 10.75 s against <1 s of device time — dominated by
    fp32 image transfer over this platform's ~25 MB/s tunnel and serial
    host decode+resize. The fixes (10.75 -> 0.61 s/pair,
    benchmarks/PERF.md "Host pipeline"):
    images ship as uint8 with on-device normalization
    (``device_preprocess`` — numerics differ from the exact host-fp32
    path only by uint8 rounding of resized pixels, so the LIBRARY default
    stays False and the CLI turns it on); upscale-bound images (the
    panos: 1600x1200 -> the 2400x3200 bucket) ship at ORIGINAL size and
    bilinear-resize on device (``device_resize``, 23 -> 5.8 MB per pair);
    a one-worker prefetch thread decodes upcoming images while the
    device computes the current pair; upcoming images' host->device
    copies are enqueued before the current pair's result is consumed
    (`pre_transfer`, 4 deep), riding along the device compute; the
    per-pair readout is ONE concatenated [5, b, n] array whose direction
    concat happens inside the jit (every extra dispatch/transfer pays
    ~80 ms latency here) and whose D2H starts via `copy_to_host_async`
    the moment compute finishes; the consume loop runs one pair BEHIND
    the dispatch loop so readout+sort+dedup of pair i overlap the device
    compute of pair i+1; and `savemat` compression runs on a writer
    thread off the consume loop. Net measured steady state: 10.75 (r3)
    -> 3.82 (r4) -> 0.61 s/pair (r5) on the tunneled host at 2
    panos/query, 0.338 at the real 10-pano ratio (~20 min full dump) —
    A/B: without ``device_resize`` the same pipeline is 1.54 s/pair
    (H2D-bound).
    """
    import concurrent.futures

    from scipy.io import loadmat

    if feature_store_dir is not None:
        if mesh is not None or device_preprocess or device_resize:
            raise ValueError(
                "feature_store_dir is incompatible with mesh/"
                "device_preprocess/device_resize (the gallery-store dump "
                "has its own pipeline; see dump_matches docstring)"
            )
        return _dump_matches_from_store(
            params, config, shortlist_path, query_path, pano_path,
            output_dir, image_size, n_queries, n_panos, both_directions,
            flip_direction, verbose, softmax, feature_store_dir,
        )

    if device_resize and not device_preprocess:
        raise ValueError(
            "device_resize requires device_preprocess (the uint8 wire "
            "format + on-device ImageNet normalization)"
        )
    k_size = config.relocalization_k_size
    stride_actual = backbone_stride(config.feature_extraction_cnn)
    if stride_actual != int(1 / SCALE_FACTOR):
        raise ValueError(
            f"backbone stride {stride_actual} does not match the dump's "
            f"SCALE_FACTOR {SCALE_FACTOR} (expects stride "
            f"{int(1 / SCALE_FACTOR)}); the .mat coordinate contract "
            "assumes the reference's 1/16 feature stride"
        )
    grid_multiple = None
    if mesh is not None:
        grid_multiple = max(k_size, 1) * mesh.shape["spatial"]

    dbmat = loadmat(shortlist_path)
    db = dbmat["ImgList"][0, :]
    pano_fn_all = np.vstack(tuple(db[q][1] for q in range(len(db))))

    os.makedirs(output_dir, exist_ok=True)
    # both-directions dumps fuse the direction concat into the jit (one
    # device dispatch less per pair) and pipeline the consume loop one
    # pair deep below
    concat = both_directions
    jitted = jax.jit(
        make_match_fn(
            config, mesh=mesh, softmax=softmax,
            device_preprocess=device_preprocess,
            concat_directions=concat,
        )
    )
    stride = backbone_stride(config.feature_extraction_cnn)

    def prep(root, fn):
        out = load_and_preprocess(
            os.path.join(root, fn), image_size, k_size, grid_multiple,
            device_normalize=device_preprocess,
            device_resize=device_resize,
        )
        # uniform (array, target_hw_or_None) item shape for the loop
        return out if device_resize else (out, None)

    # a killed run can leave torn temp files behind; they are never read
    # by resume (only exact `<q+1>.mat` names are), just clean them up
    _clean_stale_temps(output_dir)

    # (root, fn) jobs for every missing pair, in dump order: queries are
    # interleaved with their panos so one prefetch slot always holds the
    # next image to be consumed
    jobs = []
    todo = []
    for q in range(n_queries):
        out_path = os.path.join(output_dir, f"{q + 1}.mat")
        if os.path.exists(out_path):  # resumable, unlike the reference
            continue
        todo.append(q)
        jobs.append((query_path, _to_str(db[q][0])))
        for idx in range(n_panos):
            jobs.append((pano_path, _to_str(db[q][1].ravel()[idx])))

    n_slots = n_match_slots(image_size, k_size, both_directions)
    import collections

    with concurrent.futures.ThreadPoolExecutor(1) as pool, \
            concurrent.futures.ThreadPoolExecutor(1) as writer:
        # bounded look-ahead: at most `window` decoded images in flight
        # on the host (so prefetch memory stays O(window), not O(dump))
        # plus up to `device_ahead` images pre-transferred to the device
        # (4-deep measured best: enough transfers in flight to keep the
        # ~25 MB/s tunnel busy through the current pair's compute)
        window = 6
        device_ahead = 4
        jobs_iter = iter(jobs)
        pending = collections.deque()
        yielded = 0

        def top_up():
            while len(pending) < window:
                try:
                    root, fn = next(jobs_iter)
                except StopIteration:
                    return
                pending.append(pool.submit(prep, root, fn))

        def next_image():
            nonlocal yielded
            fut = pending.popleft()
            top_up()
            yielded += 1
            return fut.result()

        ahead = collections.deque()  # next images, already ON the device

        def to_device(item):
            # transfer (async) + optional on-device upscale to the bucket
            # shape (`device_resize` — the resize rides the device queue,
            # so pre-transferred images are already final-shaped by the
            # time take() hands them to the match fn)
            arr, target_hw = item
            arr = jnp.asarray(arr)
            if target_hw is not None:
                arr = device_resize_uint8(arr, *target_hw)
            return arr

        def take():
            if ahead:
                return ahead.popleft()
            return to_device(next_image())

        def pre_transfer():
            # enqueue upcoming images' host->device copies while the
            # device is busy with the current pair
            while len(ahead) < device_ahead and yielded < len(jobs):
                ahead.append(to_device(next_image()))

        writes = collections.deque()

        def flush_writes(keep=1):
            # propagate writer-thread failures promptly; keep at most
            # `keep` outstanding so a wedged disk backpressures the loop
            while writes and (len(writes) > keep or writes[0].done()):
                writes.popleft().result()

        # dispatch-ahead pipeline: the device computes pair i+1 while the
        # host reads out and postprocesses pair i (D2H + sort/dedup were
        # ~0.5 s/pair of device idle when consumed synchronously)
        matrices = {}  # q -> [1, n_panos, n_slots, 5] being filled
        inflight = collections.deque()
        pipeline_depth = 1

        m_pairs = default_registry().counter(
            "eval_pairs_total", "image pairs evaluated"
        )

        def consume():
            q, idx, out, shp = inflight.popleft()
            # the pair's readout span: D2H of the match tensor plus the
            # host-side sort/dedup (dispatch overlaps it — see below)
            with trace.span("eval/pair_readout"):
                xa, ya, xb, yb, score = match_pair(
                    None, None, None, None, k_size, stride,
                    both_directions, flip_direction, precomputed=out,
                    shapes=shp,
                )
            m_pairs.inc()
            matches = matrices[q]
            n = min(len(xa), n_slots)
            matches[0, idx, :n, 0] = xa[:n]
            matches[0, idx, :n, 1] = ya[:n]
            matches[0, idx, :n, 2] = xb[:n]
            matches[0, idx, :n, 3] = yb[:n]
            matches[0, idx, :n, 4] = score[:n]
            if idx + 1 == n_panos:
                del matrices[q]
                out_path = os.path.join(output_dir, f"{q + 1}.mat")
                # compression is ~100 ms of host CPU per query; run it
                # off the consume loop so the device never waits on it
                writes.append(
                    writer.submit(
                        _atomic_savemat,
                        out_path,
                        {"matches": matches, "query_fn": _to_str(db[q][0]),
                         "pano_fn": pano_fn_all},
                    )
                )
                flush_writes()
                if verbose:
                    print(
                        f"query {q + 1}/{n_queries} -> {out_path}",
                        flush=True,
                    )

        top_up()
        for q in todo:
            matrices[q] = np.zeros((1, n_panos, n_slots, 5))
            src = take()
            tgt = take()
            for idx in range(n_panos):
                with trace.span("eval/pair_dispatch"):
                    out = jitted(params, src, tgt)  # async dispatch
                if concat:
                    # start the result's D2H the moment compute finishes,
                    # without blocking this thread
                    out.copy_to_host_async()
                inflight.append((q, idx, out, (src.shape, tgt.shape)))
                pre_transfer()  # H2D rides along the device compute
                while len(inflight) > pipeline_depth:
                    consume()
                if idx + 1 < n_panos:
                    tgt = take()
        while inflight:
            consume()
        flush_writes(keep=0)


def _dump_matches_from_store(
    params, config, shortlist_path, query_path, pano_path, output_dir,
    image_size, n_queries, n_panos, both_directions, flip_direction,
    verbose, softmax, feature_store_dir,
):
    """The gallery-feature-store dump loop (ROADMAP "Precomputed gallery
    features for InLoc-style retrieval").

    Per query: ONE trunk forward for the query image; per pano: a store
    lookup (trunk forward only on first-ever visit, durably cached across
    queries and dump restarts). The NC/correlation match runs from
    features via `make_match_fn(from_features=True)` — identical math to
    the image path, the backbone simply never reruns. Cached panos skip
    image loading entirely: the feature shard self-describes its grid,
    and the .mat coordinate contract only needs the grid (times the
    backbone stride).

    Kept deliberately simpler than the image loop's transfer pipeline:
    what that engineering hides (fp32/uint8 image H2D, host decode) the
    store path mostly eliminates at the source — features are ~4x
    smaller than even the uint8 device_resize wire format, and the pano
    decode+resize+trunk work disappears for every cached visit.
    """
    from scipy.io import loadmat

    from ncnet_tpu.features import GalleryFeatureStore, trunk_digest
    from ncnet_tpu.models.immatchnet import extract_features

    k_size = config.relocalization_k_size
    stride = backbone_stride(config.feature_extraction_cnn)
    if stride != int(1 / SCALE_FACTOR):
        raise ValueError(
            f"backbone stride {stride} does not match the dump's "
            f"SCALE_FACTOR {SCALE_FACTOR} (expects stride "
            f"{int(1 / SCALE_FACTOR)}); the .mat coordinate contract "
            "assumes the reference's 1/16 feature stride"
        )

    store = GalleryFeatureStore.open_or_create(
        feature_store_dir,
        trunk_digest(params["feature_extraction"], config, None),
        config,
    )
    extractor = jax.jit(lambda p, img: extract_features(p, config, img))
    concat = both_directions
    match_fn = jax.jit(
        make_match_fn(
            config, softmax=softmax, concat_directions=concat,
            from_features=True,
        )
    )

    def extract_from_disk(root, fn):
        img = load_and_preprocess(
            os.path.join(root, fn), image_size, k_size
        )
        return extractor(params, jnp.asarray(img))

    def pano_features(fn):
        # keyed by the shortlist-relative filename: stable across hosts
        # and dataset roots (the digest pins the trunk side)
        if store.has(fn):
            return jnp.asarray(store.get(fn))
        feats = extract_from_disk(pano_path, fn)
        store.put(fn, np.asarray(feats))
        return feats

    dbmat = loadmat(shortlist_path)
    db = dbmat["ImgList"][0, :]
    pano_fn_all = np.vstack(tuple(db[q][1] for q in range(len(db))))

    os.makedirs(output_dir, exist_ok=True)
    _clean_stale_temps(output_dir)
    n_slots = n_match_slots(image_size, k_size, both_directions)

    for q in range(n_queries):
        out_path = os.path.join(output_dir, f"{q + 1}.mat")
        if os.path.exists(out_path):  # resumable, like the image loop
            continue
        qfeat = extract_from_disk(query_path, _to_str(db[q][0]))
        q_shape = (1, qfeat.shape[1] * stride, qfeat.shape[2] * stride, 3)
        matches = np.zeros((1, n_panos, n_slots, 5))
        for idx in range(n_panos):
            with trace.span("eval/pair"):
                pfeat = pano_features(_to_str(db[q][1].ravel()[idx]))
                p_shape = (
                    1, pfeat.shape[1] * stride, pfeat.shape[2] * stride, 3
                )
                out = match_fn(params, qfeat, pfeat)
                xa, ya, xb, yb, score = match_pair(
                    None, None, None, None, k_size, stride,
                    both_directions, flip_direction, precomputed=out,
                    shapes=(q_shape, p_shape),
                )
            default_registry().counter(
                "eval_pairs_total", "image pairs evaluated"
            ).inc()
            n = min(len(xa), n_slots)
            matches[0, idx, :n, 0] = xa[:n]
            matches[0, idx, :n, 1] = ya[:n]
            matches[0, idx, :n, 2] = xb[:n]
            matches[0, idx, :n, 3] = yb[:n]
            matches[0, idx, :n, 4] = score[:n]
        _atomic_savemat(
            out_path,
            {"matches": matches, "query_fn": _to_str(db[q][0]),
             "pano_fn": pano_fn_all},
        )
        if verbose:
            print(f"query {q + 1}/{n_queries} -> {out_path}", flush=True)
