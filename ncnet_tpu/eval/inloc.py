"""InLoc dense-match dump.

Reproduces the Python-side contract of the reference's eval_inloc.py so the
downstream MATLAB PnP-RANSAC + pose-verification pipeline runs unmodified:
one ``matches/<experiment>/<q+1>.mat`` per query containing a ``matches``
array ``[1, Npanos, N, 5]`` of ``(xA, yA, xB, yB, score)`` rows in
normalized [0, 1] coordinates (eval_inloc.py:126,199-203,221).

Pipeline per (query, pano) pair (eval_inloc.py:124-203):
  aspect-preserving resize with the feature grid quantized to multiples of
  ``k_size`` (so 4D max-pool relocalization divides evenly)
  -> bf16 forward with fused correlation+maxpool4d
  -> `corr_to_matches` in both directions (scale='positive', softmax)
  -> concatenate, sort by descending score, coordinate-level dedup
  -> recenter normalized coords to feature-cell centers.

XLA note: every distinct image shape compiles once; the k_size·stride
quantization already buckets shapes to a small set, so the jit cache acts
as the shape-bucketing layer.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp

from ncnet_tpu.data.images import (
    load_image,
    normalize_image_np,
    resize_bilinear_np,
    to_uint8_image,
)
from ncnet_tpu.models.feature_extraction import backbone_stride
from ncnet_tpu.models.immatchnet import immatchnet_apply
from ncnet_tpu.ops.matches import corr_to_matches

SCALE_FACTOR = 0.0625  # 1/backbone stride (reference eval_inloc.py:77)


def _to_str(x):
    """Unwrap scipy-loaded MATLAB cell/char nesting to a plain str."""
    while isinstance(x, np.ndarray):
        x = x.ravel()[0]
    return str(x)


def quantized_resize_shape(h, w, image_size, k_size, grid_multiple=None):
    """The reference's resize rule (eval_inloc.py:84-89): max side ->
    ``image_size``, then quantize so feature-grid dims divide by
    ``grid_multiple`` (default: ``k_size``; the sharded path additionally
    needs divisibility by the shard count)."""
    m = grid_multiple if grid_multiple is not None else k_size
    ratio = max(h, w) / image_size
    if m <= 1:
        return int(h / ratio), int(w / ratio)
    s = SCALE_FACTOR
    return (
        int(np.floor(h / ratio * s / m) / s * m),
        int(np.floor(w / ratio * s / m) / s * m),
    )


def load_and_preprocess(path, image_size, k_size, grid_multiple=None,
                        device_normalize=False):
    """Load -> quantized resize -> ImageNet-normalize.

    ``device_normalize=True`` returns the resized image as uint8 and
    leaves normalization to the device (`make_match_fn`'s
    ``device_preprocess``): the tunneled host<->device link of this
    platform moves ~25 MB/s, so shipping a (2400, 3200) image as fp32
    costs ~3.7 s against ~0.9 s as uint8 — measured round 4; on directly-
    attached TPU hosts both are microseconds and the paths are
    numerically equivalent to within the uint8 rounding of the resized
    pixels (<=0.2% of the dynamic range, far below matching tolerance).
    """
    img = load_image(path)
    h, w = quantized_resize_shape(
        img.shape[0], img.shape[1], image_size, k_size, grid_multiple
    )
    img = resize_bilinear_np(img, h, w)
    if device_normalize:
        return to_uint8_image(img)[None]
    return normalize_image_np(img)[None]  # [1, h, w, 3]


def make_match_fn(config, mesh=None, softmax=True, device_preprocess=False):
    """(params, src, tgt) -> (fwd, rev) match tuples for one pair (jittable).

    With ``mesh`` (a Mesh with a 'spatial' axis), the correlation/NC
    pipeline runs sharded over the A-grid rows via
    `parallel.spatial.make_sharded_match_pipeline` — the high-res path for
    grids whose corr4d exceeds a single chip's HBM (BASELINE config 5).
    Feature grids must divide k_size x the shard count (use
    ``grid_multiple`` in `load_and_preprocess`).

    ``device_preprocess=True`` accepts uint8 images and ImageNet-
    normalizes them ON DEVICE (pair with `load_and_preprocess
    (device_normalize=True)`) — a 4x host->device transfer saving.
    """
    from ncnet_tpu.ops.image import imagenet_normalize

    k = config.relocalization_k_size

    if mesh is None:
        def forward(params, src, tgt):
            return immatchnet_apply(params, config, src, tgt)
    else:
        from ncnet_tpu.models.immatchnet import extract_features
        from ncnet_tpu.parallel.spatial import make_sharded_match_pipeline

        pipeline = make_sharded_match_pipeline(config, mesh)

        def forward(params, src, tgt):
            feat_a = extract_features(params, config, src)
            feat_b = extract_features(params, config, tgt)
            return pipeline(params["neigh_consensus"], feat_a, feat_b)

    def fn(params, src, tgt):
        if device_preprocess:
            src = imagenet_normalize(src.astype(jnp.float32))
            tgt = imagenet_normalize(tgt.astype(jnp.float32))
        out = forward(params, src, tgt)
        corr, delta4d = out if k > 1 else (out, None)
        kw = dict(
            scale="positive", do_softmax=softmax, delta4d=delta4d,
            k_size=max(k, 1),
        )
        fwd = corr_to_matches(corr, **kw)
        rev = corr_to_matches(corr, invert_matching_direction=True, **kw)
        # one device buffer per direction (not 5): each D2H transfer pays
        # this platform's ~80 ms dispatch latency, so the dump loop reads
        # ONE stacked [5, b, n] array per direction instead of five
        return jnp.stack(fwd), jnp.stack(rev)

    return fn


def recenter(coord, n_cells):
    """Normalized [0,1] grid coords -> feature-cell centers
    (eval_inloc.py:179-189)."""
    return coord * (n_cells - 1) / n_cells + 0.5 / n_cells


def match_pair(match_fn, params, src, tgt, k_size, stride=16,
               both_directions=True, flip_direction=False, dedup=True,
               precomputed=None):
    """Returns (xA, yA, xB, yB, score) numpy arrays for one image pair.

    ``precomputed``: optionally the (fwd, rev) device output of an
    earlier (asynchronously dispatched) ``match_fn`` call — lets callers
    overlap the next pair's host->device transfer with this pair's
    device compute before this function synchronizes on the result.
    """
    fwd, rev = (
        precomputed if precomputed is not None
        else match_fn(params, src, tgt)
    )
    k = max(k_size, 1)
    # pooled correlation grid dims, derived from the image shapes
    fs1 = src.shape[1] // stride // k
    fs2 = src.shape[2] // stride // k
    fs3 = tgt.shape[1] // stride // k
    fs4 = tgt.shape[2] // stride // k
    # each direction is ONE stacked [5, b, n] device array (make_match_fn);
    # concatenating on device keeps the host sync to a single transfer
    if both_directions:
        parts = np.asarray(jnp.concatenate([fwd, rev], axis=2))
    elif flip_direction:
        parts = np.asarray(rev)
    else:
        parts = np.asarray(fwd)
    xa, ya, xb, yb, score = parts[:, 0]

    if both_directions:
        order = np.argsort(-score)  # descending; keeps max-score dup first
        xa, ya, xb, yb, score = (v[order] for v in (xa, ya, xb, yb, score))
        if dedup:
            coords = np.stack([xa, ya, xb, yb])
            _, uniq = np.unique(coords, axis=1, return_index=True)
            xa, ya, xb, yb, score = (v[uniq] for v in (xa, ya, xb, yb, score))

    ya = recenter(ya, fs1 * k)
    xa = recenter(xa, fs2 * k)
    yb = recenter(yb, fs3 * k)
    xb = recenter(xb, fs4 * k)
    return xa, ya, xb, yb, score


def n_match_slots(image_size, k_size, both_directions):
    """Fixed slot count of the .mat contract (eval_inloc.py:116-118)."""
    g = image_size * SCALE_FACTOR / k_size
    n = int(g * np.floor(g * (3 / 4)))
    return 2 * n if both_directions else n


def dump_matches(
    params,
    config,
    shortlist_path,
    query_path,
    pano_path,
    output_dir,
    image_size=3200,
    n_queries=356,
    n_panos=10,
    both_directions=True,
    flip_direction=False,
    verbose=True,
    mesh=None,
    softmax=True,
    device_preprocess=False,
):
    """Run the full dump. Writes ``<output_dir>/<q+1>.mat`` per query.

    ``mesh``: optional Mesh with a 'spatial' axis — shards the correlation
    pipeline over A-grid rows for resolutions beyond single-chip HBM. The
    resize quantization is widened so feature grids divide the shard count.

    Crash safety: each ``.mat`` is written to a temp name in the output
    dir and atomically renamed into place, so resume (which skips
    existing files) can never trust a torn write; stale temp files from a
    killed run are removed on start.

    Host pipeline engineering (round 4, measured): the per-pair wall clock
    was 10.75 s against 0.92 s of device time — dominated by fp32 image
    transfer over this platform's ~25 MB/s tunnel and serial host
    decode+resize. The fixes (10.75 -> 3.82 s/pair, benchmarks/PERF.md):
    images ship as uint8 with on-device normalization
    (``device_preprocess`` — numerics differ from the exact host-fp32
    path only by uint8 rounding of resized pixels, so the LIBRARY default
    stays False and the CLI turns it on); a one-worker prefetch thread
    decodes+resizes upcoming images while the device computes the current
    pair; upcoming images' host->device copies are enqueued before
    synchronizing on the current pair's result (`pre_transfer`, 4 deep —
    the measured optimum: 2-deep 1.9-2.5 s/pair, 4-deep 1.37-1.43,
    6-deep no better, benchmarks/micro_dump.py), riding along the device
    compute; the per-pair readout is ONE stacked [5, b, n] D2H per
    direction (each transfer pays ~80 ms dispatch latency here); and
    `savemat` compression runs on a writer thread off the consume loop
    (round 5). Net: 10.75 (r3) -> 3.82 (r4) -> ~1.4 s/pair (r5) on the
    tunneled host; device-bound 0.92 on direct-attached hosts.
    """
    import concurrent.futures

    from scipy.io import loadmat, savemat

    k_size = config.relocalization_k_size
    assert backbone_stride(config.feature_extraction_cnn) == int(1 / SCALE_FACTOR)
    grid_multiple = None
    if mesh is not None:
        grid_multiple = max(k_size, 1) * mesh.shape["spatial"]

    dbmat = loadmat(shortlist_path)
    db = dbmat["ImgList"][0, :]
    pano_fn_all = np.vstack(tuple(db[q][1] for q in range(len(db))))

    os.makedirs(output_dir, exist_ok=True)
    jitted = jax.jit(
        make_match_fn(
            config, mesh=mesh, softmax=softmax,
            device_preprocess=device_preprocess,
        )
    )
    stride = backbone_stride(config.feature_extraction_cnn)

    def prep(root, fn):
        return load_and_preprocess(
            os.path.join(root, fn), image_size, k_size, grid_multiple,
            device_normalize=device_preprocess,
        )

    # a killed run can leave torn temp files behind; they are never read
    # by resume (only exact `<q+1>.mat` names are), just clean them up —
    # but NOT temps owned by a still-running dump sharing this directory
    # (a second resume process must not delete the first's in-flight file)
    for stale in os.listdir(output_dir):
        if ".mat.tmp." not in stale:
            continue
        try:
            owner = int(stale.rsplit(".", 1)[-1])
            os.kill(owner, 0)  # raises if no such process
            continue  # owner alive: leave its temp alone
        except (ValueError, ProcessLookupError):
            pass
        except PermissionError:
            continue  # pid exists under another uid: leave it
        try:
            os.unlink(os.path.join(output_dir, stale))
        except FileNotFoundError:
            pass  # a concurrent starter already cleaned it

    # (root, fn) jobs for every missing pair, in dump order: queries are
    # interleaved with their panos so one prefetch slot always holds the
    # next image to be consumed
    jobs = []
    todo = []
    for q in range(n_queries):
        out_path = os.path.join(output_dir, f"{q + 1}.mat")
        if os.path.exists(out_path):  # resumable, unlike the reference
            continue
        todo.append(q)
        jobs.append((query_path, _to_str(db[q][0])))
        for idx in range(n_panos):
            jobs.append((pano_path, _to_str(db[q][1].ravel()[idx])))

    def atomic_savemat(out_path, payload):
        # savemat into a temp name + atomic rename: resume treats any
        # existing `<q+1>.mat` as complete, so a crash mid-write must
        # never leave a file under the final name
        tmp = f"{out_path}.tmp.{os.getpid()}"
        try:
            savemat(tmp, payload, do_compression=True)
            os.replace(tmp, out_path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    n_slots = n_match_slots(image_size, k_size, both_directions)
    import collections

    with concurrent.futures.ThreadPoolExecutor(1) as pool, \
            concurrent.futures.ThreadPoolExecutor(1) as writer:
        # bounded look-ahead: at most `window` decoded images in flight
        # on the host (so prefetch memory stays O(window), not O(dump))
        # plus up to `device_ahead` images pre-transferred to the device
        # (4-deep measured best: enough transfers in flight to keep the
        # ~25 MB/s tunnel busy through the current pair's compute)
        window = 6
        device_ahead = 4
        jobs_iter = iter(jobs)
        pending = collections.deque()
        yielded = 0

        def top_up():
            while len(pending) < window:
                try:
                    root, fn = next(jobs_iter)
                except StopIteration:
                    return
                pending.append(pool.submit(prep, root, fn))

        def next_image():
            nonlocal yielded
            fut = pending.popleft()
            top_up()
            yielded += 1
            return fut.result()

        ahead = collections.deque()  # next images, already ON the device

        def take():
            if ahead:
                return ahead.popleft()
            return jnp.asarray(next_image())

        def pre_transfer():
            # enqueue upcoming images' host->device copies while the
            # device is busy with the current pair
            while len(ahead) < device_ahead and yielded < len(jobs):
                ahead.append(jnp.asarray(next_image()))

        writes = collections.deque()

        def flush_writes(keep=1):
            # propagate writer-thread failures promptly; keep at most
            # `keep` outstanding so a wedged disk backpressures the loop
            while writes and (len(writes) > keep or writes[0].done()):
                writes.popleft().result()

        top_up()
        for q in todo:
            out_path = os.path.join(output_dir, f"{q + 1}.mat")
            matches = np.zeros((1, n_panos, n_slots, 5))
            query_fn = _to_str(db[q][0])
            src = take()
            tgt = take()
            for idx in range(n_panos):
                out = jitted(params, src, tgt)  # async dispatch
                pre_transfer()  # H2D rides along the device compute
                xa, ya, xb, yb, score = match_pair(
                    jitted, params, src, tgt, k_size, stride,
                    both_directions, flip_direction, precomputed=out,
                )
                n = min(len(xa), n_slots)
                matches[0, idx, :n, 0] = xa[:n]
                matches[0, idx, :n, 1] = ya[:n]
                matches[0, idx, :n, 2] = xb[:n]
                matches[0, idx, :n, 3] = yb[:n]
                matches[0, idx, :n, 4] = score[:n]
                if idx + 1 < n_panos:
                    tgt = take()
            # compression is ~100 ms of host CPU per query; run it off
            # the consume loop so the device never waits on it
            writes.append(
                writer.submit(
                    atomic_savemat,
                    out_path,
                    {"matches": matches, "query_fn": query_fn,
                     "pano_fn": pano_fn_all},
                )
            )
            flush_writes()
            if verbose:
                print(f"query {q + 1}/{n_queries} -> {out_path}", flush=True)
        flush_writes(keep=0)
