"""Dense pose verification (densePV): re-rank pose candidates by rendering.

Python port of the reference's MATLAB PV stage
(lib_matlab/ht_top10_NC4D_PV_localization.m + parfor_nc4d_PV.m): for each
candidate pose, render a synthetic view of the colored scan point cloud
from that pose (z-buffered point splat — the ``ht_Points2Persp`` role),
compare dense image descriptors between the real query and the synthetic
view, score = 1 / median descriptor distance, and re-rank the top-N
candidates by descending score.

Deviations from the reference, documented: the reference uses vl_feat's
``vl_phow`` (sizes 8, step 4) for dense SIFT; vl_feat does not exist here,
so `dense_root_sift` implements an equivalent dense RootSIFT descriptor
(8-orientation gradient histograms over 4x4 cells of ``bin_size`` pixels,
L1-normalize + sqrt) — same family, not bit-identical. ``inpaint_nans``
is a nearest-neighbor fill. Everything is host-side numpy/scipy (the
reference runs this stage on CPU via MATLAB parfor).
"""

import numpy as np

DOWNSAMPLE = 1.0 / 8.0  # reference dslevel (parfor_nc4d_PV.m:2)


def project_points_persp(rgb, xyz, KP, h, w):
    """Z-buffered perspective point splat (the ``ht_Points2Persp`` role).

    Args:
      rgb: ``[n, 3]`` colors (uint8 or float).
      xyz: ``[n, 3]`` world points.
      KP: ``[3, 4]`` projection ``K @ [R | t]``.
      h, w: output size.

    Returns:
      ``(rgb_persp [h, w, 3] float, xyz_persp [h, w, 3], valid [h, w])`` —
      NaN xyz / zero rgb where no point lands.
    """
    X = np.asarray(xyz, np.float64)
    C = np.asarray(rgb, np.float64)
    # ONE combined keep-mask and ONE fancy-index per array: the previous
    # three successive filters (finite -> in-front -> inside) each copied
    # every 1.9M-row float64 array and dominated the per-candidate cost
    proj = X @ KP[:, :3].T + KP[:, 3]
    z = proj[:, 2]
    with np.errstate(invalid="ignore", divide="ignore"):
        uf = np.rint(proj[:, 0] / z)  # NaN/z<=0 rows -> NaN -> masked out
        vf = np.rint(proj[:, 1] / z)
        keep = (
            np.isfinite(X).all(axis=1)
            & (z > 1e-9)
            & (uf >= 0) & (uf < w) & (vf >= 0) & (vf < h)
        )
    u = uf[keep].astype(np.int64)
    v = vf[keep].astype(np.int64)
    z, C, X = z[keep], C[keep], X[keep]

    # nearest point wins: a scatter-min z-buffer (np.minimum.at) instead
    # of sorting all points far-to-near — measured 53 ms vs 462 ms for a
    # 1.9M-point cutout-sized cloud (ties resolve arbitrarily, as the
    # unstable sort's did)
    pix = v * w + u
    zbuf = np.full(h * w, np.inf)
    np.minimum.at(zbuf, pix, z)
    win = z == zbuf[pix]
    rgb_persp = np.zeros((h * w, 3), np.float64)
    xyz_persp = np.full((h * w, 3), np.nan)
    rgb_persp[pix[win]] = C[win]
    xyz_persp[pix[win]] = X[win]
    rgb_persp = rgb_persp.reshape(h, w, 3)
    xyz_persp = xyz_persp.reshape(h, w, 3)
    valid = np.isfinite(xyz_persp).all(axis=-1)
    return rgb_persp, xyz_persp, valid


def inpaint_nearest(img, valid):
    """Fill invalid pixels with the nearest valid value (``inpaint_nans``
    role; nearest-neighbor variant)."""
    if valid.all():
        return img
    if not valid.any():
        return np.zeros_like(img)
    from scipy import ndimage

    _, idx = ndimage.distance_transform_edt(
        ~valid, return_distances=True, return_indices=True
    )
    return img[idx[0], idx[1]]


def image_normalization(img, mask):
    """Zero-mean / unit-std over the masked region (``image_normalization``
    role)."""
    vals = img[mask]
    if vals.size == 0:
        return img
    std = vals.std()
    return (img - vals.mean()) / (std + 1e-12)


def _grayscale(img):
    img = np.asarray(img, np.float64)
    if img.ndim == 3:
        return img @ np.array([0.299, 0.587, 0.114])
    return img


def dense_root_sift(img, bin_size=8, step=4):
    """Dense RootSIFT-style descriptors (the ``vl_phow`` sizes=8 step=4
    role).

    4x4 spatial cells of ``bin_size`` px, 8 orientation bins, computed at
    every ``step`` pixels; descriptors are L1-normalized then sqrt'd
    (RootSIFT, the reference's ``relja_rootsift``).

    Returns:
      ``(centers [m, 2] of (x, y) pixel coords, desc [m, 128])``.
    """
    from scipy import ndimage

    img = np.asarray(img, np.float64)
    h, w = img.shape
    gy, gx = np.gradient(img)
    mag = np.hypot(gx, gy)
    ang = np.arctan2(gy, gx) % (2 * np.pi)
    n_ori = 8
    bins = np.floor(ang / (2 * np.pi) * n_ori).astype(int) % n_ori

    # per-orientation magnitude maps, box-summed over bin_size x bin_size
    cell_sums = np.empty((n_ori, h, w))
    for o in range(n_ori):
        m = np.where(bins == o, mag, 0.0)
        cell_sums[o] = ndimage.uniform_filter(m, size=bin_size) * bin_size**2

    support = 4 * bin_size
    half = support // 2
    xs = np.arange(half, w - half + 1, step)
    ys = np.arange(half, h - half + 1, step)
    if len(xs) == 0 or len(ys) == 0:
        return np.zeros((0, 2), int), np.zeros((0, 128))
    cx, cy = np.meshgrid(xs, ys)
    centers = np.stack([cx.ravel(), cy.ravel()], axis=1)

    # cell centers: 4x4 grid offset from the descriptor center
    offs = (np.arange(4) - 1.5) * bin_size
    desc = np.empty((len(centers), 4, 4, n_ori))
    for iy, oy in enumerate(offs):
        for ix, ox in enumerate(offs):
            py = np.clip((centers[:, 1] + oy).astype(int), 0, h - 1)
            px = np.clip((centers[:, 0] + ox).astype(int), 0, w - 1)
            desc[:, iy, ix, :] = cell_sums[:, py, px].T
    desc = desc.reshape(len(centers), -1)
    # RootSIFT: L1 normalize + sqrt (clip float-noise negatives from the
    # box filter before the sqrt)
    desc = np.maximum(desc, 0.0)
    desc = desc / (desc.sum(axis=1, keepdims=True) + 1e-12)
    return centers, np.sqrt(desc)


def prepare_query(query_img, focal_length, downsample=DOWNSAMPLE,
                  bin_size=8, step=4):
    """Precompute the query side of `pose_verification_score` once per
    query (the reference recomputes it per candidate; the dense-descriptor
    grid dominates the stage's CPU cost and is candidate-independent:
    `image_normalization` is affine and gradient+RootSIFT-L1 cancels any
    affine rescale, so the per-candidate valid-mask normalization does not
    change the descriptors)."""
    from ncnet_tpu.data.images import resize_bilinear_np

    q = _grayscale(query_img)
    qh = max(int(round(q.shape[0] * downsample)), 1)
    qw = max(int(round(q.shape[1] * downsample)), 1)
    q = resize_bilinear_np(q[..., None].astype(np.float32), qh, qw)[..., 0]
    fl = focal_length * downsample
    K = np.array([[fl, 0, qw / 2.0], [0, fl, qh / 2.0], [0, 0, 1.0]])
    cq, dq = dense_root_sift(image_normalization(q, np.ones_like(q, bool)),
                             bin_size, step)
    return {"K": K, "shape": (qh, qw), "centers": cq, "desc": dq,
            "bin_size": bin_size, "step": step}


def score_prepared(prep, rgb, xyz, P):
    """Score one candidate pose against a `prepare_query` result."""
    if P is None or not np.all(np.isfinite(P)):
        return 0.0
    qh, qw = prep["shape"]
    rgb_persp, _, valid = project_points_persp(
        np.asarray(rgb), np.asarray(xyz), prep["K"] @ np.asarray(P), qh, qw
    )
    if not valid.any() or len(prep["centers"]) == 0:
        return 0.0
    synth = _grayscale(rgb_persp)
    synth = image_normalization(inpaint_nearest(synth, valid), valid)
    cs, ds = dense_root_sift(synth, prep["bin_size"], prep["step"])
    on_render = valid[cs[:, 1], cs[:, 0]]
    if not on_render.any():
        return 0.0
    err = np.linalg.norm(prep["desc"][on_render] - ds[on_render], axis=1)
    med = np.median(err)
    if not np.isfinite(med):
        return 0.0
    # finite cap (an exact-0 median would otherwise serialize as the
    # non-standard JSON token Infinity downstream)
    return float(1.0 / max(med, 1e-12))


def pose_verification_score(query_img, rgb, xyz, P, focal_length,
                            downsample=DOWNSAMPLE, bin_size=8, step=4):
    """Similarity between the query and the scan rendered at pose ``P``.

    parfor_nc4d_PV.m end to end: downsample the query, render the point
    cloud at ``K P``, normalize both grayscales, dense-RootSIFT both, and
    return ``1 / median descriptor L2 error`` over descriptors whose
    center lands on a rendered pixel (0.0 when the pose is invalid or
    nothing renders). Scoring many candidates of one query? Use
    `prepare_query` + `score_prepared`.
    """
    prep = prepare_query(query_img, focal_length, downsample, bin_size, step)
    return score_prepared(prep, rgb, xyz, P)


def rerank_by_pose_verification(entries, score_fn, top_n=10):
    """Re-rank each query's pose candidates by descending PV score
    (ht_top10_NC4D_PV_localization.m:49-63).

    Args:
      entries: list of dicts with ``topNname`` and ``P`` lists (the
        localization output records).
      score_fn: ``(entry, idx) -> float`` computing the PV score of
        candidate ``idx`` of ``entry`` (caller supplies data loading).

    Returns the entries with ``topNname``/``P`` reordered and a
    ``topNscore`` list added.
    """
    out = []
    for entry in entries:
        n = min(top_n, len(entry["P"]))
        scores = [score_fn(entry, j) for j in range(n)]
        # stable: tied scores (e.g. all-0 failed renders) keep the prior
        # PnP/retrieval ranking instead of an arbitrary permutation
        order = np.argsort(-np.asarray(scores), kind="stable")
        reordered = list(order) + list(range(n, len(entry["P"])))
        out.append(
            {
                **entry,
                "topNname": [entry["topNname"][j] for j in reordered],
                "P": [entry["P"][j] for j in reordered],
                "topNscore": [scores[j] for j in order],
            }
        )
    return out
