"""PF-Pascal PCK@alpha evaluation.

Mirrors eval_pf_pascal.py of the reference: per pair, forward ->
``corr_to_matches(do_softmax=True)`` -> bilinear keypoint transfer ->
PCK against -1-padded ground-truth keypoints with the 'scnet' L_pck
procedure (eval_pf_pascal.py:46-89). The mean is over valid (non-NaN)
pairs.

Unlike the reference (batch_size=1 only, eval_pf_pascal.py:52-53), the
metric pipeline here is fully batched and jit-compiled end-to-end.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import immatchnet_apply
from ncnet_tpu.ops.coords import points_to_pixel_coords, points_to_unit_coords
from ncnet_tpu.ops.matches import bilinear_point_transfer, corr_to_matches
from ncnet_tpu.ops.metrics import pck


def make_pck_step(config, alpha=0.1):
    """Returns jitted ``step(params, batch) -> [b] per-pair PCK``."""

    def step(params, batch):
        corr = immatchnet_apply(
            params, config, batch["source_image"], batch["target_image"]
        )
        x_a, y_a, x_b, y_b, _ = corr_to_matches(corr, do_softmax=True)
        tgt_norm = points_to_unit_coords(
            batch["target_points"], batch["target_im_size"]
        )
        warped_norm = bilinear_point_transfer((x_a, y_a, x_b, y_b), tgt_norm)
        warped = points_to_pixel_coords(warped_norm, batch["source_im_size"])
        return pck(batch["source_points"], warped, batch["L_pck"], alpha=alpha)

    return jax.jit(step)


def evaluate(params, config, loader, alpha=0.1, verbose=True):
    """Run PCK over a loader of PFPascalDataset batches.

    Returns ``{'pck': mean, 'per_pair': [...], 'n_valid': int}``.
    """
    step = make_pck_step(config, alpha)
    per_pair = []
    for i, batch in enumerate(loader):
        jbatch = {
            k: jnp.asarray(v)
            for k, v in batch.items()
            if k
            in (
                "source_image",
                "target_image",
                "source_points",
                "target_points",
                "source_im_size",
                "target_im_size",
                "L_pck",
            )
        }
        scores = np.asarray(step(params, jbatch))
        per_pair.extend(scores.tolist())
        if verbose:
            print(f"batch [{i + 1}/{len(loader)}]", flush=True)
    arr = np.asarray(per_pair)
    valid = ~np.isnan(arr) & (arr != -1)
    return {
        "pck": float(arr[valid].mean()) if valid.any() else float("nan"),
        "per_pair": per_pair,
        "n_valid": int(valid.sum()),
    }


def pck_vs_topk(params, config, loader, ks, alpha=0.1, verbose=False):
    """PF-Pascal PCK across sparse band widths (ncnet_tpu.sparse).

    Evaluates the SAME loader at every ``nc_topk`` in ``ks`` (0 = dense;
    the readout path is `corr_to_matches` on the densified band either
    way — see models/immatchnet.match_pipeline). Returns ``{k: result
    dict}`` in the `evaluate` schema; with ``k >= hB*wB`` the result must
    match the dense one, which anchors the accuracy/compute trade-off
    curve the sweep exists to measure.
    """
    batches = list(loader)
    return {
        int(k): evaluate(
            params, config.replace(nc_topk=int(k)), batches,
            alpha=alpha, verbose=verbose,
        )
        for k in ks
    }
