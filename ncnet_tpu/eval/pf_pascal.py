"""PF-Pascal PCK@alpha evaluation.

Mirrors eval_pf_pascal.py of the reference: per pair, forward ->
``corr_to_matches(do_softmax=True)`` -> bilinear keypoint transfer ->
PCK against -1-padded ground-truth keypoints with the 'scnet' L_pck
procedure (eval_pf_pascal.py:46-89). The mean is over valid (non-NaN)
pairs.

Unlike the reference (batch_size=1 only, eval_pf_pascal.py:52-53), the
metric pipeline here is fully batched and jit-compiled end-to-end.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import immatchnet_apply
from ncnet_tpu.ops.coords import points_to_pixel_coords, points_to_unit_coords
from ncnet_tpu.ops.matches import bilinear_point_transfer, corr_to_matches
from ncnet_tpu.ops.metrics import pck
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import default_registry


# the batch keys the PCK step consumes (and the serving payload carries)
PCK_BATCH_KEYS = (
    "source_image",
    "target_image",
    "source_points",
    "target_points",
    "source_im_size",
    "target_im_size",
    "L_pck",
)


def pck_step_fn(config, alpha=0.1):
    """Unjitted ``step(params, batch) -> [b] per-pair PCK`` — the one
    step body shared by the jitted sequential path (`make_pck_step`) and
    the serving path (`evaluate_serving`), so the two can only differ in
    batching, never in math."""

    def step(params, batch):
        corr = immatchnet_apply(
            params, config, batch["source_image"], batch["target_image"]
        )
        x_a, y_a, x_b, y_b, _ = corr_to_matches(corr, do_softmax=True)
        tgt_norm = points_to_unit_coords(
            batch["target_points"], batch["target_im_size"]
        )
        warped_norm = bilinear_point_transfer((x_a, y_a, x_b, y_b), tgt_norm)
        warped = points_to_pixel_coords(warped_norm, batch["source_im_size"])
        return pck(batch["source_points"], warped, batch["L_pck"], alpha=alpha)

    return step


def make_pck_step(config, alpha=0.1):
    """Returns jitted ``step(params, batch) -> [b] per-pair PCK``."""
    return jax.jit(pck_step_fn(config, alpha))


def _summarize(per_pair):
    arr = np.asarray(per_pair)
    valid = ~np.isnan(arr) & (arr != -1)
    return {
        "pck": float(arr[valid].mean()) if valid.any() else float("nan"),
        "per_pair": per_pair,
        "n_valid": int(valid.sum()),
    }


def evaluate(params, config, loader, alpha=0.1, verbose=True):
    """Run PCK over a loader of PFPascalDataset batches.

    Returns ``{'pck': mean, 'per_pair': [...], 'n_valid': int}``.
    """
    step = make_pck_step(config, alpha)
    m_pairs = default_registry().counter(
        "eval_pairs_total", "image pairs evaluated"
    )
    per_pair = []
    for i, batch in enumerate(loader):
        # one span per dispatched batch; np.asarray is the D2H sync, so
        # the span covers real device execution, not just dispatch
        with trace.span("eval/pck_batch"):
            jbatch = {
                k: jnp.asarray(v)
                for k, v in batch.items()
                if k in PCK_BATCH_KEYS
            }
            scores = np.asarray(step(params, jbatch))
        per_pair.extend(scores.tolist())
        m_pairs.inc(len(scores))
        if verbose:
            print(f"batch [{i + 1}/{len(loader)}]", flush=True)
    return _summarize(per_pair)


def evaluate_serving(
    params,
    config,
    loader,
    alpha=0.1,
    max_batch=8,
    max_wait=0.002,
    verbose=True,
):
    """PCK through the serving engine (`ncnet_tpu.serve`): the loader's
    pairs are re-submitted as individual requests, dynamically coalesced
    into padded fixed-shape micro-batches, and executed from AOT-warmed
    programs with host/device overlap.

    Per-pair scores match `evaluate` — the step body is literally the
    same function (`pck_step_fn`) and padding is masked at readout —
    exactly (bitwise) when the served batch size equals the loader's,
    and to XLA batch-size-codegen ulps otherwise; so this path changes
    throughput only (measured in benchmarks/micro_serve.py and PERF.md
    round 10). Returns the `evaluate` schema plus a ``'serve'`` stats
    dict (`ServeEngine.report`).
    """
    from ncnet_tpu.serve.engine import ServeEngine, payload_spec

    step = pck_step_fn(config, alpha)

    def apply(p, batch):
        return {"pck": step(p, batch)}

    futures = []
    warmed = set()
    with ServeEngine(
        apply, params, max_batch=max_batch, max_wait=max_wait
    ) as engine:
        for i, batch in enumerate(loader):
            arrs = {k: np.asarray(batch[k]) for k in PCK_BATCH_KEYS}
            n = len(arrs["source_image"])
            for j in range(n):
                payload = {k: v[j] for k, v in arrs.items()}
                key = (
                    payload["source_image"].shape,
                    payload["target_image"].shape,
                )
                if key not in warmed:
                    # warm every padded batch size for a new bucket
                    # before any of its requests dispatch: live traffic
                    # then triggers zero compiles
                    engine.warmup([(key, payload_spec(payload))])
                    warmed.add(key)
                futures.append(engine.submit(key=key, payload=payload))
            if verbose:
                print(f"batch [{i + 1}/{len(loader)}] submitted", flush=True)
        per_pair = [float(np.asarray(f.result()["pck"])) for f in futures]
        out = _summarize(per_pair)
        out["serve"] = engine.report()
    return out


def pck_vs_topk(params, config, loader, ks, alpha=0.1, verbose=False):
    """PF-Pascal PCK across sparse band widths (ncnet_tpu.sparse).

    Evaluates the SAME loader at every ``nc_topk`` in ``ks`` (0 = dense;
    the readout path is `corr_to_matches` on the densified band either
    way — see models/immatchnet.match_pipeline). Returns ``{k: result
    dict}`` in the `evaluate` schema; with ``k >= hB*wB`` the result must
    match the dense one, which anchors the accuracy/compute trade-off
    curve the sweep exists to measure.
    """
    batches = list(loader)
    return {
        int(k): evaluate(
            params, config.replace(nc_topk=int(k)), batches,
            alpha=alpha, verbose=verbose,
        )
        for k in ks
    }
