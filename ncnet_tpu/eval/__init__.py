"""Evaluation harnesses: PF-Pascal PCK and the InLoc match dump."""

from ncnet_tpu.eval import pf_pascal

__all__ = ["pf_pascal"]
