"""InLoc localization stage: PnP-RANSAC pose estimation from dumped matches.

Python port of the reference's MATLAB L6 pipeline (SURVEY.md §2.4) so the
whole benchmark runs without MATLAB:

  * per-(query, pano) pose estimation — `pnp_localize_pair` mirrors
    lib_matlab/parfor_NC4D_PE_pnponly.m: threshold matches by score > 0.75,
    scale normalized coords to pixels (floor + zero-fix for the DB side,
    :44-49), back-project DB pixels to 3D via the RGBD cutout ``XYZcut``
    (:57-61), apply the scan alignment transform, drop NaNs, then P3P
    LO-RANSAC with an angular inlier threshold (0.2 deg, :77);
  * `p3p_grunert` — the minimal 3-point absolute-pose solver (Grunert's
    quartic, as surveyed by Haralick et al.), replacing the external
    ``ht_lo_ransac_p3p`` dependency;
  * `pose_distance` — lib_matlab/p2dist.m: camera-center L2 +
    rotation-geodesic angle (p2c.m for the center);
  * `localization_rate_curve` — lib_matlab/ht_plotcurve_WUSTL.m:76-93: %
    of queries with position error under a sweep of thresholds (0..2 m),
    orientation error gated at 10 degrees.

This module covers the "DensePE + NCNet" (PnP-only) curve. The dense
pose-verification re-ranking stage (parfor_nc4d_PV.m: render synthetic
views from the scan, DSIFT similarity) is ported separately in
`ncnet_tpu/eval/pose_verify.py` (z-buffer splat renderer + dense RootSIFT
standing in for vl_phow), wired up via `scripts/localize_inloc.py
--densePV`.

Pure numpy — this is a host-side geometric solver, not an accelerator
workload (the reference runs it on CPU via MATLAB parfor; parallelize over
queries with multiprocessing if needed).
"""

import numpy as np


# ----------------------------------------------------------- minimal solvers


def p3p_grunert(rays, points):
    """Absolute pose from 3 ray/point correspondences (Grunert 1841).

    Args:
      rays: ``[3, 3]`` bearing vectors in the camera frame (rows; need not
        be normalized).
      points: ``[3, 3]`` corresponding world points (rows).

    Returns:
      List of ``[3, 4]`` poses ``P = [R | t]`` with ``x_cam = R x_world + t``
      (up to 4 real solutions; empty on degeneracy).
    """
    f = rays / np.linalg.norm(rays, axis=1, keepdims=True)
    X1, X2, X3 = points
    a = np.linalg.norm(X2 - X3)  # side opposite point 1
    b = np.linalg.norm(X1 - X3)
    c = np.linalg.norm(X1 - X2)
    if min(a, b, c) < 1e-12:
        return []
    cos_a = float(f[1] @ f[2])
    cos_b = float(f[0] @ f[2])
    cos_g = float(f[0] @ f[1])

    a2, b2, c2 = a * a, b * b, c * c
    # Grunert's quartic in v = s3/s1 (Haralick et al., RPP survey, eq. set)
    q = (a2 - c2) / b2
    A4 = (q - 1.0) ** 2 - 4.0 * (c2 / b2) * cos_a**2
    A3 = 4.0 * (
        q * (1.0 - q) * cos_b
        - (1.0 - (a2 + c2) / b2) * cos_a * cos_g
        + 2.0 * (c2 / b2) * cos_a**2 * cos_b
    )
    A2 = 2.0 * (
        q**2
        - 1.0
        + 2.0 * q**2 * cos_b**2
        + 2.0 * ((b2 - c2) / b2) * cos_a**2
        - 4.0 * ((a2 + c2) / b2) * cos_a * cos_b * cos_g
        + 2.0 * ((b2 - a2) / b2) * cos_g**2
    )
    A1 = 4.0 * (
        -q * (1.0 + q) * cos_b
        + 2.0 * (a2 / b2) * cos_g**2 * cos_b
        - (1.0 - (a2 + c2) / b2) * cos_a * cos_g
    )
    A0 = (1.0 + q) ** 2 - 4.0 * (a2 / b2) * cos_g**2

    coeffs = np.array([A4, A3, A2, A1, A0])
    if not np.all(np.isfinite(coeffs)) or abs(A4) < 1e-14:
        return []
    roots = np.roots(coeffs)
    poses = []
    for v in roots:
        if abs(v.imag) > 1e-8 or v.real <= 0:
            continue
        v = float(v.real)
        denom = 2.0 * (cos_g - v * cos_a)
        if abs(denom) < 1e-12:
            continue
        u = ((q - 1.0) * v * v - 2.0 * q * cos_b * v + 1.0 + q) / denom
        if u <= 0:
            continue
        s1sq = b2 / (1.0 + v * v - 2.0 * v * cos_b)
        if s1sq <= 0:
            continue
        s1 = float(np.sqrt(s1sq))
        s2, s3 = u * s1, v * s1
        cam_pts = np.stack([s1 * f[0], s2 * f[1], s3 * f[2]])
        P = _absolute_orientation(points, cam_pts)
        if P is not None:
            poses.append(P)
    return poses


def _absolute_orientation(world_pts, cam_pts):
    """Rigid transform ``x_cam = R x_world + t`` (Kabsch, no scale)."""
    cw = world_pts.mean(axis=0)
    cc = cam_pts.mean(axis=0)
    H = (world_pts - cw).T @ (cam_pts - cc)
    U, _, Vt = np.linalg.svd(H)
    d = np.sign(np.linalg.det(Vt.T @ U.T))
    R = Vt.T @ np.diag([1.0, 1.0, d]) @ U.T
    if not np.all(np.isfinite(R)):
        return None
    t = cc - R @ cw
    return np.concatenate([R, t[:, None]], axis=1)


def dlt_pnp(rays, points):
    """Direct linear transform PnP (>= 6 points) for the LO refit.

    Solves for P=[R|t] minimizing the algebraic cross-product error
    ``ray x (R X + t) = 0``, then projects onto SO(3).
    """
    n = len(points)
    if n < 6:
        return None
    f = rays / np.linalg.norm(rays, axis=1, keepdims=True)
    Xh = np.concatenate([points, np.ones((n, 1))], axis=1)  # [n, 4]
    A = np.zeros((2 * n, 12))
    # two independent rows of [f]_x * [X' 0 0; 0 X' 0; 0 0 X'] P_vec
    A[0::2, 0:4] = -f[:, 2:3] * Xh
    A[0::2, 8:12] = f[:, 0:1] * Xh
    A[1::2, 4:8] = -f[:, 2:3] * Xh
    A[1::2, 8:12] = f[:, 1:2] * Xh
    # the LO refit can see thousands of inliers: the null vector via eigh
    # of the 12x12 normal matrix costs O(n) instead of an O(n^2) full SVD
    _, evec = np.linalg.eigh(A.T @ A)
    P = evec[:, 0].reshape(3, 4)
    # The SVD null vector's sign is arbitrary; resolve it BEFORE the SO(3)
    # projection (the closest rotation to -sigma*R is unrelated to R — a
    # wrong pose in ~half of solves if skipped).
    if np.linalg.det(P[:, :3]) < 0:
        P = -P
    U, s, Vt2 = np.linalg.svd(P[:, :3])
    R = U @ Vt2  # det is +1 since det(P[:, :3]) > 0
    scale = s.mean()
    if scale < 1e-12:
        return None
    t = P[:, 3] / scale
    # cheirality: points must be in front of the camera; a violation means
    # the algebraic solution is a mirror configuration — reject it
    Xc = (R @ points.T + t[:, None]).T
    if np.median(np.sum(Xc * f, axis=1)) < 0:
        return None
    return np.concatenate([R, t[:, None]], axis=1)


def _angular_inliers(P, unit_rays, points, cos_thr):
    """``unit_rays`` must be pre-normalized (hot loop: called per RANSAC
    hypothesis; normalize once in the caller)."""
    Xc = (P[:, :3] @ points.T + P[:, 3:4]).T
    norms = np.linalg.norm(Xc, axis=1)
    ok = norms > 1e-12
    cosang = np.zeros(len(points))
    cosang[ok] = np.sum(unit_rays[ok] * Xc[ok], axis=1) / norms[ok]
    return cosang > cos_thr


def _p3p_grunert_batch(f, X):
    """Vectorized `p3p_grunert` over ``B`` sampled triplets.

    Args:
      f: ``[B, 3, 3]`` UNIT bearing triplets (rows).
      X: ``[B, 3, 3]`` world-point triplets (rows).

    Returns:
      ``(poses [M, 3, 4], owner [M])`` — all real admissible solutions
      across the batch, with ``owner[m]`` the triplet index each pose came
      from. Same math as the scalar path, batched: the quartic is solved
      for the whole batch at once via companion-matrix eigenvalues
      (np.roots is exactly this for one polynomial), and the final rigid
      fits run through one batched SVD.
    """
    B = len(f)
    a = np.linalg.norm(X[:, 1] - X[:, 2], axis=1)
    b = np.linalg.norm(X[:, 0] - X[:, 2], axis=1)
    c = np.linalg.norm(X[:, 0] - X[:, 1], axis=1)
    cos_a = np.einsum("bi,bi->b", f[:, 1], f[:, 2])
    cos_b = np.einsum("bi,bi->b", f[:, 0], f[:, 2])
    cos_g = np.einsum("bi,bi->b", f[:, 0], f[:, 1])

    with np.errstate(divide="ignore", invalid="ignore"):
        a2, b2, c2 = a * a, b * b, c * c
        q = (a2 - c2) / b2
        A4 = (q - 1.0) ** 2 - 4.0 * (c2 / b2) * cos_a**2
        A3 = 4.0 * (
            q * (1.0 - q) * cos_b
            - (1.0 - (a2 + c2) / b2) * cos_a * cos_g
            + 2.0 * (c2 / b2) * cos_a**2 * cos_b
        )
        A2 = 2.0 * (
            q**2
            - 1.0
            + 2.0 * q**2 * cos_b**2
            + 2.0 * ((b2 - c2) / b2) * cos_a**2
            - 4.0 * ((a2 + c2) / b2) * cos_a * cos_b * cos_g
            + 2.0 * ((b2 - a2) / b2) * cos_g**2
        )
        A1 = 4.0 * (
            -q * (1.0 + q) * cos_b
            + 2.0 * (a2 / b2) * cos_g**2 * cos_b
            - (1.0 - (a2 + c2) / b2) * cos_a * cos_g
        )
        A0 = (1.0 + q) ** 2 - 4.0 * (a2 / b2) * cos_g**2

    coeffs = np.stack([A4, A3, A2, A1, A0], axis=1)
    good = (
        (np.minimum(np.minimum(a, b), c) > 1e-12)
        & np.all(np.isfinite(coeffs), axis=1)
        & (np.abs(A4) > 1e-14)
    )
    if not np.any(good):
        return np.zeros((0, 3, 4)), np.zeros(0, int)
    idx = np.nonzero(good)[0]
    cf = coeffs[idx]
    # batched np.roots: monic companion matrices, one eig call
    mono = cf[:, 1:] / cf[:, :1]
    comp = np.zeros((len(idx), 4, 4))
    comp[:, 1, 0] = comp[:, 2, 1] = comp[:, 3, 2] = 1.0
    comp[:, 0, :] = -mono
    roots = np.linalg.eigvals(comp)  # [G, 4] complex

    G = len(idx)
    v = roots.real  # [G, 4]
    real_pos = (np.abs(roots.imag) <= 1e-8) & (v > 0)
    cos_ab = cos_a[idx][:, None]
    cos_bb = cos_b[idx][:, None]
    cos_gb = cos_g[idx][:, None]
    qb = q[idx][:, None]
    b2b = b2[idx][:, None]
    with np.errstate(divide="ignore", invalid="ignore"):
        denom = 2.0 * (cos_gb - v * cos_ab)
        u = ((qb - 1.0) * v * v - 2.0 * qb * cos_bb * v + 1.0 + qb) / denom
        s1sq = b2b / (1.0 + v * v - 2.0 * v * cos_bb)
    ok = (
        real_pos
        & (np.abs(denom) > 1e-12)
        & (u > 0)
        & (s1sq > 0)
        & np.isfinite(u)
        & np.isfinite(s1sq)
    )
    gi, ri = np.nonzero(ok)
    if len(gi) == 0:
        return np.zeros((0, 3, 4)), np.zeros(0, int)
    owner = idx[gi]
    s1 = np.sqrt(s1sq[gi, ri])
    s2 = u[gi, ri] * s1
    s3 = v[gi, ri] * s1
    cam = np.stack(
        [
            s1[:, None] * f[owner, 0],
            s2[:, None] * f[owner, 1],
            s3[:, None] * f[owner, 2],
        ],
        axis=1,
    )  # [M, 3, 3]
    P = _absolute_orientation_batch(X[owner], cam)
    keep = np.all(np.isfinite(P.reshape(len(P), -1)), axis=1)
    return P[keep], owner[keep]


def _absolute_orientation_batch(world_pts, cam_pts):
    """Batched Kabsch: ``[M, 3, 3]`` point triplets -> ``[M, 3, 4]`` poses."""
    cw = world_pts.mean(axis=1, keepdims=True)
    cc = cam_pts.mean(axis=1, keepdims=True)
    H = np.einsum("mki,mkj->mij", world_pts - cw, cam_pts - cc)
    U, _, Vt = np.linalg.svd(H)
    d = np.sign(np.linalg.det(np.einsum("mji,mkj->mik", Vt, U)))
    Vt_adj = Vt.copy()
    Vt_adj[:, 2, :] *= d[:, None]
    R = np.einsum("mji,mkj->mik", Vt_adj, U)
    t = cc[:, 0] - np.einsum("mij,mj->mi", R, cw[:, 0])
    return np.concatenate([R, t[:, :, None]], axis=2)


def _count_inliers_batch(P, unit_rays, points, cos_thr):
    """Inlier counts for ``[M, 3, 4]`` poses at once: the RANSAC scoring
    loop as one batched BLAS matmul instead of M small matmuls (einsum
    measured 6x slower here — it doesn't dispatch to BLAS)."""
    Xc = np.matmul(points, P[:, :, :3].transpose(0, 2, 1))
    Xc += P[:, None, :, 3]  # [M, n, 3]
    dots = (Xc * unit_rays).sum(axis=2)
    sq = (Xc * Xc).sum(axis=2)
    if cos_thr > 0:
        # cos > thr  <=>  dot > thr * ||Xc||: sign-safe both sides at
        # tight angular thresholds, avoids the divide + sqrt
        return (
            (dots > 0) & (dots * dots > cos_thr * cos_thr * sq)
        ).sum(axis=1)
    # wide thresholds (>= 90 deg, reachable via pnp_thr_deg): exact path
    with np.errstate(divide="ignore", invalid="ignore"):
        cosang = dots / np.sqrt(sq)
    cosang[~np.isfinite(cosang)] = -1.0
    return (cosang > cos_thr).sum(axis=1)


def lo_ransac_p3p(rays, points, thr_rad, max_iters=10000, seed=0,
                  confidence=0.999, chunk=128):
    """Locally-optimized RANSAC over P3P (the ``ht_lo_ransac_p3p`` role:
    parfor_NC4D_PE_pnponly.m:77).

    Hypotheses are generated and scored in vectorized chunks (round 5):
    one batched quartic solve + one einsum inlier count per ``chunk``
    samples instead of a Python loop per hypothesis — 30-40x faster at
    the reference's 10k-iteration budget (benchmarks/micro_localize.py).
    The adaptive stopping rule is applied between chunks, so at most
    ``chunk - 1`` extra hypotheses are drawn vs the serial schedule.
    Local optimization (DLT refit on inliers) runs on the chunk's best
    candidate only when it improves on the incumbent, like the serial
    version.

    Args:
      rays: ``[n, 3]`` camera-frame bearing vectors.
      points: ``[n, 3]`` world points.
      thr_rad: angular inlier threshold in radians (reference: 0.2 deg).
      max_iters: hypothesis cap (reference: 10000; adaptive early exit).

    Returns:
      ``(P, inliers)`` — best ``[3, 4]`` pose and boolean mask, or
      ``(None, zeros)`` if no model found.
    """
    n = len(points)
    empty = np.zeros(n, bool)
    if n < 3:
        return None, empty
    rng = np.random.RandomState(seed)
    cos_thr = np.cos(thr_rad)
    rays = rays / np.linalg.norm(rays, axis=1, keepdims=True)
    best_P, best_inl = None, empty
    it, needed = 0, max_iters

    def local_optimize(P0, inl0):
        # refit on inliers, re-collect, keep while improving
        best_P, best_inl = P0, inl0
        for _ in range(2):
            if best_inl.sum() < 6:
                break
            P_lo = dlt_pnp(rays[best_inl], points[best_inl])
            if P_lo is None:
                break
            inl_lo = _angular_inliers(P_lo, rays, points, cos_thr)
            if inl_lo.sum() >= best_inl.sum():
                best_P, best_inl = P_lo, inl_lo
            else:
                break
        return best_P, best_inl

    # candidate pre-scoring runs on a subsample when the tentative set is
    # large (counts only rank candidates within a chunk; the winner is
    # re-scored exactly before it can displace the incumbent)
    if n > 4000:
        sub = rng.permutation(n)[:2000]
        score_rays, score_pts = rays[sub], points[sub]
    else:
        score_rays, score_pts = rays, points
    scale = n / len(score_pts)

    while it < min(max_iters, needed):
        m = min(chunk, min(max_iters, needed) - it)
        it += m
        # m index-triplets; duplicate-containing rows are resampled (the
        # collision probability is ~3/n, so this loop runs ~once)
        sel = rng.randint(0, n, (m, 3))
        while True:
            dup = (
                (sel[:, 0] == sel[:, 1])
                | (sel[:, 0] == sel[:, 2])
                | (sel[:, 1] == sel[:, 2])
            )
            if not dup.any():
                break
            sel[dup] = rng.randint(0, n, (int(dup.sum()), 3))
        cand_P, _ = _p3p_grunert_batch(rays[sel], points[sel])
        if len(cand_P) == 0:
            continue
        counts = _count_inliers_batch(cand_P, score_rays, score_pts, cos_thr)
        bi = int(np.argmax(counts))
        if counts[bi] * scale > best_inl.sum() * 0.5:
            # promising: exact count, then the serial acceptance test
            inl = _angular_inliers(cand_P[bi], rays, points, cos_thr)
            if inl.sum() > best_inl.sum():
                best_P, best_inl = local_optimize(cand_P[bi], inl)
                w = best_inl.sum() / n
                if w > 0:
                    denom = np.log(max(1.0 - w**3, 1e-12))
                    needed = int(np.ceil(np.log(1 - confidence) / denom))
    return best_P, best_inl


# ------------------------------------------------- per-pair pose estimation


def pnp_localize_pair(
    matches,
    query_size,
    db_size,
    xyz_cut,
    focal_length,
    alignment=None,
    score_thr=0.75,
    pnp_thr_deg=0.2,
    n_subsample=None,
    max_iters=10000,
    seed=0,
    solve=True,
):
    """Pose of a query camera from dense matches against one RGBD cutout.

    Mirrors parfor_NC4D_PE_pnponly.m end to end. Args:

      matches: ``[N, 5]`` rows ``(xA, yA, xB, yB, score)`` in normalized
        [0, 1] coords (the .mat dump contract; A = query, B = DB cutout).
      query_size: (h, w) of the query image.
      db_size: (h, w) of the cutout (``XYZcut`` grid).
      xyz_cut: ``[h, w, 3]`` per-pixel 3D points (NaN where invalid).
      focal_length: query focal length in pixels (params.data.q.fl).
      alignment: optional ``[3, 4]`` or ``[4, 4]`` scan-to-global transform
        (``P_after`` of load_WUSTL_transformation); identity if None.
      score_thr: reference ``params.ncnet.thr`` = 0.75.
      pnp_thr_deg: reference ``params.ncnet.pnp_thr`` = 0.2 deg.
      n_subsample: optional cap on tentatives (params.ncnet.N_subsample).
      solve: when False, stop after tentative building (``P`` is None) —
        lets a batched back-end (ncnet_tpu.localize) consume the
        tentatives while sharing this exact preprocessing.

    Returns:
      dict with ``P`` ([3,4] or None), ``inliers``, ``tentatives_2d``
      ([4, n]: query px; db px), ``tentatives_3d`` ([6, n]: ray; 3D).
    """
    m = np.asarray(matches, np.float64)
    m = m[m[:, 4] > score_thr]
    if n_subsample is not None and len(m) > n_subsample:
        sel = np.random.RandomState(seed).permutation(len(m))[:n_subsample]
        m = m[sel]
    qh, qw = query_size
    dh, dw = db_size

    # feature upsampling (:44-49): query scales continuously; DB floors to
    # integer pixels with 0 -> 1 (MATLAB 1-indexed)
    xq = m[:, 0] * qw
    yq = m[:, 1] * qh
    xdb = np.floor(m[:, 2] * dw)
    ydb = np.floor(m[:, 3] * dh)
    xdb[xdb == 0] = 1
    ydb[ydb == 0] = 1

    # query rays through Kq^-1 (:52-55)
    rays = np.stack(
        [
            (xq - qw / 2.0) / focal_length,
            (yq - qh / 2.0) / focal_length,
            np.ones_like(xq),
        ],
        axis=1,
    )

    # DB 3D points from the cutout (1-indexed pixel -> 0-indexed array)
    xyz = np.asarray(xyz_cut, np.float64)
    pts3d = xyz[
        np.clip(ydb.astype(int) - 1, 0, dh - 1),
        np.clip(xdb.astype(int) - 1, 0, dw - 1),
    ]
    if alignment is not None:
        A = np.asarray(alignment, np.float64)
        pts3d = pts3d @ A[:3, :3].T + A[:3, 3]

    valid = np.all(np.isfinite(pts3d), axis=1)
    rays, pts3d = rays[valid], pts3d[valid]
    xq, yq, xdb, ydb = xq[valid], yq[valid], xdb[valid], ydb[valid]

    out = {
        "tentatives_2d": np.stack([xq, yq, xdb, ydb]),
        "tentatives_3d": np.concatenate([rays.T, pts3d.T]),
    }
    if len(pts3d) < 3 or not solve:
        out["P"], out["inliers"] = None, np.zeros(len(pts3d), bool)
        return out
    P, inl = lo_ransac_p3p(
        rays, pts3d, np.deg2rad(pnp_thr_deg), max_iters=max_iters, seed=seed
    )
    out["P"], out["inliers"] = P, inl
    return out


# ----------------------------------------------------------- metric + curve


def camera_center(P):
    """``p2c.m``: C = -R' t."""
    P = np.asarray(P, np.float64)
    return -P[:3, :3].T @ P[:3, 3]


def pose_distance(P1, P2):
    """``p2dist.m``: (center L2 distance, rotation geodesic angle rad)."""
    d_pos = float(np.linalg.norm(camera_center(P1) - camera_center(P2)))
    R = np.linalg.solve(np.asarray(P1, np.float64)[:3, :3],
                        np.asarray(P2, np.float64)[:3, :3])
    c = (np.trace(R) - 1.0) / 2.0
    d_ori = float(np.arccos(np.clip(c, -1.0, 1.0)))
    return d_pos, d_ori


def localization_rate_curve(pos_err, ori_err_rad, max_ori_deg=10.0):
    """``ht_plotcurve_WUSTL.m:76-93``: localized-% vs distance threshold.

    Returns ``(thresholds_m, rate_percent)`` with the reference's
    threshold grid (0:0.0625:1 then 1.125:0.125:2) and the 10-degree
    orientation gate.
    """
    pos = np.asarray(pos_err, np.float64).copy()
    ori = np.rad2deg(np.asarray(ori_err_rad, np.float64))
    pos[ori > max_ori_deg] = np.inf
    thr = np.concatenate(
        [np.arange(0.0, 1.0 + 1e-9, 0.0625), np.arange(1.125, 2.0 + 1e-9, 0.125)]
    )
    rate = (pos[:, None] < thr[None, :]).mean(axis=0) * 100.0
    return thr, rate
