"""Gather-only re-scoring of surviving band neighbourhoods at high res.

The coarse pass (the PR-4 sparse band on pooled features) leaves
``values/indices [b, hA, wA, K]``: per coarse A-cell, the K consensus-
filtered B-candidates. Refinement re-reads ONLY those neighbourhoods
against the high-res features — same no-scatter discipline as the band
itself: a jit-static ``[b, hA, wA, K, win]`` window pointer table
(``win = (r * (2*radius + 1))^2``; radius 0 gives the ``[.., K, r^2]``
block directly under each candidate), off-grid slots resolved to an
appended all-zero null row, every gather ``mode="promise_in_bounds"``,
and ONE rescore contraction
``[b, hA, wA, r^2, c] x [b, hA, wA, K, win, c]`` feeding the MXU.

Each fine A-subcell keeps its coarse candidate's consensus score and
relocates it to the best window cell, modulated by that cell's softmax
weight over the window — so a window with one dominant fine cell keeps
(nearly) the full consensus score there, while a flat window spreads
confidence thin. The modulation is built to DEGENERATE EXACTLY: a
single-entry window (equal resolutions, radius 0) has softmax weight
exactly 1.0, and ``v * 1.0 == v`` bitwise, which is the reduction-to-
the-band contract tests/test_refine.py pins.
"""

import jax
import jax.numpy as jnp


def refine_window_indices(indices, grid_b_lo, grid_b_hi, factor, radius=0):
    """Fine-grid window pointers for each surviving coarse candidate.

    Args:
      indices: ``[b, hA, wA, K]`` int32 flat coarse-B indices.
      grid_b_lo: coarse B grid ``(hB_lo, wB_lo)``.
      grid_b_hi: fine B grid ``(hB_hi, wB_hi)`` (``grid_b_lo * factor``).
      factor: resolution ratio r (>= 1).
      radius: extra window reach in COARSE cells around the candidate.

    Returns ``(widx, valid)``: ``widx [b, hA, wA, K, win]`` int32 flat
    fine-B indices with off-grid slots set to the null index
    ``hB_hi * wB_hi`` (the caller's zero-row gather makes them exact
    zeros), and ``valid`` the matching bool mask. ``win`` is jit-static:
    ``(factor * (2*radius + 1))^2``.
    """
    h_lo, w_lo = int(grid_b_lo[0]), int(grid_b_lo[1])
    h_hi, w_hi = int(grid_b_hi[0]), int(grid_b_hi[1])
    r = int(factor)
    if (h_lo * r, w_lo * r) != (h_hi, w_hi):
        raise ValueError(
            f"fine grid {h_hi}x{w_hi} is not the coarse grid "
            f"{h_lo}x{w_lo} times the factor {r}"
        )
    side = r * (2 * int(radius) + 1)
    pi = indices // w_lo  # [b, hA, wA, K] coarse B row/col
    pj = indices % w_lo
    off = jnp.arange(side, dtype=jnp.int32) - jnp.int32(int(radius) * r)
    fi = pi[..., None] * r + off  # [b, hA, wA, K, side]
    fj = pj[..., None] * r + off
    valid = (
        ((fi >= 0) & (fi < h_hi))[..., :, None]
        & ((fj >= 0) & (fj < w_hi))[..., None, :]
    )
    flat = fi[..., :, None] * w_hi + fj[..., None, :]
    widx = jnp.where(valid, flat, h_hi * w_hi).astype(jnp.int32)
    b, ha, wa, k = indices.shape
    return (
        widx.reshape(b, ha, wa, k, side * side),
        valid.reshape(b, ha, wa, k, side * side),
    )


def refine_rescore(values, indices, grid_b_lo, feat_a_hi, feat_b_hi,
                   factor, radius=0):
    """Coarse band + high-res features -> fine-grid refined band.

    Args:
      values, indices: ``[b, hA_lo, wA_lo, K]`` the filtered coarse band
        (``sparse.pipeline.sparse_match_pipeline`` output).
      grid_b_lo: the coarse B grid the indices address.
      feat_a_hi, feat_b_hi: ``[b, h*r, w*r, c]`` high-res features.
      factor, radius: window geometry (see `refine_window_indices`).

    Returns ``(values_f, indices_f, grid_b_hi)``: a ``[b, hA_hi, wA_hi,
    K]`` band on the FINE grids — the same dense-regular representation
    the sparse readout consumes (``sparse_corr_to_dense`` ->
    ``corr_to_matches``), so every downstream consumer is unchanged.
    """
    b, ha_lo, wa_lo, k = values.shape
    _, ha_hi, wa_hi, c = feat_a_hi.shape
    _, hb_hi, wb_hi, _ = feat_b_hi.shape
    r = int(factor)
    if (ha_lo * r, wa_lo * r) != (ha_hi, wa_hi):
        raise ValueError(
            f"fine A grid {ha_hi}x{wa_hi} is not the coarse band grid "
            f"{ha_lo}x{wa_lo} times the factor {r}"
        )
    widx, valid = refine_window_indices(
        indices, grid_b_lo, (hb_hi, wb_hi), r, radius
    )
    win = widx.shape[-1]

    # window features via the band-gather discipline (ops/band.py): an
    # appended all-zero row makes every null pointer read exact zeros,
    # and the pointer table is in-bounds BY CONSTRUCTION, so the gather
    # promises rather than clamps
    fb_pad = jnp.concatenate(
        [
            feat_b_hi.reshape(b, hb_hi * wb_hi, c),
            jnp.zeros((b, 1, c), feat_b_hi.dtype),
        ],
        axis=1,
    )
    fb_win = jnp.take_along_axis(
        fb_pad,
        widx.reshape(b, ha_lo * wa_lo * k * win)[..., None],
        axis=1,
        mode="promise_in_bounds",
    ).reshape(b, ha_lo, wa_lo, k, win, c)

    # the r^2 fine A-subcells under each coarse A-cell: pure relabeling
    fa = (
        feat_a_hi.reshape(b, ha_lo, r, wa_lo, r, c)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(b, ha_lo, wa_lo, r * r, c)
    )

    # THE rescore contraction — the only counted FLOPs of refinement
    # (ops.accounting.refine_rescore_flops): 2 * nA_hi * K * win * c
    s = jnp.einsum(
        "bhwac,bhwkec->bhwake", fa, fb_win,
        preferred_element_type=fa.dtype,
    )  # [b, hA_lo, wA_lo, r^2, K, win]
    s = jnp.where(
        valid[:, :, :, None, :, :], s, jnp.asarray(-jnp.inf, s.dtype)
    )
    # per-(subcell, candidate) softmax over the window: a single-entry
    # window gives exactly 1.0 (exp(0)/exp(0)) — the bitwise anchor
    gain = jax.nn.softmax(s, axis=-1)
    best = jnp.argmax(s, axis=-1)  # [b, hA_lo, wA_lo, r^2, K]
    g = jnp.take_along_axis(
        gain, best[..., None], axis=-1, mode="promise_in_bounds"
    )[..., 0]
    idx_f = jnp.take_along_axis(
        jnp.broadcast_to(widx[:, :, :, None, :, :], s.shape),
        best[..., None],
        axis=-1,
        mode="promise_in_bounds",
    )[..., 0]
    vals_f = values[:, :, :, None, :] * g  # consensus score, modulated

    def to_fine(x):  # [b, hA_lo, wA_lo, r^2, K] -> [b, hA_hi, wA_hi, K]
        return (
            x.reshape(b, ha_lo, wa_lo, r, r, k)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(b, ha_hi, wa_hi, k)
        )

    return to_fine(vals_f), to_fine(idx_f), (hb_hi, wb_hi)
