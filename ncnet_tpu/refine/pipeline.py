"""End-to-end coarse-to-fine refinement pipeline (ROADMAP item [5]).

XRCN-style adaptive-cost correspondence on parts the repo already owns:

  high-res trunk features            (one backbone pass — features/)
    -> r x r average pool + re-norm  (refine/pool.py, zero contractions)
    -> sparse band at small K        (the PR-4 coarse pass, sparse/)
    -> gather-only window re-score   (refine/rescore.py, one contraction)
    -> fine-grid band readout        (the UNCHANGED sparse consumers)

Everything is jit-static: the band width K, the window ``(r*(2*radius+
1))^2``, and both grids are config/shape constants, so a refined program
AOT-compiles and serves from the same warmed-bucket machinery as the
dense and band programs (serve/engine.py's quality ladder).

With ``refine_factor == 1`` and ``refine_radius == 0`` the pool is an
identity and every window holds exactly its own candidate, so the
refined band equals the coarse band BITWISE — chained with the band's
own ``K = hB*wB`` contract this reduces the whole ladder to the dense
pipeline, which is the exactness harness in tests/test_refine.py.
"""

from ncnet_tpu.refine.pool import pool_features
from ncnet_tpu.refine.rescore import refine_rescore
from ncnet_tpu.sparse.pipeline import resolve_corr_impl, sparse_match_pipeline


def check_refine_config(config):
    """Validate the refine settings before any tracing (the
    ``check_sparse_config`` discipline: a bad static config should fail
    at construction, not deep inside jit)."""
    resolve_corr_impl(config)  # the coarse tier inherits corr_impl
    factor = int(getattr(config, "refine_factor", 0))
    if factor < 0:
        raise ValueError(
            f"refine_factor={factor} is negative; use 0 to disable "
            "refinement or a positive pool factor"
        )
    if not factor:
        return
    if int(getattr(config, "refine_topk", 0)) <= 0:
        raise ValueError(
            f"refine_topk={getattr(config, 'refine_topk', 0)}: the "
            "coarse pass needs a positive band width"
        )
    if int(getattr(config, "refine_radius", 0)) < 0:
        raise ValueError(
            f"refine_radius={getattr(config, 'refine_radius', 0)} is "
            "negative"
        )
    if config.relocalization_k_size > 1:
        raise ValueError(
            "refinement does not support relocalization configs: the 4D "
            "max-pool offsets are a dense-readout construct and the "
            "refined band already reads out at the fine grid (set "
            "relocalization_k_size to 0)"
        )


def refine_match_pipeline(nc_params, config, feat_a, feat_b):
    """High-res features -> refined fine-grid band.

    ``feat_a``/``feat_b`` are the FULL-resolution trunk features; the
    coarse tier is pooled here, in-program, so one trunk forward (or one
    feature-store read) serves both resolutions. Returns ``(values,
    indices, grid_b)`` on the fine grids — densify with
    ``sparse.pipeline.sparse_corr_to_dense`` for the readout consumers,
    or score directly with ``sparse.score.band_match_score_per_sample``
    (the weak-loss path, train/loss.py).
    """
    check_refine_config(config)
    factor = int(config.refine_factor)
    fa_lo = pool_features(feat_a, factor, normalize=config.normalize_features)
    fb_lo = pool_features(feat_b, factor, normalize=config.normalize_features)
    coarse = sparse_match_pipeline(
        nc_params,
        # the coarse tier IS the sparse band: same pipeline, band width
        # taken from refine_topk (nc_topk stays the standard tier's
        # knob). corr_impl rides along unchanged, so a 'stream' config
        # never materializes the coarse correlation volume either.
        config.replace(refine_factor=0, nc_topk=int(config.refine_topk)),
        fa_lo,
        fb_lo,
    )
    values, indices, grid_b_lo = coarse
    return refine_rescore(
        values, indices, grid_b_lo, feat_a, feat_b,
        factor, radius=int(getattr(config, "refine_radius", 0)),
    )
