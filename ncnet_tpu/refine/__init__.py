"""Multi-resolution coarse-to-fine correspondence (ROADMAP item [5]).

A coarse sparse-band pass on pooled features plus a gather-only,
static-shape re-scoring of the surviving neighbourhoods against the
high-res features — served as the quality tier ABOVE the standard and
degraded-band programs (scripts/serve.py ``--refine``), trained and
evaluated through the unchanged band/readout consumers.
"""

from ncnet_tpu.refine.pipeline import (
    check_refine_config,
    refine_match_pipeline,
)
from ncnet_tpu.refine.pool import pool_features
from ncnet_tpu.refine.rescore import refine_rescore, refine_window_indices

__all__ = [
    "check_refine_config",
    "pool_features",
    "refine_match_pipeline",
    "refine_rescore",
    "refine_window_indices",
]
