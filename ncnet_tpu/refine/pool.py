"""Multi-resolution feature pooling for the coarse-to-fine pipeline.

One trunk forward serves BOTH resolutions of the refinement ladder: the
high-res feature map is the trunk output, and the low-res map is its
``r x r`` average pool, re-L2-normalized so the coarse correlation sees
unit-norm descriptors exactly like the dense path does. Pooling is
elementwise/reduction work (zero contraction FLOPs — the analytic ledger
in ``ops/accounting.py`` counts nothing for it), so the coarse tier
costs one cheap reduce instead of a second backbone pass, and the two
tiers can never disagree about which trunk produced them.
"""

import jax.numpy as jnp

from ncnet_tpu.ops.norm import feature_l2norm


def pool_features(feats, factor, normalize=True):
    """``[b, h, w, c]`` features -> ``[b, h/r, w/r, c]`` pooled features.

    ``factor == 1`` returns the input UNCHANGED (a static Python branch):
    re-normalizing would divide by a computed ~1.0 norm and perturb the
    last bit, and the equal-resolution case is the refinement pipeline's
    bitwise exactness anchor (tests/test_refine.py), so identity must be
    identity. For ``factor > 1`` the grid must divide evenly — a partial
    edge cell would pool a different support than every interior cell and
    silently skew the coarse correlation.
    """
    r = int(factor)
    if r < 1:
        raise ValueError(f"pool factor must be >= 1, got {factor}")
    if r == 1:
        return feats
    b, h, w, c = feats.shape
    if h % r or w % r:
        raise ValueError(
            f"feature grid {h}x{w} does not divide by the refine factor "
            f"{r}; pick an image size whose feature grid is a multiple "
            "of the factor"
        )
    pooled = jnp.mean(
        feats.reshape(b, h // r, r, w // r, r, c), axis=(2, 4)
    )
    return feature_l2norm(pooled) if normalize else pooled
