"""DenseNet-201 feature trunk (to ``transition2``), NHWC, frozen eval BN.

Replicates the torchvision DenseNet-201 front that the reference truncates
with ``features.children()[:-4]`` — "up to transitionlayer2"
(lib/model.py:69-74): conv0/norm0/relu0/pool0, denseblock1 (6 layers),
transition1, denseblock2 (12 layers), transition2. Output is stride 16 with
256 channels. BatchNorm is always inference-mode affine (the reference
freezes the backbone, lib/model.py:75-78).

Parameter tree mirrors torchvision naming for mechanical conversion
(`ncnet_tpu.utils.convert_torch.convert_densenet201_trunk`):

  {'conv0': {'kernel'}, 'norm0': bn,
   'denseblock1': [{'norm1': bn, 'conv1': {'kernel'},
                    'norm2': bn, 'conv2': {'kernel'}}, ... x6],
   'transition1': {'norm': bn, 'conv': {'kernel'}},
   'denseblock2': [... x12],
   'transition2': {'norm': bn, 'conv': {'kernel'}}}
"""

import jax
import jax.numpy as jnp
from jax import lax

from ncnet_tpu.models.resnet import (
    _bn_apply,
    _bn_init,
    _conv,
    _max_pool_3x3_s2,
)

GROWTH_RATE = 32
BN_SIZE = 4
NUM_INIT_FEATURES = 64
# denseblock sizes up to the truncation point (DenseNet-201 = 6, 12, 48, 32)
TRUNK_BLOCKS = (6, 12)


def _conv_init(rng, kh, kw, cin, cout):
    # He-normal fan-in (torchvision's DenseNet kaiming_normal_ default).
    fan_in = kh * kw * cin
    std = (2.0 / fan_in) ** 0.5
    return jax.random.normal(rng, (kh, kw, cin, cout)) * std


def _init_dense_layer(rng, cin):
    k1, k2 = jax.random.split(rng)
    bottleneck = BN_SIZE * GROWTH_RATE
    return {
        "norm1": _bn_init(cin),
        "conv1": {"kernel": _conv_init(k1, 1, 1, cin, bottleneck)},
        "norm2": _bn_init(bottleneck),
        "conv2": {"kernel": _conv_init(k2, 3, 3, bottleneck, GROWTH_RATE)},
    }


def _apply_dense_layer(p, x):
    # torchvision _DenseLayer: BN -> ReLU -> 1x1 -> BN -> ReLU -> 3x3 (pad 1),
    # then the 32 new features are concatenated onto the running stack.
    out = jax.nn.relu(_bn_apply(p["norm1"], x))
    out = _conv(out, p["conv1"]["kernel"])
    out = jax.nn.relu(_bn_apply(p["norm2"], out))
    out = _conv(out, p["conv2"]["kernel"], padding=((1, 1), (1, 1)))
    return jnp.concatenate([x, out], axis=-1)


def _avg_pool_2x2_s2(x):
    summed = lax.reduce_window(
        x,
        0.0,
        lax.add,
        window_dimensions=(1, 2, 2, 1),
        window_strides=(1, 2, 2, 1),
        padding="VALID",
    )
    return summed * 0.25


def _apply_transition(p, x):
    # torchvision _Transition: BN -> ReLU -> 1x1 (halve channels) -> avgpool.
    out = jax.nn.relu(_bn_apply(p["norm"], x))
    out = _conv(out, p["conv"]["kernel"])
    return _avg_pool_2x2_s2(out)


def init_densenet201_trunk(rng):
    """Random (He) init; real use loads converted torchvision weights."""
    keys = jax.random.split(rng, 2 * len(TRUNK_BLOCKS) + 1)
    params = {
        "conv0": {"kernel": _conv_init(keys[0], 7, 7, 3, NUM_INIT_FEATURES)},
        "norm0": _bn_init(NUM_INIT_FEATURES),
    }
    cin = NUM_INIT_FEATURES
    for bi, n_layers in enumerate(TRUNK_BLOCKS):
        layer_keys = jax.random.split(keys[1 + 2 * bi], n_layers)
        block = []
        for li in range(n_layers):
            block.append(_init_dense_layer(layer_keys[li], cin))
            cin += GROWTH_RATE
        params[f"denseblock{bi + 1}"] = block
        cout = cin // 2
        params[f"transition{bi + 1}"] = {
            "norm": _bn_init(cin),
            "conv": {
                "kernel": _conv_init(keys[2 + 2 * bi], 1, 1, cin, cout)
            },
        }
        cin = cout
    return params


def densenet201_trunk_apply(params, x):
    """``[b, h, w, 3]`` normalized image -> ``[b, h/16, w/16, 256]``."""
    x = _conv(x, params["conv0"]["kernel"], stride=2, padding=((3, 3), (3, 3)))
    x = jax.nn.relu(_bn_apply(params["norm0"], x))
    x = _max_pool_3x3_s2(x)
    for bi in range(len(TRUNK_BLOCKS)):
        for layer in params[f"denseblock{bi + 1}"]:
            x = _apply_dense_layer(layer, x)
        x = _apply_transition(params[f"transition{bi + 1}"], x)
    return x
