"""Model zoo: feature backbones, neighbourhood consensus, full matchers.

All models are pure functions over explicit parameter pytrees (nested dicts
of jnp arrays): ``init_*(rng, ...) -> params`` and ``*_apply(params, x)``.
This keeps the frozen-backbone / trainable-head split, torch checkpoint
conversion, and sharding annotations trivial.
"""

from ncnet_tpu.models import feature_extraction, immatchnet, neigh_consensus, resnet, vgg
from ncnet_tpu.models.immatchnet import ImMatchNet, ImMatchNetConfig

__all__ = [
    "ImMatchNet",
    "ImMatchNetConfig",
    "feature_extraction",
    "immatchnet",
    "neigh_consensus",
    "resnet",
    "vgg",
]
