"""VGG-16 feature trunk (to ``pool4``), NHWC.

The reference's ``feature_extraction_cnn='vgg'`` variant truncates
torchvision VGG-16 at ``pool4`` (lib/model.py:24-35): stride-16 output with
512 channels, no BatchNorm. Parameter tree is a flat list of conv layers in
torchvision ``features`` order so conversion is index-based.
"""

import jax
import jax.numpy as jnp
from jax import lax

# torchvision vgg16.features layout up to pool4:
# (out_channels per conv; 'M' = 2x2/2 max-pool)
VGG16_TO_POOL4 = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M")


def init_vgg16_trunk(rng):
    params = []
    cin = 3
    convs = [c for c in VGG16_TO_POOL4 if c != "M"]
    keys = jax.random.split(rng, len(convs))
    ki = 0
    for c in VGG16_TO_POOL4:
        if c == "M":
            continue
        fan_in = 3 * 3 * cin
        bound = (1.0 / fan_in) ** 0.5
        k1, k2 = jax.random.split(keys[ki])
        params.append(
            {
                "kernel": jax.random.uniform(
                    k1, (3, 3, cin, c), minval=-bound, maxval=bound
                ),
                "bias": jax.random.uniform(k2, (c,), minval=-bound, maxval=bound),
            }
        )
        cin = c
        ki += 1
    return params


def vgg16_trunk_apply(params, x):
    """``[b, h, w, 3]`` -> ``[b, h/16, w/16, 512]`` (through pool4)."""
    li = 0
    for c in VGG16_TO_POOL4:
        if c == "M":
            x = lax.reduce_window(
                x,
                -jnp.inf,
                lax.max,
                window_dimensions=(1, 2, 2, 1),
                window_strides=(1, 2, 2, 1),
                padding="VALID",
            )
        else:
            p = params[li]
            x = lax.conv_general_dilated(
                x,
                p["kernel"],
                window_strides=(1, 1),
                padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            x = jax.nn.relu(x + p["bias"])
            li += 1
    return x
