"""Feature-extraction front end: backbone trunk + per-location L2 norm.

Reference ``FeatureExtraction`` (lib/model.py:19-87): a truncated pretrained
backbone, frozen by default, output L2-normalized. The broken
``resnet101fpn`` path (undefined ``fpn_body``, lib/model.py:46-67) is
intentionally not reproduced.
"""

import jax.numpy as jnp

from ncnet_tpu.models import densenet, patch, resnet, vgg
from ncnet_tpu.ops.norm import feature_l2norm

BACKBONES = {
    "resnet101": (resnet.init_resnet101_trunk, resnet.resnet101_trunk_apply, 16, 1024),
    "vgg": (vgg.init_vgg16_trunk, vgg.vgg16_trunk_apply, 16, 512),
    "densenet201": (
        densenet.init_densenet201_trunk,
        densenet.densenet201_trunk_apply,
        16,
        256,
    ),
    # framework extension (models/patch.py): pretrained-free DISCRIMINATIVE
    # trunk for the zero-egress synthetic proofs — a random-orthogonal
    # patch embed preserves patch inner products, which no randomly-
    # initialized deep trunk does
    "patch16": (patch.init_patch_trunk, patch.patch_trunk_apply, 16, 256),
}


def backbone_stride(name):
    return BACKBONES[name][2]


def backbone_channels(name):
    return BACKBONES[name][3]


def init_feature_extraction(rng, cnn="resnet101"):
    if cnn not in BACKBONES:
        raise ValueError(f"unknown backbone {cnn!r}; have {sorted(BACKBONES)}")
    return BACKBONES[cnn][0](rng)


def feature_extraction_apply(
    params, image, cnn="resnet101", normalize=True, dtype=None, center=False
):
    """``[b, h, w, 3]`` normalized image -> L2-normalized feature map.

    Args:
      dtype: optional compute dtype override (e.g. jnp.bfloat16) applied to
        the input and parameters — TPU-native replacement for the reference's
        fp16 eval mode (lib/model.py:253-258).
      center: subtract the per-image spatial mean before normalizing.
        Framework extension (off by default = reference semantics): ReLU
        features of a randomly-initialized trunk collapse into the positive
        orthant (measured pairwise cosines 0.62-1.0), which starves the
        correlation of contrast; centering restores it (mean ~0, peaks ~1).
        Used by the synthetic convergence proof, where no pretrained weights
        exist.
    """
    apply_fn = BACKBONES[cnn][1]
    if dtype is not None:
        import jax

        params = jax.tree.map(lambda p: p.astype(dtype), params)
        image = image.astype(dtype)
    feats = apply_fn(params, image)
    if center:
        feats = feats - jnp.mean(feats, axis=(1, 2), keepdims=True)
    if normalize:
        feats = feature_l2norm(feats, axis=-1)
    return feats
