"""ResNet-101 feature trunk (to ``layer3``), NHWC, frozen eval-mode BN.

Replicates the torchvision ResNet-101 architecture that the reference
truncates after ``layer3`` (lib/model.py:37-44): stride-16 output with 1024
channels. BatchNorm is always in inference mode (the reference freezes the
backbone and calls ``.eval()``, lib/model.py:75-78,251), so BN is computed as
a per-channel affine from stored running statistics.

Parameter tree mirrors torchvision naming so checkpoint conversion
(`ncnet_tpu.utils.convert_torch`) is a mechanical rename:

  {'conv1': {'kernel'}, 'bn1': {scale, offset, mean, var},
   'layer1': [block, ...], 'layer2': [...], 'layer3': [...]}

block = {'conv1': .., 'bn1': .., 'conv2': .., 'bn2': .., 'conv3': .., 'bn3': ..,
         'downsample_conv': .., 'downsample_bn': ..  (first block only)}
"""

import jax
import jax.numpy as jnp
from jax import lax

BN_EPS = 1e-5

# (n_blocks, planes, stride) per stage; trunk stops after layer3.
RESNET101_STAGES = ((3, 64, 1), (4, 128, 2), (23, 256, 2))
EXPANSION = 4


def _conv_init(rng, kh, kw, cin, cout):
    # He-normal fan-out (torchvision's ResNet conv init).
    fan_out = kh * kw * cout
    std = (2.0 / fan_out) ** 0.5
    return jax.random.normal(rng, (kh, kw, cin, cout)) * std


def _bn_init(c):
    return {
        "scale": jnp.ones((c,)),
        "offset": jnp.zeros((c,)),
        "mean": jnp.zeros((c,)),
        "var": jnp.ones((c,)),
    }


def _bn_apply(p, x):
    inv = p["scale"] * lax.rsqrt(p["var"] + BN_EPS)
    return x * inv + (p["offset"] - p["mean"] * inv)


def _conv(x, kernel, stride=1, padding="SAME"):
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _max_pool_3x3_s2(x):
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        window_dimensions=(1, 3, 3, 1),
        window_strides=(1, 2, 2, 1),
        padding=((0, 0), (1, 1), (1, 1), (0, 0)),
    )


def _init_bottleneck(rng, cin, planes, stride, downsample):
    keys = jax.random.split(rng, 4)
    cout = planes * EXPANSION
    p = {
        "conv1": {"kernel": _conv_init(keys[0], 1, 1, cin, planes)},
        "bn1": _bn_init(planes),
        "conv2": {"kernel": _conv_init(keys[1], 3, 3, planes, planes)},
        "bn2": _bn_init(planes),
        "conv3": {"kernel": _conv_init(keys[2], 1, 1, planes, cout)},
        "bn3": _bn_init(cout),
    }
    if downsample:
        p["downsample_conv"] = {"kernel": _conv_init(keys[3], 1, 1, cin, cout)}
        p["downsample_bn"] = _bn_init(cout)
    return p


def _apply_bottleneck(p, x, stride):
    # torchvision v1.5 bottleneck: the stride sits on the 3x3 conv2. Padding
    # is explicit (1, 1): XLA "SAME" at stride 2 pads (0, 1), which would
    # shift sample positions relative to torch's symmetric pad=1.
    out = jax.nn.relu(_bn_apply(p["bn1"], _conv(x, p["conv1"]["kernel"])))
    out = jax.nn.relu(
        _bn_apply(
            p["bn2"],
            _conv(out, p["conv2"]["kernel"], stride=stride, padding=((1, 1), (1, 1))),
        )
    )
    out = _bn_apply(p["bn3"], _conv(out, p["conv3"]["kernel"]))
    if "downsample_conv" in p:
        shortcut = _bn_apply(
            p["downsample_bn"], _conv(x, p["downsample_conv"]["kernel"], stride=stride)
        )
    else:
        shortcut = x
    return jax.nn.relu(out + shortcut)


def init_resnet101_trunk(rng):
    """Random (He) init; real use loads converted torchvision weights."""
    n_stage_keys = len(RESNET101_STAGES)
    keys = jax.random.split(rng, n_stage_keys + 1)
    params = {
        "conv1": {"kernel": _conv_init(keys[0], 7, 7, 3, 64)},
        "bn1": _bn_init(64),
    }
    cin = 64
    for si, (n_blocks, planes, stride) in enumerate(RESNET101_STAGES):
        block_keys = jax.random.split(keys[si + 1], n_blocks)
        blocks = []
        for bi in range(n_blocks):
            blocks.append(
                _init_bottleneck(
                    block_keys[bi],
                    cin,
                    planes,
                    stride if bi == 0 else 1,
                    downsample=(bi == 0),
                )
            )
            cin = planes * EXPANSION
        params[f"layer{si + 1}"] = blocks
    return params


def resnet101_trunk_apply(params, x):
    """``[b, h, w, 3]`` normalized image -> ``[b, h/16, w/16, 1024]``."""
    x = _conv(x, params["conv1"]["kernel"], stride=2, padding=((3, 3), (3, 3)))
    x = jax.nn.relu(_bn_apply(params["bn1"], x))
    x = _max_pool_3x3_s2(x)
    for si, (n_blocks, _, stride) in enumerate(RESNET101_STAGES):
        blocks = params[f"layer{si + 1}"]
        for bi in range(n_blocks):
            x = _apply_bottleneck(blocks[bi], x, stride if bi == 0 else 1)
    return x
