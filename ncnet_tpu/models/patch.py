"""Patch-embedding trunk: one 16x16/stride-16 random-orthogonal projection.

Framework extension with no reference counterpart (the reference's trunks
are pretrained torchvision CNNs, lib/model.py:19-87). Purpose: a
DISCRIMINATIVE feature extractor that needs no pretrained weights — a
random orthonormal-column projection Q of each 16x16 patch computes
<Q^T p1, Q^T p2> = p1^T QQ^T p2, the inner product of the patches'
rank-256 projections: for natural/noise patches (energy spread over the
768 dims) feature correlation tracks raw patch correlation closely, and
identical patches map to identical features exactly.
Randomly-initialized deep trunks measurably do NOT
have this property (their ReLU stacks contract inputs toward a shared
direction: pairwise feature cosines ~0.96 regardless of content — see
`feature_extraction_apply(center=...)` notes), which makes them useless
as matching front ends without pretrained weights; this trunk is what
makes the zero-egress synthetic end-to-end proofs
(scripts/synthetic_convergence.py, scripts/synthetic_inloc_e2e.py)
genuinely exercise correspondence learning instead of a degenerate
diagonal prior.

TPU-native: the patch embed is ONE stride-16 conv (a single MXU GEMM per
location) — the ViT patch-embedding idiom.
"""

import jax
import jax.numpy as jnp
from jax import lax

PATCH = 16
CHANNELS = 256


def init_patch_trunk(rng):
    """[16, 16, 3, 256] kernel with orthonormal COLUMNS (QR of a
    Gaussian): patch -> feature is inner-product-preserving on the
    256-dim subspace the columns span."""
    flat = jax.random.normal(rng, (PATCH * PATCH * 3, CHANNELS))
    q, _ = jnp.linalg.qr(flat)  # [768, 256], orthonormal columns
    return {"kernel": q.reshape(PATCH, PATCH, 3, CHANNELS)}


def patch_trunk_apply(params, image):
    """``[b, h, w, 3]`` -> ``[b, h/16, w/16, 256]`` non-overlapping
    patch projections. Mean-subtraction per patch is implicit in the
    downstream `feature_l2norm` path when enabled via
    ``center_features``; here the raw projection is returned."""
    dn = lax.conv_dimension_numbers(
        image.shape, params["kernel"].shape, ("NHWC", "HWIO", "NHWC")
    )
    return lax.conv_general_dilated(
        image,
        params["kernel"].astype(image.dtype),
        window_strides=(PATCH, PATCH),
        padding="VALID",
        dimension_numbers=dn,
        preferred_element_type=image.dtype,
    )
