"""ImMatchNet: the full dense-matching model.

Pipeline (reference lib/model.py:261-282):
  feature extraction (frozen trunk, L2 norm)  [x2: source, target]
  -> all-pairs 4D correlation
  -> [relocalization: 4D max-pool with argmax offsets — here FUSED with the
      correlation so the high-res tensor never hits HBM]
  -> soft mutual-NN filtering
  -> symmetric neighbourhood-consensus 4D convolutions
  -> soft mutual-NN filtering

The config is self-describing and travels with every checkpoint, mirroring
the reference's checkpoint-embedded args (lib/model.py:211-220): eval tools
never need architecture flags.
"""

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ncnet_tpu.analysis import sanitizer
from ncnet_tpu.models.feature_extraction import (
    backbone_channels,
    backbone_stride,
    feature_extraction_apply,
    init_feature_extraction,
)
from ncnet_tpu.models.neigh_consensus import init_neigh_consensus, neigh_consensus_apply
from ncnet_tpu.ops.correlation import correlation_4d, correlation_maxpool4d
from ncnet_tpu.ops.matching import mutual_matching


@dataclasses.dataclass(frozen=True)
class ImMatchNetConfig:
    """Architecture + numerics config (hashable, jit-static)."""

    feature_extraction_cnn: str = "resnet101"
    ncons_kernel_sizes: Tuple[int, ...] = (3, 3, 3)
    ncons_channels: Tuple[int, ...] = (10, 10, 1)
    normalize_features: bool = True
    symmetric_mode: bool = True
    relocalization_k_size: int = 0
    half_precision: bool = False  # bf16 feature/correlation path (TPU-native fp16)
    conv4d_impl: str = "xla"
    nc_remat: bool = False  # rematerialize each NC layer in the backward pass
    # Run the symmetric NC passes as one double-batch net application
    # (True, reference-equivalent math either way) or sequentially (False
    # — halves the stack's live batch for memory-heavy conv4d impls).
    symmetric_batch: bool = True
    # Run the correlation->NC->score pipeline over sample chunks of this
    # size in the training loss (0 = whole batch): bounds the live 4D
    # tensors to the chunk, enabling the wide-lane conv4d impls at batch 16.
    loss_chunk: int = 0
    # Rematerialize each loss chunk's pipeline in the backward pass. On by
    # default: without it, `lax.map` stacks every chunk's forward residuals
    # for the backward pass, so peak memory scales with the full batch
    # again (measured OOM at batch 16 / chunk 8 on a 16G v5e).
    loss_chunk_remat: bool = True
    # Subtract the per-image spatial feature mean before L2-norm (framework
    # extension, off = reference semantics; see feature_extraction_apply).
    center_features: bool = False
    # NC weight init: 'reference' (torch _ConvNd uniform) or 'identity'
    # (center-tap pass-through + small noise — the basin from which weak
    # training demonstrably improves matching; see init_neigh_consensus).
    nc_init: str = "reference"
    # Sparse-band neighbourhood consensus (ncnet_tpu.sparse,
    # arXiv:2004.10566): keep only the top-K B-candidates per A-cell and
    # run the NC stack with submanifold semantics on that band —
    # O(K/(hB*wB)) of the dense NC FLOPs. 0 = dense (reference
    # semantics); K >= hB*wB runs the complete band and must reproduce
    # the dense path exactly. Incompatible with relocalization configs.
    nc_topk: int = 0
    # Band selection: True picks by the symmetric rank min(rank-in-A-row,
    # rank-in-B-column) so the support is closed under the A/B swap up to
    # the per-cell capacity (better B-grid coverage for the inverse
    # readout direction); False is the plain per-A top-K.
    nc_topk_mutual: bool = True
    # Sparse band NC layer backend: 'xla' (gather + GEMM composite) or
    # 'pallas' (the fused gather+GEMM+bias+ReLU TPU kernel,
    # ncnet_tpu/kernels/band_gemm_pallas.py — bitwise-equal VJP included;
    # resolves back to 'xla' on non-TPU backends). Only consulted when
    # nc_topk > 0.
    band_impl: str = "xla"
    # Multi-resolution coarse-to-fine refinement (ncnet_tpu.refine,
    # XRCN-style): pool features by this factor, run the sparse band
    # (width refine_topk) at the coarse resolution, then re-score only
    # the surviving neighbourhoods against the high-res features inside
    # (2*refine_radius+1)-coarse-cell windows. 0 = off; takes precedence
    # over nc_topk when set (the coarse tier IS a band — nc_topk stays
    # the standard tier's knob). factor 1 + radius 0 reduces BITWISE to
    # the plain band at K = refine_topk (the exactness contract,
    # tests/test_refine.py). Incompatible with relocalization configs.
    refine_factor: int = 0
    refine_topk: int = 16
    refine_radius: int = 0
    # Correlation->band implementation: 'dense' (reference semantics —
    # materialize the full [b, hA, wA, hB, wB] volume, then select) or
    # 'stream' (ops/corr_stream.py: tile B's grid and fold each GEMM
    # slab into a running top-K + row/col-maxima merge under lax.scan —
    # BITWISE-equal band, peak memory O(hA*wA*(K+tile)) instead of
    # O(hA*wA*hB*wB)). Only consulted on the band paths (nc_topk > 0 or
    # refine_factor > 0); the dense-NC path consumes the full volume and
    # rejects 'stream'. Legacy config dicts default to 'dense'.
    corr_impl: str = "dense"
    # Static B-grid slab width of the streaming GEMM (clamped to hB*wB).
    # Larger tiles amortize the per-step merge over bigger MXU GEMMs;
    # 128 aligns with the TPU lane width. Only read when
    # corr_impl='stream'.
    corr_stream_tile: int = 128

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["ncons_kernel_sizes"] = list(d["ncons_kernel_sizes"])
        d["ncons_channels"] = list(d["ncons_channels"])
        return d

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        d["ncons_kernel_sizes"] = tuple(d["ncons_kernel_sizes"])
        d["ncons_channels"] = tuple(d["ncons_channels"])
        return cls(**d)


def init_immatchnet(rng, config: ImMatchNetConfig):
    """Random init. ``params['feature_extraction']`` is the frozen trunk,
    ``params['neigh_consensus']`` the trainable head (reference freezes the
    backbone: lib/model.py:75-78)."""
    k_fe, k_nc = jax.random.split(rng)
    return {
        "feature_extraction": init_feature_extraction(
            k_fe, config.feature_extraction_cnn
        ),
        "neigh_consensus": init_neigh_consensus(
            k_nc,
            config.ncons_kernel_sizes,
            config.ncons_channels,
            scheme=config.nc_init,
        ),
    }


def match_pipeline(nc_params, config: ImMatchNetConfig, feat_a, feat_b):
    """Features -> filtered correlation: corr -> [pooled] -> MM -> NC -> MM.

    Split out from the full forward so the training loss can reuse extracted
    features for the rolled-negative pair (the reference recomputes the
    backbone for the negative pass, train.py:137-138 — with a frozen/deterministic
    backbone the features are identical, so recomputing is pure waste).

    With ``config.nc_topk > 0`` the correlation -> MM -> NC -> MM chain
    runs on the top-K band (ncnet_tpu.sparse) and the filtered band is
    densified ONLY here, for the readout consumers — exact zeros
    off-band, identical to the dense output at ``K = hB*wB``. The
    training loss bypasses this densification and scores the band
    directly (train/loss.py).

    With ``config.refine_factor > 0`` (ncnet_tpu.refine, takes
    precedence) the coarse band runs on POOLED features and the
    surviving neighbourhoods are re-scored against the full-resolution
    features; the returned correlation is then at the FINE grid —
    ``corr_to_matches`` and every other consumer are generic over grid
    size and need no changes.
    """
    dtype = jnp.bfloat16 if config.half_precision else None
    k = config.relocalization_k_size
    if getattr(config, "refine_factor", 0):
        from ncnet_tpu.refine.pipeline import refine_match_pipeline
        from ncnet_tpu.sparse.pipeline import sparse_corr_to_dense

        values, indices, grid_b = refine_match_pipeline(
            nc_params, config, feat_a, feat_b
        )
        return sparse_corr_to_dense(values, indices, grid_b)
    if getattr(config, "nc_topk", 0):
        from ncnet_tpu.sparse.pipeline import (
            sparse_corr_to_dense,
            sparse_match_pipeline,
        )

        band, indices, grid_b = sparse_match_pipeline(
            nc_params, config, feat_a, feat_b
        )
        return sparse_corr_to_dense(band, indices, grid_b)
    if getattr(config, "corr_impl", "dense") != "dense":
        raise ValueError(
            f"corr_impl={config.corr_impl!r} requires a band path "
            "(nc_topk > 0 or refine_factor > 0): the dense NC stack "
            "consumes the full correlation volume, so there is nothing "
            "to stream"
        )
    delta4d = None
    if k > 1:
        corr, delta4d = correlation_maxpool4d(feat_a, feat_b, k)
    else:
        corr = correlation_4d(feat_a, feat_b)

    # sanitizer taps are identity unless --sanitize enabled them before
    # the first trace (analysis/sanitizer.py): per-stage finiteness +
    # bf16-range probes at every pipeline boundary
    corr = sanitizer.tap("correlation", corr)
    corr = sanitizer.tap("mutual_matching_pre", mutual_matching(corr))
    corr = neigh_consensus_apply(
        nc_params,
        corr.astype(dtype) if dtype else corr,
        symmetric=config.symmetric_mode,
        impl=config.conv4d_impl,
        remat=config.nc_remat,
        symmetric_batch=config.symmetric_batch,
    )
    corr = sanitizer.tap("neigh_consensus", corr)
    corr = sanitizer.tap(
        "mutual_matching_post", mutual_matching(corr).astype(jnp.float32)
    )
    if k > 1:
        return corr, delta4d
    return corr


def extract_features(params, config: ImMatchNetConfig, image):
    dtype = jnp.bfloat16 if config.half_precision else None
    return sanitizer.tap(
        "features",
        feature_extraction_apply(
            params["feature_extraction"],
            image,
            cnn=config.feature_extraction_cnn,
            normalize=config.normalize_features,
            dtype=dtype,
            center=config.center_features,
        ),
    )


def immatchnet_apply(params, config: ImMatchNetConfig, source_image, target_image):
    """Forward pass.

    Args:
      params: from `init_immatchnet` (or converted torch checkpoint).
      source_image, target_image: ``[b, h, w, 3]`` ImageNet-normalized, NHWC.

    Returns:
      ``corr4d`` of shape ``[b, iA, jA, iB, jB]`` in float32; when
      ``config.relocalization_k_size > 1`` returns ``(corr4d, delta4d)`` with
      ``delta4d = (di, dj, dk, dl)`` fine-offset tensors.
    """
    feat_a = extract_features(params, config, source_image)
    feat_b = extract_features(params, config, target_image)
    return match_pipeline(params["neigh_consensus"], config, feat_a, feat_b)


class ImMatchNet:
    """Convenience object bundling config + params with a jitted forward.

    The functional API (`init_immatchnet` / `immatchnet_apply`) is the
    primitive; this wrapper is for scripts and notebooks.
    """

    def __init__(
        self,
        config: Optional[ImMatchNetConfig] = None,
        params=None,
        rng: Optional[jax.Array] = None,
        checkpoint: Optional[str] = None,
    ):
        if checkpoint:
            from ncnet_tpu.train.checkpoint import load_checkpoint

            loaded = load_checkpoint(checkpoint)
            config = loaded.config if config is None else config
            params = loaded.params
        if config is None:
            config = ImMatchNetConfig()
        if params is None:
            params = init_immatchnet(
                rng if rng is not None else jax.random.PRNGKey(0), config
            )
        self.config = config
        self.params = params
        self._forward = jax.jit(
            lambda p, s, t: immatchnet_apply(p, config, s, t)
        )

    def __call__(self, source_image, target_image):
        return self._forward(self.params, source_image, target_image)
