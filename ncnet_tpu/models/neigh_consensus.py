"""Learned 4D neighbourhood-consensus filter.

A stack of ``Conv4d + ReLU`` layers applied to the correlation tensor,
optionally in symmetric mode: ``net(x) + T(net(T(x)))`` where ``T`` swaps the
(iA, jA) and (iB, jB) index pairs — reference ``NeighConsensus``
(lib/model.py:122-153). Because of the interleaved ReLUs this differs from a
single pass with symmetrized filters, which is why both passes are needed.
"""

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ncnet_tpu.analysis import sanitizer
from ncnet_tpu.ops.conv4d import conv4d_packed, resolve_layer_impls


def init_neigh_consensus(rng, kernel_sizes=(3, 3, 3), channels=(10, 10, 1),
                         scheme="reference", identity_noise=0.02):
    """Per-layer ``{'kernel': [k,k,k,k,cin,cout], 'bias': [cout]}``.

    ``scheme='reference'`` matches the reference Conv4d's inherited torch
    ``_ConvNd`` default (uniform in ±1/sqrt(fan_in)).

    ``scheme='identity'`` (framework extension): a center-tap channel-0
    pass-through plus ``identity_noise``-scaled Gaussian perturbation —
    the stack starts as (approximately) the identity on the correlation.
    Measured round 4 (v5e, patch16 trunk, synthetic rolled pairs): weak-
    loss training from the REFERENCE init lands in a degenerate basin
    (the loss falls while transfer PCK drops below even the zero-shift
    diagonal baseline), while from this init the same loss takes PCK
    0.73 -> 0.98 in 400 steps. Used by the synthetic proofs
    (scripts/synthetic_convergence.py, scripts/synthetic_inloc_e2e.py).
    """
    if len(kernel_sizes) != len(channels):
        raise ValueError(
            f"kernel_sizes {tuple(kernel_sizes)} and channels "
            f"{tuple(channels)} must have one entry per NC layer"
        )
    params = []
    cin = 1
    keys = jax.random.split(rng, len(channels))
    for key, k, cout in zip(keys, kernel_sizes, channels):
        k1, k2 = jax.random.split(key)
        if scheme == "identity":
            kern = identity_noise * jax.random.normal(
                k1, (k, k, k, k, cin, cout)
            )
            c = k // 2
            kern = kern.at[c, c, c, c, 0, 0].add(1.0)
            bias = jnp.zeros((cout,))
        elif scheme == "reference":
            fan_in = cin * k**4
            bound = (1.0 / fan_in) ** 0.5
            kern = jax.random.uniform(
                k1, (k, k, k, k, cin, cout), minval=-bound, maxval=bound
            )
            bias = jax.random.uniform(
                k2, (cout,), minval=-bound, maxval=bound
            )
        else:
            raise ValueError(f"unknown NC init scheme {scheme!r}")
        params.append({"kernel": kern, "bias": bias})
        cin = cout
    return params


def _swap_ab(x):
    """Swap the A and B index pairs of ``[b, iA, jA, iB, jB, c]``."""
    return x.transpose(0, 3, 4, 1, 2, 5)


def _pack(x):
    """[b, i, j, k, l, c] -> [b, i, j, k*l*c] (pure reshape, c fastest).

    TPU HBM layout fix: tiny trailing dims (c<=16, grid 25) get padded by
    the (sublane, lane) tiling — 8x on every live NC activation, the
    measured OOM cause at batch 16. Fusing the trailing dims removes them
    from tiling: padding ~1%. See `ops.conv4d.conv4d_packed`.
    """
    b, i, j, k, l, c = x.shape
    return x.reshape(b, i, j, k * l * c)


def _unpack(x, k, l):
    """Inverse of `_pack`."""
    b, i, j, fused = x.shape
    return x.reshape(b, i, j, k, l, fused // (k * l))


def neigh_consensus_apply(params, corr, symmetric=True, impl="xla", remat=False,
                          symmetric_batch=True):
    """Filter a correlation tensor.

    Args:
      params: from `init_neigh_consensus`.
      corr: ``[b, iA, jA, iB, jB]`` (no channel axis).
      symmetric: reference ``symmetric_mode`` (default True).
      impl: conv4d implementation (see `ops.conv4d.conv4d`), either one
        name for all layers or a comma-separated per-layer list (e.g.
        ``'tlc,cf1,tlc'`` — the layers have very different channel shapes,
        and the measured-best formulation differs per layer).
      remat: additionally rematerialize each layer in the backward pass
        (saves the inter-layer activations' backward residuals at the cost
        of re-running each layer's forward).

    The stack ALWAYS runs on the packed ``[b, i, j, k*l*c]`` layout between
    layers: every inter-layer activation and relu mask that XLA saves for
    the backward pass is packed (~1% TPU tiling padding), whereas
    channels-minor 6D tensors pad 8-10x in HBM — the measured OOM cause at
    the reference's batch-16 config on a 16G v5e. Inside a conv the 6D view
    reappears only as reshapes fused into the convolution itself.

    The symmetric pass runs as ONE batched net application on
    ``concat([x, T(x)])`` (identical math to ``net(x) + T(net(T(x)))`` —
    the net is per-sample — at twice the GEMM batch).

    Returns:
      ``[b, iA, jA, iB, jB]`` (final layer must have 1 output channel).
    """

    dtype = corr.dtype

    layer_impls = resolve_layer_impls(impl, len(params))

    def packed_layer(xp, p, kl, layer_impl):
        # params follow the activation dtype (the reference casts NC
        # weights to half in fp16 mode, lib/model.py:253-258)
        y = conv4d_packed(
            xp,
            p["kernel"].astype(dtype),
            kl,
            p["bias"].astype(dtype),
            impl=layer_impl,
        )
        # named for jax.checkpoint save-policies: an outer remat (the loss
        # chunking) can save exactly these conv outputs and recompute only
        # the cheap elementwise rest in the backward pass (train/loss.py)
        y = checkpoint_name(y, "nc_conv")
        return jax.nn.relu(y)

    layer_fn = (
        jax.checkpoint(packed_layer, static_argnums=(2, 3)) if remat
        else packed_layer
    )

    def net(x):
        kl = (x.shape[3], x.shape[4])
        xp = _pack(x)
        for li, (p, layer_impl) in enumerate(zip(params, layer_impls)):
            xp = layer_fn(xp, p, kl, layer_impl)
            # identity unless --sanitize: per-NC-layer finiteness probe
            # (under remat each layer reports twice per step — fwd + the
            # backward recompute — harmless for finiteness)
            xp = sanitizer.tap(f"nc_layer{li}", xp)
        return _unpack(xp, *kl)

    x = corr[..., None]
    if symmetric:
        xt = _swap_ab(x)
        if x.shape == xt.shape and symmetric_batch:
            b = x.shape[0]
            y = net(jnp.concatenate([x, xt], axis=0))
            out = y[:b] + _swap_ab(y[b:])
        else:  # rectangular A/B grids (eval pairs) can't batch the swap;
            # symmetric_batch=False runs the passes sequentially on
            # purpose (halves the stack's live batch for memory-heavy
            # conv4d impls)
            out = net(x) + _swap_ab(net(xt))
    else:
        out = net(x)
    if out.shape[-1] != 1:
        raise ValueError("last NeighConsensus layer must have 1 output channel")
    return out[..., 0]
