"""Learned 4D neighbourhood-consensus filter.

A stack of ``Conv4d + ReLU`` layers applied to the correlation tensor,
optionally in symmetric mode: ``net(x) + T(net(T(x)))`` where ``T`` swaps the
(iA, jA) and (iB, jB) index pairs — reference ``NeighConsensus``
(lib/model.py:122-153). Because of the interleaved ReLUs this differs from a
single pass with symmetrized filters, which is why both passes are needed.
"""

import jax
import jax.numpy as jnp

from ncnet_tpu.ops.conv4d import conv4d, conv4d_packed


def init_neigh_consensus(rng, kernel_sizes=(3, 3, 3), channels=(10, 10, 1)):
    """Per-layer ``{'kernel': [k,k,k,k,cin,cout], 'bias': [cout]}``.

    Init matches the reference Conv4d's inherited torch ``_ConvNd`` default
    (uniform in ±1/sqrt(fan_in)).
    """
    assert len(kernel_sizes) == len(channels)
    params = []
    cin = 1
    keys = jax.random.split(rng, len(channels))
    for key, k, cout in zip(keys, kernel_sizes, channels):
        fan_in = cin * k**4
        bound = (1.0 / fan_in) ** 0.5
        k1, k2 = jax.random.split(key)
        params.append(
            {
                "kernel": jax.random.uniform(
                    k1, (k, k, k, k, cin, cout), minval=-bound, maxval=bound
                ),
                "bias": jax.random.uniform(k2, (cout,), minval=-bound, maxval=bound),
            }
        )
        cin = cout
    return params


def _swap_ab(x):
    """Swap the A and B index pairs of ``[b, iA, jA, iB, jB, c]``."""
    return x.transpose(0, 3, 4, 1, 2, 5)


def _pack(x):
    """[b, i, j, k, l, c] -> [b, i, j, k*l*c] (pure reshape, c fastest).

    TPU HBM layout fix: tiny trailing dims (c<=16, grid 25) get padded by
    the (sublane, lane) tiling — 8x on every live NC activation, the
    measured OOM cause at batch 16. Fusing the trailing dims removes them
    from tiling: padding ~1%. See `ops.conv4d.conv4d_packed`.
    """
    b, i, j, k, l, c = x.shape
    return x.reshape(b, i, j, k * l * c)


def _unpack(x, k, l):
    """Inverse of `_pack`."""
    b, i, j, fused = x.shape
    return x.reshape(b, i, j, k, l, fused // (k * l))


def neigh_consensus_apply(params, corr, symmetric=True, impl="xla", remat=False):
    """Filter a correlation tensor.

    Args:
      params: from `init_neigh_consensus`.
      corr: ``[b, iA, jA, iB, jB]`` (no channel axis).
      symmetric: reference ``symmetric_mode`` (default True).
      impl: conv4d implementation ('xla' | 'taps' | 'scan').
      remat: rematerialize each layer in the backward pass. The remat
        boundary is placed around the pack->unpack->conv->relu->pack unit, so
        only PACKED activations (see `_pack`) survive between forward and
        backward — without this, XLA keeps channels-minor 6D activations
        whose TPU tiling pads HBM 8x and training OOMs at the reference's
        batch 16 (measured on v5e).

    Returns:
      ``[b, iA, jA, iB, jB]`` (final layer must have 1 output channel).
    """

    dtype = corr.dtype

    def layer(x, p):
        # params follow the activation dtype (the reference casts NC
        # weights to half in fp16 mode, lib/model.py:253-258)
        return jax.nn.relu(
            conv4d(x, p["kernel"].astype(dtype), p["bias"].astype(dtype), impl=impl)
        )

    if remat:
        # Fully packed pipeline: convs, relus and the remat boundaries all
        # live in the [b, i, j, c, k*l] layout; nothing full-size is ever
        # materialized channels-minor.
        def packed_layer(xp, p, kl):
            return jax.nn.relu(
                conv4d_packed(
                    xp,
                    p["kernel"].astype(dtype),
                    kl,
                    p["bias"].astype(dtype),
                    impl=impl,
                )
            )

        remat_layer = jax.checkpoint(packed_layer, static_argnums=(2,))

        def net(x):
            kl = (x.shape[3], x.shape[4])
            xp = _pack(x)
            for p in params:
                xp = remat_layer(xp, p, kl)
            return _unpack(xp, *kl)

    else:

        def net(x):
            for p in params:
                x = layer(x, p)
            return x

    x = corr[..., None]
    if symmetric:
        out = net(x) + _swap_ab(net(_swap_ab(x)))
    else:
        out = net(x)
    if out.shape[-1] != 1:
        raise ValueError("last NeighConsensus layer must have 1 output channel")
    return out[..., 0]
