"""ncnet_tpu — a TPU-native dense-correspondence framework.

A ground-up JAX/XLA/Pallas/pjit reimplementation of the capabilities of
Neighbourhood Consensus Networks (Rocco et al., NeurIPS 2018; reference
implementation GrumpyZhou/ncnet): dense CNN feature extraction, the all-pairs
4D correlation tensor, soft mutual-nearest-neighbour filtering, learned 4D
neighbourhood-consensus convolutions, weakly-supervised training, and the
PF-Pascal / InLoc evaluation harnesses.

Design notes (TPU-first, not a port):
  * channels-last (NHWC) feature layouts; correlation tensors are
    ``[batch, iA, jA, iB, jB]`` with an explicit trailing channel axis only
    inside the neighbourhood-consensus stack;
  * the 4D convolution compiles as a single XLA convolution with four spatial
    dimensions (MXU), with a tap-decomposition fallback and a Pallas kernel;
  * relocalization fuses correlation and 4D max-pooling so the high-resolution
    correlation tensor is never materialized in HBM;
  * scaling is expressed with `jax.sharding.Mesh` + `shard_map`: batch data
    parallelism with `psum` gradient reduction, and spatial sharding of the
    correlation tensor (the long-context analog) with halo exchange.
"""

from ncnet_tpu import (
    analysis,
    data,
    models,
    ops,
    parallel,
    resilience,
    telemetry,
    train,
    utils,
)
from ncnet_tpu.models.immatchnet import ImMatchNet, ImMatchNetConfig

__version__ = "0.1.0"  # keep in sync with pyproject.toml

__all__ = [
    "ImMatchNet",
    "ImMatchNetConfig",
    "analysis",
    "data",
    "models",
    "ops",
    "parallel",
    "resilience",
    "telemetry",
    "train",
    "utils",
]
