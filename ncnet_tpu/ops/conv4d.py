"""4D convolution — the core custom primitive of neighbourhood consensus.

Semantics (shared by every impl): zero-padded SAME convolution with stride 1
over the four correlation dims, bias added once — identical math to the
reference's ``conv4d`` (lib/conv4d.py:11-51), which decomposes into a Python
loop of conv3d calls with the bias applied only on the center tap. A standard
4D convolution plus a single bias term is exactly that sum, so no special
bias handling is needed here.

Layout is channels-last: inputs ``[b, i, j, k, l, c_in]``, filters
``[ki, kj, kk, kl, c_in, c_out]``.

Implementations:
  * ``impl='xla'`` (default): one `lax.conv_general_dilated` with FOUR spatial
    dimensions. XLA's convolution HLO is rank-generic and the TPU backend
    lowers it onto the MXU directly — one fused op, no Python-level looping.
  * ``impl='taps'``: decomposition over the leading kernel dim: a 3D
    convolution of the full tensor per tap, shifted and summed along ``i``.
    Useful as a cross-check and on backends without 4-spatial-dim support.
  * ``impl='scan'``: `lax.scan` over output slices of the leading spatial
    dim, one small 3D convolution stack per slice — the sequential
    formulation of the reference's Python loop (lib/conv4d.py:39-48), but
    compiled. O(1/I) live memory vs 'xla'/'taps': the memory-safe choice
    for training, where the TPU layouts of the one-shot impls pad the big
    6D temps 4-5x (see bench notes).
"""

import jax
import jax.numpy as jnp
from jax import lax

# Canonical impl registry: every name `conv4d` dispatches on. 'pallas' is
# listed separately by callers that can run interpret mode; CLI surfaces
# exclude it (it does not lower on TPU — kernels/conv4d_pallas.py STATUS).
CONV4D_IMPLS = (
    "xla", "taps", "scan", "tlc", "btl", "btl2", "btl3", "btl4", "btl5",
    "btl6", "tlcv",
    "tf3", "tf2", "cf", "cfs", "cf1", "cf1s", "ck1", "tk1", "gemm", "gemms",
    "gemm4",
)


# Direct kernel-gradient (dw) lowerings accepted in the third slot of a
# composite impl ('<fwd>/<dx>/<dw>'), alongside any forward impl name
# (which means: linear-transpose THAT formulation wrt w).
#   'dwe'  — one wide GEMM: (dk, dl) taps folded into x's channel axis,
#            (di, dj) taps into g's channel axis (memory-hungry: both
#            operands are ki*kj x the activation size).
#   'dweN' — the same, scanned over blocks of N rows of the padded
#            leading dim (N in 1, 2, 4, 8): O(N/I) gather memory.
DW_IMPLS = ("dwe", "dwe1", "dwe2", "dwe4", "dwe8")


def is_valid_impl(name):
    """True for a registry name, a '<fwd>/<dx>' composite, or a
    '<fwd>/<dx>/<dw>' composite. In composites the dx and dw slots may be
    empty ('btl4//dwe4'), meaning: use the autodiff linear transpose of
    the FORWARD formulation for that input. The dw slot accepts forward
    impl names (transpose that formulation wrt w) or a DW_IMPLS name."""
    parts = name.split("/")
    if len(parts) == 1:
        return parts[0] in CONV4D_IMPLS
    if not 2 <= len(parts) <= 3 or parts[0] not in CONV4D_IMPLS:
        return False
    if parts[1] and parts[1] not in CONV4D_IMPLS:
        return False
    if len(parts) == 3 and parts[2] and (
        parts[2] not in CONV4D_IMPLS and parts[2] not in DW_IMPLS
    ):
        return False
    return True


def resolve_layer_impls(impl, n_layers):
    """One impl name or a comma-separated per-layer list -> list of
    ``n_layers`` names (shared by the unsharded and sharded NC stacks)."""
    impls = impl.split(",") if isinstance(impl, str) else list(impl)
    if len(impls) == 1:
        impls = impls * n_layers
    if len(impls) != n_layers:
        raise ValueError(
            f"conv4d impl list {impls} does not match {n_layers} NC layers"
        )
    # validate names here (not only in the CLI parsers) so a typo in a
    # programmatically-built config fails with this message instead of
    # surfacing deep inside jit tracing of the dispatch
    for name in impls:
        # 'pallas' is legal at this layer (interpret-mode runs route it
        # through conv4d_packed); only the CLIs exclude it
        if name != "pallas" and not is_valid_impl(name):
            raise ValueError(
                f"unknown conv4d impl {name!r}: expect a name from "
                f"{CONV4D_IMPLS}, or '<fwd>/<dx>[/<dw>]' composites of "
                f"them (dw also accepts {DW_IMPLS}; empty dx/dw slots "
                "mean 'autodiff transpose of the forward')"
            )
    return impls


def _banded_weights(w, n_rows, n_cols, offset):
    """Expand ``w`` into a banded (Toeplitz) channel-mixing matrix over l:
    ``[ki,kj,kk,kl,cin,cout] -> [ki,kj,kk, n_rows*cin, n_cols*cout]`` with
    ``T[r, c, col, o] = w[..., r - col + offset, c, o]`` (zero off-band).

    Rows index input-l positions, cols output-l positions. The square case
    (n_rows = n_cols = l, offset = kl//2) is the dense Toeplitz of
    impl='tlc'; the rectangular case (n_rows = block window, offset = 0)
    is the per-block band of impl='btl'.
    """
    ki, kj, kk, kl, cin, cout = w.shape
    r = jnp.arange(n_rows)[:, None]
    c = jnp.arange(n_cols)[None, :]
    dl = r - c + offset  # [n_rows, n_cols]
    valid = (dl >= 0) & (dl < kl)
    t = jnp.take(w, jnp.clip(dl, 0, kl - 1), axis=3, mode="clip")
    t = jnp.where(valid[None, None, None, :, :, None, None], t, 0)
    # [ki,kj,kk, rows, cols, cin, cout] -> [.., rows*cin, cols*cout]
    t = t.transpose(0, 1, 2, 3, 5, 4, 6)
    return t.reshape(ki, kj, kk, n_rows * cin, n_cols * cout)


def _toeplitz_l_weights(w, l_size):
    """Dense banded matrix over the full l dim (impl='tlc').

    Inflates FLOPs by ``l_size / kl`` (5x at the training grid 25) but
    gives the MXU full 128-lane tiles (l*c = l*o = 400 at the PF-Pascal
    config) instead of ``cout``-wide (16 or 1) output tiles, which cap
    every direct formulation at ~12 TFLOP/s measured.
    """
    return _banded_weights(w, l_size, l_size, w.shape[3] // 2)


def _conv4d_tlc(x, w):
    """conv4d as ONE conv3d over (i, j, k) with (l, c) fused into channels."""
    b, i, j, k, l, cin = x.shape
    cout = w.shape[-1]
    t = _toeplitz_l_weights(w, l).astype(x.dtype)
    x3 = x.reshape(b, i, j, k, l * cin)
    dn = lax.conv_dimension_numbers(
        x3.shape, t.shape, ("NijkC", "ijkIO", "NijkC")
    )
    out = lax.conv_general_dilated(
        x3,
        t,
        window_strides=(1, 1, 1),
        padding="SAME",
        dimension_numbers=dn,
        preferred_element_type=x.dtype,
    )
    return out.reshape(b, i, j, k, l, cout)


def _conv4d_btl(x, w, block=8):
    """Blocked-Toeplitz conv4d: conv3d over (i, j, k) with the l dim split
    into blocks of ``block``; each block's band window (block + kl - 1
    columns) folds into input channels and the block's outputs into output
    channels.

    Same wide-lane idea as 'tlc' (dense Toeplitz, l/kl = 5x FLOP
    inflation at the training grid) but banded per block: inflation drops
    to ``ceil(l/block)*block/l * (block+kl-1)/kl`` (~3.1x at l=25,
    block=8) while keeping in/out channel lanes at 192/128 for the
    16-channel NC layers.
    """
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pad = kl // 2
    nb = -(-l // block)
    lpad = nb * block
    window = block + kl - 1
    # pad l by the band halo on the left and (halo + round-up) on the right
    xp = jnp.pad(
        x, ((0, 0),) * 4 + ((pad, lpad - l + pad), (0, 0))
    )  # l axis length lpad + 2*pad
    # windows: block lb covers padded-l [lb*block, lb*block + window).
    # Each window is reshaped to 5D BEFORE the stack: the previous 7D
    # [b, nb, i, j, k, window, cin] intermediate drew a pathological XLA
    # layout on TPU (same failure mode as the 6D channel-fused gathers).
    xw = jnp.concatenate(
        [
            xp[:, :, :, :, lb * block : lb * block + window].reshape(
                b, i, j, k, window * cin
            )
            for lb in range(nb)
        ],
        axis=0,
    )  # [nb*b, i, j, k, window*cin] (block-major on the batch axis)
    t = _banded_weights(w, window, block, 0).astype(x.dtype)
    dn = lax.conv_dimension_numbers(
        xw.shape, t.shape, ("NijkC", "ijkIO", "NijkC")
    )
    y = lax.conv_general_dilated(
        xw,
        t,
        window_strides=(1, 1, 1),
        padding="SAME",
        dimension_numbers=dn,
        preferred_element_type=x.dtype,
    )  # [nb*b, i, j, k, block*cout] (block-major batch, matching xw)
    # Reassemble l from the batch blocks with 5D ops only: slice each
    # block back out and concat on the channel axis, giving minor order
    # (lb, pos, cout) = (l, cout); then one small 6D view to trim l.
    y = jnp.concatenate(
        [y[lb * b : (lb + 1) * b] for lb in range(nb)], axis=-1
    )  # [b, i, j, k, nb*block*cout]
    y = y.reshape(b, i, j, k, nb * block, cout)
    return y[:, :, :, :, :l]


@jax.custom_vjp
def _conv4d_tlcv(x, w):
    """'tlc' forward with a custom VJP: dx reuses the wide-lane Toeplitz
    conv (a conv4d identity with flipped, channel-transposed filters), but
    dw bypasses the dense-Toeplitz gradient — autodiff through 'tlc' pays
    the 5x FLOP inflation AGAIN for the [.., l*c, l*o] matrix gradient,
    while the true kernel gradient is the rank-4 conv's (XLA computes it
    at the original [k^4, cin, cout] size)."""
    return _conv4d_tlc(x, w)


def _conv4d_tlcv_fwd(x, w):
    return _conv4d_tlc(x, w), (x, w)


def _conv4d_tlcv_bwd(res, g):
    x, w = res
    dx = _conv4d_tlc(g, _flip_transpose(w).astype(g.dtype))
    # conv4d is linear in w: transpose directly (jax.vjp would evaluate
    # and discard a full extra primal forward outside jit)
    transpose_w = jax.linear_transpose(lambda ww: _conv4d_xla(x, ww), w)
    (dw,) = transpose_w(g)
    return dx, dw


_conv4d_tlcv.defvjp(_conv4d_tlcv_fwd, _conv4d_tlcv_bwd)


def _conv4d_xla(x, w):
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, ("NijklC", "ijklIO", "NijklC")
    )
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=(1, 1, 1, 1),
        padding="SAME",
        dimension_numbers=dn,
        preferred_element_type=x.dtype,
    )


def _conv4d_taps(x, w):
    """Sum over taps of the leading kernel dim, each a 3D convolution."""
    ki = w.shape[0]
    pad = ki // 2
    b, i, j, k, l, cin = x.shape
    dn3 = lax.conv_dimension_numbers(
        (b * i, j, k, l, cin),
        w.shape[1:],
        ("NjklC", "jklIO", "NjklC"),
    )
    x3 = x.reshape(b * i, j, k, l, cin)
    out = None
    for p in range(ki):
        y = lax.conv_general_dilated(
            x3,
            w[p],
            window_strides=(1, 1, 1),
            padding="SAME",
            dimension_numbers=dn3,
            preferred_element_type=x.dtype,
        )
        y = y.reshape(b, i, j, k, l, -1)
        # out[:, m] += y[:, m + p - pad]  -> shift y by (pad - p) with zero fill
        shift = pad - p
        if shift > 0:
            y = jnp.pad(y[:, :-shift], ((0, 0), (shift, 0)) + ((0, 0),) * 4)
        elif shift < 0:
            y = jnp.pad(y[:, -shift:], ((0, 0), (0, -shift)) + ((0, 0),) * 4)
        out = y if out is None else out + y
    return out


def _conv4d_scan(x, w):
    ki = w.shape[0]
    pad = ki // 2
    b, i, j, k, l, cin = x.shape
    dn3 = lax.conv_dimension_numbers(
        (b, j, k, l, cin), w.shape[1:], ("NjklC", "jklIO", "NjklC")
    )
    xpad = jnp.pad(x, ((0, 0), (pad, pad)) + ((0, 0),) * 4)

    def slice_out(_, out_i):
        window = lax.dynamic_slice_in_dim(xpad, out_i, ki, axis=1)
        acc = None
        for p in range(ki):
            y = lax.conv_general_dilated(
                window[:, p],
                w[p],
                window_strides=(1, 1, 1),
                padding="SAME",
                dimension_numbers=dn3,
                preferred_element_type=x.dtype,
            )
            acc = y if acc is None else acc + y
        return None, acc

    _, out = lax.scan(slice_out, None, jnp.arange(i))
    # scan stacks on axis 0: [i, b, j, k, l, cout] -> [b, i, ...]
    return jnp.moveaxis(out, 0, 1)


def _check_packed(kl_shape, cin, fused):
    """The packed-layout contract: trailing dim is exactly k*l*cin. A
    mismatch means the caller's kl_shape/weights disagree with the packed
    activation — raise (not assert: must survive python -O)."""
    k, l = kl_shape
    if k * l * cin != fused:
        raise ValueError(
            f"packed trailing dim {fused} != k*l*cin = "
            f"{k}*{l}*{cin} (kl_shape {kl_shape}); the [b, i, j, k*l*c] "
            "layout and the weight tensor disagree"
        )


def conv4d_packed(xp, w, kl_shape, bias=None, impl="scan", interpret=None):
    """4D convolution on the fused layout ``[b, i, j, k*l*c]`` (c fastest).

    TPU memory-layout native: the channels-minor 6D activation layout pads
    HBM 8x under (sublane, lane) tiling (c<=16 padded to 128 lanes) — the
    measured cause of training OOM at the reference's batch-16 config on a
    16G v5e, and XLA's layout assignment re-derives that layout even for a
    transposed logical shape. Fusing (k, l, c) into ONE trailing dim (c
    fastest — a pure reshape of the conv's natural NjklC layout) removes the
    small dim from tiling entirely: padding drops to ~1%. The conv scans
    over the leading spatial dim; only per-window slices are ever reshaped
    back to 6D, in both the forward and the scanned backward.

    Args:
      xp: ``[b, i, j, k*l*c_in]``, element order (k, l, c) with c fastest.
      w: ``[ki, kj, kk, kl, c_in, c_out]``.
      kl_shape: the static (k, l) factorization of the fused dim.
      bias: optional ``[c_out]``.
      impl: 'scan' (sequential over i, O(1/I) live memory, implemented
        directly on the packed layout below) or any `conv4d` impl name
        ('tlc', 'tf3', ... — fastest at small grids), routed through a pure
        unpack -> conv4d -> repack; all consume/produce the packed layout.
      interpret: for impl='pallas' only — run the kernel in the Pallas
        interpreter (None = auto: interpret unless the default backend is
        TPU; pass explicitly when tracing for a non-default device).

    Returns:
      ``[b, i, j, k*l*c_out]``.
    """
    if impl == "pallas":
        from ncnet_tpu.kernels.conv4d_pallas import conv4d_packed_pallas

        k, l = kl_shape
        cin, cout = w.shape[-2], w.shape[-1]
        _check_packed(kl_shape, cin, xp.shape[-1])
        b = jnp.zeros((cout,), jnp.float32) if bias is None else bias
        # Interpret mode runs the kernel in the Pallas interpreter so the
        # CPU test mesh exercises the exact same code path as the TPU.
        # Default follows the backend; override with interpret=True/False
        # when tracing for a device that differs from the default backend.
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        if not interpret:
            # Honest guard: the kernel's in-kernel 4D reshape does not pass
            # Mosaic layout inference on current libtpu ("unsupported shape
            # cast"), and a lowerable redesign cannot beat the XLA tap-
            # folding impls anyway (<=16 output channels caps any direct
            # patch GEMM at 16/128 MXU lanes — see kernels/conv4d_pallas.py).
            raise NotImplementedError(
                "conv4d impl='pallas' currently lowers only in interpret "
                "mode (pass interpret=True); on TPU use impl='cf'/'cfs' "
                "(the fastest measured formulations)"
            )
        return conv4d_packed_pallas(
            xp, w, b, kl_shape, cin, cout, interpret
        )
    if impl != "scan":
        b, i, j, fused = xp.shape
        k, l = kl_shape
        cin = w.shape[-2]
        cout = w.shape[-1]
        _check_packed(kl_shape, cin, fused)
        out = conv4d(xp.reshape(b, i, j, k, l, cin), w, bias=bias, impl=impl)
        return out.reshape(b, i, j, k * l * cout)
    ki = w.shape[0]
    pad = ki // 2
    b, i, j, fused = xp.shape
    k, l = kl_shape
    cin = w.shape[-2]
    cout = w.shape[-1]
    _check_packed(kl_shape, cin, fused)
    dn3 = lax.conv_dimension_numbers(
        (b, j, k, l, cin), w.shape[1:], ("NjklC", "jklIO", "NjklC")
    )
    xpad = jnp.pad(xp, ((0, 0), (pad, pad), (0, 0), (0, 0)))

    def slice_out(_, out_i):
        window = lax.dynamic_slice_in_dim(xpad, out_i, ki, axis=1)
        acc = None
        for p in range(ki):
            xs = window[:, p].reshape(b, j, k, l, cin)  # pure reshape
            y = lax.conv_general_dilated(
                xs,
                w[p],
                window_strides=(1, 1, 1),
                padding="SAME",
                dimension_numbers=dn3,
                preferred_element_type=xp.dtype,
            )
            acc = y if acc is None else acc + y
        if bias is not None:
            acc = acc + bias
        return None, acc.reshape(b, j, k * l * cout)

    _, out = lax.scan(slice_out, None, jnp.arange(i))
    return jnp.moveaxis(out, 0, 1)  # [b, i, j, k*l*cout]


def _conv4d_tapsfused3(x, w):
    """Fuse the ki taps into output channels of ONE conv3d, then shift-sum.

    The MXU lane dim carries conv output channels; with cout<=16 every
    direct lowering wastes >=7/8 of the lanes (measured ~11 TFLOP/s). Here
    one conv3d over (j, k, l) produces ``ki * cout`` channels — the
    contribution of each leading-dim tap — and the cheap epilogue shifts
    each tap group along i and sums: identical math, ki-times wider lanes.
    """
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pad = ki // 2
    w2 = w.transpose(1, 2, 3, 4, 0, 5).reshape(kj, kk, kl, cin, ki * cout)
    x3 = x.reshape(b * i, j, k, l, cin)
    dn = lax.conv_dimension_numbers(
        x3.shape, w2.shape, ("NjklC", "jklIO", "NjklC")
    )
    y = lax.conv_general_dilated(
        x3,
        w2,
        window_strides=(1, 1, 1),
        padding="SAME",
        dimension_numbers=dn,
        preferred_element_type=x.dtype,
    ).reshape(b, i, j, k, l, ki * cout)
    # out[:, m] = sum_di y[:, m + di - pad, ..., di-th channel block].
    # Channel blocks are sliced on the FUSED trailing dim: a 7D view with a
    # trailing (ki, cout) pair tiles to 5x HBM padding on TPU (measured
    # OOM), the 6D fused form stays ~2x.
    ypad = jnp.pad(y, ((0, 0), (pad, pad)) + ((0, 0),) * 4)
    out = None
    for di in range(ki):
        term = ypad[:, di : di + i, :, :, :, di * cout : (di + 1) * cout]
        out = term if out is None else out + term
    return out


def _conv4d_tapsfused2(x, w):
    """Fuse the (ki, kj) taps into output channels of ONE conv2d over (k, l),
    then shift-sum over (i, j). Lane width ``ki*kj*cout`` (400 at the
    PF-Pascal config) — full MXU tiles; epilogue is elementwise."""
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pj = ki // 2, kj // 2
    w2 = w.transpose(2, 3, 4, 0, 1, 5).reshape(kk, kl, cin, ki * kj * cout)
    x2 = x.reshape(b * i * j, k, l, cin)
    dn = lax.conv_dimension_numbers(
        x2.shape, w2.shape, ("NklC", "klIO", "NklC")
    )
    # epilogue on a 5D view with (k, l) fused — they are never shifted
    # here, and 6D intermediates draw pathological XLA layouts on TPU
    # (see the cf/btl notes)
    y = lax.conv_general_dilated(
        x2,
        w2,
        window_strides=(1, 1),
        padding="SAME",
        dimension_numbers=dn,
        preferred_element_type=x.dtype,
    ).reshape(b, i, j, k * l, ki * kj * cout)
    ypad = jnp.pad(y, ((0, 0), (pi, pi), (pj, pj), (0, 0), (0, 0)))
    out = None
    for di in range(ki):
        for dj in range(kj):
            t = di * kj + dj
            term = ypad[
                :, di : di + i, dj : dj + j, :, t * cout : (t + 1) * cout
            ]
            out = term if out is None else out + term
    return out.reshape(b, i, j, k, l, cout)


def _cf_kernel(w):
    """[ki,kj,kk,kl,cin,cout] -> conv2d kernel [kk, kl, ki*cin, kj*cout]
    with (di major, c minor) input blocks and (dj major, o minor) output
    blocks."""
    ki, kj, kk, kl, cin, cout = w.shape
    return w.transpose(2, 3, 0, 4, 1, 5).reshape(kk, kl, ki * cin, kj * cout)


def _conv4d_cf(x, w):
    """Channel-fused conv4d: ONE conv2d over (k, l) with the ki leading taps
    folded into input channels and the kj taps into output channels.

    in-channels = ki*cin, out-channels = kj*cout (80 at the PF-Pascal
    config): full MXU lane tiles in the forward AND both backward convs —
    the narrow-cout formulations cap at ~12% utilization, and XLA's conv
    was measured at >150 TFLOP/s once lanes are wide. True FLOP count
    (every tap computed once); epilogue is a cheap shift-sum over j using
    channel-block slices, so no high-rank intermediates that tile badly.
    """
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pj = ki // 2, kj // 2
    xpad = jnp.pad(x, ((0, 0), (pi, pi)) + ((0, 0),) * 4)
    # [b*i*j, k, l, ki*cin]: channel block di holds x shifted by di-pi in i.
    # Each slice is reshaped to 4D BEFORE the concat: a 6D gather tensor
    # (and its 6D split in the backward transpose) gets a pathological
    # XLA layout on TPU (measured 10.2x tile padding -> OOM at batch 16);
    # the 4D form keeps the natural [.., k, l, c] layout on both sides.
    xs = jnp.concatenate(
        [
            xpad[:, di : di + i].reshape(b * i * j, k, l, cin)
            for di in range(ki)
        ],
        axis=-1,
    )
    # NOT checkpoint-named: saving the gathered patches across the loss-
    # chunk remat boundary was measured to make things WORSE — buffers
    # that live across the lax.map while-loop get layout-pessimized by XLA
    # (5.1x tile padding -> OOM), costing more than the re-gather's
    # remat-compress copies save.
    x2 = xs
    w2 = _cf_kernel(w)
    dn = lax.conv_dimension_numbers(
        x2.shape, w2.shape, ("NklC", "klIO", "NklC")
    )
    y = lax.conv_general_dilated(
        x2,
        w2,
        window_strides=(1, 1),
        padding="SAME",
        dimension_numbers=dn,
        preferred_element_type=x.dtype,
    ).reshape(b, i, j, k, l, kj * cout)
    # out[:, :, m] = sum_dj y[:, :, m + dj - pj, ..., dj-th channel block]
    ypad = jnp.pad(y, ((0, 0), (0, 0), (pj, pj)) + ((0, 0),) * 3)
    out = None
    for dj in range(kj):
        term = ypad[:, :, dj : dj + j, :, :, dj * cout : (dj + 1) * cout]
        out = term if out is None else out + term
    return out


def _conv4d_cfs(x, w):
    """`_conv4d_cf` restructured as a `lax.scan` over the leading spatial
    dim: O(1/I) live memory (the reference loop's memory shape,
    lib/conv4d.py:39-48) with the same wide-lane conv2d inside."""
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pj = ki // 2, kj // 2
    xpad = jnp.pad(x, ((0, 0), (pi, pi)) + ((0, 0),) * 4)
    w2 = _cf_kernel(w)
    dn = lax.conv_dimension_numbers(
        (b * j, k, l, ki * cin), w2.shape, ("NklC", "klIO", "NklC")
    )

    def slice_out(_, out_i):
        window = lax.dynamic_slice_in_dim(xpad, out_i, ki, axis=1)
        # [b, ki, j, k, l, c] -> [b, j, k, l, ki*cin] (di major, c minor)
        xs = window.transpose(0, 2, 3, 4, 1, 5).reshape(b, j, k, l, ki * cin)
        y = lax.conv_general_dilated(
            xs.reshape(b * j, k, l, ki * cin),
            w2,
            window_strides=(1, 1),
            padding="SAME",
            dimension_numbers=dn,
            preferred_element_type=x.dtype,
        ).reshape(b, j, k, l, kj * cout)
        ypad = jnp.pad(y, ((0, 0), (pj, pj)) + ((0, 0),) * 3)
        acc = None
        for dj in range(kj):
            term = ypad[:, dj : dj + j, :, :, dj * cout : (dj + 1) * cout]
            acc = term if acc is None else acc + term
        return None, acc

    _, out = lax.scan(slice_out, None, jnp.arange(i))
    return jnp.moveaxis(out, 0, 1)


def _conv4d_cf1(x, w):
    """Channel-fused conv4d with a 1D convolution core: the ki leading taps
    fold into INPUT channels, the (kj, kk) taps into OUTPUT channels, and
    the conv runs over l only.

    At the PF-Pascal middle layer (16->16, 5^4) this is the measured-best
    XLA formulation (round 3): in-channels ki*cin = 80, out-channels
    kj*kk*cout = 400 — wide MXU lanes BOTH sides with TRUE FLOPs (no
    Toeplitz inflation), measured ~84 TFLOP/s true rate vs ~27 for 'tlc'
    (137 TFLOP/s hardware / 5x inflation). Cost: the conv output
    materializes at kj*kk/cout x the activation size (5 GB at net batch 16
    in bf16) — use via per-layer mixing with a lean impl on the 1-channel
    edge layers, and bound live memory with loss chunking.
    """
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pj, pk = ki // 2, kj // 2, kk // 2
    xpad = jnp.pad(x, ((0, 0), (pi, pi)) + ((0, 0),) * 4)
    # [b, i, j, k, l, ki*cin]: channel block di holds x shifted by di-pi in i
    xs = jnp.concatenate([xpad[:, di : di + i] for di in range(ki)], axis=-1)
    w2 = w.transpose(3, 0, 4, 1, 2, 5).reshape(kl, ki * cin, kj * kk * cout)
    x1 = xs.reshape(b * i * j * k, l, ki * cin)
    dn = lax.conv_dimension_numbers(
        x1.shape, w2.shape, ("NWC", "WIO", "NWC")
    )
    # epilogue on a 5D view: a 6D [b, i, j, k, l, kj*kk*cout] intermediate
    # was measured to get a pathological transpose-copy layout from XLA
    # (4x padded, OOM at the training config); [b*i, j, k, l, N] keeps the
    # natural minor-dim layout.
    y = lax.conv_general_dilated(
        x1,
        w2,
        window_strides=(1,),
        padding="SAME",
        dimension_numbers=dn,
        preferred_element_type=x.dtype,
    ).reshape(b * i, j, k, l, kj * kk * cout)
    # out[:, m, n] = sum_{dj,dk} y[:, m+dj-pj, n+dk-pk, :, block(dj,dk)]
    ypad = jnp.pad(y, ((0, 0), (pj, pj), (pk, pk), (0, 0), (0, 0)))
    out = None
    for dj in range(kj):
        for dk in range(kk):
            t = dj * kk + dk
            term = ypad[
                :, dj : dj + j, dk : dk + k, :, t * cout : (t + 1) * cout
            ]
            out = term if out is None else out + term
    return out.reshape(b, i, j, k, l, cout)


def _conv4d_ck1(x, w):
    """Channel-fused conv4d, conv1d core, balanced folding: the (ki, kk)
    taps fold into INPUT channels, the kj taps into OUTPUT channels, conv
    over l.

    Complement of `_conv4d_cf1` trading the output blow-up for an input
    one: in-channels ki*kk*cin (400 at the PF-Pascal middle layer: full
    contraction lanes), out-channels kj*cout (80), so the conv output is
    only kj x the activation size and the epilogue shift-sum has kj terms
    (cf1's kj*kk-term epilogue over a kj*kk-times-larger tensor was the
    measured bottleneck — slice-sums don't fuse, each term re-reads the
    padded tensor). The input-side gather is ki*kk shifted copies, read
    once by the conv. True FLOPs throughout."""
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pj, pk = ki // 2, kj // 2, kk // 2
    xpad = jnp.pad(
        x, ((0, 0), (pi, pi), (0, 0), (pk, pk), (0, 0), (0, 0))
    )
    # [b, i, j, k, l, ki*kk*cin]: block (di, dk) holds x shifted in i and k
    xs = jnp.concatenate(
        [
            xpad[:, di : di + i, :, dk : dk + k]
            for di in range(ki)
            for dk in range(kk)
        ],
        axis=-1,
    )
    # kernel [kl, (di, dk, cin), (dj, cout)]
    w2 = w.transpose(3, 0, 2, 4, 1, 5).reshape(
        kl, ki * kk * cin, kj * cout
    )
    x1 = xs.reshape(b * i * j * k, l, ki * kk * cin)
    dn = lax.conv_dimension_numbers(
        x1.shape, w2.shape, ("NWC", "WIO", "NWC")
    )
    y = lax.conv_general_dilated(
        x1,
        w2,
        window_strides=(1,),
        padding="SAME",
        dimension_numbers=dn,
        preferred_element_type=x.dtype,
    ).reshape(b * i, j, k, l, kj * cout)  # 5D: see cf1 layout note
    ypad = jnp.pad(y, ((0, 0), (pj, pj), (0, 0), (0, 0), (0, 0)))
    out = None
    for dj in range(kj):
        term = ypad[:, dj : dj + j, :, :, dj * cout : (dj + 1) * cout]
        out = term if out is None else out + term
    return out.reshape(b, i, j, k, l, cout)


def _conv4d_tk1(x, w):
    """conv4d as ki conv1d calls: outer Python loop over the di taps, the
    kk taps folded into INPUT channels, the kj taps into OUTPUT channels,
    conv over l.

    Measured rationale (round 3, v5e): XLA lowers conv1d (NWC) near the
    MXU rate at these shapes while conv2d manages ~1/4 of it, and
    slice-sum epilogues do not fuse (each term re-reads the padded
    tensor), so the tap folding must keep EVERY materialized tensor small
    and every epilogue short. Here each of the ki convs reads the shared
    (dk, c)-gathered input (kk*cin = 80 lanes) and produces a kj*cout
    (= 80)-channel output — the di/dj epilogues are ki shifted adds of
    those 1x-sized outputs. True FLOPs; all intermediates <= kk x input."""
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pj, pk = ki // 2, kj // 2, kk // 2
    xpad = jnp.pad(x, ((0, 0), (pi, pi), (0, 0), (pk, pk), (0, 0), (0, 0)))
    # shared (dk, c) gather: [b, i+2pi, j, k, l, kk*cin]
    xs = jnp.concatenate(
        [xpad[:, :, :, dk : dk + k] for dk in range(kk)], axis=-1
    )
    dn = lax.conv_dimension_numbers(
        (b * i * j * k, l, kk * cin),
        (kl, kk * cin, kj * cout),
        ("NWC", "WIO", "NWC"),
    )
    out = None
    for di in range(ki):
        # kernel for this di tap: [kl, (dk, cin), (dj, cout)]
        w_di = w[di].transpose(2, 1, 3, 0, 4).reshape(
            kl, kk * cin, kj * cout
        )
        y = lax.conv_general_dilated(
            xs[:, di : di + i].reshape(b * i * j * k, l, kk * cin),
            w_di,
            window_strides=(1,),
            padding="SAME",
            dimension_numbers=dn,
            preferred_element_type=x.dtype,
        ).reshape(b * i, j, k, l, kj * cout)
        out = y if out is None else out + y
    # dj epilogue: out[:, m] = sum_dj acc[:, m+dj-pj, ..., dj-block]
    ypad = jnp.pad(out, ((0, 0), (pj, pj), (0, 0), (0, 0), (0, 0)))
    acc = None
    for dj in range(kj):
        term = ypad[:, dj : dj + j, :, :, dj * cout : (dj + 1) * cout]
        acc = term if acc is None else acc + term
    return acc.reshape(b, i, j, k, l, cout)


def _conv4d_cf1s(x, w, block=5):
    """`_conv4d_cf1` as a `lax.scan` over BLOCKS of the leading spatial dim.

    cf1's conv output is kj*kk/cout times the activation size (8 GB at the
    symmetric-batched training config) and OOMs whole; per-block it is
    1/ceil(i/block) of that, while the conv1d keeps a large enough M
    (b*block*j*k) to stay near cf1's measured MXU rate (small-M conv1d
    calls collapse to ~7 TFLOP/s; M >= ~1e5 measured ~84)."""
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pj, pk = ki // 2, kj // 2, kk // 2
    nb = -(-i // block)
    ipad = nb * block
    # pad i by the conv halo plus round-up so every block is full-size
    xpad = jnp.pad(x, ((0, 0), (pi, pi + ipad - i)) + ((0, 0),) * 4)
    w2 = w.transpose(3, 0, 4, 1, 2, 5).reshape(kl, ki * cin, kj * kk * cout)
    dn = lax.conv_dimension_numbers(
        (b * block * j * k, l, ki * cin), w2.shape, ("NWC", "WIO", "NWC")
    )

    def block_out(_, blk):
        window = lax.dynamic_slice_in_dim(
            xpad, blk * block, block + 2 * pi, axis=1
        )
        xs = jnp.concatenate(
            [window[:, di : di + block] for di in range(ki)], axis=-1
        )  # [b, block, j, k, l, ki*cin]
        y = lax.conv_general_dilated(
            xs.reshape(b * block * j * k, l, ki * cin),
            w2,
            window_strides=(1,),
            padding="SAME",
            dimension_numbers=dn,
            preferred_element_type=x.dtype,
        ).reshape(b * block, j, k, l, kj * kk * cout)
        ypad = jnp.pad(y, ((0, 0), (pj, pj), (pk, pk), (0, 0), (0, 0)))
        acc = None
        for dj in range(kj):
            for dk in range(kk):
                t = dj * kk + dk
                term = ypad[
                    :, dj : dj + j, dk : dk + k, :, t * cout : (t + 1) * cout
                ]
                acc = term if acc is None else acc + term
        return None, acc.reshape(b, block, j, k, l, cout)

    _, out = lax.scan(block_out, None, jnp.arange(nb))
    # [nb, b, block, j, k, l, cout] -> [b, nb*block, ...] -> trim round-up
    out = jnp.moveaxis(out, 0, 1).reshape(b, ipad, j, k, l, cout)
    return out[:, :i]


def _gemm_kernel(w):
    """[ki,kj,kk,kl,cin,cout] -> [(di, dl, c) rows, (dj, dk, o) cols]."""
    ki, kj, kk, kl, cin, cout = w.shape
    return w.transpose(0, 3, 4, 1, 2, 5).reshape(
        ki * kl * cin, kj * kk * cout
    )


def _gemm_epilogue(y, j, k, kj, kk, cout):
    """Shift-sum the (dj, dk) output-channel blocks of ``y`` over (j, k).

    ``y``: [..., j, k, l, kj*kk*cout] with block t = dj*kk + dk holding that
    tap pair's contribution. Channel blocks are sliced on the FUSED trailing
    dim (a trailing (kj, kk, cout) split would tile terribly on TPU).
    """
    pj, pk = kj // 2, kk // 2
    nb = y.ndim - 4  # leading batch-like dims
    ypad = jnp.pad(
        y, ((0, 0),) * nb + ((pj, pj), (pk, pk), (0, 0), (0, 0))
    )
    out = None
    ix = (slice(None),) * nb
    for dj in range(kj):
        for dk in range(kk):
            t = dj * kk + dk
            term = ypad[
                ix
                + (
                    slice(dj, dj + j),
                    slice(dk, dk + k),
                    slice(None),
                    slice(t * cout, (t + 1) * cout),
                )
            ]
            out = term if out is None else out + term
    return out


def _conv4d_gemm(x, w):
    """conv4d as ONE MXU GEMM: (di, dl) taps gathered into the contraction
    dim, (dj, dk) taps folded into output channels.

    K = ki*kl*cin and N = kj*kk*cout (400 at the PF-Pascal config — full
    128-lane MXU tiles with zero FLOP inflation; every narrower direct
    lowering measured <=30 TFLOP/s on v5e while a wide-lane conv ran at
    >130). M = b*i*j*k*l. The input-side gather materializes ki*kl shifted
    copies (bounded by the caller's loss chunking); the epilogue is the
    cheap (dj, dk) shift-sum.
    """
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pl_ = ki // 2, kl // 2
    xpad = jnp.pad(x, ((0, 0), (pi, pi), (0, 0), (0, 0), (pl_, pl_), (0, 0)))
    cols = jnp.concatenate(
        [
            xpad[:, di : di + i, :, :, dl : dl + l, :]
            for di in range(ki)
            for dl in range(kl)
        ],
        axis=-1,
    )  # [b, i, j, k, l, ki*kl*cin]
    y = jnp.einsum(
        "bijklK,KN->bijklN",
        cols,
        _gemm_kernel(w).astype(x.dtype),
        preferred_element_type=x.dtype,
    )
    return _gemm_epilogue(y, j, k, kj, kk, cout)


def _conv4d_gemms(x, w):
    """`_conv4d_gemm` as a `lax.scan` over the leading spatial dim:
    O(1/I) live memory for the gathered columns and tap outputs."""
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pl_ = ki // 2, kl // 2
    xpad = jnp.pad(x, ((0, 0), (pi, pi), (0, 0), (0, 0), (pl_, pl_), (0, 0)))
    w2 = _gemm_kernel(w).astype(x.dtype)

    def slice_out(_, out_i):
        window = lax.dynamic_slice_in_dim(xpad, out_i, ki, axis=1)
        cols = jnp.concatenate(
            [
                window[:, di, :, :, dl : dl + l, :]
                for di in range(ki)
                for dl in range(kl)
            ],
            axis=-1,
        )  # [b, j, k, l, ki*kl*cin]
        y = jnp.einsum(
            "bjklK,KN->bjklN", cols, w2, preferred_element_type=x.dtype
        )
        return None, _gemm_epilogue(y, j, k, kj, kk, cout)

    _, out = lax.scan(slice_out, None, jnp.arange(i))
    return jnp.moveaxis(out, 0, 1)


def _conv4d_gemm4(x, w):
    """conv4d as ONE GEMM with ALL ``k^4`` taps gathered into the
    contraction dim: rows ``[b, i*j*k*l]``, contraction ``k^4 * cin``
    (tap-major, channel-minor), no epilogue.

    This is the arithmetic mirror of the sparse band path
    (``ncnet_tpu/sparse/nc.py``) evaluated on the complete band: the
    gathered operand holds the same values in the same order and the
    flattened kernel is the same ``[k^4*cin, cout]`` matrix, so at
    ``K = hB*wB`` the band GEMM and this lowering agree BITWISE in eager
    mode — the exactness harness of tests/test_sparse.py. As a training
    impl it is memory-hungry (the gather materializes ``k^4`` shifted
    copies, vs `_conv4d_gemm`'s ``ki*kl``); use 'gemm'/'gemms' or the
    tap-folded impls for throughput.
    """
    b, i, j, k, l, cin = x.shape
    ki, kj, kk, kl, _, cout = w.shape
    pi, pj, pk, pl_ = ki // 2, kj // 2, kk // 2, kl // 2
    xpad = jnp.pad(
        x, ((0, 0), (pi, pi), (pj, pj), (pk, pk), (pl_, pl_), (0, 0))
    )
    # every slice is reshaped to 3D BEFORE the concat (law 1: >=6D
    # intermediates draw pathological TPU layouts)
    cols = jnp.concatenate(
        [
            xpad[:, d1 : d1 + i, d2 : d2 + j, d3 : d3 + k, d4 : d4 + l, :]
            .reshape(b, i * j * k * l, cin)
            for d1 in range(ki)
            for d2 in range(kj)
            for d3 in range(kk)
            for d4 in range(kl)
        ],
        axis=-1,
    )  # [b, M, k^4*cin], tap-major / channel-minor
    y = jnp.einsum(
        "bnf,fo->bno",
        cols,
        w.reshape(ki * kj * kk * kl * cin, cout).astype(x.dtype),
        preferred_element_type=x.dtype,
    )
    return y.reshape(b, i, j, k, l, cout)


def _flip_transpose(w):
    """Filters of the conv4d input-gradient identity: spatially flipped,
    in/out channels swapped (stride-1 SAME, odd kernels)."""
    # the identity only holds for odd kernels under SAME stride-1 padding;
    # an even kernel would yield silently wrong input gradients (raise, not
    # assert: input validation must survive python -O)
    if any(k % 2 == 0 for k in w.shape[:4]):
        raise ValueError(
            f"composite conv4d dx requires odd kernel sizes, got {w.shape[:4]}"
        )
    return jnp.flip(w, axis=(0, 1, 2, 3)).transpose(0, 1, 2, 3, 5, 4)


def _dw_fold(x, g, w_shape, block=0):
    """Direct conv4d kernel gradient as one wide MXU GEMM (or an i-blocked
    scan of them): ``dw[di,dj,dk,dl,c,o] = sum_{b,i,j,k,l}
    x[b, i+di-pi, j+dj-pj, k+dk-pk, l+dl-pl, c] * g[b,i,j,k,l,o]``.

    Fold the (dk, dl) taps into x's channel axis and the (di, dj) taps
    into g's channel axis; the whole gradient is then ONE
    ``[kk*kl*cin, ki*kj*cout]`` contraction over the zero-extended
    (b, i, j, k, l) volume — 400x400 full 128-lane MXU tiles at the NC
    middle layer, with only the (Ip*Jp)/(I*J) ~ 1.35x domain-padding FLOP
    inflation (vs 1.79x for the blocked-Toeplitz transpose and 5x for the
    dense one). The cost is gather traffic: both operands materialize at
    kk*kl (resp. ki*kj) times the activation size, so ``block`` bounds
    live memory by scanning over `block` rows of the padded leading dim
    and accumulating the (tiny) fp32 flat gradient.

    Gradient of the op the reference realises as torch autograd through
    its conv3d loop (lib/conv4d.py:39-48).
    """
    ki, kj, kk, kl, cin, cout = w_shape
    if any(k % 2 == 0 for k in w_shape[:4]):
        raise ValueError(
            f"_dw_fold requires odd kernel sizes, got {w_shape[:4]}"
        )
    b, I, J, K, L, _ = x.shape
    pi, pj, pk, pl = ki // 2, kj // 2, kk // 2, kl // 2
    Ip, Jp = I + 2 * pi, J + 2 * pj
    # x zero-embedded in (i, j) — the extended contraction domain — and
    # halo-padded in (k, l) for the window gather.
    xpad = jnp.pad(
        x, ((0, 0), (pi, pi), (pj, pj), (pk, pk), (pl, pl), (0, 0))
    )
    # g extended by the full shift range in i so per-block (di) row
    # windows are plain slices of a [s, s + rows + 2*pi) dynamic window.
    gpad = jnp.pad(g, ((0, 0), (2 * pi, 2 * pi)) + ((0, 0),) * 4)

    def block_dw(s, rows):
        # xg[q, jp, k, l, (dk, dl, c)] over padded-i rows [s, s+rows);
        # every slice is reshaped to 5D BEFORE the concat (law 1: >=6D
        # intermediates draw pathological TPU layouts).
        xw = lax.dynamic_slice_in_dim(xpad, s, rows, axis=1)
        xg = jnp.concatenate(
            [
                xw[:, :, :, dk : dk + K, dl : dl + L, :].reshape(
                    b * rows, Jp, K, L, cin
                )
                for dk in range(kk)
                for dl in range(kl)
            ],
            axis=-1,
        )
        # gg[q, jp, k, l, (di, dj, o)] = g[b, ip - di, jp - dj, k, l, o]
        # (zero outside): row ip = s + t of shift di is gpad row
        # s + t + 2*pi - di, and the dj shift is a zero-embed in j.
        gw = lax.dynamic_slice_in_dim(gpad, s, rows + 2 * pi, axis=1)
        gg = jnp.concatenate(
            [
                jnp.pad(
                    gw[:, 2 * pi - di : 2 * pi - di + rows],
                    ((0, 0), (0, 0), (dj, 2 * pj - dj)) + ((0, 0),) * 3,
                ).reshape(b * rows, Jp, K, L, cout)
                for di in range(ki)
                for dj in range(kj)
            ],
            axis=-1,
        )
        return jnp.einsum(
            "qjklX,qjklY->XY", xg, gg, preferred_element_type=jnp.float32
        )

    if block:
        nb = -(-Ip // block)
        # round the padded-i domain up to whole blocks; the extra zero
        # rows contribute nothing to the contraction
        xpad = jnp.pad(
            xpad, ((0, 0), (0, nb * block - Ip)) + ((0, 0),) * 4
        )
        gpad = jnp.pad(
            gpad,
            ((0, 0), (0, nb * block + 2 * pi - gpad.shape[1]))
            + ((0, 0),) * 4,
        )

        def body(acc, t):
            return acc + block_dw(t * block, block), None

        flat, _ = lax.scan(
            body,
            jnp.zeros((kk * kl * cin, ki * kj * cout), jnp.float32),
            jnp.arange(nb),
        )
    else:
        flat = block_dw(0, Ip)
    dw = flat.reshape(kk, kl, cin, ki, kj, cout).transpose(3, 4, 0, 1, 2, 5)
    return dw


def _dw_direct(dw_impl, x, g, w_shape):
    """Dispatch a DW_IMPLS name: 'dwe' = one GEMM, 'dweN' = N-row scan."""
    block = int(dw_impl[3:]) if len(dw_impl) > 3 else 0
    return _dw_fold(x, g, w_shape, block=block)


_COMPOSITE_CACHE = {}


def _composite_conv4d(fwd_impl, dx_impl, dw_impl=""):
    """conv4d with independent forward, input-gradient and kernel-gradient
    lowerings (impl string '<fwd>/<dx>' or '<fwd>/<dx>/<dw>'; empty dx/dw
    slots fall back to the autodiff linear transpose of the forward).

    Motivation (round 3, measured): XLA's autodiff transposes a conv in
    the SAME formulation as its forward. For the 16->1 NC layer under
    'tlc' that transpose is a 25-in/400-out-channel conv3d — 128-lane
    padding on the 25 side makes it ~66x the layer's true FLOPs and the
    single hottest op of the whole training step (66 ms of a 241 ms
    stack f+b). dx is itself a conv4d (flipped/transposed filters), so
    it can use whichever lowering fits ITS channel shape — 'tlc/btl'
    computes the same gradient as a 1->16-shaped 'btl' forward (~15 ms).

    The dw slot (round 4): a forward impl name transposes THAT
    formulation wrt w instead of the forward's own; a DW_IMPLS name
    ('dwe', 'dwe4', ...) computes the kernel gradient directly as the
    wide tap-folded GEMM of `_dw_fold`.
    """
    key = (fwd_impl, dx_impl, dw_impl)
    if key in _COMPOSITE_CACHE:
        return _COMPOSITE_CACHE[key]

    @jax.custom_vjp
    def f(x, w):
        return conv4d(x, w, impl=fwd_impl)

    def fwd(x, w):
        return f(x, w), (x, w)

    def bwd(res, g):
        x, w = res
        if dx_impl:
            dx = conv4d(g, _flip_transpose(w).astype(g.dtype), impl=dx_impl)
        else:
            # conv4d is linear in x: autodiff transpose of the forward
            transpose_x = jax.linear_transpose(
                lambda xx: conv4d(xx, w, impl=fwd_impl), x
            )
            (dx,) = transpose_x(g)
        if dw_impl in DW_IMPLS:
            dw = _dw_direct(dw_impl, x, g, w.shape).astype(w.dtype)
        else:
            # conv4d is linear in w: transpose the chosen formulation
            # directly (jax.vjp would evaluate and discard an extra primal)
            transpose_w = jax.linear_transpose(
                lambda ww: conv4d(x, ww, impl=dw_impl or fwd_impl), w
            )
            (dw,) = transpose_w(g)
        return dx, dw

    f.defvjp(fwd, bwd)
    _COMPOSITE_CACHE[key] = f
    return f


def _add_bias_flat(out, bias):
    """Bias add on the ``[b, M, c_out]`` flattened view (pure reshapes:
    elementwise-identical output). The REDUCE SHAPE of the bias gradient
    follows the shape the add happened on, and XLA's reduction order is
    factorization-dependent — adding on the flat view gives the bias
    gradient the same shape as the sparse band path's
    (``ncnet_tpu/sparse/nc.py``), which keeps the full-K sparse==dense
    training equivalence bitwise instead of merely ULP-close."""
    if bias is None:
        return out
    b = out.shape[0]
    cout = out.shape[-1]
    flat = out.reshape(b, -1, cout) + bias
    return flat.reshape(out.shape)


def conv4d(x, w, bias=None, impl="xla", interpret=None):
    """SAME, stride-1 4D convolution.

    Args:
      x: ``[b, i, j, k, l, c_in]``.
      w: ``[ki, kj, kk, kl, c_in, c_out]`` (odd kernel sizes).
      bias: optional ``[c_out]``, added once (reference bias-at-center-tap
        semantics, lib/conv4d.py:41-48).
      impl: 'xla' (one rank-4 conv HLO) | 'taps' (per-tap conv3d sum) |
        'scan' (sequential over i, minimal memory) | 'tlc' (Toeplitz-l
        conv3d, 5x FLOPs but wide lanes) | 'btl'/'btl2'/'btl3'/'btl4'/
        'btl5'/'btl6' (blocked Toeplitz-l at block 8/2/3/4/5/6: lower
        FLOP inflation, narrower lanes; block 4 is the measured sweet
        spot for the 16->16 middle NC layer) | 'tlcv' (tlc forward + custom
        VJP with a true-FLOP rank-4 kernel gradient — measured SLOWER
        end-to-end than tlc, kept as a documented negative result) |
        'tf3'/'tf2' (taps folded into
        output channels + shift-sum) | 'cf'/'cfs' (taps folded into BOTH
        input and output channels of one conv2d — true FLOPs, wide lanes
        both directions; 'cfs' is the scanned low-memory variant) |
        'cf1' (ki taps into input channels, (kj, kk) taps into output
        channels, conv1d over l: true FLOPs with ki*cin / kj*kk*cout
        lanes — the measured-best middle-layer impl, at a large transient
        memory cost) |
        'gemm'/'gemms' ((di, dl) taps gathered into the contraction dim,
        (dj, dk) into output channels: ONE full-lane MXU GEMM, true FLOPs;
        'gemms' is the scanned low-memory variant) |
        'gemm4' (ALL k^4 taps in the contraction dim, no epilogue — the
        arithmetic mirror of the sparse band path at full K, kept as the
        bitwise-equivalence reference; k^4 gather copies make it a
        memory-hungry training choice) |
        'pallas' (hand-written TPU kernel on the packed layout,
        kernels/conv4d_pallas.py; hypercubic kernels only).
      interpret: for impl='pallas' only — see `conv4d_packed`.

    Returns:
      ``[b, i, j, k, l, c_out]``.
    """
    if impl == "pallas":
        b, i, j, k, l, cin = x.shape
        cout = w.shape[-1]
        out = conv4d_packed(
            x.reshape(b, i, j, k * l * cin), w, (k, l), bias=bias,
            impl="pallas", interpret=interpret,
        )
        return out.reshape(b, i, j, k, l, cout)
    if "/" in impl:
        if not is_valid_impl(impl):
            raise ValueError(
                f"invalid composite conv4d impl {impl!r} (expect "
                "'<fwd>/<dx>' or '<fwd>/<dx>/<dw>' with names from "
                "CONV4D_IMPLS — dw also accepts DW_IMPLS; dx/dw may be "
                "empty meaning 'autodiff transpose of the forward')"
            )
        parts = impl.split("/")
        out = _composite_conv4d(*parts)(x, w)
        return _add_bias_flat(out, bias)
    if impl == "xla":
        out = _conv4d_xla(x, w)
    elif impl == "taps":
        out = _conv4d_taps(x, w)
    elif impl == "scan":
        out = _conv4d_scan(x, w)
    elif impl == "tlc":
        out = _conv4d_tlc(x, w)
    elif impl == "btl":
        out = _conv4d_btl(x, w)
    elif impl in CONV4D_IMPLS and impl.startswith("btl") and impl[3:].isdigit():
        out = _conv4d_btl(x, w, block=int(impl[3:]))
    elif impl == "tlcv":
        out = _conv4d_tlcv(x, w)
    elif impl == "tf3":
        out = _conv4d_tapsfused3(x, w)
    elif impl == "tf2":
        out = _conv4d_tapsfused2(x, w)
    elif impl == "cf":
        out = _conv4d_cf(x, w)
    elif impl == "cfs":
        out = _conv4d_cfs(x, w)
    elif impl == "cf1":
        out = _conv4d_cf1(x, w)
    elif impl == "cf1s":
        out = _conv4d_cf1s(x, w)
    elif impl == "ck1":
        out = _conv4d_ck1(x, w)
    elif impl == "tk1":
        out = _conv4d_tk1(x, w)
    elif impl == "gemm":
        out = _conv4d_gemm(x, w)
    elif impl == "gemms":
        out = _conv4d_gemms(x, w)
    elif impl == "gemm4":
        out = _conv4d_gemm4(x, w)
    else:
        raise ValueError(f"unknown conv4d impl: {impl!r}")
    return _add_bias_flat(out, bias)
