"""4D convolution — the core custom primitive of neighbourhood consensus.

Semantics (shared by every impl): zero-padded SAME convolution with stride 1
over the four correlation dims, bias added once — identical math to the
reference's ``conv4d`` (lib/conv4d.py:11-51), which decomposes into a Python
loop of conv3d calls with the bias applied only on the center tap. A standard
4D convolution plus a single bias term is exactly that sum, so no special
bias handling is needed here.

Layout is channels-last: inputs ``[b, i, j, k, l, c_in]``, filters
``[ki, kj, kk, kl, c_in, c_out]``.

Implementations:
  * ``impl='xla'`` (default): one `lax.conv_general_dilated` with FOUR spatial
    dimensions. XLA's convolution HLO is rank-generic and the TPU backend
    lowers it onto the MXU directly — one fused op, no Python-level looping.
  * ``impl='taps'``: decomposition over the leading kernel dim: a 3D
    convolution of the full tensor per tap, shifted and summed along ``i``.
    Useful as a cross-check and on backends without 4-spatial-dim support.
  * ``impl='scan'``: `lax.scan` over output slices of the leading spatial
    dim, one small 3D convolution stack per slice — the sequential
    formulation of the reference's Python loop (lib/conv4d.py:39-48), but
    compiled. O(1/I) live memory vs 'xla'/'taps': the memory-safe choice
    for training, where the TPU layouts of the one-shot impls pad the big
    6D temps 4-5x (see bench notes).
"""

import jax.numpy as jnp
from jax import lax


def _conv4d_xla(x, w):
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, ("NijklC", "ijklIO", "NijklC")
    )
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=(1, 1, 1, 1),
        padding="SAME",
        dimension_numbers=dn,
        preferred_element_type=x.dtype,
    )


def _conv4d_taps(x, w):
    """Sum over taps of the leading kernel dim, each a 3D convolution."""
    ki = w.shape[0]
    pad = ki // 2
    b, i, j, k, l, cin = x.shape
    dn3 = lax.conv_dimension_numbers(
        (b * i, j, k, l, cin),
        w.shape[1:],
        ("NjklC", "jklIO", "NjklC"),
    )
    x3 = x.reshape(b * i, j, k, l, cin)
    out = None
    for p in range(ki):
        y = lax.conv_general_dilated(
            x3,
            w[p],
            window_strides=(1, 1, 1),
            padding="SAME",
            dimension_numbers=dn3,
            preferred_element_type=x.dtype,
        )
        y = y.reshape(b, i, j, k, l, -1)
        # out[:, m] += y[:, m + p - pad]  -> shift y by (pad - p) with zero fill
        shift = pad - p
        if shift > 0:
            y = jnp.pad(y[:, :-shift], ((0, 0), (shift, 0)) + ((0, 0),) * 4)
        elif shift < 0:
            y = jnp.pad(y[:, -shift:], ((0, 0), (0, -shift)) + ((0, 0),) * 4)
        out = y if out is None else out + y
    return out


def _conv4d_scan(x, w):
    ki = w.shape[0]
    pad = ki // 2
    b, i, j, k, l, cin = x.shape
    dn3 = lax.conv_dimension_numbers(
        (b, j, k, l, cin), w.shape[1:], ("NjklC", "jklIO", "NjklC")
    )
    xpad = jnp.pad(x, ((0, 0), (pad, pad)) + ((0, 0),) * 4)

    def slice_out(_, out_i):
        window = lax.dynamic_slice_in_dim(xpad, out_i, ki, axis=1)
        acc = None
        for p in range(ki):
            y = lax.conv_general_dilated(
                window[:, p],
                w[p],
                window_strides=(1, 1, 1),
                padding="SAME",
                dimension_numbers=dn3,
                preferred_element_type=x.dtype,
            )
            acc = y if acc is None else acc + y
        return None, acc

    _, out = lax.scan(slice_out, None, jnp.arange(i))
    # scan stacks on axis 0: [i, b, j, k, l, cout] -> [b, i, ...]
    return jnp.moveaxis(out, 0, 1)


def conv4d_packed(xp, w, kl_shape, bias=None):
    """4D convolution on the fused layout ``[b, i, j, k*l*c]`` (c fastest).

    TPU memory-layout native: the channels-minor 6D activation layout pads
    HBM 8x under (sublane, lane) tiling (c<=16 padded to 128 lanes) — the
    measured cause of training OOM at the reference's batch-16 config on a
    16G v5e, and XLA's layout assignment re-derives that layout even for a
    transposed logical shape. Fusing (k, l, c) into ONE trailing dim (c
    fastest — a pure reshape of the conv's natural NjklC layout) removes the
    small dim from tiling entirely: padding drops to ~1%. The conv scans
    over the leading spatial dim; only per-window slices are ever reshaped
    back to 6D, in both the forward and the scanned backward.

    Args:
      xp: ``[b, i, j, k*l*c_in]``, element order (k, l, c) with c fastest.
      w: ``[ki, kj, kk, kl, c_in, c_out]``.
      kl_shape: the static (k, l) factorization of the fused dim.
      bias: optional ``[c_out]``.

    Returns:
      ``[b, i, j, k*l*c_out]``.
    """
    ki = w.shape[0]
    pad = ki // 2
    b, i, j, fused = xp.shape
    k, l = kl_shape
    cin = w.shape[-2]
    cout = w.shape[-1]
    assert k * l * cin == fused, (kl_shape, cin, fused)
    dn3 = lax.conv_dimension_numbers(
        (b, j, k, l, cin), w.shape[1:], ("NjklC", "jklIO", "NjklC")
    )
    xpad = jnp.pad(xp, ((0, 0), (pad, pad), (0, 0), (0, 0)))

    def slice_out(_, out_i):
        window = lax.dynamic_slice_in_dim(xpad, out_i, ki, axis=1)
        acc = None
        for p in range(ki):
            xs = window[:, p].reshape(b, j, k, l, cin)  # pure reshape
            y = lax.conv_general_dilated(
                xs,
                w[p],
                window_strides=(1, 1, 1),
                padding="SAME",
                dimension_numbers=dn3,
                preferred_element_type=xp.dtype,
            )
            acc = y if acc is None else acc + y
        if bias is not None:
            acc = acc + bias
        return None, acc.reshape(b, j, k * l * cout)

    _, out = lax.scan(slice_out, None, jnp.arange(i))
    return jnp.moveaxis(out, 0, 1)  # [b, i, j, k*l*cout]


def conv4d(x, w, bias=None, impl="xla"):
    """SAME, stride-1 4D convolution.

    Args:
      x: ``[b, i, j, k, l, c_in]``.
      w: ``[ki, kj, kk, kl, c_in, c_out]`` (odd kernel sizes).
      bias: optional ``[c_out]``, added once (reference bias-at-center-tap
        semantics, lib/conv4d.py:41-48).
      impl: 'xla' | 'taps'.

    Returns:
      ``[b, i, j, k, l, c_out]``.
    """
    if impl == "xla":
        out = _conv4d_xla(x, w)
    elif impl == "taps":
        out = _conv4d_taps(x, w)
    elif impl == "scan":
        out = _conv4d_scan(x, w)
    else:
        raise ValueError(f"unknown conv4d impl: {impl!r}")
    if bias is not None:
        out = out + bias
    return out
