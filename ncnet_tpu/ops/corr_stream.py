"""Streaming tiled correlation -> top-K band selection (corr_impl='stream').

`sparse_match_pipeline` historically materialized the full dense
``[b, hA, wA, hB, wB]`` correlation (``correlation_4d``) just to run
``topk_band`` over it — making corr materialization the memory highwater
of the sparse train, serve, and refine-coarse paths even though
everything downstream of selection is O(K)-sparse. This module computes
the SAME band without ever materializing the volume: B's grid is tiled,
each ``[b, hA*wA, tile]`` correlation slab is one MXU GEMM, and the slab
is folded into a running per-A-cell top-K merge under ``lax.scan``,
together with the running row/col maxima the soft mutual-matching gate
needs. Peak memory drops from O(nA*nB) to O(nA*(K + tile)) for the
non-mutual band (plus an O(nB*K) column-candidate table and an
O(nA*K^2) membership transient for ``mutual=True`` — still free of any
nA*nB term).

Exactness contract (pinned in tests/test_corr_stream.py): eagerly, the
streamed band is BITWISE equal — values and indices, mutual on and off,
rectangular grids, tiles that do not divide hB*wB — to the dense
reference

    corr  = correlation_4d(feat_a, feat_b)
    gated = mutual_matching(corr, eps)
    topk_band(corr, k, values_from=gated, mutual=mutual)

This works because (a) a ``[b, nA, tile]`` einsum slab is bitwise equal
to the corresponding slice of the full ``bijc,bklc->bijkl`` einsum (same
contraction shape per output element; verified for f32 and bf16 on the
CPU backend), (b) the top-K merge invariant top-K(top-K(S1) ∪ S2) =
top-K(S1 ∪ S2) holds under the total order (value desc, index asc) that
``lax.top_k`` resolves ties with, and (c) max is exact and associative,
so the running row maxima equal the dense ``jnp.max`` reductions and the
per-column maxima are complete within the single tile that owns the
column. A ±0.0-signed row/col max cannot leak into the gate: the maxima
are only ever consumed as ``max + eps``, which maps both zeros to the
same sum.

Mutual selection streams exactly via a candidate-superset theorem: the
dense key is ``min(rank_a, rank_b) * nb + rank_a`` and every selected
entry satisfies ``min(rank_a, rank_b) < K`` (any entry with
``rank_a < K`` has key ``<= (K-1)*nb + nb-1 < K*nb``, which bounds every
key with ``min >= K`` from below), so the selected set is contained in
(row top-K by value) ∪ (column top-K by value). Row candidates carry
their exact ``rank_a`` (their position in the merged row list);
``rank_b`` is recovered by membership lookup in the owning column's
top-Kc table (absence implies ``rank_b >= Kc >= min(K, nA)``, in which
case ``min = rank_a`` already). Column candidates absent from the row
list have ``rank_a >= K``, so their dense key ``(rank_b, rank_a)``
ordering reduces to ``(rank_b, value desc, column asc)`` — no global
rank needed. Their per-row grouping uses one static boundary scatter
(the ``band_to_dense`` precedent: selection runs once, O(nB*K) sized —
the dense reference itself materializes O(nA*nB) rank matrices here).
A convenient corollary: ``mutual=True`` needs no int32 rank-key, so the
streamed path lifts the dense ``nb <= 46340`` mutual limit (selection is
identical wherever both are defined).

The custom VJP is gather-only (the band backward discipline, see
``sparse/nc.py``): cotangents route through the selected entries, the
row-max entry ``(a, argmax_row a)`` and the col-max entry
``(argmax_col j, j)``; ``d feat_b`` accumulates per B-tile under a
second scan, so the backward never materializes nA*nB either and
contains no scatter. Where the dense ``jnp.max`` VJP splits a tied
maximum evenly, this routing picks the FIRST argmax — a measure-zero
divergence on real features, and the forward (which is what the bitwise
contract covers) is unaffected.

Not supported: ``correlation_4d(normalization=True)`` (unused by
ImMatchNet) and non-finite features (selection order under NaN is
unspecified, exactly as for ``lax.top_k``).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def resolve_corr_tile(tile, nb):
    """Clamp the static B-grid tile to ``[1, nb]``, rejecting nonsense."""
    t = int(tile)
    if t <= 0:
        raise ValueError(
            f"corr stream tile={t} must be positive (it is the static "
            "B-grid slab width of the streaming GEMM)"
        )
    return min(t, int(nb))


def _check_band_width(k, nb):
    if not 1 <= k <= nb:
        raise ValueError(
            f"band width k={k} must be in [1, hB*wB={nb}] for the "
            "streamed correlation band"
        )


def _tiles_of(fb_flat, tile):
    """Pad the flattened B grid to a multiple of ``tile`` and split it
    into scan-major ``[s, b, tile, c]`` slabs plus per-tile global column
    ids and validity masks."""
    b, nb, c = fb_flat.shape
    s = -(-nb // tile)
    pad = s * tile - nb
    fbp = jnp.pad(fb_flat, ((0, 0), (0, pad), (0, 0)))
    tiles = fbp.reshape(b, s, tile, c).transpose(1, 0, 2, 3)
    cols = jnp.arange(s * tile, dtype=jnp.int32).reshape(s, tile)
    valid = cols < nb
    return tiles, cols, valid, s


def _stack_cols(ys, nb):
    """Un-tile a scan-stacked ``[s, b, tile, ...]`` output back to
    column-major ``[b, nb, ...]`` (dropping the padded columns)."""
    s, b, t = ys.shape[:3]
    rest = ys.shape[3:]
    out = ys.transpose(1, 0, 2, *range(3, ys.ndim))
    return out.reshape(b, s * t, *rest)[:, :nb]


def _stream_scan(fa_flat, fb_flat, k, mutual, tile):
    """One pass over B's tiles.

    Returns the raw-value row top-K ``(vals, idx)`` in row-rank order
    (position == rank_a — the merge keeps the list sorted by
    (value desc, index asc), the exact ``lax.top_k`` tie order), the
    running row maxima/argmaxima, the per-column maxima/argmaxima, and
    (mutual only) the per-column top-Kc value/row tables.
    """
    b, na, c = fa_flat.shape
    nb = fb_flat.shape[1]
    dt = fa_flat.dtype
    neg_inf = jnp.array(-jnp.inf, dt)
    kc = min(k, na)
    idx_sentinel = jnp.int32(nb)

    tiles, cols, valid, _ = _tiles_of(fb_flat, tile)

    def step(carry, xs):
        vals, idx, rm, argrm = carry
        fb_tile, col, ok = xs
        # the slab: bitwise equal to the dense einsum's column slice
        slab = jnp.einsum(
            "bnc,btc->bnt", fa_flat, fb_tile, preferred_element_type=dt
        )
        slab = jnp.where(ok[None, None, :], slab, neg_inf)
        gidx = jnp.where(ok, col, idx_sentinel)
        # fold the slab into the running row top-K: sort the K + tile
        # candidates by (value desc, index asc) — lax.top_k's tie order —
        # and keep the first K. top-K(top-K(S1) ∪ S2) == top-K(S1 ∪ S2).
        cand_v = jnp.concatenate([vals, slab], axis=-1)
        cand_i = jnp.concatenate(
            [idx, jnp.broadcast_to(gidx[None, None, :], slab.shape)],
            axis=-1,
        )
        neg_v, new_i = lax.sort((-cand_v, cand_i), dimension=-1, num_keys=2)
        vals, idx = -neg_v[..., :k], new_i[..., :k]
        # running row maximum; strict > keeps the FIRST argmax (VJP
        # routing only — the forward never reads argrm)
        tmax = jnp.max(slab, axis=-1)
        targ = jnp.take(
            gidx, jnp.argmax(slab, axis=-1), mode="clip"  # always in range
        )
        argrm = jnp.where(tmax > rm, targ, argrm)
        rm = jnp.maximum(rm, tmax)
        # column statistics are COMPLETE within the owning tile
        cmax = jnp.max(slab, axis=1)
        carg = jnp.argmax(slab, axis=1).astype(jnp.int32)
        if mutual:
            cv, ca = lax.top_k(jnp.swapaxes(slab, 1, 2), kc)
            ys = (cmax, carg, cv, ca.astype(jnp.int32))
        else:
            ys = (cmax, carg)
        return (vals, idx, rm, argrm), ys

    init = (
        jnp.full((b, na, k), neg_inf, dt),
        jnp.full((b, na, k), idx_sentinel, jnp.int32),
        jnp.full((b, na), neg_inf, dt),
        jnp.zeros((b, na), jnp.int32),
    )
    (vals, idx, rm, argrm), ys = lax.scan(step, init, (tiles, cols, valid))
    cm, argcm = _stack_cols(ys[0], nb), _stack_cols(ys[1], nb)
    ctab = None
    if mutual:
        ctab = (_stack_cols(ys[2], nb), _stack_cols(ys[3], nb))
    return vals, idx, rm, argrm, cm, argcm, ctab


def _mutual_select(vals, idx, ctab_v, ctab_a, k):
    """Exact ``mutual=True`` selection from the streamed candidates.

    ``vals``/``idx`` are the row top-K in row-rank order (position ==
    rank_a); ``ctab_v``/``ctab_a`` are the per-column top-Kc tables
    (position == rank_b). Reproduces the dense key ``(min(ra, rb), ra)``
    ordering on the candidate superset — see the module docstring for
    why the superset is complete and why (value desc, column asc)
    substitutes for rank_a among column-only candidates.
    """
    b, na, _ = vals.shape
    nb, kc = ctab_v.shape[1], ctab_v.shape[2]
    kk = jnp.int32(k)
    trash = jnp.int32(na)

    # rank_b of each row candidate: its position in the owning column's
    # table (absence => rank_b >= Kc, where min(ra, rb) = ra already)
    calist = jnp.take_along_axis(
        ctab_a,
        idx.reshape(b, na * k)[..., None],
        axis=1,
        mode="promise_in_bounds",
    ).reshape(b, na, k, kc)
    hit = calist == jnp.arange(na, dtype=jnp.int32)[None, :, None, None]
    q = jnp.argmax(hit, axis=-1).astype(jnp.int32)
    p = jnp.broadcast_to(
        jnp.arange(k, dtype=jnp.int32)[None, None, :], (b, na, k)
    )
    rb_row = jnp.where(jnp.any(hit, axis=-1), q, kk)
    k1_row = jnp.minimum(p, rb_row)

    # column-only (class-3) candidates: flatten the tables to one entry
    # list, drop entries already in their row's top-K list, group the
    # survivors by row with a stable 4-key sort, and keep the best K per
    # row (>= K better same-row column candidates rule an entry out)
    e = nb * kc
    a_e = ctab_a.reshape(b, e)
    neg_e = -ctab_v.reshape(b, e)
    j_e = jnp.broadcast_to(
        jnp.arange(nb, dtype=jnp.int32)[None, :, None], (b, nb, kc)
    ).reshape(b, e)
    rb_e = jnp.broadcast_to(
        jnp.arange(kc, dtype=jnp.int32)[None, None, :], (b, nb, kc)
    ).reshape(b, e)
    rlist = jnp.take_along_axis(
        idx, a_e[..., None], axis=1, mode="promise_in_bounds"
    )
    in_row = jnp.any(rlist == j_e[..., None], axis=-1)
    a_key = jnp.where(in_row, trash, a_e)
    a_s, rb_s, neg_s, j_s = lax.sort(
        (a_key, rb_e, neg_e, j_e), dimension=-1, num_keys=4
    )
    eids = jnp.arange(e, dtype=jnp.int32)[None, :]
    first = jnp.concatenate(
        [jnp.ones((b, 1), bool), a_s[:, 1:] != a_s[:, :-1]], axis=1
    )
    pos = eids - lax.cummax(jnp.where(first, eids, 0), axis=1)
    keep = (pos < k) & (a_s < trash)
    a_scat = jnp.where(keep, a_s, trash)
    pos_scat = jnp.where(keep, pos, 0)
    # the one static boundary scatter (band_to_dense precedent): row-
    # grouped class-3 buffers, sentinel-initialized so empty slots sort
    # after every real candidate (real primary keys are < K)
    bi = jnp.arange(b, dtype=jnp.int32)[:, None]
    shape3 = (b, na + 1, k)
    c3_k1 = (
        jnp.full(shape3, kk)
        .at[bi, a_scat, pos_scat]
        .set(rb_s, mode="promise_in_bounds")[:, :na]
    )
    c3_nv = (
        jnp.zeros(shape3, vals.dtype)
        .at[bi, a_scat, pos_scat]
        .set(neg_s, mode="promise_in_bounds")[:, :na]
    )
    c3_j = (
        jnp.full(shape3, jnp.int32(nb))
        .at[bi, a_scat, pos_scat]
        .set(j_s, mode="promise_in_bounds")[:, :na]
    )

    # final per-row merge of the 2K candidates under the dense order:
    # (min-rank, rank_a-or-K, value desc, column asc). Row candidates
    # have unique exact rank_a < K; class-3 ties resolve by the last two
    # keys, the row-rank order restricted to rank_a >= K entries.
    m_k1 = jnp.concatenate([k1_row, c3_k1], axis=-1)
    m_k2 = jnp.concatenate(
        [p, jnp.broadcast_to(kk, (b, na, k))], axis=-1
    )
    m_nv = jnp.concatenate([-vals, c3_nv], axis=-1)
    m_j = jnp.concatenate([idx, c3_j], axis=-1)
    _, _, s_nv, s_j = lax.sort(
        (m_k1, m_k2, m_nv, m_j), dimension=-1, num_keys=4
    )
    return -s_nv[..., :k], s_j[..., :k]


def _gate(vraw, rm, cm_sel, eps):
    """The mutual-matching soft gate on band entries — the exact
    elementwise form of ``ops.matching.mutual_matching`` restricted to
    the selected cells: value * (value/(rowmax+eps)) * (value/(colmax+
    eps)), grouped as the dense op groups it."""
    ratio_a = vraw / (rm + eps)
    ratio_b = vraw / (cm_sel + eps)
    return vraw * (ratio_a * ratio_b)


def _forward(feat_a, feat_b, k, mutual, tile, eps):
    b, ha, wa, c = feat_a.shape
    _, hb, wb, _ = feat_b.shape
    na, nb = ha * wa, hb * wb
    fa_flat = feat_a.reshape(b, na, c)
    fb_flat = feat_b.reshape(b, nb, c)

    vals, idx, rm, argrm, cm, argcm, ctab = _stream_scan(
        fa_flat, fb_flat, k, mutual, tile
    )
    if mutual:
        vals, idx = _mutual_select(vals, idx, ctab[0], ctab[1], k)
    # canonical band order: indices ascending per A-cell (dense
    # `jnp.sort(idx)`); selected columns are unique, so the 1-key stable
    # sort is a deterministic permutation carrying the values along
    idx, vraw = lax.sort((idx, vals), dimension=-1, num_keys=1)
    cm_sel = jnp.take_along_axis(
        cm, idx.reshape(b, na * k), axis=1, mode="promise_in_bounds"
    ).reshape(b, na, k)
    values = _gate(vraw, rm[..., None], cm_sel, eps)
    shape = (b, ha, wa, k)
    return (
        (values.reshape(shape), idx.reshape(shape)),
        (feat_a, feat_b, vraw, idx, rm, argrm, cm, argcm),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _stream_band(feat_a, feat_b, k, mutual, tile, eps):
    out, _ = _forward(feat_a, feat_b, k, mutual, tile, eps)
    return out


def _stream_band_fwd(feat_a, feat_b, k, mutual, tile, eps):
    return _forward(feat_a, feat_b, k, mutual, tile, eps)


def _stream_band_bwd(k, mutual, tile, eps, res, ct):
    """Gather-only backward (no scatter, no nA*nB tensor).

    Each selected value is v = c^3 / ((rm+eps)(cm+eps)) with c the raw
    correlation at the cell, rm/cm the row/column maxima — themselves
    dot products at the (first-)argmax cells carried from the forward.
    Cotangents route through exactly those three dot products; d feat_b
    accumulates per B-tile under a scan so the transients stay
    O(nA * K * tile).
    """
    feat_a, feat_b, vraw, idx, rm, argrm, cm, argcm = res
    dval = ct[0]  # ct[1] is the float0 cotangent of the int32 indices
    b, ha, wa, c = feat_a.shape
    _, hb, wb, _ = feat_b.shape
    na, nb = ha * wa, hb * wb
    fa_flat = feat_a.reshape(b, na, c)
    fb_flat = feat_b.reshape(b, nb, c)
    dv = dval.reshape(b, na, k)

    rmx = rm[..., None] + eps
    cms = (
        jnp.take_along_axis(
            cm, idx.reshape(b, na * k), axis=1, mode="promise_in_bounds"
        ).reshape(b, na, k)
        + eps
    )
    val = vraw * ((vraw / rmx) * (vraw / cms))
    g_c = dv * (3.0 * vraw * vraw) / (rmx * cms)
    rm_terms = dv * (-val / rmx)
    cm_terms = dv * (-val / cms)
    d_rm = jnp.sum(rm_terms, axis=-1)

    # d feat_a: selected entries and the row-max entry are plain gathers
    fb_sel = jnp.take_along_axis(
        fb_flat,
        idx.reshape(b, na * k)[..., None],
        axis=1,
        mode="promise_in_bounds",
    ).reshape(b, na, k, c)
    dfa = jnp.einsum("bak,bakc->bac", g_c, fb_sel)
    fb_rm = jnp.take_along_axis(
        fb_flat, argrm[..., None], axis=1, mode="promise_in_bounds"
    )
    dfa = dfa + d_rm[..., None] * fb_rm

    # d feat_b (and the col-max share of d feat_a), one B-tile at a time
    tiles, cols, _, s = _tiles_of(fb_flat, tile)
    pad = s * tile - nb
    argcm_tiles = (
        jnp.pad(argcm, ((0, 0), (0, pad)))
        .reshape(b, s, tile)
        .transpose(1, 0, 2)
    )
    a_ids = jnp.arange(na, dtype=jnp.int32)

    def step(dfa_carry, xs):
        fb_tile, col, acm = xs
        onehot = (idx[..., None] == col[None, None, None, :]).astype(
            g_c.dtype
        )
        w_fb = jnp.einsum("bak,bakt->bat", g_c, onehot)
        dcm_t = jnp.einsum("bak,bakt->bt", cm_terms, onehot)
        oh_rm = (argrm[..., None] == col[None, None, :]).astype(g_c.dtype)
        w_fb = w_fb + oh_rm * d_rm[..., None]
        dfb_tile = jnp.einsum("bat,bac->btc", w_fb, fa_flat)
        # column-max routing: column j's max row gets dcm_j * fb[j] ...
        oh_cm = (acm[:, None, :] == a_ids[None, :, None]).astype(g_c.dtype)
        dfa_carry = dfa_carry + jnp.einsum(
            "bat,btc->bac", oh_cm * dcm_t[:, None, :], fb_tile
        )
        # ... and fb[j] gets dcm_j * fa[argmax_col j] (a gather)
        fa_cm = jnp.take_along_axis(
            fa_flat, acm[..., None], axis=1, mode="promise_in_bounds"
        )
        dfb_tile = dfb_tile + dcm_t[..., None] * fa_cm
        return dfa_carry, dfb_tile

    dfa, dfb_tiles = lax.scan(step, dfa, (tiles, cols, argcm_tiles))
    dfb = _stack_cols(dfb_tiles, nb)
    return dfa.reshape(feat_a.shape), dfb.reshape(feat_b.shape)


_stream_band.defvjp(_stream_band_fwd, _stream_band_bwd)


def corr_stream_band(feat_a, feat_b, k, mutual=False, tile=128, eps=1e-5):
    """Streamed correlation band: bitwise equal to

        corr = correlation_4d(feat_a, feat_b)
        topk_band(corr, k, values_from=mutual_matching(corr, eps),
                  mutual=mutual)

    without materializing ``corr``.

    Args:
      feat_a: ``[b, hA, wA, c]`` source features (channels-last).
      feat_b: ``[b, hB, wB, c]`` target features.
      k: static band width, ``1 <= k <= hB*wB``.
      mutual: symmetric rank-union selection (see ``topk_band``). The
        streamed path has no int32 rank-key, so it lifts the dense
        ``hB*wB <= 46340`` mutual limit.
      tile: static B-grid slab width of the streaming GEMM (clamped to
        ``hB*wB``). Peak memory scales with ``hA*wA*(k + tile)``; larger
        tiles amortize the merge over bigger MXU GEMMs.
      eps: the mutual-matching gate epsilon (``mutual_matching``'s
        default). Static.

    Returns:
      ``(values [b, hA, wA, K], indices int32 [b, hA, wA, K])`` with
      indices sorted ascending per A-cell — the `topk_band` contract.
    """
    _, hb, wb, _ = feat_b.shape
    nb = hb * wb
    k = int(k)
    _check_band_width(k, nb)
    t = resolve_corr_tile(tile, nb)
    return _stream_band(feat_a, feat_b, k, bool(mutual), t, float(eps))
