"""Soft mutual-nearest-neighbour filtering and 4D max-pooling."""

import jax.numpy as jnp
import numpy as np


def mutual_matching(corr, eps=1e-5):
    """Soft mutual-NN gate on a 4D correlation tensor.

    ``out = corr * (corr / (max_over_B + eps)) * (corr / (max_over_A + eps))``
    where ``max_over_A`` reduces over the (iA, jA) dims and ``max_over_B``
    over (iB, jB). Mirrors the reference ``MutualMatching``
    (lib/model.py:155-175), eps 1e-5; the two ratio factors are multiplied
    together before scaling ``corr`` so the output is symmetric in A/B.

    Args:
      corr: ``[b, iA, jA, iB, jB]``.
    """
    max_over_a = jnp.max(corr, axis=(1, 2), keepdims=True)
    max_over_b = jnp.max(corr, axis=(3, 4), keepdims=True)
    ratio_b = corr / (max_over_a + eps)  # best-over-A normalization
    ratio_a = corr / (max_over_b + eps)  # best-over-B normalization
    return corr * (ratio_a * ratio_b)


def maxpool4d(corr, k_size):
    """4D max-pool with stride ``k_size`` over all four dims, with offsets.

    Returns the pooled tensor plus the within-cell argmax offsets
    ``(di, dj, dk, dl)`` used to restore fine coordinates at readout —
    reference ``maxpool4d`` (lib/model.py:177-191). Offset encoding matches
    the reference slice enumeration: combined index ``((di*k+dj)*k+dk)*k+dl``.

    Args:
      corr: ``[b, iA, jA, iB, jB]`` with all four spatial dims divisible by
        ``k_size``.

    Returns:
      ``(pooled, (di, dj, dk, dl))``; pooled is
      ``[b, iA/k, jA/k, iB/k, jB/k]``, offsets are int32 of the same shape.

    Implementation note: formulated as a strided-slice max-accumulation
    over the ``k^4`` within-cell offsets — the same shape the fused
    `ops.correlation.correlation_maxpool4d` uses — with every
    intermediate a 5D tensor. The previous blocked formulation built a
    transposed 9D intermediate, and the repo's measured layout law is
    that >=6D intermediates draw pathological TPU layouts (4-10x tile
    padding — bench.py header, benchmarks/PERF.md). Offsets are
    identical: enumeration runs in ascending combined-offset order with
    a strict ``>``, so ties keep the FIRST maximum exactly like argmax
    over the reference's slice enumeration (lib/model.py:177-191).
    See benchmarks/micro_maxpool.py for the measured comparison.
    """
    k = int(k_size)
    b, d1, d2, d3, d4 = corr.shape
    pooled_shape = (b, d1 // k, d2 // k, d3 // k, d4 // k)
    neg_inf = (
        -jnp.inf
        if jnp.issubdtype(corr.dtype, jnp.floating)
        else jnp.iinfo(corr.dtype).min
    )
    best = jnp.full(pooled_shape, neg_inf, corr.dtype)
    best_idx = jnp.zeros(pooled_shape, jnp.int32)
    for combo in range(k**4):
        di, rem = divmod(combo, k * k * k)
        dj, rem = divmod(rem, k * k)
        dk, dl = divmod(rem, k)
        sub = corr[:, di::k, dj::k, dk::k, dl::k]  # 5D strided slice
        take = sub > best
        best = jnp.where(take, sub, best)
        best_idx = jnp.where(take, np.int32(combo), best_idx)
    dl = best_idx % k
    dk = (best_idx // k) % k
    dj = (best_idx // (k * k)) % k
    di = best_idx // (k * k * k)
    return best, (di, dj, dk, dl)
