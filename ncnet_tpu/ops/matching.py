"""Soft mutual-nearest-neighbour filtering and 4D max-pooling."""

import jax.numpy as jnp


def mutual_matching(corr, eps=1e-5):
    """Soft mutual-NN gate on a 4D correlation tensor.

    ``out = corr * (corr / (max_over_B + eps)) * (corr / (max_over_A + eps))``
    where ``max_over_A`` reduces over the (iA, jA) dims and ``max_over_B``
    over (iB, jB). Mirrors the reference ``MutualMatching``
    (lib/model.py:155-175), eps 1e-5; the two ratio factors are multiplied
    together before scaling ``corr`` so the output is symmetric in A/B.

    Args:
      corr: ``[b, iA, jA, iB, jB]``.
    """
    max_over_a = jnp.max(corr, axis=(1, 2), keepdims=True)
    max_over_b = jnp.max(corr, axis=(3, 4), keepdims=True)
    ratio_b = corr / (max_over_a + eps)  # best-over-A normalization
    ratio_a = corr / (max_over_b + eps)  # best-over-B normalization
    return corr * (ratio_a * ratio_b)


def maxpool4d(corr, k_size):
    """4D max-pool with stride ``k_size`` over all four dims, with offsets.

    Returns the pooled tensor plus the within-cell argmax offsets
    ``(di, dj, dk, dl)`` used to restore fine coordinates at readout —
    reference ``maxpool4d`` (lib/model.py:177-191). Offset encoding matches
    the reference slice enumeration: combined index ``((di*k+dj)*k+dk)*k+dl``.

    Args:
      corr: ``[b, iA, jA, iB, jB]`` with all four spatial dims divisible by
        ``k_size``.

    Returns:
      ``(pooled, (di, dj, dk, dl))``; pooled is
      ``[b, iA/k, jA/k, iB/k, jB/k]``, offsets are int32 of the same shape.
    """
    k = int(k_size)
    b, d1, d2, d3, d4 = corr.shape
    blocks = corr.reshape(b, d1 // k, k, d2 // k, k, d3 // k, k, d4 // k, k)
    # -> [b, d1/k, d2/k, d3/k, d4/k, k, k, k, k]
    blocks = blocks.transpose(0, 1, 3, 5, 7, 2, 4, 6, 8)
    flat = blocks.reshape(b, d1 // k, d2 // k, d3 // k, d4 // k, k**4)
    pooled = jnp.max(flat, axis=-1)
    idx = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    dl = idx % k
    dk = (idx // k) % k
    dj = (idx // (k * k)) % k
    di = idx // (k * k * k)
    return pooled, (di, dj, dk, dl)
