"""Pure-functional op library (no module state, no framework deps).

Every op here mirrors math documented in SURVEY.md §2 against the reference
(GrumpyZhou/ncnet), but is written channels-last and XLA-first.
"""

from ncnet_tpu.ops.accounting import (
    V5E_BF16_PEAK_FLOPS,
    train_step_flops,
    train_step_flops_for_batch,
)
from ncnet_tpu.ops.band import (
    band_coverage,
    band_gather_neighbors,
    band_neighbor_pointers,
    band_to_dense,
    topk_band,
)
from ncnet_tpu.ops.conv4d import conv4d
from ncnet_tpu.ops.corr_stream import corr_stream_band, resolve_corr_tile
from ncnet_tpu.ops.coords import (
    normalize_axis,
    points_to_pixel_coords,
    points_to_unit_coords,
    unnormalize_axis,
)
from ncnet_tpu.ops.correlation import (
    correlation_3d,
    correlation_4d,
    correlation_maxpool4d,
)
from ncnet_tpu.ops.image import (
    affine_grid,
    affine_transform,
    grid_sample,
    imagenet_normalize,
    resize_bilinear_align_corners,
)
from ncnet_tpu.ops.matches import (
    bilinear_point_transfer,
    corr_to_matches,
    nearest_point_transfer,
)
from ncnet_tpu.ops.matching import maxpool4d, mutual_matching
from ncnet_tpu.ops.metrics import pck
from ncnet_tpu.ops.norm import feature_l2norm

__all__ = [
    "V5E_BF16_PEAK_FLOPS",
    "train_step_flops",
    "train_step_flops_for_batch",
    "band_coverage",
    "band_gather_neighbors",
    "band_neighbor_pointers",
    "band_to_dense",
    "topk_band",
    "conv4d",
    "corr_stream_band",
    "resolve_corr_tile",
    "correlation_3d",
    "correlation_4d",
    "correlation_maxpool4d",
    "corr_to_matches",
    "bilinear_point_transfer",
    "nearest_point_transfer",
    "maxpool4d",
    "mutual_matching",
    "feature_l2norm",
    "pck",
    "normalize_axis",
    "unnormalize_axis",
    "points_to_unit_coords",
    "points_to_pixel_coords",
    "imagenet_normalize",
    "resize_bilinear_align_corners",
    "affine_grid",
    "affine_transform",
    "grid_sample",
]
