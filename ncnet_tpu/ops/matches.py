"""Correlation-tensor readout: dense matches and keypoint transfer.

Mirrors lib/point_tnf.py of the reference (corr_to_matches:12-80,
bilinearInterpPointTnf:96-148, nearestNeighPointTnf:82-94) with vectorized,
batch-correct JAX implementations (the reference's gathers silently assume
batch size 1; here everything is vmapped over batch).
"""

import jax
import jax.numpy as jnp


def _lin(scale, n, dtype=jnp.float32):
    if scale == "centered":
        return jnp.linspace(-1.0, 1.0, n, dtype=dtype)
    if scale == "positive":
        return jnp.linspace(0.0, 1.0, n, dtype=dtype)
    raise ValueError(f"unknown scale {scale!r}")


def corr_to_matches(
    corr,
    delta4d=None,
    k_size=1,
    do_softmax=False,
    scale="centered",
    invert_matching_direction=False,
    return_indices=False,
):
    """Hard-argmax match readout from a correlation tensor.

    Args:
      corr: ``[b, fs1, fs2, fs3, fs4]`` = ``[b, iA, jA, iB, jB]``.
      delta4d: optional relocalization offsets ``(di, dj, dk, dl)`` each
        ``[b, fs1, fs2, fs3, fs4]`` (from `correlation_maxpool4d`/`maxpool4d`).
      k_size: relocalization factor; coordinate grids span ``fs * k_size``.
      do_softmax: softmax-normalize scores over the source dimension before
        the max (over A dims in the default direction, over B dims when
        inverted).
      scale: 'centered' ([-1, 1]) or 'positive' ([0, 1]) coordinates.
      invert_matching_direction: default (False) finds, for every B cell, the
        best A cell; True inverts the roles.

    Returns:
      ``(xA, yA, xB, yB, score)`` each ``[b, N]`` with ``N = fs3*fs4``
      (default) or ``fs1*fs2`` (inverted); with ``return_indices`` also
      ``(iA, jA, iB, jB)`` grid indices (pre-relocalization scale times
      ``k_size`` plus deltas, i.e. fine-grid indices when relocalizing).
    """
    b, fs1, fs2, fs3, fs4 = corr.shape

    if invert_matching_direction:
        # for each A cell, best B cell
        flat = corr.reshape(b, fs1 * fs2, fs3 * fs4)
        if do_softmax:
            flat = jax.nn.softmax(flat, axis=2)
        score = jnp.max(flat, axis=2)
        idx = jnp.argmax(flat, axis=2)
        i_b, j_b = idx // fs4, idx % fs4
        n = fs1 * fs2
        i_a = jnp.broadcast_to(jnp.arange(n) // fs2, (b, n))
        j_a = jnp.broadcast_to(jnp.arange(n) % fs2, (b, n))
    else:
        flat = corr.reshape(b, fs1 * fs2, fs3 * fs4)
        if do_softmax:
            flat = jax.nn.softmax(flat, axis=1)
        score = jnp.max(flat, axis=1)
        idx = jnp.argmax(flat, axis=1)
        i_a, j_a = idx // fs2, idx % fs2
        n = fs3 * fs4
        i_b = jnp.broadcast_to(jnp.arange(n) // fs4, (b, n))
        j_b = jnp.broadcast_to(jnp.arange(n) % fs4, (b, n))

    if delta4d is not None:  # relocalization: restore fine-grid indices
        di, dj, dk, dl = delta4d
        bidx = jnp.arange(b)[:, None]
        d_ia = di[bidx, i_a, j_a, i_b, j_b]
        d_ja = dj[bidx, i_a, j_a, i_b, j_b]
        d_ib = dk[bidx, i_a, j_a, i_b, j_b]
        d_jb = dl[bidx, i_a, j_a, i_b, j_b]
        i_a = i_a * k_size + d_ia
        j_a = j_a * k_size + d_ja
        i_b = i_b * k_size + d_ib
        j_b = j_b * k_size + d_jb
    elif k_size != 1:
        i_a, j_a = i_a * k_size, j_a * k_size
        i_b, j_b = i_b * k_size, j_b * k_size

    x_a = _lin(scale, fs2 * k_size)[j_a]
    y_a = _lin(scale, fs1 * k_size)[i_a]
    x_b = _lin(scale, fs4 * k_size)[j_b]
    y_b = _lin(scale, fs3 * k_size)[i_b]

    if return_indices:
        return x_a, y_a, x_b, y_b, score, i_a, j_a, i_b, j_b
    return x_a, y_a, x_b, y_b, score


def _bilinear_transfer_single(x_a, y_a, x_b, y_b, target_points, grid_shape):
    h, w = grid_shape
    grid_x = jnp.linspace(-1.0, 1.0, w, dtype=x_a.dtype)
    grid_y = jnp.linspace(-1.0, 1.0, h, dtype=x_a.dtype)
    tx, ty = target_points[0], target_points[1]  # [Np]

    def lower_idx(coord, grid, n):
        cnt = jnp.sum(coord[None, :] > grid[:, None], axis=0) - 1
        return jnp.clip(cnt, 0, n - 2)

    x_minus = lower_idx(tx, grid_x, w)
    y_minus = lower_idx(ty, grid_y, h)
    x_plus = x_minus + 1
    y_plus = y_minus + 1

    def to_idx(xi, yi):
        return yi * w + xi

    def p_at(idx):  # matched-grid (B) corner coordinates
        return jnp.stack([x_b[idx], y_b[idx]])

    def q_at(idx):  # warped (A) coordinates at that corner
        return jnp.stack([x_a[idx], y_a[idx]])

    idx_mm = to_idx(x_minus, y_minus)
    idx_pp = to_idx(x_plus, y_plus)
    idx_pm = to_idx(x_plus, y_minus)
    idx_mp = to_idx(x_minus, y_plus)

    t = jnp.stack([tx, ty])
    area = lambda p: jnp.prod(jnp.abs(t - p), axis=0)
    # weight for each corner = area of the opposite sub-rectangle
    f_pp = area(p_at(idx_mm))
    f_mm = area(p_at(idx_pp))
    f_mp = area(p_at(idx_pm))
    f_pm = area(p_at(idx_mp))

    num = (
        q_at(idx_mm) * f_mm
        + q_at(idx_pp) * f_pp
        + q_at(idx_mp) * f_mp
        + q_at(idx_pm) * f_pm
    )
    return num / (f_pp + f_mm + f_mp + f_pm)


def bilinear_point_transfer(matches, target_points_norm, grid_shape=None):
    """Warp target keypoints into the source image via the match grid.

    Args:
      matches: ``(xA, yA, xB, yB)`` from `corr_to_matches` in the default
        (B->A) direction, each ``[b, N]`` with ``N = h*w`` match-grid
        cells in row-major order (the reference hardcodes the square case
        via ``int(sqrt(N))``, lib/point_tnf.py:104).
      target_points_norm: ``[b, 2, Np]`` in [-1, 1].
      grid_shape: the ``(h, w)`` of the match grid. Default: inferred as
        square from N; REQUIRED for rectangular eval grids (e.g. a
        non-square `corr_to_matches` `output_size`).

    Returns:
      ``[b, 2, Np]`` warped points in [-1, 1] (source-image frame).
    """
    x_a, y_a, x_b, y_b = matches
    n = x_b.shape[-1]
    if grid_shape is None:
        side = int(round(n**0.5))
        if side * side != n:
            raise ValueError(
                f"match grid is not square: N={n}; pass grid_shape=(h, w) "
                "matching the correlation output_size"
            )
        grid_shape = (side, side)
    if grid_shape[0] * grid_shape[1] != n:
        raise ValueError(f"grid_shape {grid_shape} does not factor N={n}")
    return jax.vmap(
        lambda a, b_, c, d, t: _bilinear_transfer_single(
            a, b_, c, d, t, grid_shape
        )
    )(x_a, y_a, x_b, y_b, target_points_norm)


def nearest_point_transfer(matches, target_points_norm):
    """Warp target keypoints via the nearest match (reference
    nearestNeighPointTnf, lib/point_tnf.py:82-94)."""
    x_a, y_a, x_b, y_b = matches

    def single(xa, ya, xb, yb, t):
        d2 = jnp.square(t[0][:, None] - xb[None, :]) + jnp.square(
            t[1][:, None] - yb[None, :]
        )
        idx = jnp.argmin(d2, axis=1)
        return jnp.stack([xa[idx], ya[idx]])

    return jax.vmap(single)(x_a, y_a, x_b, y_b, target_points_norm)
