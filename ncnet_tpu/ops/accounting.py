"""Analytic FLOP accounting for the training step, and the MFU anchor.

Moved out of ``bench.py`` so the library can compute model-FLOP
utilization live: the training loop's telemetry gauge
(``train_mfu``) and the bench CLI share this one count — the reported
MFU is the same number whether it comes from a benchmark run or from a
``--telemetry`` training run.

Pure arithmetic over config-shaped integers; no jax, no module state
(the `ops` contract).
"""

# v5e bf16 peak per chip — the MFU denominator (bench.py's anchor).
V5E_BF16_PEAK_FLOPS = 197e12

# f32 anchor for the dual-MFU report: the v5e MXU has no native f32
# multiply — XLA decomposes an f32 contraction into bf16 passes, so f32
# compute tops out at roughly half the bf16 rate. A fixed convention,
# not a datasheet number: the point of the pair is two STABLE
# denominators so bf16 and f32 runs each get judged against the ceiling
# their compute dtype can actually reach.
V5E_F32_PEAK_FLOPS = V5E_BF16_PEAK_FLOPS / 2


def compute_dtype(config):
    """The step's contraction dtype as a string, from the model config.

    ``half_precision`` runs features/correlation/NC in bf16 (master
    params, loss, and optimizer state stay f32 — the mixed-precision
    contract in train/step.py); everything else contracts in f32. This
    is the dtype the MFU denominator must match.
    """
    return "bfloat16" if getattr(config, "half_precision", False) else "float32"


def peak_flops(dtype):
    """Per-chip peak for a compute dtype ('bfloat16' or 'float32') —
    the denominator for that dtype's MFU."""
    if dtype in ("bfloat16", "bf16"):
        return V5E_BF16_PEAK_FLOPS
    if dtype in ("float32", "f32"):
        return V5E_F32_PEAK_FLOPS
    raise ValueError(f"no peak-FLOPs anchor for compute dtype {dtype!r}")


def trunk_forward_flops(cnn, image):
    """Trunk forward FLOPs (2*MACs) per image at ``image``x``image``.

    patch16 is exact (one 16x16/stride-16 conv to 256 channels —
    ``models/patch.py``): ``2 * g^2 * (16*16*3) * 256`` with
    ``g = image // 16``. resnet101 keeps the calibrated conv1..layer3
    anchor (6.5 GFLOPs @ 224, quadratic in side). Other trunks fall back
    to the resnet101 curve — callers needing exactness for them should
    extend this table (the jaxpr auditor cross-checks it per-trunk).
    """
    if cnn == "patch16":
        g = max(int(image) // 16, 1)
        return 2.0 * g * g * (16 * 16 * 3) * 256
    resnet101_layer3_224 = 6.5e9  # conv1..layer3 @ 224x224 per image
    return resnet101_layer3_224 * (image / 224.0) ** 2


def corr_select_flops(batch, n_a, n_b, feat_ch, corr_impl="dense",
                      corr_tile=128):
    """Contraction FLOPs (2*MACs) of one correlation->band selection pass
    (the ``corr/dense`` and ``corr/stream`` audit programs).

    Dense: the single all-pairs einsum, ``2 * n_a * n_b * c`` per sample
    — the mutual-matching gate, ranking, top-K and gathers are
    elementwise/comparison work the ledger counts as zero, matching the
    jaxpr walk's convention.

    Stream (ops/corr_stream.py): the SAME GEMMs, one B-tile at a time —
    ``2 * n_a * ceil(n_b/tile)*tile * c`` per sample. When the tile
    divides ``n_b`` this is EXACTLY the dense count; otherwise the
    zero-padded tail columns of the last tile add
    ``2 * n_a * (ceil(n_b/tile)*tile - n_b) * c``. Streaming is a
    memory/bandwidth optimization, not a FLOP one: the win is peak
    memory O(n_a*(K+tile)) vs O(n_a*n_b), never the arithmetic.
    """
    if corr_impl == "stream":
        t = int(corr_tile)
        if t <= 0:
            raise ValueError(f"corr_tile={corr_tile} must be positive")
        t = min(t, int(n_b))  # mirrors ops.corr_stream.resolve_corr_tile
        n_tiles = -(-int(n_b) // t)
        return float(batch) * 2.0 * n_a * (n_tiles * t) * feat_ch
    if corr_impl != "dense":
        raise ValueError(f"corr_impl={corr_impl!r} is not 'dense'|'stream'")
    return float(batch) * 2.0 * n_a * n_b * feat_ch


def train_step_flops(batch, kernels, channels, grid=25, feat_ch=1024,
                     image=400, from_features=False, nc_topk=0,
                     cnn="resnet101", trunk_trainable=False,
                     corr_impl="dense", corr_tile=128):
    """Analytic FLOPs (2*MACs) per training step.

    Counted: 2 trunk forwards/sample (features reused for the rolled
    negatives), pos+neg correlation einsums, the symmetric NC stack
    forward for pos+neg, and its backward (the frozen trunk takes no
    backward). With ``from_features`` (the feature cache,
    ncnet_tpu.features) the step contains ZERO backbone ops, so the trunk
    term drops out and MFU is reported against the reduced count.

    The backward term is AD-exact, not the 2x-forward folklore: with a
    frozen trunk the correlation volume is param-independent, so JAX
    prunes the FIRST NC layer's input cotangent (dx_1) from the dense
    backward — the count subtracts that layer's dx work unless
    ``trunk_trainable`` (gradients must flow through corr back into the
    trunk) or ``nc_topk`` (the sparse band's custom VJP computes dx
    unconditionally). Verified against a jaxpr FLOP walk by
    ``ncnet_tpu.analysis.jaxpr_audit`` — mismatch there is a finding, so
    this count (the telemetry MFU numerator) cannot silently rot.

    With ``nc_topk`` > 0 (sparse band, ncnet_tpu.sparse) the NC layers
    run on ``hA*wA * K`` band entries instead of the dense
    ``hA*wA * hB*wB`` support — the per-layer count becomes
    ``2 * grid^2 * min(K, grid^2) * k^4 * cin * cout`` — and MFU is
    reported against the reduced count. The top-K selection, pointer
    build, and gathers are integer/comparison work and are not counted
    (the correlation einsum, which the sparse path still runs, is).

    With ``corr_impl='stream'`` (only legal on the band paths) the
    correlation GEMMs run tiled (`corr_select_flops`): identical FLOPs
    when ``corr_tile`` divides ``grid^2``, plus the padded-tail columns
    otherwise. The streamed band's custom VJP never runs in a
    frozen-trunk step — the features are constants under
    ``d loss / d params``, so JAX AD prunes the whole selection from the
    backward — which keeps the stream and dense counts' backward terms
    identical.
    """
    trunk = 0.0 if from_features else 2 * trunk_forward_flops(cnn, image)
    # pos + neg; the streamed variant only pads the last tile's columns
    corr = 2 * corr_select_flops(
        1, grid**2, grid**2, feat_ch, corr_impl=corr_impl,
        corr_tile=corr_tile,
    )
    n_b = grid**2 if not nc_topk else min(int(nc_topk), grid**2)
    nc_channels = [1, *channels]
    layer_flops = [
        2.0 * grid**2 * n_b * k**4 * cin * cout
        for k, cin, cout in zip(kernels, nc_channels[:-1], nc_channels[1:])
    ]
    nc_pass = sum(layer_flops)
    nc_fwd = nc_pass * 2 * 2  # symmetric x (pos + neg)
    nc_bwd = 2 * nc_fwd
    if layer_flops and not trunk_trainable and not nc_topk:
        # dense frozen-trunk backward: dx_1 (input cotangent of the first
        # NC layer) is dead — corr depends on no trainable param — and JAX
        # AD prunes it; one dx pass of layer 1, x2 symmetric x2 pos/neg
        nc_bwd -= layer_flops[0] * 2 * 2
    return batch * (trunk + corr + nc_fwd + nc_bwd)


def train_step_flops_for_batch(config, batch, from_features=False,
                               trunk_trainable=False):
    """`train_step_flops` derived from a config + a concrete batch dict.

    ``batch`` maps names to ``[b, h, w, ...]`` arrays: images
    (``source_image``) on the raw-pixel path, ``[b, gh, gw, c]`` feature
    maps (``source_features``) on the cached path. The trunk term uses
    the image side (stride-16 backbone: grid = side // 16) and the
    config's trunk (patch16 features are 256-channel, the resnet-family
    layer3 features 1024); the analytic count assumes a square grid,
    which both the training datasets and the synthetic benches satisfy.
    ``trunk_trainable`` mirrors ``train_fe or fe_finetune_blocks > 0``
    at the call site — it keeps the first NC layer's input-cotangent
    work in the backward count (see `train_step_flops`).
    """
    from_features = from_features or "source_features" in batch
    cnn = getattr(config, "feature_extraction_cnn", "resnet101")
    arr = (
        batch["source_features"]
        if "source_features" in batch
        else batch["source_image"]
    )
    b = int(arr.shape[0])
    if from_features:
        grid, feat_ch, image = int(arr.shape[1]), int(arr.shape[-1]), 0
    else:
        image = int(arr.shape[1])
        grid = max(image // 16, 1)
        feat_ch = 256 if cnn == "patch16" else 1024
    if int(getattr(config, "refine_factor", 0)):
        # coarse-to-fine step (ncnet_tpu.refine): the batch carries the
        # FINE grid; the coarse band and the rescore window are config
        return refine_train_step_flops(
            b,
            config.ncons_kernel_sizes,
            config.ncons_channels,
            grid_hi=grid,
            factor=int(config.refine_factor),
            nc_topk=int(config.refine_topk),
            radius=int(getattr(config, "refine_radius", 0)),
            feat_ch=feat_ch,
            image=image,
            cnn=cnn,
            from_features=from_features,
            corr_impl=getattr(config, "corr_impl", "dense"),
            corr_tile=int(getattr(config, "corr_stream_tile", 128)),
        )
    return train_step_flops(
        b,
        config.ncons_kernel_sizes,
        config.ncons_channels,
        grid=grid,
        feat_ch=feat_ch,
        image=image,
        from_features=from_features,
        nc_topk=int(getattr(config, "nc_topk", 0)),
        cnn=cnn,
        trunk_trainable=trunk_trainable,
        corr_impl=getattr(config, "corr_impl", "dense"),
        corr_tile=int(getattr(config, "corr_stream_tile", 128)),
    )


# ---------------------------------------------------------------------------
# coarse-to-fine refinement (ncnet_tpu.refine)


def refine_window(factor, radius=0):
    """Fine cells re-scored per surviving coarse candidate:
    ``(factor * (2*radius + 1))^2`` (`refine.rescore.refine_window_indices`)."""
    return (int(factor) * (2 * int(radius) + 1)) ** 2


def _coarse_band_flops(kernels, channels, grid_lo, nc_topk, feat_ch,
                       corr_impl="dense", corr_tile=128):
    """One pair's coarse tier: correlation einsum + symmetric NC band
    forward at the pooled grid (the pooling itself is reduction work —
    zero contraction FLOPs). ``corr_impl='stream'`` tiles the coarse
    correlation (`corr_select_flops`); the tile clamps to the pooled
    grid, so the default tile adds no padding at coarse sizes <= 128."""
    corr = corr_select_flops(
        1, grid_lo**2, grid_lo**2, feat_ch, corr_impl=corr_impl,
        corr_tile=corr_tile,
    )
    n_b = min(int(nc_topk), grid_lo**2)
    nc_channels = [1, *channels]
    nc_pass = sum(
        2.0 * grid_lo**2 * n_b * k**4 * cin * cout
        for k, cin, cout in zip(kernels, nc_channels[:-1], nc_channels[1:])
    )
    return corr, nc_pass


def refine_rescore_flops(batch, grid_hi, nc_topk, window, feat_ch):
    """The rescore contraction (`refine.rescore.refine_rescore`):
    ``einsum('bhwac,bhwkec->bhwake')`` over the gathered windows —
    ``2 * grid_hi^2 * K * window * c`` per sample. The window gathers,
    softmax, argmax and relocation are gather/elementwise work the
    ledger counts as zero, matching the jaxpr walk's convention."""
    return float(batch) * 2.0 * grid_hi**2 * int(nc_topk) * int(window) * feat_ch


def refine_match_flops(batch, kernels, channels, grid_hi, factor, nc_topk,
                       radius=0, feat_ch=256, image=0, cnn="patch16",
                       from_features=False, corr_impl="dense",
                       corr_tile=128):
    """Analytic FLOPs (2*MACs) of one refined match pass per batch
    (the ``refine/rescore`` serving program): 2 trunk forwards (unless
    fed from the feature store), the coarse correlation + symmetric NC
    band at the pooled grid, and the high-res rescore contraction.
    Verified walk-vs-form by `analysis.jaxpr_audit`."""
    if int(grid_hi) % int(factor):
        raise ValueError(
            f"fine grid {grid_hi} does not divide by factor {factor}"
        )
    grid_lo = int(grid_hi) // int(factor)
    trunk = 0.0 if from_features else 2 * trunk_forward_flops(cnn, image)
    corr, nc_pass = _coarse_band_flops(
        kernels, channels, grid_lo, nc_topk, feat_ch,
        corr_impl=corr_impl, corr_tile=corr_tile,
    )
    rescore = refine_rescore_flops(
        1, grid_hi, min(int(nc_topk), grid_lo**2),
        refine_window(factor, radius), feat_ch,
    )
    return float(batch) * (trunk + corr + 2 * nc_pass + rescore)


def refine_train_step_flops(batch, kernels, channels, grid_hi, factor,
                            nc_topk, radius=0, feat_ch=256, image=0,
                            cnn="patch16", from_features=False,
                            corr_impl="dense", corr_tile=128):
    """Analytic FLOPs (2*MACs) per refined training step (the
    ``train/refine`` program): the coarse tier runs pos + neg like the
    band path — correlation x2, symmetric NC forward x2, band backward
    at the sparse convention ``2x forward`` (the band VJP computes dx
    unconditionally) — plus the rescore contraction x2 FORWARD ONLY:
    the rescore scores are a pure function of the (param-independent)
    features, so the gain each band value is modulated by is a constant
    under ``d loss / d params`` and JAX AD prunes the whole einsum from
    the backward. Verified walk-vs-form by `analysis.jaxpr_audit`."""
    if int(grid_hi) % int(factor):
        raise ValueError(
            f"fine grid {grid_hi} does not divide by factor {factor}"
        )
    grid_lo = int(grid_hi) // int(factor)
    trunk = 0.0 if from_features else 2 * trunk_forward_flops(cnn, image)
    corr, nc_pass = _coarse_band_flops(
        kernels, channels, grid_lo, nc_topk, feat_ch,
        corr_impl=corr_impl, corr_tile=corr_tile,
    )
    nc_fwd = nc_pass * 2 * 2  # symmetric x (pos + neg)
    nc_bwd = 2 * nc_fwd
    rescore = 2 * refine_rescore_flops(  # pos + neg, forward only
        1, grid_hi, min(int(nc_topk), grid_lo**2),
        refine_window(factor, radius), feat_ch,
    )
    return float(batch) * (trunk + 2 * corr + nc_fwd + nc_bwd + rescore)


def pose_ransac_flops(batch, n_pad, n_hypotheses, lo_iters=2):
    """Contraction FLOPs (2*MACs) of the ``localize/ransac`` program.

    Counts the dot_generals of one batched LO-RANSAC solve
    (`localize.ransac.pose_from_matches` vmapped over ``batch``
    queries), matching `analysis.jaxpr_audit.jaxpr_flops`' convention:
    elementwise/reduction work and the eig/svd/eigh LAPACK custom calls
    are excluded on both sides, so the walk-vs-form cross-check compares
    like with like. Per query, with ``H`` hypotheses, ``n = n_pad``
    padded matches and ``L`` LO refits:

      * Kabsch rigid fits over the 4-slot slates: the cross-covariance,
        reflection-sign and rotation einsums (3 x ``2*4*3*3*3``) plus
        the translation (``2*4*3*3``) -> ``720 H``;
      * hypothesis scoring as one masked reduction over ``M = 4H``
        poses: point rotation ``2*M*n*3*3`` + ray dots ``2*M*n*3``
        -> ``96 H n``;
      * each LO refit: inlier re-mask (``18 n``), the two weighted
        12x12 normal-matrix products (``2 * 2*144*n``), the 3x3 SO(3)
        projection product (54), cheirality re-projection (``18 n``)
        and the acceptance re-score (``24 n``) -> ``636 n + 54``
        per iteration;
      * the final inlier mask: ``18 n``.
    """
    h, n, li = float(n_hypotheses), float(n_pad), float(lo_iters)
    per_query = (
        720.0 * h
        + 96.0 * h * n
        + li * (636.0 * n + 54.0)
        + 18.0 * n
    )
    return float(batch) * per_query
