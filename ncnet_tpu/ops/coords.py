"""Pixel <-> normalized [-1, 1] coordinate transforms.

1-indexed pixel convention of the reference (lib/point_tnf.py:6-10,151-167):
``normalize_axis(x, L) = (x - 1 - (L-1)/2) * 2 / (L-1)``.

Points tensors are ``[b, 2, N]`` with row 0 = x, row 1 = y; image sizes are
``[b, 2]`` ordered ``(h, w)`` (numpy shape order, as produced by the data
pipeline).
"""

import jax.numpy as jnp


def normalize_axis(x, length):
    """Pixel coordinate (1-indexed) -> [-1, 1]."""
    return (x - 1 - (length - 1) / 2) * 2 / (length - 1)


def unnormalize_axis(x, length):
    """[-1, 1] -> pixel coordinate (1-indexed)."""
    return x * (length - 1) / 2 + 1 + (length - 1) / 2


def points_to_unit_coords(points, im_size):
    """``[b, 2, N]`` pixel points -> [-1, 1], x against width, y against height."""
    h = im_size[:, 0][:, None]
    w = im_size[:, 1][:, None]
    return jnp.stack(
        [normalize_axis(points[:, 0, :], w), normalize_axis(points[:, 1, :], h)],
        axis=1,
    )


def points_to_pixel_coords(points, im_size):
    """``[b, 2, N]`` [-1, 1] points -> pixel coordinates."""
    h = im_size[:, 0][:, None]
    w = im_size[:, 1][:, None]
    return jnp.stack(
        [unnormalize_axis(points[:, 0, :], w), unnormalize_axis(points[:, 1, :], h)],
        axis=1,
    )
