"""Feature normalization."""

import jax.numpy as jnp


def feature_l2norm(x, axis=-1, eps=1e-6):
    """Per-location L2 normalization along ``axis``.

    Matches the reference ``featureL2Norm`` (lib/model.py:14-17):
    ``x / sqrt(sum(x**2, axis) + eps)`` with ``eps = 1e-6`` added to the sum
    of squares (inside the square root), channel axis here defaulting to the
    trailing (channels-last) axis instead of the reference's dim 1.
    """
    denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return x / denom
