"""Evaluation metrics."""

import jax.numpy as jnp


def pck(source_points, warped_points, l_pck, alpha=0.1):
    """Percentage of Correct Keypoints.

    Fraction of valid keypoints whose warped position lies within
    ``alpha * L_pck`` of the ground-truth source position — reference ``pck``
    (lib/eval_util.py:12-24). Valid keypoints are those not equal to the -1
    padding in both coordinates of the source points (the reference slices
    the first N valid columns; padding is trailing, so masking is
    equivalent).

    Args:
      source_points: ``[b, 2, N]`` ground-truth points, -1-padded.
      warped_points: ``[b, 2, N]`` model-warped points.
      l_pck: ``[b]`` or ``[b, 1]`` per-sample reference length.
      alpha: threshold fraction (0.1).

    Returns:
      ``[b]`` per-sample PCK in [0, 1].
    """
    l_pck = jnp.reshape(l_pck, (-1,))
    valid = (source_points[:, 0, :] != -1) & (source_points[:, 1, :] != -1)
    dist = jnp.sqrt(
        jnp.sum(jnp.square(source_points - warped_points), axis=1)
    )
    correct = (dist <= l_pck[:, None] * alpha) & valid
    n_valid = jnp.sum(valid, axis=1)
    return jnp.sum(correct, axis=1) / jnp.maximum(n_valid, 1)
