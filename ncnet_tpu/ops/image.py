"""Host/device image preprocessing ops.

The reference resizes images with an identity-affine ``F.grid_sample``
(lib/transformation.py:41-63) and ``F.upsample(mode='bilinear')``
(eval_inloc.py:84-89); under PyTorch 0.3 both use align_corners=True
semantics, i.e. sampling at ``linspace(0, L-1, out)``. `jax.image.resize`
uses half-pixel centers, so a dedicated align-corners bilinear resize is
provided for parity.
"""

import jax.numpy as jnp

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def imagenet_normalize(image, scale_255=True):
    """ImageNet normalization, channels-last.

    ``(image/255 - mean) / std`` — reference ``NormalizeImageDict``
    (lib/normalization.py:19-27).
    """
    mean = jnp.asarray(IMAGENET_MEAN, image.dtype)
    std = jnp.asarray(IMAGENET_STD, image.dtype)
    if scale_255:
        image = image / 255.0
    return (image - mean) / std


def imagenet_unnormalize(image):
    """Inverse of `imagenet_normalize` (without the 255 scale)."""
    mean = jnp.asarray(IMAGENET_MEAN, image.dtype)
    std = jnp.asarray(IMAGENET_STD, image.dtype)
    return image * std + mean


def resize_bilinear_align_corners(image, out_h, out_w):
    """Bilinear resize with align-corners sample positions.

    Matches PyTorch-0.3 ``grid_sample`` on an identity affine grid and
    ``upsample(mode='bilinear')``: output pixel ``o`` samples input position
    ``o * (L_in - 1) / (L_out - 1)``.

    Args:
      image: ``[..., h, w, c]``.
    """
    h, w = image.shape[-3], image.shape[-2]

    def interp(x, axis, out_n, in_n):
        if out_n == in_n:
            return x
        pos = jnp.linspace(0.0, in_n - 1.0, out_n)
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, in_n - 1)
        frac = (pos - lo).astype(x.dtype)
        shape = [1] * x.ndim
        shape[axis] = out_n
        frac = frac.reshape(shape)
        return jnp.take(x, lo, axis=axis) * (1 - frac) + jnp.take(
            x, hi, axis=axis
        ) * frac

    image = interp(image, image.ndim - 3, out_h, h)
    image = interp(image, image.ndim - 2, out_w, w)
    return image
