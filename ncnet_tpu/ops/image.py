"""Host/device image preprocessing ops.

The reference resizes images with an identity-affine ``F.grid_sample``
(lib/transformation.py:41-63) and ``F.upsample(mode='bilinear')``
(eval_inloc.py:84-89); under PyTorch 0.3 both use align_corners=True
semantics, i.e. sampling at ``linspace(0, L-1, out)``. `jax.image.resize`
uses half-pixel centers, so a dedicated align-corners bilinear resize is
provided for parity.

`affine_grid` + `grid_sample` generalize this to arbitrary affine thetas
(the full ``AffineGridGen``/``AffineTnf`` surface of the reference,
lib/transformation.py:15-63), enabling device-side affine augmentation.
"""

import jax
import jax.numpy as jnp

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def imagenet_normalize(image, scale_255=True):
    """ImageNet normalization, channels-last.

    ``(image/255 - mean) / std`` — reference ``NormalizeImageDict``
    (lib/normalization.py:19-27).
    """
    mean = jnp.asarray(IMAGENET_MEAN, image.dtype)
    std = jnp.asarray(IMAGENET_STD, image.dtype)
    if scale_255:
        image = image / 255.0
    return (image - mean) / std


def imagenet_unnormalize(image):
    """Inverse of `imagenet_normalize` (without the 255 scale)."""
    mean = jnp.asarray(IMAGENET_MEAN, image.dtype)
    std = jnp.asarray(IMAGENET_STD, image.dtype)
    return image * std + mean


def affine_grid(theta, out_h, out_w):
    """Affine sampling grid, torch ``F.affine_grid`` align-corners semantics.

    Reference ``AffineGridGen`` (lib/transformation.py:51-63). The base grid
    spans [-1, 1] inclusive on both axes (align_corners=True).

    Args:
      theta: ``[b, 2, 3]`` affine matrices mapping OUTPUT normalized coords
        (x, y, 1) to INPUT normalized sample positions.

    Returns:
      ``[b, out_h, out_w, 2]`` of (x, y) sample positions in [-1, 1].
    """
    xs = jnp.linspace(-1.0, 1.0, out_w, dtype=theta.dtype)
    ys = jnp.linspace(-1.0, 1.0, out_h, dtype=theta.dtype)
    gx, gy = jnp.meshgrid(xs, ys)  # [out_h, out_w]
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [h, w, 3]
    return jnp.einsum("hwk,bik->bhwi", base, theta)


def grid_sample(image, grid):
    """Bilinear sampling, torch ``F.grid_sample`` align-corners + zeros
    padding semantics (reference ``AffineTnf``, lib/transformation.py:41-46).

    Each of the four corner taps is zeroed individually when it falls
    outside the image (torch 'zeros' padding_mode).

    Args:
      image: ``[b, h, w, c]`` channels-last.
      grid: ``[b, gh, gw, 2]`` of (x, y) sample positions in [-1, 1].

    Returns:
      ``[b, gh, gw, c]``.
    """
    b, h, w, c = image.shape
    gx = (grid[..., 0] + 1.0) * 0.5 * (w - 1)
    gy = (grid[..., 1] + 1.0) * 0.5 * (h - 1)
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)

    def tap(xi, yi):
        inb = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        vals = jax.vmap(lambda img, yy, xx: img[yy, xx])(image, yc, xc)
        return vals * inb[..., None].astype(image.dtype)

    wx1 = (gx - x0).astype(image.dtype)[..., None]
    wy1 = (gy - y0).astype(image.dtype)[..., None]
    wx0 = 1.0 - wx1
    wy0 = 1.0 - wy1
    return (
        tap(x0, y0) * wx0 * wy0
        + tap(x0 + 1, y0) * wx1 * wy0
        + tap(x0, y0 + 1) * wx0 * wy1
        + tap(x0 + 1, y0 + 1) * wx1 * wy1
    )


def affine_transform(image, theta, out_h, out_w):
    """Warp ``image`` by affine ``theta`` — the reference ``AffineTnf``
    forward (lib/transformation.py:37-46). Identity theta reduces to
    `resize_bilinear_align_corners`."""
    return grid_sample(image, affine_grid(theta, out_h, out_w))


def resize_bilinear_align_corners(image, out_h, out_w):
    """Bilinear resize with align-corners sample positions.

    Matches PyTorch-0.3 ``grid_sample`` on an identity affine grid and
    ``upsample(mode='bilinear')``: output pixel ``o`` samples input position
    ``o * (L_in - 1) / (L_out - 1)``.

    Args:
      image: ``[..., h, w, c]``.
    """
    h, w = image.shape[-3], image.shape[-2]

    def interp(x, axis, out_n, in_n):
        if out_n == in_n:
            return x
        pos = jnp.linspace(0.0, in_n - 1.0, out_n)
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, in_n - 1)
        frac = (pos - lo).astype(x.dtype)
        shape = [1] * x.ndim
        shape[axis] = out_n
        frac = frac.reshape(shape)
        return jnp.take(x, lo, axis=axis, mode="clip") * (1 - frac) + jnp.take(
            x, hi, axis=axis, mode="clip"
        ) * frac

    image = interp(image, image.ndim - 3, out_h, h)
    image = interp(image, image.ndim - 2, out_w, w)
    return image
