"""All-pairs 4D feature correlation, plain and fused with 4D max-pooling.

The correlation tensor is the framework's central object:
``corr[b, iA, jA, iB, jB] = <fA[b, iA, jA, :], fB[b, iB, jB, :]>``.

Reference semantics: ``FeatureCorrelation(shape='4D')`` (lib/model.py:106-115),
which computes a batched GEMM between flattened feature maps. Here it is a
single einsum, which XLA lowers to one large MXU matmul; features are
channels-last (NHWC).
"""

import jax
import jax.numpy as jnp

from ncnet_tpu.ops.norm import feature_l2norm


def correlation_4d(feature_a, feature_b, normalization=False, relu=True):
    """All-pairs dot-product correlation.

    Args:
      feature_a: ``[b, hA, wA, c]`` source feature map (channels-last).
      feature_b: ``[b, hB, wB, c]`` target feature map.
      normalization: if True, apply (optional ReLU then) per-location L2
        normalization over the flattened B grid, mirroring the reference's
        ``FeatureCorrelation(normalization=True)`` branch (lib/model.py:117-118).
        ImMatchNet uses ``normalization=False`` (lib/model.py:235).
      relu: only used when ``normalization`` is True.

    Returns:
      ``[b, hA, wA, hB, wB]`` correlation tensor (no channel axis).
    """
    corr = jnp.einsum(
        "bijc,bklc->bijkl",
        feature_a,
        feature_b,
        preferred_element_type=feature_a.dtype,
    )
    if normalization:
        if relu:
            corr = jax.nn.relu(corr)
        b, ha, wa, hb, wb = corr.shape
        corr = feature_l2norm(corr.reshape(b, ha, wa, hb * wb), axis=-1)
        corr = corr.reshape(b, ha, wa, hb, wb)
    return corr


def correlation_3d(feature_a, feature_b, normalization=True, relu=True):
    """3D-shaped correlation: A's grid flattened into a channel axis.

    Reference ``FeatureCorrelation(shape='3D')`` (lib/model.py:97-105) —
    used by geometric-matching models built on the same module (not by
    ImMatchNet, which uses the 4D shape); part of the reference's exported
    surface. Output is channels-last ``[b, hB, wB, hA*wA]`` with channel
    index ``idx_A = iA + hA * jA`` (column-major over A's grid), matching
    the reference's ``[b, idx_A, hB, wB]`` tensor up to the NHWC layout.

    Args:
      feature_a, feature_b: ``[b, h, w, c]`` feature maps (same grid —
        the reference's 3D branch assumes matching shapes).
      normalization: reference default True — ReLU then per-location L2
        normalization over the flattened-A channel axis.
      relu: only used when ``normalization`` is True.
    """
    b, h, w, _ = feature_a.shape
    corr = jnp.einsum(
        "bijc,bklc->bklji",
        feature_a,
        feature_b,
        preferred_element_type=feature_a.dtype,
    )  # [b, iB, jB, jA, iA]: (jA, iA) row-major flattens to iA + h*jA
    corr = corr.reshape(b, h, w, h * w)
    if normalization:
        if relu:
            corr = jax.nn.relu(corr)
        corr = feature_l2norm(corr, axis=-1)
    return corr


def correlation_maxpool4d(feature_a, feature_b, k_size):
    """Fused correlation + 4D max-pool ("relocalization"), HBM-friendly.

    Equivalent to ``maxpool4d(correlation_4d(fA, fB), k_size)`` — the
    reference computes the full high-resolution correlation and then pools it
    (lib/model.py:269-272, 177-191) — but never materializes the pre-pool
    tensor: the feature grids are split into ``k_size``-strided sub-grids and
    the ``k_size**4`` sub-correlations are max-accumulated one at a time with
    `lax.scan`, so peak HBM is O(pooled size), a ``k_size**4`` (16x for k=2)
    reduction.

    Args:
      feature_a: ``[b, hA, wA, c]`` with hA, wA divisible by k_size.
      feature_b: ``[b, hB, wB, c]`` with hB, wB divisible by k_size.
      k_size: pooling factor applied to all four correlation dims.

    Returns:
      ``(corr, (di, dj, dk, dl))`` where ``corr`` is the pooled
      ``[b, hA/k, wA/k, hB/k, wB/k]`` tensor and the deltas are int32 tensors
      of the same shape giving the within-cell offset of the max along each of
      the four dims — identical to the reference's ``maxpool4d`` outputs.
    """
    k = int(k_size)
    b, ha, wa, c = feature_a.shape
    _, hb, wb, _ = feature_b.shape
    # [b, hA/k, k, wA/k, k, c] -> [k, k, b, hA/k, wA/k, c] -> [k*k, ...]
    sub_a = feature_a.reshape(b, ha // k, k, wa // k, k, c)
    sub_a = sub_a.transpose(2, 4, 0, 1, 3, 5).reshape(k * k, b, ha // k, wa // k, c)
    sub_b = feature_b.reshape(b, hb // k, k, wb // k, k, c)
    sub_b = sub_b.transpose(2, 4, 0, 1, 3, 5).reshape(k * k, b, hb // k, wb // k, c)

    pooled_shape = (b, ha // k, wa // k, hb // k, wb // k)
    neg_inf = jnp.finfo(feature_a.dtype).min

    def step(carry, ab):
        best, best_idx = carry
        idx_a, idx_b = ab
        corr = jnp.einsum(
            "bijc,bklc->bijkl",
            sub_a[idx_a],
            sub_b[idx_b],
            preferred_element_type=feature_a.dtype,
        )
        # Combined offset index in the reference's slice enumeration order
        # (i, j, k, l) with i slowest (lib/model.py:179-184): the A sub-grid
        # offsets (i, j) come from idx_a, B's (k, l) from idx_b.
        combo = idx_a * (k * k) + idx_b
        take = corr > best
        best = jnp.where(take, corr, best)
        best_idx = jnp.where(take, combo, best_idx)
        return (best, best_idx), None

    init = (
        jnp.full(pooled_shape, neg_inf, feature_a.dtype),
        jnp.zeros(pooled_shape, jnp.int32),
    )
    idx_a_grid, idx_b_grid = jnp.meshgrid(
        jnp.arange(k * k), jnp.arange(k * k), indexing="ij"
    )
    (corr, best_idx), _ = jax.lax.scan(
        step, init, (idx_a_grid.reshape(-1), idx_b_grid.reshape(-1))
    )
    # Decode combo -> (di, dj, dk, dl), i slowest, matching the reference's
    # fmod/div decode (lib/model.py:185-189).
    dl = best_idx % k
    dk = (best_idx // k) % k
    dj = (best_idx // (k * k)) % k
    di = best_idx // (k * k * k)
    return corr, (di, dj, dk, dl)
