"""Top-K correlation band: selection, neighbour pointers, gathers.

The 4D correlation is overwhelmingly noise: Sparse-NCNet (arXiv:2004.10566)
keeps only the top-K B-candidates per source cell and filters on that
support at >10x less compute/memory with equal-or-better PCK. These are the
band primitives the sparse neighbourhood-consensus path
(``ncnet_tpu.sparse``) is built from.

Representation — dense-regular, static under jit, NO scatter and NO ragged
shapes on the hot path:

  values  ``[b, hA, wA, K]``        band entry values
  indices ``[b, hA, wA, K]`` int32  flattened B-grid index ``iB * wB + jB``,
                                    SORTED ascending per A-cell

Sorting by B-index makes the band canonical: at ``K = hB*wB`` the band IS
the dense correlation row in row-major order, which is what makes the
full-K sparse==dense equivalence contract testable bitwise (the sparse NC
GEMM then contracts the exact arrays the dense ``'gemm4'`` lowering
contracts, in the same order — see ``ncnet_tpu/sparse/nc.py``).

Out-of-range semantics are explicit everywhere: every gather in this module
passes ``mode=`` (the ``unchecked-gather`` lint rule), and neighbour reads
that fall off the B grid, off the A grid, or off the band resolve to a
dedicated all-zero null slot — exact zeros, not clamped copies of edge
values (silent clip semantics would mask band-index bugs).
"""

import jax
import jax.numpy as jnp
from jax import lax


def _ranks_descending(x):
    """Per-row dense ranks of ``x`` along the last axis (0 = largest).

    Stable: ties rank in index order, so the selection below is
    deterministic for equal scores.
    """
    order = jnp.argsort(-x, axis=-1)
    return jnp.argsort(order, axis=-1).astype(jnp.int32)


def topk_band(scores, k, values_from=None, mutual=False):
    """Select the per-A-cell top-K band from a dense correlation.

    Args:
      scores: ``[b, hA, wA, hB, wB]`` selection scores (the RAW
        correlation in the sparse NC pipeline).
      k: static band width, ``1 <= k <= hB*wB``. ``k = hB*wB`` keeps
        everything (the band is complete and the sparse path must equal
        the dense path).
      values_from: optional ``[b, hA, wA, hB, wB]`` tensor to read the
        band VALUES from (default: ``scores``). The sparse pipeline
        selects on the raw correlation but carries the mutual-matching
        gated values, mirroring the dense corr -> MM -> NC order.
      mutual: symmetric/transposed selection. With False the band is the
        plain per-A top-K of ``scores`` (lax.top_k over the flattened B
        grid). With True the selection key is the SYMMETRIC rank
        ``min(rank within the A-row, rank within the B-column)`` — the
        union of "a picks b" and "b picks a" selections, grown jointly —
        so the support is closed under the A/B swap up to the per-cell
        capacity K (rows where the union overflows K drop their worst
        entries; at ``k = hB*wB`` the band is complete and exactly
        swap-closed). Ties break by the within-row rank, so the order is
        total and deterministic.

    Returns:
      ``(values [b, hA, wA, K], indices int32 [b, hA, wA, K])`` with
      indices sorted ascending per A-cell.
    """
    b, ha, wa, hb, wb = scores.shape
    nb = hb * wb
    k = int(k)
    if not 1 <= k <= nb:
        raise ValueError(
            f"band width k={k} must be in [1, hB*wB={nb}] "
            f"for a {hb}x{wb} B grid"
        )
    flat = scores.reshape(b, ha, wa, nb)
    if mutual:
        if nb > 46340:  # sqrt(int32 max): the rank key below is min*nb+ra
            raise ValueError(
                f"mutual band selection needs nb=hB*wB <= 46340 (int32 "
                f"rank key), got {nb}; use mutual=False at this grid size"
            )
        rank_a = _ranks_descending(flat)  # rank of b within its A-row
        # rank of a within its B-column: rank along the flattened A axis
        cols = scores.reshape(b, ha * wa, nb)
        rank_b = _ranks_descending(jnp.swapaxes(cols, 1, 2))  # [b, nB, nA]
        rank_b = jnp.swapaxes(rank_b, 1, 2).reshape(b, ha, wa, nb)
        # primary key: symmetric rank (union growth order); secondary:
        # the unique within-row rank — a total order, so top_k is
        # deterministic and reproducible
        key = jnp.minimum(rank_a, rank_b) * nb + rank_a
        _, idx = lax.top_k(-key, k)
    else:
        _, idx = lax.top_k(flat, k)
    idx = jnp.sort(idx, axis=-1).astype(jnp.int32)  # canonical band order
    source = flat if values_from is None else values_from.reshape(
        b, ha, wa, nb
    )
    values = jnp.take_along_axis(
        source, idx, axis=-1, mode="promise_in_bounds"  # top_k indices
    )
    return values, idx


def band_to_dense(values, indices, grid_b, fill=0.0):
    """Expand a band back to the dense ``[b, hA, wA, hB, wB]`` tensor.

    Off-band cells read ``fill`` (0 = the submanifold off-support value;
    the band scores use ``-inf`` so off-band entries carry no softmax
    mass). The scatter is static-shaped and runs ONCE at the readout /
    scoring boundary — the NC stack itself never materializes it. With
    ``K = hB*wB`` this is an exact (bitwise) inverse of `topk_band`'s
    flatten: every cell is written exactly once.
    """
    b, ha, wa, k = values.shape
    hb, wb = grid_b
    na, nb = ha * wa, hb * wb
    dense = jnp.full((b, na, nb), fill, values.dtype)
    bi = jnp.arange(b)[:, None, None]
    ai = jnp.arange(na)[None, :, None]
    dense = dense.at[bi, ai, indices.reshape(b, na, k)].set(
        values.reshape(b, na, k)
    )
    return dense.reshape(b, ha, wa, hb, wb)


def band_coverage(indices, grid_b):
    """Bool ``[b, hB, wB]``: B-cells referenced by at least one band entry.

    The per-B ("for every B cell, its best A") readout and score
    directions are only defined on covered cells; uncovered cells are
    masked out of band scores (at ``K = hB*wB`` everything is covered).
    """
    b = indices.shape[0]
    hb, wb = grid_b
    covered = jnp.zeros((b, hb * wb), bool)
    covered = covered.at[
        jnp.arange(b)[:, None], indices.reshape(b, -1)
    ].set(True)
    return covered.reshape(b, hb, wb)


def band_neighbor_pointers(indices, grid_b, kernel, swapped=False):
    """Flat gather pointers from each band entry to its 4D-conv neighbours.

    For band entry ``(a, b)`` and kernel tap ``t = (d1, d2, d3, d4)``
    (row-major over ``kernel``), the submanifold 4D convolution reads the
    band value at ``(a + oA(t), b + oB(t))`` — zero when that neighbour
    is off the A grid, off the B grid, or not on the band. The returned
    table resolves each read to a slot in the flattened band
    ``[b, hA*wA*K]`` (plus one trailing all-zero null row at index
    ``hA*wA*K``), so a layer's whole input gather is ONE
    ``take_along_axis``:

      ptr ``[b, hA, wA, K, T]`` int32, ``T = k1*k2*k3*k4``.

    ``swapped=False``: ``oA(t) = (d1, d2) - center``, ``oB = (d3, d4) -
    center`` — the plain pass. ``swapped=True``: the roles invert
    (``oA = (d3, d4)``, ``oB = (d1, d2)``), which makes
    ``GEMM(gather(ptr_swapped), w_flat)`` compute the symmetric
    ``T(net(T(x)))`` term directly on the A-major band — entry ``(a, b)``
    of the swapped pass reads exactly the taps the dense transposed pass
    reads at ``(b, a)``, in the same order, so no B-major band
    representation is ever needed (see ``ncnet_tpu/sparse/nc.py``).

    The support (hence this table) is fixed across NC layers — build once
    per band per kernel size and reuse. Construction is integer VPU work:
    per A-tap, a broadcast membership test of each target B-index against
    the K slots of the neighbouring A-cell's (sorted) band row. The
    transient comparison tensor is ``[b, hA, wA, K, kB, K]`` — bounded by
    the A-tap loop; the k^4-tap table itself is the same size as one
    gathered activation layer input.
    """
    k1, k2, k3, k4 = (int(s) for s in kernel)
    b, ha, wa, kslots = indices.shape
    hb, wb = grid_b
    na = ha * wa
    null = na * kslots  # the all-zero row appended by band_gather_neighbors

    if swapped:
        # A-offsets range over (k3, k4), B-offsets over (k1, k2); the tap
        # sequence must stay (d1, d2, d3, d4) row-major so the SAME
        # flattened kernel pairs with both tables.
        ka_i, ka_j, kb_i, kb_j = k3, k4, k1, k2
    else:
        ka_i, ka_j, kb_i, kb_j = k1, k2, k3, k4
    pa_i, pa_j = ka_i // 2, ka_j // 2
    pb_i, pb_j = kb_i // 2, kb_j // 2

    ib = indices // wb  # [b, hA, wA, K]
    jb = indices % wb

    # B-target indices for every B-offset, shared by all A-taps
    di_b = jnp.arange(kb_i) - pb_i
    dj_b = jnp.arange(kb_j) - pb_j
    tb_i = ib[..., None, None] + di_b[:, None]  # [b,hA,wA,K,kb_i,kb_j]
    tb_j = jb[..., None, None] + dj_b[None, :]
    valid_b = (tb_i >= 0) & (tb_i < hb) & (tb_j >= 0) & (tb_j < wb)
    target = (tb_i * wb + tb_j).reshape(b, ha, wa, kslots, kb_i * kb_j)
    valid_b = valid_b.reshape(b, ha, wa, kslots, kb_i * kb_j)

    # A-neighbour band rows: pad the index grid with -1 (matches no
    # target, every target is >= 0 where valid_b holds)
    idx_pad = jnp.pad(
        indices, ((0, 0), (pa_i, pa_i), (pa_j, pa_j), (0, 0)),
        constant_values=-1,
    )
    ia = jnp.arange(ha)[:, None]
    ja = jnp.arange(wa)[None, :]

    chunks = []
    for da_i in range(ka_i):
        for da_j in range(ka_j):
            nbr_rows = idx_pad[:, da_i : da_i + ha, da_j : da_j + wa, :]
            # membership of each target in the neighbour's sorted row:
            # [b, hA, wA, K, kB, Kslots] transient, bounded by this loop
            eq = (
                target[..., None]
                == nbr_rows[:, :, :, None, None, :]
            )
            found = jnp.any(eq, axis=-1)
            slot = jnp.argmax(eq, axis=-1).astype(jnp.int32)
            ni = ia + (da_i - pa_i)
            nj = ja + (da_j - pa_j)
            valid_a = (ni >= 0) & (ni < ha) & (nj >= 0) & (nj < wa)
            base = (ni * wa + nj) * kslots  # flat band row start
            ptr = jnp.where(
                found & valid_b & valid_a[None, :, :, None, None],
                base[None, :, :, None, None] + slot,
                null,
            )
            chunks.append(ptr)  # [b, hA, wA, K, kB]
    ptr = jnp.stack(chunks, axis=4)  # [b,hA,wA,K, kA, kB]
    if swapped:
        # assembled A-offset-major; the tap contract is (d1..d4) row-major
        # = B-offset-major here, so swap the two tap axes
        ptr = jnp.swapaxes(ptr, 4, 5)
    return ptr.reshape(b, ha, wa, kslots, k1 * k2 * k3 * k4)


def band_conv_gemm(x_entries, w, ptr):
    """One submanifold conv pass: neighbour gather + one GEMM (no bias).

    The primitive both band-conv backends share: the XLA path
    (``ncnet_tpu/sparse/nc.py``) runs it as-is forward AND backward, the
    fused Pallas kernel (``ncnet_tpu/kernels/band_gemm_pallas.py``) uses
    it for its gather-only VJP — the backward must stay bitwise-identical
    to the XLA path's, so there is exactly one definition of the
    contraction (operand order included: XLA picks reduction strategies
    per operand order, and the full-K bitwise contract holds against THIS
    einsum).
    """
    cout = w.shape[-1]
    g = band_gather_neighbors(x_entries, ptr)
    return jnp.einsum(
        "bnf,fo->bno",
        g,
        w.reshape(-1, cout).astype(x_entries.dtype),
        preferred_element_type=x_entries.dtype,
    )


def band_gather_neighbors(x_entries, ptr):
    """Gather every band entry's conv-window neighbours as one dense block.

    Args:
      x_entries: ``[b, N, c]`` band activations as a flat entry list
        (``N = hA*wA*K`` in any entry order — the pointer VALUES address
        this same order).
      ptr: ``[b, N, T]`` from `band_neighbor_pointers` (reshaped, and
        row-permuted/remapped by the caller when the entry order is not
        the canonical cell-major one — see the swapped symmetric pass in
        ``ncnet_tpu/sparse/nc.py``).

    Returns:
      ``[b, N, T*c]`` (tap-major, channel-minor trailing dim — the row
      layout of ``w.reshape(T*c_in, c_out)``), ready for the one MXU GEMM
      per NC layer. Off-grid / off-band pointers hit the appended null
      row and contribute EXACT zeros.
    """
    b, n, c = x_entries.shape
    t = ptr.shape[-1]
    x_pad = jnp.concatenate(
        [x_entries, jnp.zeros((b, 1, c), x_entries.dtype)], axis=1
    )
    gathered = jnp.take_along_axis(
        x_pad,
        ptr.reshape(b, n * t)[..., None],
        axis=1,
        mode="promise_in_bounds",  # pointers are clamped to null by build
    )
    return gathered.reshape(b, n, t * c)
