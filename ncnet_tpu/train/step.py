"""Jitted training/eval steps with a frozen-trunk / trainable-head split.

The reference trains only the NeighConsensus head by default (backbone
frozen, train.py:60-71, Adam lr 5e-4). Here the trainable subset is an
explicit sub-pytree, so gradients are only computed and optimizer state only
kept for what actually trains.
"""

import functools
from typing import Any, NamedTuple

import jax
import optax

from ncnet_tpu.analysis import sanitizer
from ncnet_tpu.train.loss import weak_loss, weak_loss_from_features


class TrainState(NamedTuple):
    params: Any  # full model params (trunk + head)
    opt_state: Any
    step: Any


def _finetune_tail_blocks(fe_params, cnn):
    """Deepest-to-shallowest trainable units of the trunk's tail — what
    ``fe_finetune_params`` counts backwards over (the reference unfreezes
    ``FeatureExtraction.model[-1][-(i+1)]``, train.py:60-63: the trailing
    children of the LAST trunk module).

    resnet101: layer3's bottleneck blocks — exact reference parity.
    vgg / densenet201: the reference's indexing is degenerate for these
    trunks (for densenet201 ``model[-1][-(i+1)]`` walks transition2's
    pool/conv/relu/norm sublayers; for vgg the last module is a single
    conv), so the unit granularity here is a framework interpretation:
    vgg counts over the flat conv list; densenet201 treats transition2 as
    the last unit, preceded by denseblock2's denselayers. Users comparing
    finetune configs against the reference should rely on resnet101 only.

    Returns ``(blocks, write)`` where ``write(fe, new_blocks)`` produces a
    new fe tree with the block list replaced.
    """
    if isinstance(fe_params, list):  # vgg: flat conv list

        def write_vgg(fe, new_blocks):
            return list(new_blocks)

        return list(fe_params), write_vgg
    if cnn == "resnet101":

        def write_resnet(fe, new_blocks):
            out = dict(fe)
            out["layer3"] = list(new_blocks)
            return out

        return list(fe_params["layer3"]), write_resnet
    if cnn == "densenet201":

        def write_densenet(fe, new_blocks):
            out = dict(fe)
            out["denseblock2"] = list(new_blocks[:-1])
            out["transition2"] = new_blocks[-1]
            return out

        return (
            list(fe_params["denseblock2"]) + [fe_params["transition2"]],
            write_densenet,
        )
    raise ValueError(f"no finetune tail defined for backbone {cnn!r}")


def trainable_subset(params, train_fe=False, fe_finetune_blocks=0,
                     cnn="resnet101"):
    """The trainable sub-pytree: the NC head, plus the whole trunk if
    ``train_fe``, plus the last ``fe_finetune_blocks`` tail units of the
    trunk otherwise."""
    if train_fe:
        return dict(params)
    sub = {"neigh_consensus": params["neigh_consensus"]}
    if fe_finetune_blocks > 0:
        blocks, _ = _finetune_tail_blocks(params["feature_extraction"], cnn)
        if fe_finetune_blocks > len(blocks):
            # the reference would IndexError past model[-1]'s children; a
            # silent clamp would train a different set than asked
            raise ValueError(
                f"fe_finetune_blocks={fe_finetune_blocks} exceeds the "
                f"{len(blocks)} tail units of the {cnn} trunk"
            )
        sub["fe_tail"] = blocks[-fe_finetune_blocks:]
    return sub


def merge_trainable(params, trainable, cnn="resnet101"):
    """Inverse of `trainable_subset`: write the trainable sub-pytree back
    into a full param tree (pure; no mutation)."""
    t = dict(trainable)
    tail = t.pop("fe_tail", None)
    out = dict(params)
    out.update(t)
    if tail is not None:
        fe = params["feature_extraction"]
        blocks, write = _finetune_tail_blocks(fe, cnn)
        blocks[-len(tail):] = tail
        out["feature_extraction"] = write(fe, blocks)
    return out


@functools.lru_cache(maxsize=None)
def make_optimizer(learning_rate=5e-4):
    # memoized so repeated `train()` calls in one process (resume loops,
    # preemption retries, tests) get the SAME transform object — which
    # lets `make_train_step` reuse its jitted step instead of recompiling
    # an identical program (optax transforms are stateless; state lives
    # in opt_state)
    return optax.adam(learning_rate)


def create_train_state(params, optimizer, train_fe=False, step=0,
                       fe_finetune_blocks=0, cnn="resnet101"):
    opt_state = optimizer.init(
        trainable_subset(params, train_fe, fe_finetune_blocks, cnn)
    )
    return TrainState(params=params, opt_state=opt_state, step=step)


def check_sparse_config(config):
    """Validate the sparse-band (nc_topk) settings before any tracing.

    A negative band width is always a bug, and relocalization configs
    have no band formulation (the 4D max-pool offsets are dense-readout
    constructs) — both would otherwise surface deep inside jit tracing of
    the first step."""
    nc_topk = getattr(config, "nc_topk", 0)
    if nc_topk < 0:
        raise ValueError(
            f"nc_topk={nc_topk} is negative; use 0 for the dense path or "
            "a positive top-K band width (ncnet_tpu.sparse)"
        )
    if nc_topk and config.relocalization_k_size > 1:
        raise ValueError(
            f"nc_topk={nc_topk} with relocalization_k_size="
            f"{config.relocalization_k_size}: the sparse band path does "
            "not support relocalization (train with "
            "relocalization_k_size=0, as the reference does)"
        )
    from ncnet_tpu.sparse.pipeline import resolve_corr_impl

    impl = resolve_corr_impl(config)  # raises on unknown values
    if impl != "dense" and not (nc_topk or getattr(config, "refine_factor", 0)):
        raise ValueError(
            f"corr_impl={impl!r} requires a band path (nc_topk > 0 or "
            "refine_factor > 0): the dense NC stack consumes the full "
            "correlation volume, so there is nothing to stream"
        )


def check_from_features_frozen(train_fe, fe_finetune_blocks):
    """The feature cache is only correct for a FULLY frozen trunk: any
    trunk training makes the cached features stale after one optimizer
    step — training would silently consume features of the PREVIOUS trunk
    forever. Raised at step/loop construction, before any tracing."""
    if train_fe or fe_finetune_blocks > 0:
        raise ValueError(
            "from_features (the feature cache) requires a fully frozen "
            f"trunk, but train_fe={train_fe} and fe_finetune_blocks="
            f"{fe_finetune_blocks}: the trunk would train while the loss "
            "reads features extracted from its pre-training weights. "
            "Drop --feature-cache or the finetune flags."
        )


def make_train_step(
    config, optimizer, train_fe=False, normalization="softmax", donate=True,
    fe_finetune_blocks=0, from_features=False,
):
    """Returns ``step(state, batch) -> (state, loss)``, jit-compiled.

    ``batch`` is a dict with ``source_image``/``target_image`` ``[b,h,w,3]``
    (ImageNet-normalized NHWC) — or, with ``from_features=True``,
    ``source_features``/``target_features`` precomputed trunk features
    (``ncnet_tpu.features``): the step then contains ZERO backbone ops.
    ``from_features`` with a training trunk raises immediately (the cache
    would be stale after one step). Under a `jax.sharding.Mesh` with the
    batch sharded over the data axis and params replicated, XLA inserts
    the gradient all-reduce automatically; no hand-written collectives
    needed.

    Audit contract (``scripts/audit.py``, programs ``train/*``): the
    carried ``state`` (argnum 0) is donated — the jaxpr gate's
    ``missing-donation`` rule fails if that regresses — the compiled
    program contains no f64 values and no host callbacks, and its
    walked dot/conv FLOPs must equal ``ops.accounting.train_step_flops``
    exactly (the telemetry MFU numerator).

    Mixed-precision contract (``config.half_precision``, the default
    train path): features, correlation, and the NC stack compute in
    bf16 — every MXU contraction — while the MASTER params, the loss
    reduction, the gradients as applied, and the optimizer state stay
    f32. The cast happens on the way INTO the pipeline (features /
    correlation values); gradients arriving back at the f32 params are
    accumulated and applied in f32, so repeated tiny updates are not
    swallowed by bf16's 8-bit mantissa. Checkpoints therefore always
    hold f32 weights — bf16 and f32 runs load each other's checkpoints
    freely. Verified by the ``train/*-bf16`` audit programs
    (``bf16-promotion-drift`` gate) and the 3-step drill in
    tests/test_train.py.
    """
    check_sparse_config(config)
    if from_features:
        check_from_features_frozen(train_fe, fe_finetune_blocks)
    # one jitted step per distinct configuration per process: a resumed
    # or retried `train()` reuses the executable instead of recompiling
    # an identical program (also makes resume-vs-uninterrupted bitwise
    # equality hold by construction — same executable object). The
    # sanitizer flag is part of the key because `sanitize_pytree` bakes
    # its taps in at trace time. Unhashable args (a live mesh closure,
    # say) just skip the cache.
    try:
        return _cached_train_step(
            config, optimizer, train_fe, normalization, donate,
            fe_finetune_blocks, from_features, sanitizer.is_enabled(),
        )
    except TypeError:
        return _build_train_step(
            config, optimizer, train_fe, normalization, donate,
            fe_finetune_blocks, from_features,
        )


@functools.lru_cache(maxsize=64)
def _cached_train_step(config, optimizer, train_fe, normalization, donate,
                       fe_finetune_blocks, from_features, _sanitize):
    return _build_train_step(
        config, optimizer, train_fe, normalization, donate,
        fe_finetune_blocks, from_features,
    )


def _build_train_step(config, optimizer, train_fe, normalization, donate,
                      fe_finetune_blocks, from_features):
    loss_impl = weak_loss_from_features if from_features else weak_loss
    cnn = config.feature_extraction_cnn

    def loss_fn(trainable, params, batch):
        merged = merge_trainable(params, trainable, cnn)
        return loss_impl(merged, config, batch, normalization)

    def step_fn(state, batch):
        trainable = trainable_subset(
            state.params, train_fe, fe_finetune_blocks, cnn
        )
        loss, grads = jax.value_and_grad(loss_fn)(trainable, state.params, batch)
        # identity unless --sanitize: the gradient pytree is where bf16
        # blowups surface after the forward still looks finite
        grads = sanitizer.sanitize_pytree("grad", grads)
        updates, opt_state = optimizer.update(grads, state.opt_state, trainable)
        updates = sanitizer.sanitize_pytree("update", updates)
        new_trainable = optax.apply_updates(trainable, updates)
        params = merge_trainable(state.params, new_trainable, cnn)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())


def make_eval_step(config, normalization="softmax", from_features=False):
    """Validation loss on a batch (reference process_epoch('test')).
    ``from_features=True`` evaluates from cached trunk features
    (``source_features``/``target_features`` batches) with zero backbone
    ops — same math, the trunk forward simply never runs."""
    check_sparse_config(config)
    try:
        return _cached_eval_step(config, normalization, from_features)
    except TypeError:
        return _build_eval_step(config, normalization, from_features)


@functools.lru_cache(maxsize=64)
def _cached_eval_step(config, normalization, from_features):
    return _build_eval_step(config, normalization, from_features)


def _build_eval_step(config, normalization, from_features):
    loss_impl = weak_loss_from_features if from_features else weak_loss

    def eval_fn(params, batch):
        return loss_impl(params, config, batch, normalization)

    return jax.jit(eval_fn)
