"""Jitted training/eval steps with a frozen-trunk / trainable-head split.

The reference trains only the NeighConsensus head by default (backbone
frozen, train.py:60-71, Adam lr 5e-4). Here the trainable subset is an
explicit sub-pytree, so gradients are only computed and optimizer state only
kept for what actually trains.
"""

from typing import Any, NamedTuple

import jax
import optax

from ncnet_tpu.train.loss import weak_loss


class TrainState(NamedTuple):
    params: Any  # full model params (trunk + head)
    opt_state: Any
    step: Any


def trainable_subset(params, train_fe=False):
    """The trainable sub-pytree: the NC head, plus the trunk if train_fe."""
    if train_fe:
        return dict(params)
    return {"neigh_consensus": params["neigh_consensus"]}


def make_optimizer(learning_rate=5e-4):
    return optax.adam(learning_rate)


def create_train_state(params, optimizer, train_fe=False, step=0):
    opt_state = optimizer.init(trainable_subset(params, train_fe))
    return TrainState(params=params, opt_state=opt_state, step=step)


def make_train_step(
    config, optimizer, train_fe=False, normalization="softmax", donate=True
):
    """Returns ``step(state, batch) -> (state, loss)``, jit-compiled.

    ``batch`` is a dict with ``source_image``/``target_image`` ``[b,h,w,3]``
    (ImageNet-normalized NHWC). Under a `jax.sharding.Mesh` with the batch
    sharded over the data axis and params replicated, XLA inserts the
    gradient all-reduce automatically; no hand-written collectives needed.
    """

    def loss_fn(trainable, params, batch):
        merged = dict(params)
        merged.update(trainable)
        return weak_loss(merged, config, batch, normalization)

    def step_fn(state, batch):
        trainable = trainable_subset(state.params, train_fe)
        loss, grads = jax.value_and_grad(loss_fn)(trainable, state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, trainable)
        new_trainable = optax.apply_updates(trainable, updates)
        params = dict(state.params)
        params.update(new_trainable)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())


def make_eval_step(config, normalization="softmax"):
    """Validation loss on a batch (reference process_epoch('test'))."""

    def eval_fn(params, batch):
        return weak_loss(params, config, batch, normalization)

    return jax.jit(eval_fn)
