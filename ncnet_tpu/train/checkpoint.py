"""Self-describing checkpoints.

Like the reference's ``save_checkpoint`` (lib/torch_util.py:48-61,
train.py:197-205) a checkpoint carries the architecture config with the
weights, so eval tools need no flags. Unlike the reference, optimizer state
and the step counter are saved too, making resume exact rather than
weights-only (SURVEY.md §5 notes the reference's resume drops them).

Format: a single msgpack file (flax.serialization) holding numpy-fied
pytrees, plus the config as a plain dict. A ``best_<name>`` copy is written
when the validation loss improves, mirroring the reference.
"""

import dataclasses
import os
import shutil
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

from ncnet_tpu.models.immatchnet import ImMatchNetConfig


@dataclasses.dataclass
class CheckpointData:
    config: ImMatchNetConfig
    params: Any
    opt_state: Any = None
    step: int = 0
    epoch: int = 0
    train_loss: Any = None
    val_loss: Any = None
    best_val_loss: Optional[float] = None
    # which params were training (shapes the opt_state pytree): resume must
    # rebuild the same trainable subset or from_state_dict fails opaquely
    train_fe: bool = False
    fe_finetune_blocks: int = 0


def _to_numpy(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _relistify(obj):
    """Invert to_state_dict's list -> {'0': ..} conversion on restore."""
    if isinstance(obj, dict):
        if obj and all(k.isdigit() for k in obj):
            keys = sorted(obj, key=int)
            if [int(k) for k in keys] == list(range(len(keys))):
                return [_relistify(obj[k]) for k in keys]
        return {k: _relistify(v) for k, v in obj.items()}
    return obj


def save_checkpoint(path, data: CheckpointData, is_best=False):
    payload = {
        "config": data.config.to_dict(),
        "params": serialization.to_state_dict(_to_numpy(data.params)),
        # to_state_dict turns tuple-structured pytrees (e.g. optax states)
        # into msgpack-able dicts; restore needs a target pytree.
        "opt_state": serialization.to_state_dict(_to_numpy(data.opt_state))
        if data.opt_state is not None
        else {},
        "step": int(data.step),
        "epoch": int(data.epoch),
        "train_loss": np.asarray(
            data.train_loss if data.train_loss is not None else []
        ),
        "val_loss": np.asarray(data.val_loss if data.val_loss is not None else []),
        "best_val_loss": float(
            data.best_val_loss if data.best_val_loss is not None else np.inf
        ),
        "train_fe": bool(data.train_fe),
        "fe_finetune_blocks": int(data.fe_finetune_blocks),
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(serialization.msgpack_serialize(payload))
    if is_best:
        base = os.path.basename(path)
        best = os.path.join(os.path.dirname(os.path.abspath(path)), "best_" + base)
        shutil.copyfile(path, best)


def load_checkpoint(path, opt_state_target=None) -> CheckpointData:
    """Load a checkpoint. To restore optimizer state into the right pytree
    structure, pass a freshly-initialized ``opt_state_target``."""
    with open(path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    config = ImMatchNetConfig.from_dict(payload["config"])
    opt_state = payload.get("opt_state") or None
    if opt_state is not None and opt_state_target is not None:
        opt_state = serialization.from_state_dict(opt_state_target, opt_state)
    return CheckpointData(
        config=config,
        params=_relistify(payload["params"]),
        opt_state=opt_state,
        step=int(payload.get("step", 0)),
        epoch=int(payload.get("epoch", 0)),
        train_loss=payload.get("train_loss"),
        val_loss=payload.get("val_loss"),
        best_val_loss=payload.get("best_val_loss"),
        train_fe=bool(payload.get("train_fe", False)),
        fe_finetune_blocks=int(payload.get("fe_finetune_blocks", 0)),
    )
