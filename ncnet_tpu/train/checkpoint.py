"""Self-describing, preemption-safe checkpoints.

Like the reference's ``save_checkpoint`` (lib/torch_util.py:48-61,
train.py:197-205) a checkpoint carries the architecture config with the
weights, so eval tools need no flags. Unlike the reference, optimizer state
and the step counter are saved too, making resume exact rather than
weights-only (SURVEY.md §5 notes the reference's resume drops them), plus a
LOADER CURSOR (epoch, batch index, shuffle seed, per-step losses of the
in-flight epoch) so a preempted run resumes mid-epoch, not at the last
epoch boundary.

Format: a single msgpack file (flax.serialization) holding numpy-fied
pytrees, plus the config as a plain dict. A ``best_<name>`` copy is written
when the validation loss improves, mirroring the reference.

Durability (ncnet_tpu.resilience.durable): every file — main and best —
is written temp + fsync + atomic rename with a ``<path>.sha256`` sidecar
verified at load; the last ``keep`` saves are retained as hardlinked
``<path>.step<N>`` history so `load_latest_valid` can walk back past a
torn or corrupt latest file instead of crashing the resume.
"""

import dataclasses
import os
import re
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

from ncnet_tpu.models.immatchnet import ImMatchNetConfig
from ncnet_tpu.resilience import durable
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import default_registry


def _ckpt_bytes_counter():
    return default_registry().counter(
        "checkpoint_bytes_written_total",
        "serialized checkpoint bytes durably committed",
    )


@dataclasses.dataclass
class CheckpointData:
    config: ImMatchNetConfig
    params: Any
    opt_state: Any = None
    step: int = 0
    epoch: int = 0
    train_loss: Any = None
    val_loss: Any = None
    best_val_loss: Optional[float] = None
    # which params were training (shapes the opt_state pytree): resume must
    # rebuild the same trainable subset or from_state_dict fails opaquely
    train_fe: bool = False
    fe_finetune_blocks: int = 0
    # mid-epoch resume cursor: {"epoch": int, "batch_index": int,
    # "shuffle_seed": int, "epoch_losses": [float, ...]}. None for
    # epoch-boundary checkpoints (nothing in flight).
    cursor: Optional[dict] = None


def _to_numpy(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def materialize_on_host(data: CheckpointData) -> CheckpointData:
    """Replace device params/opt_state trees with host copies — the
    O(state) gather that is the legacy single-file layout's defining
    constraint. The training loop hands this to the checkpoint writer
    thread (resilience.async_ckpt) as the legacy ``prepare`` stage, so
    the funnel runs OFF the step thread in sync and async mode alike;
    sharded saves skip it (their per-host gathers happen chunkwise
    inside `distributed.save_sharded`)."""
    return dataclasses.replace(
        data,
        params=jax.device_get(data.params),  # nclint: disable=process-zero-only-io -- legacy layout needs the full tree on one host
        opt_state=jax.device_get(data.opt_state),  # nclint: disable=process-zero-only-io -- legacy layout needs the full tree on one host
    )


def _relistify(obj):
    """Invert to_state_dict's list -> {'0': ..} conversion on restore."""
    if isinstance(obj, dict):
        if obj and all(k.isdigit() for k in obj):
            keys = sorted(obj, key=int)
            if [int(k) for k in keys] == list(range(len(keys))):
                return [_relistify(obj[k]) for k in keys]
        return {k: _relistify(v) for k, v in obj.items()}
    return obj


def _cursor_payload(cursor):
    if cursor is None:
        return {}
    return {
        "epoch": int(cursor.get("epoch", 0)),
        "batch_index": int(cursor.get("batch_index", 0)),
        "shuffle_seed": int(cursor.get("shuffle_seed", 0)),
        # float64 keeps the host-side float(loss) values bit-exact, so a
        # resumed epoch's mean loss equals the uninterrupted run's
        "epoch_losses": np.asarray(
            cursor.get("epoch_losses", []), np.float64
        ),
    }


def _cursor_from_payload(payload):
    cur = payload.get("cursor") or None
    if not cur:
        return None
    return {
        "epoch": int(cur.get("epoch", 0)),
        "batch_index": int(cur.get("batch_index", 0)),
        "shuffle_seed": int(cur.get("shuffle_seed", 0)),
        "epoch_losses": [
            float(v) for v in np.asarray(cur.get("epoch_losses", [])).ravel()
        ],
    }


def serialize_checkpoint(data: CheckpointData) -> bytes:
    payload = {
        "config": data.config.to_dict(),
        "params": serialization.to_state_dict(_to_numpy(data.params)),
        # to_state_dict turns tuple-structured pytrees (e.g. optax states)
        # into msgpack-able dicts; restore needs a target pytree.
        "opt_state": serialization.to_state_dict(_to_numpy(data.opt_state))
        if data.opt_state is not None
        else {},
        "step": int(data.step),
        "epoch": int(data.epoch),
        "train_loss": np.asarray(
            data.train_loss if data.train_loss is not None else []
        ),
        "val_loss": np.asarray(data.val_loss if data.val_loss is not None else []),
        "best_val_loss": float(
            data.best_val_loss if data.best_val_loss is not None else np.inf
        ),
        "train_fe": bool(data.train_fe),
        "fe_finetune_blocks": int(data.fe_finetune_blocks),
        "cursor": _cursor_payload(data.cursor),
    }
    return serialization.msgpack_serialize(payload)


def save_checkpoint(path, data: CheckpointData, is_best=False, keep=3):
    """Durably write ``path`` (and ``best_<name>`` when ``is_best``).

    Both files go through temp + fsync + atomic rename with a sidecar
    digest (a kill mid-write leaves the PREVIOUS checkpoint intact), and
    the newest ``keep`` saves are retained as ``<path>.step<N>`` history
    for `load_latest_valid` to fall back on.
    """
    path = os.path.abspath(path)
    with trace.span("checkpoint/save"):
        blob = serialize_checkpoint(data)
        durable.durable_write_bytes(path, blob)
        durable.retain(path, data.step, keep=keep)
        _ckpt_bytes_counter().inc(len(blob))
        if is_best:
            # ``best_`` is a hardlinked pointer to the just-committed main
            # file (O(1), no re-serialization of the tree); the link target
            # was written durably above, so readers still see old-or-new,
            # never torn
            base = os.path.basename(path)
            best = os.path.join(os.path.dirname(path), "best_" + base)
            durable.link_or_copy(path, best)


def load_checkpoint(path, opt_state_target=None) -> CheckpointData:
    """Load a checkpoint, verifying the sidecar digest when present (raises
    ``resilience.durable.IntegrityError`` on mismatch). To restore optimizer
    state into the right pytree structure, pass a freshly-initialized
    ``opt_state_target``."""
    with trace.span("checkpoint/restore"):
        payload = serialization.msgpack_restore(
            durable.read_verified_bytes(path)
        )
        config = ImMatchNetConfig.from_dict(payload["config"])
        opt_state = payload.get("opt_state") or None
        if opt_state is not None and opt_state_target is not None:
            opt_state = serialization.from_state_dict(
                opt_state_target, opt_state
            )
        return CheckpointData(
            config=config,
            params=_relistify(payload["params"]),
            opt_state=opt_state,
            step=int(payload.get("step", 0)),
            epoch=int(payload.get("epoch", 0)),
            train_loss=payload.get("train_loss"),
            val_loss=payload.get("val_loss"),
            best_val_loss=payload.get("best_val_loss"),
            train_fe=bool(payload.get("train_fe", False)),
            fe_finetune_blocks=int(payload.get("fe_finetune_blocks", 0)),
            cursor=_cursor_from_payload(payload),
        )


def load_latest_valid(path, opt_state_target=None):
    """Load the newest checkpoint that verifies AND parses, walking back
    through the main file and its ``.step<N>`` history past torn/corrupt
    files. Returns ``(CheckpointData, used_path)``; raises
    ``FileNotFoundError`` when no candidate loads."""
    return durable.latest_valid(
        path, lambda p: load_checkpoint(p, opt_state_target=opt_state_target)
    )


# --- per-host sharded layout (resilience.distributed) ------------------------
#
# Same CheckpointData in, same CheckpointData out, different bytes on disk:
# params/opt_state leaves are replaced in the msgpack meta payload by
# ``__dckpt_leaf_<i>__`` references and the tensor bytes go through
# `distributed.save_sharded` — every process writes only its own shards,
# nothing O(state) crosses a single host. The meta payload is otherwise
# IDENTICAL to the legacy format, so cursor/history semantics (and their
# bitwise-resume guarantees) carry over unchanged.

SHARDED_SUFFIX = ".dckpt"

_LEAF_REF_FMT = "__dckpt_leaf_{}__"
_LEAF_REF_RE = re.compile(r"^__dckpt_leaf_(\d+)__$")


def sharded_dir_for(path):
    """The sharded-layout directory shadowing a legacy checkpoint path:
    ``trained_models/ncnet_tpu.msgpack`` -> ``trained_models/ncnet_tpu.dckpt``
    (auto-migration keeps both names stable across the format switch)."""
    root, _ = os.path.splitext(os.path.abspath(path))
    return root + SHARDED_SUFFIX


def _sharded_parts(data: CheckpointData):
    """Split a CheckpointData into ``(leaves, meta_blob)``: the canonical
    ``(key, value)`` tensor list every process must agree on, and the tiny
    replicated msgpack payload with leaf references in place of tensors."""
    trees = {
        "params": data.params,
        "opt_state": data.opt_state if data.opt_state is not None else {},
    }
    flat, treedef = jax.tree_util.tree_flatten_with_path(trees)
    leaves = [(jax.tree_util.keystr(p), v) for p, v in flat]
    refs = jax.tree_util.tree_unflatten(
        treedef, [_LEAF_REF_FMT.format(i) for i in range(len(flat))]
    )
    payload = {
        "config": data.config.to_dict(),
        "params": serialization.to_state_dict(refs["params"]),
        "opt_state": serialization.to_state_dict(refs["opt_state"]),
        "step": int(data.step),
        "epoch": int(data.epoch),
        "train_loss": np.asarray(
            data.train_loss if data.train_loss is not None else []
        ),
        "val_loss": np.asarray(data.val_loss if data.val_loss is not None else []),
        "best_val_loss": float(
            data.best_val_loss if data.best_val_loss is not None else np.inf
        ),
        "train_fe": bool(data.train_fe),
        "fe_finetune_blocks": int(data.fe_finetune_blocks),
        "cursor": _cursor_payload(data.cursor),
    }
    return leaves, serialization.msgpack_serialize(payload)


def save_checkpoint_sharded(
    dir_path, data: CheckpointData, is_best=False, keep=3, **save_kwargs
):
    """Collectively write one sharded save under ``dir_path`` — EVERY
    process calls this with its shard-carrying (or replicated) jax arrays
    still on device; no ``jax.device_get`` of the full tree anywhere.
    ``is_best`` publishes the O(1) ``best.json`` pointer (no
    re-serialization). Returns the committed ``step_<N>/`` directory."""
    from ncnet_tpu.resilience import distributed

    with trace.span("checkpoint/save"):
        leaves, meta_blob = _sharded_parts(data)
        out = distributed.save_sharded(
            dir_path, int(data.step), leaves, meta_blob,
            keep=keep, is_best=is_best, **save_kwargs,
        )
        # this process's contribution: the replicated meta plus its own
        # unique shard chunks (numpy leaves count whole; jax.Arrays count
        # each addressable shard once — replica copies excluded)
        nbytes = len(meta_blob)
        for _, leaf in leaves:
            shards = getattr(leaf, "addressable_shards", None)
            if shards is not None:
                nbytes += sum(
                    s.data.nbytes for s in shards if s.replica_id == 0
                )
            else:
                nbytes += np.asarray(leaf).nbytes
        _ckpt_bytes_counter().inc(nbytes)
        return out


def _checkpoint_from_reader(reader, opt_state_target=None, shardings=None):
    payload = serialization.msgpack_restore(reader.meta_bytes())

    def lookup_sharding(i):
        if shardings is None:
            return None
        if callable(shardings):
            return shardings(reader.leaf_info(i)["key"], reader.leaf_info(i))
        return shardings.get(reader.leaf_info(i)["key"])

    def subst(obj):
        if isinstance(obj, str):
            m = _LEAF_REF_RE.match(obj)
            if m:
                i = int(m.group(1))
                return reader.read(i, sharding=lookup_sharding(i))
        if isinstance(obj, dict):
            return {k: subst(v) for k, v in obj.items()}
        return obj

    payload["params"] = subst(payload["params"])
    payload["opt_state"] = subst(payload["opt_state"])
    config = ImMatchNetConfig.from_dict(payload["config"])
    opt_state = payload.get("opt_state") or None
    if opt_state is not None and opt_state_target is not None:
        opt_state = serialization.from_state_dict(opt_state_target, opt_state)
    return CheckpointData(
        config=config,
        params=_relistify(payload["params"]),
        opt_state=opt_state,
        step=int(payload.get("step", 0)),
        epoch=int(payload.get("epoch", 0)),
        train_loss=payload.get("train_loss"),
        val_loss=payload.get("val_loss"),
        best_val_loss=payload.get("best_val_loss"),
        train_fe=bool(payload.get("train_fe", False)),
        fe_finetune_blocks=int(payload.get("fe_finetune_blocks", 0)),
        cursor=_cursor_from_payload(payload),
    )


def load_checkpoint_sharded(step_dir, opt_state_target=None, shardings=None):
    """Load one committed ``step_<N>/`` save (every manifest entry is
    digest-verified first). ``shardings`` — a ``{leaf_key: Sharding}`` dict
    or a ``(key, info) -> Sharding`` callable — restores those leaves as
    global jax.Arrays re-sharded for the CURRENT topology (each process
    reads only the chunk regions its local devices need); leaves without a
    sharding come back as host numpy, matching `load_checkpoint`."""
    from ncnet_tpu.resilience import distributed

    with trace.span("checkpoint/restore"):
        return _checkpoint_from_reader(
            distributed.SaveReader(step_dir),
            opt_state_target=opt_state_target,
            shardings=shardings,
        )


def load_latest_valid_sharded(dir_path, opt_state_target=None, shardings=None):
    """`load_latest_valid` over the sharded layout: newest committed
    ``step_<N>/`` whose every manifest entry verifies; walks back past
    uncommitted/torn directories AND committed saves with missing or
    corrupt shards. Returns ``(CheckpointData, step_dir)``.

    Any live `AsyncCheckpointer` is flushed first: a restore overlapping
    an in-flight async save (the elastic-restart path restores while the
    previous generation's writer may still be draining) must see the
    save either committed or absent — never mid-write — and must not
    deadlock against it.
    """
    from ncnet_tpu.resilience import async_ckpt, distributed

    async_ckpt.flush_live_checkpointers()
    return distributed.latest_valid_save(
        dir_path,
        lambda reader: _checkpoint_from_reader(
            reader, opt_state_target=opt_state_target, shardings=shardings
        ),
    )


def load_latest_valid_any(path, opt_state_target=None, shardings=None):
    """Resume from whatever layout exists at ``path``: its sharded shadow
    directory when that holds a committed save (preferring the newer
    format), else the legacy single file — a run migrated mid-history
    resumes from the right place either way. Flushes any live
    `AsyncCheckpointer` first (see `load_latest_valid_sharded`) so a
    restore never overlaps an in-flight async save."""
    from ncnet_tpu.resilience import async_ckpt

    async_ckpt.flush_live_checkpointers()
    sharded = path if os.path.isdir(path) else sharded_dir_for(path)
    if os.path.isdir(sharded):
        try:
            return load_latest_valid_sharded(
                sharded, opt_state_target=opt_state_target,
                shardings=shardings,
            )
        except FileNotFoundError:
            if os.path.isdir(path):
                raise  # explicitly a directory: no legacy fallback exists
    return load_latest_valid(path, opt_state_target=opt_state_target)
