"""Self-describing, preemption-safe checkpoints.

Like the reference's ``save_checkpoint`` (lib/torch_util.py:48-61,
train.py:197-205) a checkpoint carries the architecture config with the
weights, so eval tools need no flags. Unlike the reference, optimizer state
and the step counter are saved too, making resume exact rather than
weights-only (SURVEY.md §5 notes the reference's resume drops them), plus a
LOADER CURSOR (epoch, batch index, shuffle seed, per-step losses of the
in-flight epoch) so a preempted run resumes mid-epoch, not at the last
epoch boundary.

Format: a single msgpack file (flax.serialization) holding numpy-fied
pytrees, plus the config as a plain dict. A ``best_<name>`` copy is written
when the validation loss improves, mirroring the reference.

Durability (ncnet_tpu.resilience.durable): every file — main and best —
is written temp + fsync + atomic rename with a ``<path>.sha256`` sidecar
verified at load; the last ``keep`` saves are retained as hardlinked
``<path>.step<N>`` history so `load_latest_valid` can walk back past a
torn or corrupt latest file instead of crashing the resume.
"""

import dataclasses
import os
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

from ncnet_tpu.models.immatchnet import ImMatchNetConfig
from ncnet_tpu.resilience import durable


@dataclasses.dataclass
class CheckpointData:
    config: ImMatchNetConfig
    params: Any
    opt_state: Any = None
    step: int = 0
    epoch: int = 0
    train_loss: Any = None
    val_loss: Any = None
    best_val_loss: Optional[float] = None
    # which params were training (shapes the opt_state pytree): resume must
    # rebuild the same trainable subset or from_state_dict fails opaquely
    train_fe: bool = False
    fe_finetune_blocks: int = 0
    # mid-epoch resume cursor: {"epoch": int, "batch_index": int,
    # "shuffle_seed": int, "epoch_losses": [float, ...]}. None for
    # epoch-boundary checkpoints (nothing in flight).
    cursor: Optional[dict] = None


def _to_numpy(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _relistify(obj):
    """Invert to_state_dict's list -> {'0': ..} conversion on restore."""
    if isinstance(obj, dict):
        if obj and all(k.isdigit() for k in obj):
            keys = sorted(obj, key=int)
            if [int(k) for k in keys] == list(range(len(keys))):
                return [_relistify(obj[k]) for k in keys]
        return {k: _relistify(v) for k, v in obj.items()}
    return obj


def _cursor_payload(cursor):
    if cursor is None:
        return {}
    return {
        "epoch": int(cursor.get("epoch", 0)),
        "batch_index": int(cursor.get("batch_index", 0)),
        "shuffle_seed": int(cursor.get("shuffle_seed", 0)),
        # float64 keeps the host-side float(loss) values bit-exact, so a
        # resumed epoch's mean loss equals the uninterrupted run's
        "epoch_losses": np.asarray(
            cursor.get("epoch_losses", []), np.float64
        ),
    }


def _cursor_from_payload(payload):
    cur = payload.get("cursor") or None
    if not cur:
        return None
    return {
        "epoch": int(cur.get("epoch", 0)),
        "batch_index": int(cur.get("batch_index", 0)),
        "shuffle_seed": int(cur.get("shuffle_seed", 0)),
        "epoch_losses": [
            float(v) for v in np.asarray(cur.get("epoch_losses", [])).ravel()
        ],
    }


def serialize_checkpoint(data: CheckpointData) -> bytes:
    payload = {
        "config": data.config.to_dict(),
        "params": serialization.to_state_dict(_to_numpy(data.params)),
        # to_state_dict turns tuple-structured pytrees (e.g. optax states)
        # into msgpack-able dicts; restore needs a target pytree.
        "opt_state": serialization.to_state_dict(_to_numpy(data.opt_state))
        if data.opt_state is not None
        else {},
        "step": int(data.step),
        "epoch": int(data.epoch),
        "train_loss": np.asarray(
            data.train_loss if data.train_loss is not None else []
        ),
        "val_loss": np.asarray(data.val_loss if data.val_loss is not None else []),
        "best_val_loss": float(
            data.best_val_loss if data.best_val_loss is not None else np.inf
        ),
        "train_fe": bool(data.train_fe),
        "fe_finetune_blocks": int(data.fe_finetune_blocks),
        "cursor": _cursor_payload(data.cursor),
    }
    return serialization.msgpack_serialize(payload)


def save_checkpoint(path, data: CheckpointData, is_best=False, keep=3):
    """Durably write ``path`` (and ``best_<name>`` when ``is_best``).

    Both files go through temp + fsync + atomic rename with a sidecar
    digest (a kill mid-write leaves the PREVIOUS checkpoint intact), and
    the newest ``keep`` saves are retained as ``<path>.step<N>`` history
    for `load_latest_valid` to fall back on.
    """
    path = os.path.abspath(path)
    blob = serialize_checkpoint(data)
    durable.durable_write_bytes(path, blob)
    durable.retain(path, data.step, keep=keep)
    if is_best:
        # the same durable path as the main file: the old shutil.copyfile
        # could be observed half-written by a concurrent eval/preemption
        base = os.path.basename(path)
        best = os.path.join(os.path.dirname(path), "best_" + base)
        durable.durable_write_bytes(best, blob)


def load_checkpoint(path, opt_state_target=None) -> CheckpointData:
    """Load a checkpoint, verifying the sidecar digest when present (raises
    ``resilience.durable.IntegrityError`` on mismatch). To restore optimizer
    state into the right pytree structure, pass a freshly-initialized
    ``opt_state_target``."""
    payload = serialization.msgpack_restore(
        durable.read_verified_bytes(path)
    )
    config = ImMatchNetConfig.from_dict(payload["config"])
    opt_state = payload.get("opt_state") or None
    if opt_state is not None and opt_state_target is not None:
        opt_state = serialization.from_state_dict(opt_state_target, opt_state)
    return CheckpointData(
        config=config,
        params=_relistify(payload["params"]),
        opt_state=opt_state,
        step=int(payload.get("step", 0)),
        epoch=int(payload.get("epoch", 0)),
        train_loss=payload.get("train_loss"),
        val_loss=payload.get("val_loss"),
        best_val_loss=payload.get("best_val_loss"),
        train_fe=bool(payload.get("train_fe", False)),
        fe_finetune_blocks=int(payload.get("fe_finetune_blocks", 0)),
        cursor=_cursor_from_payload(payload),
    )


def load_latest_valid(path, opt_state_target=None):
    """Load the newest checkpoint that verifies AND parses, walking back
    through the main file and its ``.step<N>`` history past torn/corrupt
    files. Returns ``(CheckpointData, used_path)``; raises
    ``FileNotFoundError`` when no candidate loads."""
    return durable.latest_valid(
        path, lambda p: load_checkpoint(p, opt_state_target=opt_state_target)
    )
