"""Weakly-supervised matching loss.

Reference ``weak_loss`` (train.py:110-156): normalize match scores over the
source dimension (softmax by default), take the per-cell max in both
matching directions, average, and subtract the same quantity computed on
negative pairs formed by rolling the source-image batch by one
(``np.roll(arange(b), -1)``, train.py:137): ``loss = score_neg - score_pos``.

The reference mutates the batch in place to build negatives; here the roll
is applied functionally to the *extracted source features* (identical result
— the backbone is deterministic — at half the backbone cost).

Mixed precision (``config.half_precision``, see train/step.py for the
full contract): the pipeline contracts in bf16 but BOTH pipelines cast
back to f32 at the post-NC mutual-matching boundary, so the score
normalization, the per-sample means, and the final ``neg - pos``
reduction — everything a tiny loss difference must survive — run in
f32. The bf16 region is exactly the MXU-heavy middle.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ncnet_tpu.analysis import sanitizer
from ncnet_tpu.models.immatchnet import extract_features, match_pipeline
from ncnet_tpu.sparse.score import band_match_score_per_sample
from ncnet_tpu.sparse.score import normalize_scores as _normalize


def match_score_per_sample(corr, normalization="softmax"):
    """Per-sample best normalized match score, both directions averaged.

    ``corr``: ``[b, fs1, fs2, fs3, fs4]``. Returns ``[b]``; the reference's
    scalar score (train.py:125-134) is the mean of this over the batch.
    """
    b, fs1, fs2, fs3, fs4 = corr.shape
    b_avec = corr.reshape(b, fs1 * fs2, fs3, fs4)  # scores over A per B cell
    a_bvec = corr.reshape(b, fs1, fs2, fs3 * fs4)  # scores over B per A cell
    scores_b = jnp.max(_normalize(b_avec, 1, normalization), axis=1)
    scores_a = jnp.max(_normalize(a_bvec, 3, normalization), axis=3)
    return (
        jnp.mean(scores_a, axis=(1, 2)) + jnp.mean(scores_b, axis=(1, 2))
    ) / 2


def match_score(corr, normalization="softmax"):
    """Mean of the best normalized match score, both directions (scalar)."""
    return jnp.mean(match_score_per_sample(corr, normalization))


def weak_loss(params, config, batch, normalization="softmax"):
    """Positive-vs-rolled-negative weak supervision loss (scalar).

    When ``config.loss_chunk`` > 0 the post-backbone pipeline (correlation
    -> MM -> NC -> MM -> score) runs over sample chunks of that size via
    `lax.map`, rematerialized per chunk when ``config.loss_chunk_remat``
    (default True): peak memory for the big 4D tensors then scales with
    the chunk, not the batch (with it off, `lax.map` stacks residuals
    across chunks and memory scales with the batch again). When
    ``loss_chunk >= batch`` the single covering chunk applies the same
    'nc_conv'-saving checkpoint WITHOUT the `lax.map` loop (identical
    math to the unchunked path, but the remat memory/speed profile —
    set ``loss_chunk_remat=False`` for the plain no-remat path).
    Identical math throughout — the rolled-negative pairing is fixed on
    the full batch of features BEFORE chunking, and all scores are
    per-sample means.
    """
    src, tgt = batch["source_image"], batch["target_image"]
    if src.dtype == jnp.uint8 or tgt.dtype == jnp.uint8:
        # uint8 batches ship 4x less host->device traffic (the loader's
        # uint8_output path); ImageNet normalization then runs on device —
        # dtype is static under jit, so this branch costs nothing. Each
        # image is keyed on its OWN dtype: a mixed batch (hand-built
        # loader) must not double-normalize the float half.
        from ncnet_tpu.ops.image import imagenet_normalize

        if src.dtype == jnp.uint8:
            src = imagenet_normalize(src.astype(jnp.float32))
        if tgt.dtype == jnp.uint8:
            tgt = imagenet_normalize(tgt.astype(jnp.float32))
    feat_a = extract_features(params, config, src)
    feat_b = extract_features(params, config, tgt)
    return weak_loss_core(
        params["neigh_consensus"], config, feat_a, feat_b, normalization
    )


def weak_loss_from_features(params, config, batch, normalization="softmax"):
    """`weak_loss` from PRECOMPUTED trunk features — the cache-consuming
    entry point (``ncnet_tpu.features``): ``batch`` carries
    ``source_features``/``target_features`` ``[b, fh, fw, c]`` exactly as
    `extract_features` would have produced them (same dtype, same
    normalize/center flags — the feature-store manifest digest enforces
    this), and the backbone never runs. Only valid for a FROZEN trunk:
    with ``train_fe`` or ``fe_finetune_blocks > 0`` the cached features
    go stale after the first optimizer step (train/step.py raises before
    tracing ever gets here).
    """
    feat_a = batch["source_features"]
    feat_b = batch["target_features"]
    if config.half_precision:
        # mirror extract_features' dtype policy: a bf16-config store
        # already shards bf16 (no-op cast), but an f32 feature batch
        # handed to a bf16 config would otherwise run the correlation —
        # the step's FIRST contraction — in f32, which the audit's
        # bf16-promotion-drift gate flags on the declared-bf16 programs
        feat_a = feat_a.astype(jnp.bfloat16)
        feat_b = feat_b.astype(jnp.bfloat16)
    feat_a = sanitizer.tap("features", feat_a)
    feat_b = sanitizer.tap("features", feat_b)
    return weak_loss_core(
        params["neigh_consensus"], config, feat_a, feat_b, normalization
    )


def weak_loss_core(nc_params, config, feat_a, feat_b, normalization="softmax"):
    """The shared post-backbone loss: rolled-negative pairing, optional
    chunking/remat, symmetric score difference. Identical math whether the
    features come from the in-graph trunk (`weak_loss`) or from a cache
    (`weak_loss_from_features`)."""
    if config.relocalization_k_size > 1:
        raise ValueError(
            "weak_loss does not support relocalization configs "
            "(the reference trains with relocalization_k_size=0; "
            "relocalization is an eval-time memory optimization)"
        )
    feat_a_neg = jnp.roll(feat_a, -1, axis=0)

    if getattr(config, "refine_factor", 0):
        # coarse-to-fine path (ncnet_tpu.refine, takes precedence over
        # nc_topk exactly like match_pipeline): the coarse band runs on
        # pooled features and the refined FINE-grid band is scored with
        # the same band scorer the sparse path uses — at refine_factor=1
        # the two branches produce bitwise-identical losses.
        from ncnet_tpu.refine.pipeline import refine_match_pipeline

        def _refine_score(fa, fb):
            values_f, indices_f, grid_f = refine_match_pipeline(
                nc_params, config, fa, fb
            )
            return band_match_score_per_sample(
                values_f, indices_f, grid_f, normalization
            )

        def pair_scores(fa, fb, fan):
            return (
                sanitizer.tap("score_pos", _refine_score(fa, fb)),
                sanitizer.tap("score_neg", _refine_score(fan, fb)),
            )

    elif getattr(config, "nc_topk", 0):
        # sparse-band path (ncnet_tpu.sparse): positives AND negatives are
        # scored on each pair's own top-K band — the NC stack never sees
        # the dense correlation. The chunking/remat machinery below wraps
        # pair_scores unchanged; the 'nc_conv' save-policy tags are set by
        # the sparse stack exactly like the dense one.
        from ncnet_tpu.sparse.pipeline import sparse_match_pipeline

        def _band_score(fa, fb):
            band, indices, grid_b = sparse_match_pipeline(
                nc_params, config, fa, fb
            )
            return band_match_score_per_sample(
                band, indices, grid_b, normalization
            )

        def pair_scores(fa, fb, fan):
            return (
                sanitizer.tap("score_pos", _band_score(fa, fb)),
                sanitizer.tap("score_neg", _band_score(fan, fb)),
            )

    else:

        def pair_scores(fa, fb, fan):
            corr_pos = match_pipeline(nc_params, config, fa, fb)
            corr_neg = match_pipeline(nc_params, config, fan, fb)
            return (
                sanitizer.tap(
                    "score_pos",
                    match_score_per_sample(corr_pos, normalization),
                ),
                sanitizer.tap(
                    "score_neg",
                    match_score_per_sample(corr_neg, normalization),
                ),
            )

    chunk = getattr(config, "loss_chunk", 0) or 0
    b = feat_a.shape[0]
    if chunk >= b > 0 and getattr(config, "loss_chunk_remat", True):
        # One chunk covering the whole batch: apply the same conv-saving
        # remat WITHOUT the lax.map loop (buffers crossing the loop get
        # layout-pessimized by XLA; a plain checkpoint does not).
        remat_fn = jax.checkpoint(
            lambda fa, fb, fan: pair_scores(fa, fb, fan),
            policy=jax.checkpoint_policies.save_only_these_names("nc_conv"),
        )
        pos, neg = remat_fn(feat_a, feat_b, feat_a_neg)
        return sanitizer.tap("weak_loss", jnp.mean(neg) - jnp.mean(pos))
    if 0 < chunk < b:
        if b % chunk:
            raise ValueError(f"batch {b} not divisible by loss_chunk {chunk}")
        shape = (b // chunk, chunk) + feat_a.shape[1:]
        chunks = (
            feat_a.reshape(shape),
            feat_b.reshape(shape),
            feat_a_neg.reshape(shape),
        )
        chunk_fn = lambda t: pair_scores(*t)
        if getattr(config, "loss_chunk_remat", True):
            # Save the NC conv outputs (tagged 'nc_conv' in
            # neigh_consensus_apply) across the remat boundary: the
            # backward pass then re-runs only the cheap elementwise ops
            # (MM ratios, relu, softmax scores), not the convolutions —
            # the convs are ~98% of the chunk's forward FLOPs. (Also
            # saving the channel-fused impls' gathered patches was
            # measured WORSE: buffers living across the lax.map loop get
            # layout-pessimized by XLA — 5.1x padding, OOM.)
            chunk_fn = jax.checkpoint(
                chunk_fn,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "nc_conv"
                ),
            )
        pos, neg = lax.map(chunk_fn, chunks)
        # JAX drops debug callbacks from the PRIMAL pass of a
        # differentiated lax.map (they fire again only when the remat'd
        # backward re-runs the body), so under grad the in-chunk stage
        # probes go silent on the no-remat path; probing the stacked
        # chunk outputs here keeps score-level visibility regardless
        # (see analysis/sanitizer.py "Coverage under lax.map")
        pos = sanitizer.tap("score_pos_chunks", pos)
        neg = sanitizer.tap("score_neg_chunks", neg)
        score_pos, score_neg = jnp.mean(pos), jnp.mean(neg)
    else:
        pos, neg = pair_scores(feat_a, feat_b, feat_a_neg)
        score_pos, score_neg = jnp.mean(pos), jnp.mean(neg)

    return sanitizer.tap("weak_loss", score_neg - score_pos)
