"""Weakly-supervised matching loss.

Reference ``weak_loss`` (train.py:110-156): normalize match scores over the
source dimension (softmax by default), take the per-cell max in both
matching directions, average, and subtract the same quantity computed on
negative pairs formed by rolling the source-image batch by one
(``np.roll(arange(b), -1)``, train.py:137): ``loss = score_neg - score_pos``.

The reference mutates the batch in place to build negatives; here the roll
is applied functionally to the *extracted source features* (identical result
— the backbone is deterministic — at half the backbone cost).
"""

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import extract_features, match_pipeline


def _normalize(x, axis, normalization):
    if normalization is None or normalization == "none":
        return x
    if normalization == "softmax":
        return jax.nn.softmax(x, axis=axis)
    if normalization == "l1":
        return x / (jnp.sum(x, axis=axis, keepdims=True) + 1e-4)
    raise ValueError(f"unknown score normalization {normalization!r}")


def match_score(corr, normalization="softmax"):
    """Mean of the best normalized match score, both directions.

    ``corr``: ``[b, fs1, fs2, fs3, fs4]``. Returns a scalar: the
    reference's ``mean(scores_A + scores_B) / 2`` (train.py:125-134).
    """
    b, fs1, fs2, fs3, fs4 = corr.shape
    b_avec = corr.reshape(b, fs1 * fs2, fs3, fs4)  # scores over A per B cell
    a_bvec = corr.reshape(b, fs1, fs2, fs3 * fs4)  # scores over B per A cell
    scores_b = jnp.max(_normalize(b_avec, 1, normalization), axis=1)
    scores_a = jnp.max(_normalize(a_bvec, 3, normalization), axis=3)
    return (jnp.mean(scores_a) + jnp.mean(scores_b)) / 2


def weak_loss(params, config, batch, normalization="softmax"):
    """Positive-vs-rolled-negative weak supervision loss (scalar)."""
    if config.relocalization_k_size > 1:
        raise ValueError(
            "weak_loss does not support relocalization configs "
            "(the reference trains with relocalization_k_size=0; "
            "relocalization is an eval-time memory optimization)"
        )
    feat_a = extract_features(params, config, batch["source_image"])
    feat_b = extract_features(params, config, batch["target_image"])

    corr_pos = match_pipeline(params["neigh_consensus"], config, feat_a, feat_b)
    score_pos = match_score(corr_pos, normalization)

    feat_a_neg = jnp.roll(feat_a, -1, axis=0)
    corr_neg = match_pipeline(params["neigh_consensus"], config, feat_a_neg, feat_b)
    score_neg = match_score(corr_neg, normalization)

    return score_neg - score_pos
