"""Epoch-based training driver (the reference train.py loop, TPU-native).

Reference behavior preserved (train.py:158-205): per-epoch train + val
passes over CSV pair datasets, checkpoint each epoch with a ``best_`` copy
on improved validation loss, loss histories stored in the checkpoint.
Improvements over the reference: exact resume (optimizer state + epoch),
data-parallel over a device mesh, donate-args jitted step.

Preemption safety (ncnet_tpu.resilience): checkpoints are durable
(temp + fsync + rename + digest + rotation); ``save_every_steps`` writes
mid-epoch snapshots carrying a loader cursor (epoch, batch index, shuffle
seed, the in-flight epoch's per-step losses) so a killed run resumes at
the exact step — bitwise-identical to never having been killed; a
``preemption`` guard (resilience.signals.PreemptionGuard) turns
SIGTERM/SIGINT into one final cursor checkpoint and a clean return. The
loader is driven by ABSOLUTE epoch (`iter_epoch`) so epoch shuffles are
identical whether or not the run was ever restarted.
"""

import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from ncnet_tpu.analysis import sanitizer
from ncnet_tpu.ops.accounting import (
    V5E_BF16_PEAK_FLOPS,
    peak_flops,
    train_step_flops_for_batch,
)
from ncnet_tpu.parallel import mesh as mesh_lib
from ncnet_tpu.parallel.mesh import make_hybrid_mesh, replicate, shard_batch
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.resilience.async_ckpt import AsyncCheckpointer, device_snapshot
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.profiler import ProfileWindow
from ncnet_tpu.telemetry.registry import default_registry
from ncnet_tpu.train.checkpoint import (
    CheckpointData,
    materialize_on_host,
    save_checkpoint,
    save_checkpoint_sharded,
    sharded_dir_for,
)
from ncnet_tpu.train.step import (
    create_train_state,
    make_eval_step,
    make_optimizer,
    make_train_step,
)


def _device_batch(mesh, batch):
    # image batches and cached-feature batches (data/features_loader.py)
    # ride the same path; feature batches from a pinned loader are already
    # device arrays, for which jnp.asarray is a no-op
    keys = (
        ("source_features", "target_features")
        if "source_features" in batch
        else ("source_image", "target_image")
    )
    sub = {k: batch[k] for k in keys}
    if mesh is not None:
        # host-local numpy goes straight to shard_batch (multi-host
        # assembles the global array from per-process slices)
        return shard_batch(mesh, sub)
    return {k: jnp.asarray(v) for k, v in sub.items()}


class _LossLog:
    """Per-epoch loss accumulator with an INCREMENTALLY-converted host
    prefix: every device loss crosses D2H exactly once, no matter how
    many times the host list is needed (mid-epoch cursor snapshots, the
    log-line sync, the epoch mean). The previous code re-ran ``float(l)``
    over the whole prefix at every snapshot — O(n^2) syncs per epoch."""

    def __init__(self, seed_losses=None):
        # seeded values (a resumed epoch's already-computed step losses)
        # are host floats already; only appended device scalars transfer
        self._host = [float(v) for v in (seed_losses or [])]
        self._pending = []

    def append(self, loss):
        self._pending.append(loss)

    def host(self):
        """The full host-float list; converts only the unconverted tail
        (and thereby syncs on the most recent step)."""
        if self._pending:
            self._host.extend(float(l) for l in self._pending)
            self._pending.clear()
        return self._host

    def __len__(self):
        return len(self._host) + len(self._pending)


def _prefetch_device_batches(mesh, loader, size=2):
    """Double-buffer host->device transfers: the transfer for batch i+1 is
    issued while step i runs on the device (device_put is asynchronous), so
    H2D never sits on the critical path between steps. ``size=2`` is the
    standard flax prefetch depth: one batch in flight, one being consumed."""
    from collections import deque

    queue = deque()
    it = iter(loader)

    def enqueue():
        try:
            queue.append(_device_batch(mesh, next(it)))
        except StopIteration:
            return False
        return True

    while len(queue) < size and enqueue():
        pass
    while queue:
        yield queue.popleft()
        enqueue()


def _epoch_iter(loader, epoch, skip=0):
    """Drive a loader by ABSOLUTE epoch when it supports `iter_epoch`
    (resume-correct shuffle: the epoch-e batch sequence is the same
    whether or not the process was ever restarted). Plain iterables (tests
    pass lists of batches) fall back to their own ordering."""
    if hasattr(loader, "iter_epoch"):
        return loader.iter_epoch(epoch, skip_batches=skip)
    it = iter(loader)
    for _ in range(skip):
        next(it, None)
    return it


def _close_quietly(*loaders):
    for loader in loaders:
        close = getattr(loader, "close", None)
        if close is not None:
            try:
                close()
            except Exception as e:  # cleanup must never mask the real exit
                print(f"loader close failed: {e!r}", flush=True)


def train(
    config,
    params,
    train_loader,
    val_loader=None,
    num_epochs=5,
    learning_rate=5e-4,
    train_fe=False,
    fe_finetune_blocks=0,
    checkpoint_dir="trained_models",
    checkpoint_name="ncnet_tpu.msgpack",
    data_parallel=True,
    start_epoch=0,
    start_step=0,
    start_batch=0,
    start_epoch_losses=None,
    opt_state=None,
    initial_best_val=None,
    initial_train_hist=None,
    initial_val_hist=None,
    log_every=10,
    profile_dir=None,
    profile_steps=(3, 8),
    save_every_steps=0,
    keep_checkpoints=3,
    preemption=None,
    from_features=False,
    distributed_checkpoints=False,
    async_checkpoints=False,
    cluster=None,
):
    """Run the training loop; returns ``(state, history)``.

    ``from_features=True`` consumes cached-trunk-feature batches
    (``source_features``/``target_features``, e.g. from
    `ncnet_tpu.data.features_loader.FeatureBatchLoader`) instead of image
    batches — zero backbone ops per step; requires a fully frozen trunk
    (raises otherwise, before any compilation).

    Resilience knobs: ``start_batch``/``start_epoch_losses`` resume
    mid-epoch from a checkpoint cursor; ``save_every_steps > 0`` writes a
    durable cursor snapshot every N steps; ``preemption`` (an object with
    a ``requested`` flag, e.g. `resilience.signals.PreemptionGuard`)
    triggers one final snapshot and a clean early return —
    ``history["preempted"]`` reports which way the loop ended. Loaders
    exposing ``close()`` are closed on every exit path.

    ``distributed_checkpoints=True`` switches saves to the per-host
    sharded layout (`resilience.distributed`): EVERY process participates
    in each snapshot, writing only its own addressable shards under
    ``<checkpoint_name stem>.dckpt/step_<N>/`` — the O(state) process-0
    ``device_get`` funnel of the legacy path disappears. Metrics and plots
    stay process-0-only (they are tiny and host-side either way).

    ``cluster`` (a started `resilience.cluster.ClusterSupervisor`) adds
    multi-host coordination: its health check runs at every step
    boundary, before every cross-process collective in `parallel.mesh`,
    and inside the sharded-save barrier polls — a dead peer raises a
    typed ``PeerDown`` within the staleness budget instead of hanging a
    collective; a stop flag published by ANY host (its PreemptionGuard,
    or ours) triggers a drain round that lands every host on the SAME
    final committed save step; and in async multi-process sharded mode
    the supervisor's save-cursor consensus re-enables coalescing (every
    host skips or saves each overlapped snapshot together).

    ``async_checkpoints=True`` overlaps mid-epoch cursor saves with
    training (`resilience.async_ckpt`): the step thread hands the writer
    thread a donation-proof device snapshot (an O(leaves) copy DISPATCH,
    no host sync) and keeps stepping while D2H + serialization + the
    durable write happen off-thread; back-to-back snapshots coalesce to
    the newest. Epoch-end/best and preemption-final saves still barrier
    (``flush``), and the loop exit joins the writer — shutdown semantics
    and the crash/walk-back contract are unchanged. In sync mode the
    SAME writer thread is used with every save blocking, so the
    ``device_get`` funnel is off the step thread either way and sync and
    async runs produce byte-identical checkpoint files.
    """
    # guard every cross-process collective (batch assembly, replication)
    # with the cluster health check for the duration of the run: a dead
    # peer raises a typed PeerDown at collective ENTRY instead of hanging
    # the transfer (parallel.mesh.checked_collective)
    prev_check = (
        mesh_lib.set_collective_check(cluster.check)
        if cluster is not None
        else None
    )
    try:
        return _train_impl(
            config, params, train_loader, val_loader, num_epochs,
            learning_rate, train_fe, fe_finetune_blocks, checkpoint_dir,
            checkpoint_name, data_parallel, start_epoch, start_step,
            start_batch, start_epoch_losses, opt_state, initial_best_val,
            initial_train_hist, initial_val_hist, log_every, profile_dir,
            profile_steps, save_every_steps, keep_checkpoints, preemption,
            from_features, distributed_checkpoints, async_checkpoints,
            cluster,
        )
    finally:
        if cluster is not None:
            mesh_lib.set_collective_check(prev_check)
        _close_quietly(train_loader, val_loader)


def _train_impl(
    config, params, train_loader, val_loader, num_epochs, learning_rate,
    train_fe, fe_finetune_blocks, checkpoint_dir, checkpoint_name,
    data_parallel, start_epoch, start_step, start_batch, start_epoch_losses,
    opt_state, initial_best_val, initial_train_hist, initial_val_hist,
    log_every, profile_dir, profile_steps, save_every_steps,
    keep_checkpoints, preemption, from_features, distributed_checkpoints,
    async_checkpoints, cluster,
):
    if from_features:
        from ncnet_tpu.train.step import check_from_features_frozen

        check_from_features_frozen(train_fe, fe_finetune_blocks)
    # hybrid mesh: leading axis maps across hosts (DCN), trailing within a
    # host's ICI domain; reduces to a plain all-device mesh single-process
    mesh = make_hybrid_mesh() if data_parallel and jax.device_count() > 1 else None
    if mesh is not None:
        params = replicate(mesh, params)

    optimizer = make_optimizer(learning_rate)
    state = create_train_state(
        params, optimizer, train_fe, step=start_step,
        fe_finetune_blocks=fe_finetune_blocks,
        cnn=config.feature_extraction_cnn,
    )
    if opt_state is not None:
        if isinstance(opt_state, dict):
            # raw state dict from a checkpoint loaded without a target
            from flax import serialization

            opt_state = serialization.from_state_dict(state.opt_state, opt_state)
        state = state._replace(opt_state=opt_state)
    if mesh is not None:
        state = state._replace(opt_state=replicate(mesh, state.opt_state))

    train_step = make_train_step(
        config, optimizer, train_fe, fe_finetune_blocks=fe_finetune_blocks,
        from_features=from_features,
    )
    eval_step = make_eval_step(config, from_features=from_features)

    best_val = float("inf") if initial_best_val is None else float(initial_best_val)
    # Resume continues the loss histories rather than restarting them (the
    # reference keeps full train_loss/test_loss arrays, train.py:197-205).
    train_hist = [float(v) for v in np.asarray(initial_train_hist).ravel()] \
        if initial_train_hist is not None else []
    val_hist = [float(v) for v in np.asarray(initial_val_hist).ravel()] \
        if initial_val_hist is not None else []
    # Optional jax.profiler capture (SURVEY §5: the reference has no
    # tracing at all): trace steps [profile_steps) of the first epoch into
    # profile_dir, viewable with tensorboard/xprof.
    metrics_path = os.path.join(checkpoint_dir, "metrics.jsonl")
    if jax.process_index() == 0 and start_epoch == 0 and start_batch == 0:
        # fresh (non-resume) run: don't mix epochs with a prior run's
        # lines; any resume — epoch- or step-granular — keeps appending
        os.makedirs(checkpoint_dir, exist_ok=True)
        open(metrics_path, "w").close()

    # One checkpoint writer per run, in SYNC mode too: every save's D2H
    # funnel + serialization + fsync runs on the writer thread (submits
    # just block for it), so the step thread never executes the gather
    # itself. Multi-process sharded saves are collective — a snapshot
    # coalesced on one host but written on another would wedge the
    # commit barrier — so without a cluster supervisor coalescing
    # degrades to deterministic backpressure there (every process writes
    # the same save sequence). WITH a supervisor, skipping becomes the
    # collective decision it has to be: the save-cursor consensus round
    # (cluster.agree_save_cursor) makes every host coalesce or save each
    # overlapped snapshot together, re-enabling coalescing multi-process.
    multi_sharded = distributed_checkpoints and jax.process_count() > 1
    consensus = cluster is not None and multi_sharded
    ackpt = AsyncCheckpointer(
        async_mode=async_checkpoints,
        coalesce=consensus or not multi_sharded,
        coalesce_arbiter=cluster.agree_save_cursor if consensus else None,
    )
    # a second SIGTERM during an in-flight final save gets a bounded
    # grace to commit before the guard re-delivers (signals.py)
    preempt_flush = lambda: ackpt.flush(timeout=5.0, reraise=False)
    if preemption is not None and hasattr(preemption, "add_flush_hook"):
        preemption.add_flush_hook(preempt_flush)

    def snapshot(epoch, losses, is_best=False, cursor_batch=None, wait=True):
        """One durable checkpoint; ``cursor_batch`` marks a mid-epoch
        snapshot carrying the loader cursor for step-granular resume.
        Sharded mode is COLLECTIVE — every process enters and writes its
        own shards; legacy mode stays process-0-only. ``wait=False``
        (async mode only) overlaps the save with training: the handoff
        snapshots the immutable tree refs and returns; D2H and the
        durable write happen on the writer thread."""
        if not distributed_checkpoints and jax.process_index() != 0:
            return  # legacy multi-host: only process 0 writes checkpoints
        cursor = None
        if cursor_batch is not None:
            cursor = {
                "epoch": epoch,
                "batch_index": cursor_batch,
                "shuffle_seed": int(getattr(train_loader, "seed", 0)),
                # float() is exact f32->f64, so a resumed epoch's mean
                # equals the uninterrupted run's bit-for-bit; the _LossLog
                # converts incrementally — each loss crosses D2H once even
                # across many snapshots (the old per-snapshot full re-
                # conversion made mid-epoch saves O(n^2) in syncs)
                "epoch_losses": list(losses.host()),
            }
        os.makedirs(checkpoint_dir, exist_ok=True)
        overlap = async_checkpoints and not wait
        params_ref, opt_ref = state.params, state.opt_state
        if overlap:
            # the jitted step donates its carried state, so an overlapped
            # writer can't hold the live buffers across the next dispatch;
            # snapshot through device-side copies (dispatch only, no sync)
            params_ref = device_snapshot(params_ref)
            opt_ref = device_snapshot(opt_ref)
        data = CheckpointData(
            config=config,
            params=params_ref,
            opt_state=opt_ref,
            step=int(state.step),
            epoch=epoch if cursor_batch is not None else epoch + 1,
            train_loss=np.asarray(train_hist),
            val_loss=np.asarray(val_hist),
            best_val_loss=best_val,
            train_fe=train_fe,
            fe_finetune_blocks=fe_finetune_blocks,
            cursor=cursor,
        )
        if distributed_checkpoints:
            # params/opt_state stay on device: each process serializes
            # only the shard chunks it owns — nothing O(state) funnels
            # through any single host (the chunk gathers run inside
            # save_sharded, on the writer thread)
            sdir = sharded_dir_for(os.path.join(checkpoint_dir, checkpoint_name))

            def write(d):
                # the barrier polls run the cluster health check so a
                # peer that dies mid-save raises typed PeerDown instead
                # of burning the full barrier timeout
                save_checkpoint_sharded(
                    sdir, d, is_best=is_best, keep=keep_checkpoints,
                    health_check=cluster.check if cluster is not None else None,
                )

            prepare = None
        else:
            path = os.path.join(checkpoint_dir, checkpoint_name)

            def write(d):
                save_checkpoint(path, d, is_best=is_best, keep=keep_checkpoints)

            # the O(state) gather the legacy single-file format demands,
            # as the writer-thread prepare stage (checkpoint.py)
            prepare = materialize_on_host
        ackpt.submit(
            data, write, prepare=prepare, step=int(data.step), wait=wait
        )

    # Telemetry (ncnet_tpu.telemetry): per-step spans split host data-wait
    # vs device compute dispatch vs the D2H loss sync; gauges carry the
    # log-interval step time and analytic MFU — the SAME FLOP count
    # bench.py reports (ops.accounting), so a --telemetry training run and
    # a bench run disagree only by measurement, never by accounting.
    metrics = default_registry()
    m_steps = metrics.counter("train_steps_total", "optimizer steps taken")
    m_step_s = metrics.histogram(
        "train_step_seconds", "wall seconds per training step"
    )
    m_step_ms = metrics.gauge(
        "train_step_ms", "mean ms/step over the last log interval"
    )
    m_mfu = metrics.gauge(
        "train_mfu",
        "analytic model FLOP utilization vs the v5e bf16 peak",
    )
    # the f32 twin of train_mfu (ops.accounting dual-MFU pair): the same
    # achieved rate against the f32 ceiling, so f32 runs are judged
    # against a peak their compute dtype can reach
    m_mfu_f32 = metrics.gauge(
        "train_mfu_vs_f32_peak",
        "analytic model FLOP utilization vs the v5e f32 peak",
    )
    window = ProfileWindow(profile_dir, profile_steps)
    preempted = False
    done = object()  # prefetch-exhausted sentinel
    clean_exit = False
    try:
        for epoch in range(start_epoch, num_epochs):
            t0 = time.perf_counter()
            t_last = t0
            t_step = t0
            skip = start_batch if epoch == start_epoch else 0
            # a resumed epoch re-seeds its already-computed step losses so the
            # epoch mean is over ALL its steps, not just the replayed tail
            losses = _LossLog(start_epoch_losses if skip else None)
            batches = _epoch_iter(train_loader, epoch, skip=skip)
            prefetch = _prefetch_device_batches(mesh, batches)

            def sync_losses():
                # D2H sync so the device finishes the profiled steps before a
                # trace closes (block_until_ready does not block on the
                # tunneled platform — see bench.py)
                if len(losses):
                    losses.host()

            i = skip - 1
            while True:
                # the data-wait span is the host blocked on the loader +
                # H2D prefetch — when it dominates, the input pipeline is
                # the bottleneck, not the device
                with trace.span("step/data_wait"):
                    dbatch = next(prefetch, done)
                if dbatch is done:
                    break
                i += 1
                if profile_dir and epoch == start_epoch:
                    window.on_step(i, sync=sync_losses)
                with trace.span("step/device_compute"):
                    # asynchronous dispatch: host-side cost of launching the
                    # step; device execution time lands in the NEXT sync
                    # (step/loss_sync or the epoch-end mean)
                    state, loss = train_step(state, dbatch)
                losses.append(loss)
                m_steps.inc()
                now_step = time.perf_counter()
                m_step_s.observe(now_step - t_step)
                t_step = now_step
                faultinject.fire("step.boundary")
                if sanitizer.is_enabled():
                    # sanitized runs are diagnostic: pay a per-step D2H sync so
                    # a non-finite loss stops IMMEDIATELY with the per-stage
                    # report + first non-finite stage, instead of averaging
                    # NaN into the epoch
                    with trace.span("step/loss_sync"):
                        loss_last = losses.host()[-1]
                    sanitizer.check_finite_or_report(
                        loss_last,
                        context=f"epoch {epoch + 1} step {i + 1}",
                    )
                if (i + 1) % log_every == 0:
                    # host() syncs on the just-appended loss, keeping the step
                    # timing honest without a second transfer of that loss
                    with trace.span("step/loss_sync"):
                        loss_host = losses.host()[-1]
                    now = time.perf_counter()
                    ms = (now - t_last) / log_every * 1e3
                    t_last = now
                    m_step_ms.set(ms)
                    achieved = train_step_flops_for_batch(
                        config, dbatch, from_features=from_features,
                        trunk_trainable=train_fe or fe_finetune_blocks > 0,
                    ) / (max(ms, 1e-6) / 1e3)
                    m_mfu.set(achieved / V5E_BF16_PEAK_FLOPS)
                    m_mfu_f32.set(achieved / peak_flops("float32"))
                    print(
                        f"epoch {epoch + 1} [{i + 1}/{len(train_loader)}] "
                        f"loss {loss_host:.6f} ({ms:.0f} ms/step)",
                        flush=True,
                    )
                want_preempt = preemption is not None and preemption.requested
                if cluster is not None:
                    # a dead peer surfaces HERE as a typed PeerDown, not
                    # as a hang inside the next collective or barrier
                    cluster.check("step boundary")
                    if want_preempt:
                        # the guard's in-handler publish is best-effort;
                        # republishing at the boundary is idempotent and
                        # guarantees the flag reaches the peers
                        cluster.publish_stop(reason="preemption signal")
                    if cluster.stop_requested():
                        # non-blocking drain state machine: ack once,
                        # keep training (and keep joining the collective
                        # save schedule — that's what bounds host skew
                        # and keeps the cluster deadlock-free while the
                        # acks settle), stop at the agreed step once the
                        # leader publishes it
                        drain_at = cluster.drain_step(
                            int(state.step),
                            interval=max(int(save_every_steps or 0), 1),
                        )
                        want_preempt = (
                            drain_at is not None
                            and int(state.step) >= drain_at
                        )
                    else:
                        want_preempt = False
                if (
                    save_every_steps and (i + 1) % save_every_steps == 0
                ) or want_preempt:
                    # mid-epoch durable snapshot with the loader cursor; the
                    # float() syncs are confined to snapshot boundaries
                    snapshot(epoch, losses, cursor_batch=i + 1, wait=want_preempt)
                if want_preempt:
                    print(
                        f"preempted at epoch {epoch + 1} step {i + 1}: "
                        "checkpoint written, exiting cleanly",
                        flush=True,
                    )
                    preempted = True
                    break
            window.close(sync=sync_losses)  # epoch shorter than the window
            if preempted:
                break
            train_loss = float(np.mean(losses.host())) if len(losses) else 0.0
            train_hist.append(train_loss)

            val_loss = float("nan")
            if val_loader is not None:
                # collect DEVICE scalars and convert after the loop: a float()
                # inside it would force a D2H sync per batch, serializing the
                # validation pass against _prefetch_device_batches' H2D overlap
                vdev = [
                    eval_step(state.params, b)
                    for b in _prefetch_device_batches(
                        mesh, _epoch_iter(val_loader, epoch)
                    )
                ]
                vlosses = [float(v) for v in vdev]
                val_loss = float(np.mean(vlosses)) if vlosses else float("nan")
            val_hist.append(val_loss)
            is_best = val_loss < best_val
            best_val = min(best_val, val_loss) if not np.isnan(val_loss) else best_val

            epoch_s = time.perf_counter() - t0
            print(
                f"epoch {epoch + 1}/{num_epochs}: train {train_loss:.6f} "
                f"val {val_loss:.6f} ({epoch_s:.1f}s)"
                + (" [best]" if is_best else ""),
                flush=True,
            )
            # Metrics/plots stay process-0-only (tiny, host-side); the snapshot
            # below runs on EVERY process — in sharded mode it is a collective
            # (non-zero processes no-op out of it in the legacy layout).
            if jax.process_index() == 0:
                # Persisted observability (SURVEY §5: the reference is
                # print-only; its loss arrays live only inside checkpoints):
                # per-epoch metrics as JSONL plus a loss-curve figure, next to
                # the checkpoint.
                os.makedirs(checkpoint_dir, exist_ok=True)
                with open(metrics_path, "a") as f:
                    f.write(json.dumps({
                        "epoch": epoch + 1,
                        "train_loss": train_loss,
                        # strict JSON: NaN (no/empty val loader) is not valid
                        "val_loss": None if np.isnan(val_loss) else val_loss,
                        "epoch_seconds": round(epoch_s, 2),
                        "steps": int(state.step),
                        "best": bool(is_best),
                    }) + "\n")
                try:
                    import matplotlib.pyplot as plt

                    from ncnet_tpu.utils.plot import plot_loss_curves, save_plot

                    fig = plot_loss_curves(train_hist, val_hist)
                    save_plot(
                        os.path.join(checkpoint_dir, "loss_curve.png"), fig=fig
                    )
                    plt.close(fig)
                except Exception as e:  # headless plotting must never kill training
                    print(f"loss-curve plot skipped: {e}", flush=True)
            snapshot(epoch, losses, is_best=is_best)
        clean_exit = True
    finally:
        if preemption is not None and hasattr(preemption, "remove_flush_hook"):
            preemption.remove_flush_hook(preempt_flush)
        # loop-exit barrier: join the writer. On the clean path a failed
        # async save raises HERE (training must not outlive its
        # durability); on the exception path close stays quiet so it
        # never masks the error already unwinding.
        ackpt.close(reraise=clean_exit)
    if sanitizer.is_enabled():
        print(sanitizer.report_text(), flush=True)
    return state, {
        "train_loss": train_hist,
        "val_loss": val_hist,
        "preempted": preempted,
    }

