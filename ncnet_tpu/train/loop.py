"""Epoch-based training driver (the reference train.py loop, TPU-native).

Reference behavior preserved (train.py:158-205): per-epoch train + val
passes over CSV pair datasets, checkpoint each epoch with a ``best_`` copy
on improved validation loss, loss histories stored in the checkpoint.
Improvements over the reference: exact resume (optimizer state + epoch),
data-parallel over a device mesh, donate-args jitted step.
"""

import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from ncnet_tpu.analysis import sanitizer
from ncnet_tpu.parallel.mesh import make_hybrid_mesh, replicate, shard_batch
from ncnet_tpu.train.checkpoint import CheckpointData, save_checkpoint
from ncnet_tpu.train.step import (
    create_train_state,
    make_eval_step,
    make_optimizer,
    make_train_step,
)


def _device_batch(mesh, batch):
    sub = {
        "source_image": batch["source_image"],
        "target_image": batch["target_image"],
    }
    if mesh is not None:
        # host-local numpy goes straight to shard_batch (multi-host
        # assembles the global array from per-process slices)
        return shard_batch(mesh, sub)
    return {k: jnp.asarray(v) for k, v in sub.items()}


def _prefetch_device_batches(mesh, loader, size=2):
    """Double-buffer host->device transfers: the transfer for batch i+1 is
    issued while step i runs on the device (device_put is asynchronous), so
    H2D never sits on the critical path between steps. ``size=2`` is the
    standard flax prefetch depth: one batch in flight, one being consumed."""
    from collections import deque

    queue = deque()
    it = iter(loader)

    def enqueue():
        try:
            queue.append(_device_batch(mesh, next(it)))
        except StopIteration:
            return False
        return True

    while len(queue) < size and enqueue():
        pass
    while queue:
        yield queue.popleft()
        enqueue()


def train(
    config,
    params,
    train_loader,
    val_loader=None,
    num_epochs=5,
    learning_rate=5e-4,
    train_fe=False,
    fe_finetune_blocks=0,
    checkpoint_dir="trained_models",
    checkpoint_name="ncnet_tpu.msgpack",
    data_parallel=True,
    start_epoch=0,
    start_step=0,
    opt_state=None,
    initial_best_val=None,
    initial_train_hist=None,
    initial_val_hist=None,
    log_every=10,
    profile_dir=None,
    profile_steps=(3, 8),
):
    # hybrid mesh: leading axis maps across hosts (DCN), trailing within a
    # host's ICI domain; reduces to a plain all-device mesh single-process
    mesh = make_hybrid_mesh() if data_parallel and jax.device_count() > 1 else None
    if mesh is not None:
        params = replicate(mesh, params)

    optimizer = make_optimizer(learning_rate)
    state = create_train_state(
        params, optimizer, train_fe, step=start_step,
        fe_finetune_blocks=fe_finetune_blocks,
        cnn=config.feature_extraction_cnn,
    )
    if opt_state is not None:
        if isinstance(opt_state, dict):
            # raw state dict from a checkpoint loaded without a target
            from flax import serialization

            opt_state = serialization.from_state_dict(state.opt_state, opt_state)
        state = state._replace(opt_state=opt_state)
    if mesh is not None:
        state = state._replace(opt_state=replicate(mesh, state.opt_state))

    train_step = make_train_step(
        config, optimizer, train_fe, fe_finetune_blocks=fe_finetune_blocks
    )
    eval_step = make_eval_step(config)

    best_val = float("inf") if initial_best_val is None else float(initial_best_val)
    # Resume continues the loss histories rather than restarting them (the
    # reference keeps full train_loss/test_loss arrays, train.py:197-205).
    train_hist = [float(v) for v in np.asarray(initial_train_hist).ravel()] \
        if initial_train_hist is not None else []
    val_hist = [float(v) for v in np.asarray(initial_val_hist).ravel()] \
        if initial_val_hist is not None else []
    # Optional jax.profiler capture (SURVEY §5: the reference has no
    # tracing at all): trace steps [profile_steps) of the first epoch into
    # profile_dir, viewable with tensorboard/xprof.
    metrics_path = os.path.join(checkpoint_dir, "metrics.jsonl")
    if jax.process_index() == 0 and start_epoch == 0:
        # fresh (non-resume) run: don't mix epochs with a prior run's
        # lines; resume keeps appending to its own history
        os.makedirs(checkpoint_dir, exist_ok=True)
        open(metrics_path, "w").close()
    profiling = False
    for epoch in range(start_epoch, num_epochs):
        t0 = time.time()
        t_last = t0
        losses = []
        for i, dbatch in enumerate(_prefetch_device_batches(mesh, train_loader)):
            if profile_dir and epoch == start_epoch:
                if i == profile_steps[0]:
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                elif i == profile_steps[1] and profiling:
                    # D2H sync so the device finishes the profiled steps
                    # before the trace closes (block_until_ready does not
                    # block on the tunneled platform — see bench.py)
                    if losses:
                        float(losses[-1])
                    jax.profiler.stop_trace()
                    profiling = False
                    print(f"profile trace written to {profile_dir}", flush=True)
            state, loss = train_step(state, dbatch)
            if sanitizer.is_enabled():
                # sanitized runs are diagnostic: pay a per-step D2H sync so
                # a non-finite loss stops IMMEDIATELY with the per-stage
                # report + first non-finite stage, instead of averaging
                # NaN into the epoch
                sanitizer.check_finite_or_report(
                    float(loss), context=f"epoch {epoch + 1} step {i + 1}"
                )
            if (i + 1) % log_every == 0:
                # the float() D2H sync makes the step timing honest
                loss_host = float(loss)
                now = time.time()
                ms = (now - t_last) / log_every * 1e3
                t_last = now
                print(
                    f"epoch {epoch + 1} [{i + 1}/{len(train_loader)}] "
                    f"loss {loss_host:.6f} ({ms:.0f} ms/step)",
                    flush=True,
                )
            losses.append(loss)
        if profiling:  # epoch shorter than the profile window
            jax.profiler.stop_trace()
            profiling = False
        train_loss = float(np.mean([float(l) for l in losses])) if losses else 0.0
        train_hist.append(train_loss)

        val_loss = float("nan")
        if val_loader is not None:
            vlosses = [
                float(eval_step(state.params, b))
                for b in _prefetch_device_batches(mesh, val_loader)
            ]
            val_loss = float(np.mean(vlosses)) if vlosses else float("nan")
        val_hist.append(val_loss)
        is_best = val_loss < best_val
        best_val = min(best_val, val_loss) if not np.isnan(val_loss) else best_val

        epoch_s = time.time() - t0
        print(
            f"epoch {epoch + 1}/{num_epochs}: train {train_loss:.6f} "
            f"val {val_loss:.6f} ({epoch_s:.1f}s)"
            + (" [best]" if is_best else ""),
            flush=True,
        )
        if jax.process_index() != 0:
            continue  # multi-host: only process 0 writes checkpoints
        # Persisted observability (SURVEY §5: the reference is print-only;
        # its loss arrays live only inside checkpoints): per-epoch metrics
        # as JSONL plus a loss-curve figure, next to the checkpoint.
        os.makedirs(checkpoint_dir, exist_ok=True)
        with open(metrics_path, "a") as f:
            f.write(json.dumps({
                "epoch": epoch + 1,
                "train_loss": train_loss,
                # strict JSON: NaN (no/empty val loader) is not valid JSON
                "val_loss": None if np.isnan(val_loss) else val_loss,
                "epoch_seconds": round(epoch_s, 2),
                "steps": int(state.step),
                "best": bool(is_best),
            }) + "\n")
        try:
            import matplotlib.pyplot as plt

            from ncnet_tpu.utils.plot import plot_loss_curves, save_plot

            fig = plot_loss_curves(train_hist, val_hist)
            save_plot(os.path.join(checkpoint_dir, "loss_curve.png"), fig=fig)
            plt.close(fig)
        except Exception as e:  # headless plotting must never kill training
            print(f"loss-curve plot skipped: {e}", flush=True)
        save_checkpoint(
            os.path.join(checkpoint_dir, checkpoint_name),
            CheckpointData(
                config=config,
                params=jax.device_get(state.params),
                opt_state=jax.device_get(state.opt_state),
                step=int(state.step),
                epoch=epoch + 1,
                train_loss=np.asarray(train_hist),
                val_loss=np.asarray(val_hist),
                best_val_loss=best_val,
                train_fe=train_fe,
                fe_finetune_blocks=fe_finetune_blocks,
            ),
            is_best=is_best,
        )
    if sanitizer.is_enabled():
        print(sanitizer.report_text(), flush=True)
    return state, {"train_loss": train_hist, "val_loss": val_hist}
