"""Training: weak-supervision loss, jitted/sharded steps, checkpointing."""

from ncnet_tpu.train import checkpoint, loss, step

__all__ = ["checkpoint", "loss", "step"]
