"""Pallas TPU kernel for the 4D convolution (packed layout).

The 4D convolution is the hot op of neighbourhood consensus (SURVEY.md
§7.3 ranks it the #1 hard part). XLA's generic lowerings either pad HBM 8x
(channels-minor layouts) or serialize into many tiny convolutions with poor
MXU utilization. This kernel:

  * operates on the fused ``[b, i, j, k*l*c]`` layout (c fastest) shared
    with `ops.conv4d.conv4d_packed` — ~1% HBM padding;
  * DMAs one ``[ki, J, K*L*C]`` slab of A-rows per (b, i) grid step from
    HBM into VMEM;
  * for each (di, dj) kernel-tap pair builds an im2col patch tensor over
    the (dl, c) window columns once, then runs kk MXU GEMMs
    ``[J*K*L, kl*C] @ [kl*C, O]`` against the flattened filters,
    accumulating in float32 VMEM;
  * writes the ``[J, K*L*O]`` output block.

The backward pass is a custom VJP: dx reuses this kernel with
spatially-flipped, channel-transposed filters (a 4D convolution identity);
dw runs a second kernel that contracts the same patches against the
incoming cotangent per tap-triple.

STATUS (rounds 2-3, measured on v5e): the kernel is numerically verified
in interpret mode (forward + full VJP, tests/test_conv4d.py) but does NOT
lower through Mosaic — re-confirmed on round 3's libtpu: the in-kernel
``[J, K*L*C] -> [J, K, L, C]`` reshape still fails layout inference
("unsupported shape cast", vector<1x8x1024> -> vector<8x8x8x16>).

Round 3 closed the question of whether a redesigned kernel could win:
  * the MXU itself is fast at these dims (a [M, 400] @ [400, 400] GEMM
    sustains ~200 TFLOP/s; XLA's tlc conv3d runs at 137 = 70% of peak),
    so the prize would be feeding it un-inflated patches from VMEM;
  * but Mosaic requires sublane (row) offsets provably 8-aligned, and
    conv4d's tap shifts have granularity 1 row in any fused-rows layout
    ((i,j,k) fused: dk shifts by 1; (i,j): dj by 1). Padding the fused
    dims to 8-multiples (J, K -> 32) costs 1.64x, the l-band costs
    12/5 = 2.4x, and K-dim tile pads 1.33x — >=5x effective inflation,
    i.e. no better than the banded formulations XLA already compiles at
    70% peak (`ops.conv4d` 'tlc'/'btl4');
  * a VMEM-budget-accurate probe of the banded inner loop additionally
    hit the 16 MB scoped-vmem wall at useful tile sizes.
The production answer is per-layer impl mixing in XLA ('tlc,btl4,tlc' —
see bench.py). Kept as the interpret-verified scaffold and the record of
WHY a hand kernel loses on this op/hardware pair.

STATUS addendum (round 14): the conclusion above is specific to the
DENSE packed layout, whose tap shifts have 1-row granularity. The
sparse band's formulation (one pre-gathered GEMM per layer, PR 4) has
no such shifts — its fused kernel (`band_gemm_pallas.py`, this
directory) is the successor that DOES lower through Mosaic, and is
production-dispatched via `band_impl='pallas'`. This file stays as the
dense-path record and negative result.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fwd_kernel(x_hbm, w_ref, b_ref, out_ref, slab, acc, sem, *, shapes):
    B, I, J, K, L, C, O, KI, KJ, KK, KL = shapes
    P = KI // 2
    i = pl.program_id(1)

    istart = jnp.clip(i - P, 0, max(I - KI, 0))
    copy = pltpu.make_async_copy(
        x_hbm.at[pl.program_id(0), pl.ds(istart, min(KI, I))], slab, sem
    )
    copy.start()
    copy.wait()

    acc[...] = jnp.zeros_like(acc)

    for di in range(KI):
        gi = i + di - P  # global A-row feeding this tap
        with_row = (gi >= 0) & (gi < I)

        @pl.when(with_row)
        def _():
            r = jnp.clip(gi - istart, 0, min(KI, I) - 1)
            xrow = slab[pl.ds(r, 1)][0]  # [J, K*L*C]
            xv = xrow.reshape(J, K, L, C)
            # zero-pad the three in-block spatial dims
            xp = jnp.pad(xv, ((P, P), (P, P), (P, P), (0, 0)))

            for dj in range(KJ):
                # static-index slices (lax.dynamic_slice is not lowerable
                # inside TPU Pallas kernels; these indices are Python ints)
                xj = xp[dj : dj + J]
                # build the (dl, c) window columns once per (di, dj):
                # pbig[j, k', l, (dl, c)] = xj[j, k', l + dl, c]
                pbig = jnp.concatenate(
                    [xj[:, :, dl : dl + L] for dl in range(KL)],
                    axis=3,
                )  # [J, K+2P, L, KL*C]
                for dk in range(KK):
                    patch = pbig[:, dk : dk + K]
                    pm = patch.reshape(J * K * L, KL * C)
                    t = (di * KJ + dj) * KK + dk
                    wt = w_ref[pl.ds(t * KL * C, KL * C), :]  # [KL*C, O]
                    acc[...] += jnp.dot(
                        pm, wt, preferred_element_type=jnp.float32
                    ).reshape(J * K, L * O)

    out = acc[...] + jnp.tile(b_ref[0], L)[None, :]
    out_ref[...] = out.reshape(1, 1, J, K * L * O).astype(out_ref.dtype)


def _conv4d_packed_pallas_fwd(xp, w2, bias, kl_shape, cin, cout, interpret=False):
    B, I, J, fused = xp.shape
    K, L = kl_shape
    C, O = cin, cout
    KI, KJ, KK, KL_ = w2_kernel_dims(w2, C, O)
    shapes = (B, I, J, K, L, C, O, KI, KJ, KK, KL_)

    kernel = functools.partial(_fwd_kernel, shapes=shapes)
    return pl.pallas_call(
        kernel,
        grid=(B, I),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # x stays in HBM, DMA'd
            pl.BlockSpec(memory_space=pltpu.VMEM),  # flattened weights
            pl.BlockSpec(memory_space=pltpu.VMEM),  # bias row
        ],
        out_specs=pl.BlockSpec(
            (1, 1, J, K * L * O), lambda b, i: (b, i, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, I, J, K * L * O), xp.dtype),
        scratch_shapes=[
            pltpu.VMEM((min(KI, I), J, K * L * C), xp.dtype),
            pltpu.VMEM((J * K, L * O), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(xp, w2, bias)


def w2_kernel_dims(w2, cin, cout):
    """Recover (ki, kj, kk, kl) from the flattened [ki*kj*kk*kl*cin, cout]
    weight matrix, assuming a hypercubic kernel."""
    taps = w2.shape[0] // cin
    k = round(taps ** 0.25)
    if k**4 * cin != w2.shape[0] or w2.shape[1] != cout:
        raise ValueError(
            f"flattened weight {w2.shape} is not a hypercubic "
            f"[k^4*cin, cout] matrix for cin={cin}, cout={cout}"
        )
    return k, k, k, k


def _flatten_weights(w):
    """[ki,kj,kk,kl,cin,cout] -> [(ki kj kk) is row-blocked: [ki*kj*kk*kl*cin, cout]]
    with (dl, c) minor within each (di, dj, dk) row block."""
    ki, kj, kk, kl, cin, cout = w.shape
    return w.reshape(ki * kj * kk * kl * cin, cout)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def conv4d_packed_pallas(xp, w, bias, kl_shape, cin, cout, interpret=False):
    """4D convolution on the fused packed layout, Pallas forward + VJP.

    Args:
      xp: ``[b, i, j, k*l*cin]`` (c fastest).
      w: ``[k, k, k, k, cin, cout]`` (hypercubic kernel).
      bias: ``[cout]``.
      kl_shape: static (k, l) grid dims of the fused axis.
      cin, cout: static channel counts.
      interpret: run in the Pallas interpreter (tests on CPU).

    Returns:
      ``[b, i, j, k*l*cout]``.
    """
    w2 = _flatten_weights(w).astype(xp.dtype)
    return _conv4d_packed_pallas_fwd(
        xp, w2, bias.reshape(1, -1).astype(jnp.float32), kl_shape, cin, cout,
        interpret,
    )


def _vjp_fwd(xp, w, bias, kl_shape, cin, cout, interpret=False):
    out = conv4d_packed_pallas(xp, w, bias, kl_shape, cin, cout, interpret)
    return out, (xp, w)


def _vjp_bwd(kl_shape, cin, cout, interpret, residuals, g):
    xp, w = residuals
    # dx: correlate the cotangent with the flipped, channel-transposed
    # filters — conv4d identity: dL/dx = conv4d(g, flip(w)^T).
    w_flip = jnp.flip(w, axis=(0, 1, 2, 3)).transpose(0, 1, 2, 3, 5, 4)
    zero_bias = jnp.zeros((cin,), jnp.float32)
    dx = conv4d_packed_pallas(
        g, w_flip, zero_bias, kl_shape, cout, cin, interpret
    )
    # dw / dbias via the XLA scan formulation (memory-bounded, MXU GEMMs
    # with a large contraction dim) — a dedicated Pallas dw kernel is not
    # warranted given the module's measured verdict (see module docstring).
    dw = _dw_scan(xp, g, w.shape, kl_shape, cin, cout)
    db = jnp.sum(
        g.reshape(g.shape[0], g.shape[1], g.shape[2], -1, cout),
        axis=(0, 1, 2, 3),
        dtype=jnp.float32,
    )
    return dx, dw, db


def _dw_scan(xp, g, w_shape, kl_shape, cin, cout):
    """dw[di,dj,dk,dl,c,o] = sum over positions of x_shifted * g.

    Implemented as a scan over the ki taps of the leading dim; each tap is
    one big GEMM ``[cin*kj*kk*kl? ...]`` — concretely, for tap di we shift
    x rows and contract the full remaining volume via a 3D convolution
    transpose trick: here the straightforward einsum over shifted slices,
    which XLA maps to tall-skinny GEMMs with contraction b*i*j*k*l.
    """
    B, I, J, fused = xp.shape
    K, L = kl_shape
    ki, kj, kk, kl, _, _ = w_shape
    p = ki // 2
    x6 = xp.reshape(B, I, J, K, L, cin)
    g6 = g.reshape(B, I, J, K, L, cout)
    xpad = jnp.pad(
        x6, ((0, 0), (p, p), (p, p), (p, p), (p, p), (0, 0))
    )

    def tap(carry, t):
        di = t // (kj * kk * kl)
        dj = (t // (kk * kl)) % kj
        dk = (t // kl) % kk
        dl = t % kl
        xs = jax.lax.dynamic_slice(
            xpad, (0, di, dj, dk, dl, 0), (B, I, J, K, L, cin)
        )
        dwt = jnp.einsum(
            "bijklc,bijklo->co",
            xs,
            g6,
            preferred_element_type=jnp.float32,
        )
        return carry, dwt

    _, dws = jax.lax.scan(tap, None, jnp.arange(ki * kj * kk * kl))
    return dws.reshape(ki, kj, kk, kl, cin, cout)


conv4d_packed_pallas.defvjp(_vjp_fwd, _vjp_bwd)
