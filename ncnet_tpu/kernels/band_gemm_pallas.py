"""Pallas TPU kernel for the fused sparse band GEMM (gather+MM+bias+ReLU).

The sparse top-K band (``ncnet_tpu.sparse``) already reshaped each NC
layer into ONE gathered GEMM ``[b, nA*K, k^4*cin] @ [k^4*cin, cout]`` —
the shape that sidesteps the Mosaic sublane-alignment wall that killed
the dense conv4d kernel (``conv4d_pallas.py`` STATUS, rounds 2-3): the
contraction rows are band ENTRIES, not spatial windows, so no
granularity-1 row shifts exist anywhere in the layout. What XLA still
does on this path is materialize the gathered ``[b, N, T*c]`` block in
HBM between the gather and the GEMM and round-trip again for bias+ReLU.
This kernel fuses the whole layer:

  * the band entry list ``[N+1, c]`` (trailing all-zero null row, the
    same convention as ``ops.band.band_gather_neighbors``) lives in VMEM
    once per batch element;
  * per ``(batch, row-block)`` grid step the kernel gathers the
    ``[ROWS, T]`` pointer block's neighbours directly from VMEM, runs
    one MXU GEMM ``[ROWS, T*c] @ [T*c, cout]``, adds the bias and
    applies ReLU before the single output write — the gathered patch
    tensor never exists in HBM;
  * off-band / off-grid pointers hit the null row and contribute exact
    zeros, identical to the XLA path.

The custom VJP stays gather-only (no scatter anywhere): the ReLU mask is
recovered from the SAVED OUTPUT (``out > 0`` iff pre-activation > 0 —
ReLU's derivative at 0 is 0 by JAX convention, so the mask equality is
exact), dx is the flipped-kernel/channel-transposed gather conv over the
SAME pointer table, and dw is the linear transpose of the forward
contraction — all three built on ``ops.band.band_conv_gemm``, the ONE
definition of the band contraction, which keeps the backward
bitwise-identical to the XLA path's custom VJP (``sparse/nc.py``) and
therefore inside the full-K bitwise training-equivalence contract.

STATUS (round 14): numerically verified in interpret mode on CPU —
forward AND full VJP are bitwise-equal to the eager XLA band path (hence
to the dense ``'gemm4'`` composite at ``K = hB*wB``), see
tests/test_band_pallas.py. Real-Mosaic lowering is NOT yet validated in
this (CPU-only) container: the open question is the in-kernel dynamic
gather ``x[idx]`` along the sublane axis (Mosaic's dynamic-gather
support, or a two-step DMA formulation, decides it — NOT the reshape
wall that killed conv4d: ``[ROWS, T, c] -> [ROWS, T*c]`` collapses
minor-most dims only). Dispatch (`resolve_band_impl`) therefore keeps
the XLA path on every non-TPU backend and the kernel opt-in on TPU.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ncnet_tpu.ops.band import band_conv_gemm

#: rows of band entries per grid step: a multiple of the bf16 sublane
#: tile (16) that keeps the gathered [ROWS, T*c] block well under VMEM
#: limits at every geometry the sparse path runs (T*c <= k^4 * 10)
BLOCK_ROWS = 128


def resolve_band_impl(requested):
    """Resolve a requested band impl against the runtime platform.

    ``'pallas'`` holds only on a TPU backend; anywhere else it falls
    back to ``'xla'`` (the serve zero-recompile and parity contracts
    must never see a failed lowering). ``NCNET_BAND_PALLAS_INTERPRET=1``
    forces ``'pallas_interpret'`` instead — the CPU integration tests'
    hook for running the REAL kernel body through the Pallas
    interpreter end-to-end.
    """
    if requested != "pallas":
        return "xla"
    if os.environ.get("NCNET_BAND_PALLAS_INTERPRET"):
        return "pallas_interpret"
    try:
        backend = jax.default_backend()
    except RuntimeError:
        return "xla"
    return "pallas" if backend == "tpu" else "xla"


def _fused_kernel(x_ref, ptr_ref, w_ref, b_ref, out_ref):
    """One (batch, row-block) step: gather -> GEMM -> bias -> ReLU."""
    x = x_ref[0]  # [N+1, c] entry list + null row, VMEM-resident
    idx = ptr_ref[0]  # [ROWS, T] int32 pointers into the entry list
    rows = idx.shape[0]
    # the gathered block in tap-major/channel-minor layout — exactly
    # band_gather_neighbors' row layout, so the SAME flattened kernel
    # contracts it; the trailing-dims collapse is minor-most only
    g = x[idx].reshape(rows, -1)  # [ROWS, T*c]
    # the eager path contracts in the activation dtype
    # (preferred_element_type=x.dtype in band_conv_gemm) — match it
    # exactly for the bitwise contract
    y = jnp.dot(g, w_ref[...], preferred_element_type=x.dtype)
    y = y + b_ref[0][None, :]
    out_ref[0] = jnp.maximum(y, jnp.zeros_like(y))


def _fused_forward(x_entries, w2, bias2, ptr, interpret, block_rows):
    b, n, c = x_entries.shape
    t = ptr.shape[-1]
    cout = w2.shape[-1]
    # the same null-slot convention as band_gather_neighbors: one
    # appended all-zero row, pointer value n addresses it
    x_pad = jnp.concatenate(
        [x_entries, jnp.zeros((b, 1, c), x_entries.dtype)], axis=1
    )
    block = min(block_rows, max(n, 1))
    n_pad = -(-n // block) * block
    if n_pad != n:
        # padded rows read the null slot everywhere -> relu(bias) rows,
        # sliced off below before anything consumes them
        ptr = jnp.concatenate(
            [ptr, jnp.full((b, n_pad - n, t), n, ptr.dtype)], axis=1
        )
    out = pl.pallas_call(
        _fused_kernel,
        grid=(b, n_pad // block),
        in_specs=[
            # whole entry list per batch element, re-used by every row
            # block of that batch
            pl.BlockSpec((1, n + 1, c), lambda bi, ri: (bi, 0, 0)),
            pl.BlockSpec((1, block, t), lambda bi, ri: (bi, ri, 0)),
            pl.BlockSpec((t * c, cout), lambda bi, ri: (0, 0)),
            pl.BlockSpec((1, cout), lambda bi, ri: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, cout), lambda bi, ri: (bi, ri, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n_pad, cout), x_entries.dtype),
        interpret=interpret,
    )(x_pad, ptr, w2, bias2)
    return out[:, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _band_conv_bias_relu(x_entries, w, bias, ptr, interpret, block_rows):
    w2 = w.reshape(-1, w.shape[-1]).astype(x_entries.dtype)
    bias2 = bias.astype(x_entries.dtype).reshape(1, -1)
    return _fused_forward(x_entries, w2, bias2, ptr, interpret, block_rows)


def _fwd(x_entries, w, bias, ptr, interpret, block_rows):
    out = _band_conv_bias_relu(x_entries, w, bias, ptr, interpret, block_rows)
    return out, (x_entries, w, bias, ptr, out)


def _bwd(interpret, block_rows, res, gy):
    x_entries, w, bias, ptr, out = res
    if any(int(k) % 2 == 0 for k in w.shape[:4]):
        # the flipped-kernel dx identity needs symmetric tap offsets
        # (raise, not assert: must survive python -O)
        raise ValueError(
            f"sparse band conv requires odd kernel sizes, got {w.shape[:4]}"
        )
    # ReLU mask from the saved output: out = max(pre, 0), so out > 0
    # iff pre > 0, and ReLU's JAX derivative at exactly 0 is 0 — the
    # masked cotangent equals what autodiff hands the eager composite
    gp = jnp.where(out > 0, gy, jnp.zeros_like(gy))
    # bias: linear transpose of the broadcast-after-cast the eager path
    # applies — NOT a hand-written sum (jnp.sum picks its own
    # accumulation dtype for bf16; the transpose machinery emits the
    # exact reduce+convert autodiff does, which the bitwise contract
    # needs)
    transpose_b = jax.linear_transpose(
        lambda bb: jnp.broadcast_to(bb.astype(gp.dtype), gp.shape), bias
    )
    (db,) = transpose_b(gp)
    # dx: the flipped/channel-transposed gather conv over the SAME
    # pointer table (see sparse/nc.py for the identity's derivation)
    wflip = jnp.flip(w, axis=(0, 1, 2, 3)).transpose(0, 1, 2, 3, 5, 4)
    dx = band_conv_gemm(gp, wflip.astype(gp.dtype), ptr)
    dx = dx.astype(x_entries.dtype)
    # dw: linear transpose of the forward contraction — NOT an explicit
    # einsum (measured not-bitwise against the dense composite; XLA
    # picks a different reduction strategy per operand order)
    transpose_w = jax.linear_transpose(
        lambda ww: band_conv_gemm(x_entries, ww, ptr), w
    )
    (dw,) = transpose_w(gp)
    return dx, dw, db, None


_band_conv_bias_relu.defvjp(_fwd, _bwd)


def band_conv_bias_relu_pallas(x_entries, w, bias, ptr, interpret=False,
                               block_rows=BLOCK_ROWS):
    """Fused band NC layer: ``relu(gather(x, ptr) @ w_flat + bias)``.

    Args:
      x_entries: ``[b, N, c]`` band activations, flat entry list
        (``N = hA*wA*K``; pointer VALUES address this order).
      w: ``[k1, k2, k3, k4, cin, cout]`` NC layer kernel (odd sizes).
      bias: ``[cout]`` master-dtype bias (cast to the activation dtype
        in-kernel, exactly like the XLA path's ``astype``).
      ptr: ``[b, N, T]`` int32 from `ops.band.band_neighbor_pointers`
        (reshaped/permuted by the caller; null pointer = N).
      interpret: run through the Pallas interpreter (CPU tests).
      block_rows: band entries per grid step (N is padded up to a
        multiple; padded rows are sliced off).

    Returns:
      ``[b, N, cout]`` post-ReLU activations, bitwise-equal to the XLA
      path's ``relu(band_conv_gemm(x, w, ptr) + bias.astype(dtype))``.
    """
    return _band_conv_bias_relu(x_entries, w, bias, ptr, interpret,
                                block_rows)
