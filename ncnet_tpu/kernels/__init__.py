"""Pallas TPU kernels for the hot ops."""
