"""Device mesh and sharding helpers.

The reference is single-GPU (SURVEY.md §2.3: no DataParallel, no NCCL/MPI).
Scaling here is mesh-native: a `jax.sharding.Mesh` with a ``data`` axis for
batch data parallelism and a ``spatial`` axis for sharding the 4D
correlation tensor over its (iA, jA) dims (the long-context analog; see
`ncnet_tpu.parallel.spatial`). Collectives ride ICI/DCN via XLA.
"""

import os

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# --- collective health checking ----------------------------------------------
#
# A dead peer turns any cross-process array assembly into an indefinite
# gloo/DCN hang. The training loop installs its cluster supervisor's
# `check` here (resilience.cluster, train.loop); the multi-process
# branches below call `checked_collective` at entry so a stale-heartbeat
# peer raises a typed `PeerDown` BEFORE this host commits to a transfer
# that can never complete. Single-process runs (and runs without a
# supervisor) are untouched — the hook is None and the call is a no-op.

_collective_check = None  # set only from the step thread (set_collective_check)


def set_collective_check(fn):
    """Install ``fn(what)`` to run before every cross-process collective
    in this module; pass None to uninstall. Returns the previous hook so
    the training loop can restore it on exit."""
    global _collective_check
    prev = _collective_check
    _collective_check = fn
    return prev


def checked_collective(what):
    """Run the installed health check (if any) before a collective."""
    if _collective_check is not None:
        _collective_check(what)


def make_mesh(mesh_shape=None, axis_names=("data",), devices=None):
    """Create a mesh. Default: all devices on a single ``data`` axis."""
    if devices is None:
        devices = jax.devices()
    if mesh_shape is None:
        mesh_shape = (len(devices),)
    dev_array = np.asarray(devices).reshape(mesh_shape)
    return Mesh(dev_array, axis_names)


def multiprocess_cpu_collectives_available():
    """True when this jaxlib can run REAL multi-process collectives on the
    CPU backend: it ships gloo TCP collectives AND the config flag that
    wires them into the CPU client. Older jaxlibs lack one or both and
    fail any cross-process CPU collective with "Multiprocess computations
    aren't implemented on the CPU backend" — callers (tests, the CPU
    drill harness) use this to skip rather than fail there."""
    try:
        # importing xla_bridge REGISTERS the flag; hasattr on jax.config
        # stays False either way, so probe the value-holder table directly
        from jax._src import xla_bridge  # noqa: F401
        from jax._src.lib import xla_extension
    except Exception:  # nclint: disable=swallowed-exception -- capability probe: any import/ABI failure just means "no gloo collectives here"
        return False
    if not hasattr(xla_extension, "make_gloo_tcp_collectives"):
        return False
    holders = getattr(jax.config, "_value_holders", {})
    return "jax_cpu_collectives_implementation" in holders


def ensure_cpu_collectives():
    """Select the gloo CPU collectives implementation when this jaxlib has
    one. Must run BEFORE the CPU backend client is created (i.e. before
    ``jax.devices()``/``jax.distributed.initialize``); returns whether
    gloo was selected. Single-process runtimes are unaffected — gloo only
    changes how cross-process collectives are transported."""
    if not multiprocess_cpu_collectives_available():
        return False
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # nclint: disable=swallowed-exception -- capability probe: a jaxlib that rejects the flag means gloo is unavailable, not an error
        return False
    return True


def initialize_multihost(coordinator_address=None, num_processes=None,
                         process_id=None):
    """Join a multi-host JAX runtime (the NCCL/MPI-backend analog).

    MUST be called before anything touches the XLA backend (including
    ``jax.devices()``/``jax.process_count()`` — they initialize it). On TPU
    pods the arguments are auto-detected from the environment; elsewhere
    pass them explicitly. After this, ``jax.devices()`` spans all hosts and
    XLA routes collectives over ICI within a slice / DCN across slices.

    With explicit arguments, initialization failures raise. With
    auto-detection, the expected no-cluster case falls back to single-host
    WITH a visible log line (a silent fallback on a real pod would leave
    every host training its own divergent model). To turn that hazard into
    a hard failure on a real deployment, set ``NCNET_REQUIRE_MULTIHOST``:
    ``N >= 2`` requires at least N processes; ``1`` or any non-numeric
    truthy value requires a real multi-host runtime (> 1 process); ``0``
    or unset disables the guard. Auto-detection that falls back or lands
    below the expectation then raises instead of printing.

    Returns ``(process_index, process_count)`` for per-host data feeding
    (`data.loader.DataLoader(host_id=..., n_hosts=...)`).
    """
    require = os.environ.get("NCNET_REQUIRE_MULTIHOST", "")
    # '' / '0' disable the guard; '1' and non-numeric truthy values mean
    # "enabled, require a real multi-host runtime (>1)"; N>=2 requires N
    if require in ("", "0"):
        require_n = 0
    elif require.isdigit():
        require_n = max(int(require), 2)
    else:
        require_n = 2
    explicit = coordinator_address is not None or num_processes is not None
    # CPU-backend clusters (the test/drill harness) need gloo collectives
    # selected BEFORE the client exists; on TPU pods the platform isn't
    # cpu and this is a no-op
    platforms = str(
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS", "")
    )
    if "cpu" in platforms.split(","):
        ensure_cpu_collectives()
    try:
        if explicit:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        else:
            jax.distributed.initialize()  # env/TPU-pod auto-detection
    except Exception as e:  # noqa: BLE001 — explicit path re-raises
        if explicit:
            raise
        if require_n:
            raise RuntimeError(
                "initialize_multihost: auto-detection failed but "
                f"NCNET_REQUIRE_MULTIHOST={require!r} is set — refusing "
                "the single-host fallback (every host would silently "
                "train its own divergent model)"
            ) from e
        print(
            "initialize_multihost: single-host fallback "
            f"({type(e).__name__}: {e})",
            flush=True,
        )
    if require_n and jax.process_count() < require_n:
        raise RuntimeError(
            f"initialize_multihost: joined a {jax.process_count()}-process "
            f"runtime but NCNET_REQUIRE_MULTIHOST={require!r} expects "
            f">= {require_n}"
        )
    return jax.process_index(), jax.process_count()


def make_hybrid_mesh(per_host_shape=None, axis_names=("data",)):
    """Mesh spanning all hosts with DCN-aware device placement.

    Uses `mesh_utils.create_hybrid_device_mesh` so the leading mesh dim
    maps across hosts (DCN) and the trailing dims stay within a host's ICI
    domain — collectives along the trailing axes never cross DCN. With one
    process this reduces to `make_mesh`.

    Args:
      per_host_shape: shape of the within-host part of the mesh (default:
        all local devices on one axis).
    """
    n_proc = jax.process_count()
    if n_proc == 1:
        return make_mesh(per_host_shape, axis_names)

    local = jax.local_device_count()
    if per_host_shape is None:
        per_host_shape = (local,)

    # create_hybrid_device_mesh keys the DCN dimension on `slice_index`,
    # which only TPU slices carry — multi-process CPU clusters (and
    # single-slice multi-host setups) present as one slice and make it
    # raise (found by tests/test_multihost.py, the first time this branch
    # truly executed). Use it when slice attribution exists; otherwise
    # group by process_index, which is the same "leading axis crosses
    # DCN, trailing axes stay within a host" placement.
    slices = {getattr(d, "slice_index", None) for d in jax.devices()}
    if len(slices) == n_proc and None not in slices:
        from jax.experimental import mesh_utils

        dev = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=per_host_shape,
            dcn_mesh_shape=(n_proc,) + (1,) * (len(per_host_shape) - 1),
        )  # shape: (n_proc * per_host_shape[0], *per_host_shape[1:])
        return Mesh(dev, axis_names)

    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    dev = np.asarray(devs).reshape((n_proc,) + tuple(per_host_shape))
    dev = dev.reshape(
        (n_proc * per_host_shape[0],) + tuple(per_host_shape[1:])
    )
    return Mesh(dev, axis_names)


def shard_batch(mesh, batch, axis="data"):
    """Put a batch dict on device, sharded along the leading (batch) dim.

    Single-process: a plain sharded device_put. Multi-host: each process
    passes its HOST-LOCAL slice of the global batch (global batch size =
    local size x process_count along ``axis``) and the global array is
    assembled with `jax.make_array_from_process_local_data` — no host ever
    materializes the full batch.
    """
    sharding = NamedSharding(mesh, P(axis))
    if jax.process_count() > 1:
        checked_collective("shard_batch global-array assembly")
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            ),
            batch,
        )
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def make_batch_sharded_apply(apply_fn, mesh, axis="data"):
    """Batch-axis `shard_map` variant of ``apply_fn(params, batch)``.

    Params replicate (``P()``); every batch leaf and every output leaf
    shards its leading (batch) dim along ``axis``. Inside the mapped fn
    each device sees a ``global_batch / mesh.size`` slice and runs the
    UNCHANGED single-device program on it, so the result is bitwise the
    single-device program applied per shard and concatenated — the
    serving engine's parity contract for its ``shard_mesh`` dispatch
    path (tests/test_fleet.py pins it). The caller jits the returned fn
    (donation plumbing included: the engine wraps it exactly like the
    single-device apply, ``donate_argnums=(1,)``).

    Requires every batch leaf's leading dim to divide by ``mesh.size``
    (the engine only selects this variant for such padded sizes).
    """
    specs = (P(), P(axis))  # tree prefixes: params replicated, batch sharded
    # API shim as parallel.spatial: jax >= 0.6 spells it jax.shard_map
    # with check_vma; 0.4.x has the experimental module with check_rep.
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            apply_fn, mesh=mesh, in_specs=specs, out_specs=P(axis),
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        apply_fn, mesh=mesh, in_specs=specs, out_specs=P(axis),
        check_rep=False,
    )


def replicate(mesh, tree):
    """Replicate a pytree (params, opt state) across the mesh.

    Multi-process: a plain ``device_put`` of host values onto a
    process-spanning sharding runs ``multihost_utils.assert_equal`` — a
    per-leaf gloo/DCN broadcast of the whole tree just to re-check what is
    deterministic by construction here (every host computes the same init
    from the same PRNGKey / loads the same checkpoint), and one that the
    gloo CPU transport handles unreliably when differently-sized ops
    overlap. Build the global array from explicit per-device copies
    instead: no collective, each host touches only its local devices.
    """
    sharding = NamedSharding(mesh, P())
    if jax.process_count() > 1:
        checked_collective("replicate global-array assembly")

        def rep(x):
            x = np.asarray(x)
            locals_ = [
                jax.device_put(x, d) for d in sharding.addressable_devices
            ]
            return jax.make_array_from_single_device_arrays(
                x.shape, sharding, locals_
            )

        return jax.tree.map(rep, tree)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
