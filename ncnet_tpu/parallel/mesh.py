"""Device mesh and sharding helpers.

The reference is single-GPU (SURVEY.md §2.3: no DataParallel, no NCCL/MPI).
Scaling here is mesh-native: a `jax.sharding.Mesh` with a ``data`` axis for
batch data parallelism and a ``spatial`` axis for sharding the 4D
correlation tensor over its (iA, jA) dims (the long-context analog; see
`ncnet_tpu.parallel.spatial`). Collectives ride ICI/DCN via XLA.
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(mesh_shape=None, axis_names=("data",), devices=None):
    """Create a mesh. Default: all devices on a single ``data`` axis."""
    if devices is None:
        devices = jax.devices()
    if mesh_shape is None:
        mesh_shape = (len(devices),)
    dev_array = np.asarray(devices).reshape(mesh_shape)
    return Mesh(dev_array, axis_names)


def shard_batch(mesh, batch, axis="data"):
    """Put a batch dict on device, sharded along the leading (batch) dim."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicate(mesh, tree):
    """Replicate a pytree (params, opt state) across the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
