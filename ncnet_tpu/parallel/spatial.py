"""Spatial sharding of the 4D correlation tensor — the long-context analog.

The correlation tensor is O((h*w)^2); at InLoc resolution (grid ~200x150)
it dwarfs HBM. The reference mitigates with fp16 + 4D max-pooling
(SURVEY.md §5); here the additional TPU-native axis is to shard corr4d over
its iA dim across a ``spatial`` mesh axis: every device holds the full B
grid x a slab of A rows. This is the direct ring-attention-style
decomposition over ICI:

  * correlation: local einsum of the A-row slab against replicated B;
  * mutual matching: max over B is local; max over A is a cross-device
    `lax.pmax`;
  * conv4d: needs ``ki//2`` halo rows of iA from ring neighbours —
    exchanged with `lax.ppermute` (non-cyclic, so edge devices receive
    zeros = the zero-padding semantics of the reference conv4d);
  * symmetric NeighConsensus applies the net to the A<->B transposed tensor
    too; the transpose moves the sharded dim, implemented with
    `lax.all_to_all` (iA-sharded <-> iB-sharded).

All collectives are expressed inside one `shard_map`, compiled by XLA onto
ICI.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ncnet_tpu.ops.conv4d import conv4d, resolve_layer_impls
from ncnet_tpu.ops.correlation import correlation_4d, correlation_maxpool4d


def _pmax(x, axis_name):
    return lax.pmax(x, axis_name)


def mutual_matching_sharded(corr, axis_name, eps=1e-5):
    """`ops.matching.mutual_matching` for an iA-sharded slab."""
    local_max_a = jnp.max(corr, axis=(1, 2), keepdims=True)
    max_over_a = _pmax(local_max_a, axis_name)
    max_over_b = jnp.max(corr, axis=(3, 4), keepdims=True)  # B dims are local
    ratio_b = corr / (max_over_a + eps)
    ratio_a = corr / (max_over_b + eps)
    return corr * (ratio_a * ratio_b)


def halo_exchange_rows(x, axis_name, halo):
    """Concatenate ``halo`` rows of dim 1 from ring neighbours (zeros at the
    ends — matching zero padding)."""
    # lax.axis_size only exists on newer jax; psum of 1 is the portable
    # spelling of "how many devices on this axis"
    n = (
        lax.axis_size(axis_name) if hasattr(lax, "axis_size")
        else lax.psum(1, axis_name)
    )
    fwd = [(i, i + 1) for i in range(n - 1)]  # send right
    bwd = [(i + 1, i) for i in range(n - 1)]  # send left
    from_left = lax.ppermute(x[:, -halo:], axis_name, fwd)
    from_right = lax.ppermute(x[:, :halo], axis_name, bwd)
    # ppermute delivers zeros where no peer sends, so edges are zero-padded.
    return jnp.concatenate([from_left, x, from_right], axis=1)


def conv4d_sharded(x, w, bias, axis_name, impl="xla"):
    """conv4d on an iA-sharded ``[b, iA_loc, jA, iB, jB, c]`` slab."""
    ki = w.shape[0]
    halo = ki // 2
    if halo:
        x = halo_exchange_rows(x, axis_name, halo)
    out = conv4d(x, w, bias=bias, impl=impl)
    if halo:
        out = out[:, halo:-halo]
    return out


def _swap_ab_sharded(x, axis_name):
    """Global A<->B transpose of an iA-sharded slab -> an (originally) iB
    -sharded slab, via all_to_all: split the local iB dim across devices,
    gather all local-iA slabs."""
    # x: [b, ia_loc, jA, iB, jB, c] -> all_to_all splits iB (axis 3),
    # concatenates ia shards (axis 1) -> [b, iA_full? ...]
    y = lax.all_to_all(x, axis_name, split_axis=3, concat_axis=1, tiled=True)
    # y: [b, iA_full, jA, iB_loc, jB, c]; transpose pairs
    return y.transpose(0, 3, 4, 1, 2, 5)


def neigh_consensus_sharded(params, corr, axis_name, symmetric=True, impl="xla"):
    """Symmetric NC stack on an iA-sharded correlation slab (with channel
    axis and per-layer impl handling identical to `neigh_consensus_apply`)."""
    dtype = corr.dtype

    layer_impls = resolve_layer_impls(impl, len(params))

    def net(x):
        for p, layer_impl in zip(params, layer_impls):
            x = jax.nn.relu(
                conv4d_sharded(
                    x,
                    p["kernel"].astype(dtype),
                    p["bias"].astype(dtype),
                    axis_name,
                    impl=layer_impl,
                )
            )
        return x

    x = corr[..., None]
    if symmetric:
        xt = _swap_ab_sharded(x, axis_name)
        out = net(x) + _swap_ab_sharded(net(xt), axis_name)
    else:
        out = net(x)
    return out[..., 0]


def make_sharded_match_pipeline(config, mesh, axis_name="spatial"):
    """Features -> filtered corr4d with the A grid sharded over ``axis_name``.

    Returns a function ``(nc_params, feat_a, feat_b) -> corr4d`` (or
    ``-> (corr4d, (di, dj, dk, dl))`` when ``config.relocalization_k_size
    > 1``) where ``feat_a`` is sharded over rows (dim 1) of the feature
    grid and the outputs are sharded over (pooled) iA.

    Relocalization composes with sharding because the fused
    correlate+maxpool4d is LOCAL to an A-row slab (it needs only the slab
    and the full B grid), provided each slab covers whole pooling cells —
    hence the ``k_size``-aware divisibility checks below. The argmax
    offsets are within-cell, so they shard alongside the pooled tensor.
    """
    k = max(config.relocalization_k_size, 1)
    n_shards = mesh.shape[axis_name]

    def body(nc_params, feat_a, feat_b):
        deltas = None
        if k > 1:
            corr, deltas = correlation_maxpool4d(feat_a, feat_b, k)
        else:
            corr = correlation_4d(feat_a, feat_b)
        corr = mutual_matching_sharded(corr, axis_name)
        corr = neigh_consensus_sharded(
            nc_params,
            corr,
            axis_name,
            symmetric=config.symmetric_mode,
            impl=config.conv4d_impl,
        )
        corr = mutual_matching_sharded(corr, axis_name).astype(jnp.float32)
        if k > 1:
            return corr, deltas
        return corr

    spec = P(None, axis_name)
    out_specs = (spec, (spec, spec, spec, spec)) if k > 1 else spec
    # API shim: jax >= 0.6 exposes jax.shard_map (replication checking
    # flag spelled check_vma); 0.4.x only has the experimental module
    # (flag spelled check_rep). Same semantics either way.
    if hasattr(jax, "shard_map"):
        mapped = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), spec, P()),
            out_specs=out_specs,
            check_vma=False,
        )
    else:
        from jax.experimental.shard_map import shard_map as _shard_map

        mapped = _shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), spec, P()),
            out_specs=out_specs,
            check_rep=False,
        )

    def pipeline(nc_params, feat_a, feat_b):
        if feat_a.shape[1] % (n_shards * k):
            raise ValueError(
                f"A-grid rows ({feat_a.shape[1]}) must divide "
                f"{n_shards} shards x k_size {k} (each slab must cover "
                "whole pooling cells)"
            )
        if k > 1 and (
            feat_a.shape[2] % k or feat_b.shape[1] % k or feat_b.shape[2] % k
        ):
            raise ValueError(
                f"all feature-grid dims must divide k_size {k} for 4D "
                f"pooling; got A {feat_a.shape[1:3]}, B {feat_b.shape[1:3]}"
            )
        if config.symmetric_mode and (feat_b.shape[1] // k) % n_shards:
            raise ValueError(
                "symmetric mode transposes A<->B, so pooled B-grid rows "
                f"({feat_b.shape[1]} / {k}) must divide {n_shards} "
                "(all_to_all resharding)"
            )
        return mapped(nc_params, feat_a, feat_b)

    return pipeline
