"""Parallelism: device meshes, sharding helpers, spatially-sharded ops."""

from ncnet_tpu.parallel.mesh import make_mesh, replicate, shard_batch

__all__ = ["make_mesh", "replicate", "shard_batch"]
