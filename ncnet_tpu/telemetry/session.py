"""Telemetry sessions: one ``--telemetry DIR`` run, one event log.

`start(out_dir)` opens the JSONL event log
(``events_proc<P>.jsonl`` — per-PROCESS, so every host of a multihost
run can share one ``--telemetry DIR`` without clobbering a single
file), enables the span tracer with the log as its sink, and remembers
which registry to snapshot; `stop()` appends a final metric record per
registered metric, closes the log, and durably writes the Prometheus
snapshot (``metrics_proc<P>.prom``). The CLIs (`scripts/train.py`, `scripts/serve.py`,
``bench.py``) wrap their work in exactly this pair, so a single run of
any of them produces the one schema `scripts/telemetry_report.py`
renders.

One session per process: spans are global (the tracer is a module
singleton), so a second concurrent session would interleave sinks.
"""

import os
import sys
import threading
import time

from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.export import (
    SCHEMA_VERSION,
    JsonlWriter,
    MetricStreamer,
    events_name,
    metric_events,
    prom_name,
    write_prometheus,
)
from ncnet_tpu.telemetry.registry import default_registry

_lock = threading.Lock()
_active = None


def _process_index():
    """Multihost process index for the per-process file names.

    Telemetry stays importable without jax by contract, so this only
    ASKS jax when something else already imported it; single-process
    runs (and jax-free consumers) get index 0.
    """
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return 0
    try:
        return int(jax_mod.process_index())
    except Exception:  # nclint: disable=swallowed-exception -- a partially-initialized or backendless jax degrades to single-process telemetry; session start must never fail
        return 0


class TelemetrySession:
    def __init__(self, out_dir, registry=None, label=None):
        self.out_dir = out_dir
        self.registry = registry if registry is not None else default_registry()
        os.makedirs(out_dir, exist_ok=True)
        self.process_index = _process_index()
        self.events_path = os.path.join(
            out_dir, events_name(self.process_index)
        )
        self.prom_path = os.path.join(out_dir, prom_name(self.process_index))
        self.writer = JsonlWriter(self.events_path)
        self.writer.write({
            "type": "meta",
            "schema": SCHEMA_VERSION,
            "ts": time.time(),
            "label": label,
            "pid": os.getpid(),
            "process_index": self.process_index,
        })
        trace.enable(sink=self.writer.write)
        self._extra = []  # [(registry, tags)] — see add_registry
        self._streamer = None
        self._stopped = False

    def add_registry(self, registry, tags=None):
        """Snapshot an ADDITIONAL registry at every `flush_metrics`,
        stamping its metric events with ``tags`` (e.g. the serving fleet
        registers each replica engine's private registry with
        ``{"replica": R}`` — private registries keep per-replica totals
        apart, and the tags let `scripts/telemetry_report.py` key them
        ``name{replica=R}`` in one fleet view)."""
        self._extra.append((registry, dict(tags or {})))

    def flush_metrics(self):
        """Append one metric record per registered metric (also runs at
        `stop`; call mid-run for coarse time series)."""
        for event in metric_events(self.registry):
            self.writer.write(event)
        for registry, tags in self._extra:
            for event in metric_events(registry):
                event.update(tags)
                self.writer.write(event)
        self.writer.flush()

    def start_streaming(self, interval_s):
        """Flush incremental metric records every ``interval_s`` seconds
        (`export.MetricStreamer`): the live events JSONL becomes
        tail-able mid-run — e.g. a scraper watching
        `scripts/serve_http.py --telemetry-stream-s` — and
        `scripts/telemetry_report.py` reads it unchanged (last record
        per name wins). Returns the streamer; `stop` stops it."""
        if self._streamer is not None:
            raise RuntimeError("metric streaming already started")
        self._streamer = MetricStreamer(
            self.flush_metrics, interval_s
        ).start()
        return self._streamer

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        if self._streamer is not None:
            self._streamer.stop()
        trace.disable()
        self.flush_metrics()
        self.writer.close()
        write_prometheus(self.prom_path, self.registry)


def start(out_dir, registry=None, label=None):
    """Begin the process-wide telemetry session writing under
    ``out_dir``; returns the `TelemetrySession`."""
    global _active
    with _lock:
        if _active is not None:
            raise RuntimeError(
                f"telemetry session already active ({_active.out_dir})"
            )
        _active = TelemetrySession(out_dir, registry=registry, label=label)
        return _active


def stop():
    """End the active session (no-op without one)."""
    global _active
    with _lock:
        session, _active = _active, None
    if session is not None:
        session.stop()


def active():
    return _active
