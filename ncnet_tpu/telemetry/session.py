"""Telemetry sessions: one ``--telemetry DIR`` run, one event log.

`start(out_dir)` opens the JSONL event log (``events.jsonl``), enables
the span tracer with the log as its sink, and remembers which registry
to snapshot; `stop()` appends a final metric record per registered
metric, closes the log, and durably writes the Prometheus snapshot
(``metrics.prom``). The CLIs (`scripts/train.py`, `scripts/serve.py`,
``bench.py``) wrap their work in exactly this pair, so a single run of
any of them produces the one schema `scripts/telemetry_report.py`
renders.

One session per process: spans are global (the tracer is a module
singleton), so a second concurrent session would interleave sinks.
"""

import os
import threading
import time

from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.export import (
    EVENTS_NAME,
    PROM_NAME,
    SCHEMA_VERSION,
    JsonlWriter,
    metric_events,
    write_prometheus,
)
from ncnet_tpu.telemetry.registry import default_registry

_lock = threading.Lock()
_active = None


class TelemetrySession:
    def __init__(self, out_dir, registry=None, label=None):
        self.out_dir = out_dir
        self.registry = registry if registry is not None else default_registry()
        os.makedirs(out_dir, exist_ok=True)
        self.events_path = os.path.join(out_dir, EVENTS_NAME)
        self.prom_path = os.path.join(out_dir, PROM_NAME)
        self.writer = JsonlWriter(self.events_path)
        self.writer.write({
            "type": "meta",
            "schema": SCHEMA_VERSION,
            "ts": time.time(),
            "label": label,
            "pid": os.getpid(),
        })
        trace.enable(sink=self.writer.write)
        self._stopped = False

    def flush_metrics(self):
        """Append one metric record per registered metric (also runs at
        `stop`; call mid-run for coarse time series)."""
        for event in metric_events(self.registry):
            self.writer.write(event)
        self.writer.flush()

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        trace.disable()
        self.flush_metrics()
        self.writer.close()
        write_prometheus(self.prom_path, self.registry)


def start(out_dir, registry=None, label=None):
    """Begin the process-wide telemetry session writing under
    ``out_dir``; returns the `TelemetrySession`."""
    global _active
    with _lock:
        if _active is not None:
            raise RuntimeError(
                f"telemetry session already active ({_active.out_dir})"
            )
        _active = TelemetrySession(out_dir, registry=registry, label=label)
        return _active


def stop():
    """End the active session (no-op without one)."""
    global _active
    with _lock:
        session, _active = _active, None
    if session is not None:
        session.stop()


def active():
    return _active
