"""Exporters: append-only JSONL event log + Prometheus text snapshot.

Two formats for two consumers:

  * **JSONL** (`JsonlWriter`) — the durable event stream
    `scripts/telemetry_report.py` renders. Append discipline follows
    `resilience.durable`: events buffer in memory and flush as
    COMPLETE lines followed by ``fsync``, so a preemption mid-run
    loses at most the unflushed tail and can tear at most the final
    line — which `read_events` skips, the same walk-back-past-damage
    posture as `durable.latest_valid`. Every physical write first
    fires the ``telemetry.write`` fault point (`resilience.faultinject`)
    so tests can prove the crash behavior instead of asserting it.
  * **Prometheus text exposition** (`write_prometheus`) — a
    point-in-time ``.prom`` scrape snapshot of a `MetricsRegistry`,
    written via `durable.durable_write_bytes` (atomic rename + sidecar
    digest), so a scraper never reads a torn snapshot.
"""

import json
import os
import threading
import time

from ncnet_tpu.resilience import durable, faultinject

SCHEMA_VERSION = 1

# Legacy (pre-PR-10, single-process) file names inside a ``--telemetry
# DIR`` run directory. Writers now use the per-process names below —
# multihost runs share one run dir and must not clobber one file — and
# readers (`find_event_logs`, `scripts/telemetry_report.py`) accept both
# layouts.
EVENTS_NAME = "events.jsonl"
PROM_NAME = "metrics.prom"


def events_name(process_index):
    """Per-process event-log file name (``events_proc<P>.jsonl``)."""
    return f"events_proc{int(process_index)}.jsonl"


def prom_name(process_index):
    """Per-process Prometheus snapshot name (``metrics_proc<P>.prom``)."""
    return f"metrics_proc{int(process_index)}.prom"


def find_event_logs(run_dir):
    """Every event log in a run dir, sorted: the legacy single-process
    ``events.jsonl`` (if present) plus the per-process
    ``events_proc<P>.jsonl`` files ordered by process index."""
    out = []
    legacy = os.path.join(run_dir, EVENTS_NAME)
    if os.path.isfile(legacy):
        out.append(legacy)
    procs = []
    for name in os.listdir(run_dir):
        if name.startswith("events_proc") and name.endswith(".jsonl"):
            try:
                p = int(name[len("events_proc"):-len(".jsonl")])
            except ValueError:
                continue
            procs.append((p, os.path.join(run_dir, name)))
    out.extend(path for _, path in sorted(procs))
    return out


def _json_default(obj):
    # numpy scalars and similar reach the sink from device-adjacent code
    for attr in ("item",):
        fn = getattr(obj, attr, None)
        if callable(fn):
            return fn()
    return repr(obj)


class JsonlWriter:
    """Append-only JSONL sink with complete-line durable flushes."""

    def __init__(self, path, flush_every=256):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._pending = []
        self._flush_every = flush_every
        self._f = open(path, "ab")
        self._closed = False

    def write(self, event):
        line = (
            json.dumps(event, sort_keys=True, default=_json_default) + "\n"
        ).encode("utf-8")
        with self._lock:
            if self._closed:
                return  # late events from draining threads are dropped
            self._pending.append(line)
            if len(self._pending) >= self._flush_every:
                self._flush_locked()

    def flush(self):
        with self._lock:
            self._flush_locked()

    def _flush_locked(self):  # guarded-by: _lock
        if not self._pending:
            return
        blob = b"".join(self._pending)
        faultinject.fire(
            "telemetry.write", {"path": self.path, "nbytes": len(blob)}
        )
        self._f.write(blob)
        self._f.flush()
        os.fsync(self._f.fileno())
        # cleared only after the durable write: a raised flush (injected
        # crash, ENOSPC) keeps the events pending for the next attempt
        self._pending = []

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._flush_locked()
            self._closed = True
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


def read_events(path):
    """Parse a JSONL event log; skips blank/torn lines (a crash mid-append
    can tear at most the trailing line — see `JsonlWriter`)."""
    events = []
    with open(path, "rb") as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                events.append(json.loads(raw.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                continue
    return events


def metric_events(registry, ts=None):
    """One ``{"type": "metric", ...}`` event per registered metric — the
    JSONL form of a registry snapshot."""
    stamp = time.time() if ts is None else ts
    return [
        {"type": "metric", "name": name, "ts": stamp, **snap}
        for name, snap in registry.snapshot().items()
    ]


class MetricStreamer:
    """Periodic incremental metric flush: the streaming/OTLP-shaped
    bridge (ISSUE 17). A daemon thread calls ``flush_fn`` (normally
    `TelemetrySession.flush_metrics`) every ``interval_s`` seconds, so
    the events JSONL grows a metric record per registered metric while
    the server is LIVE — a scraper can tail the file instead of waiting
    for session stop. Each flush uses the writer's existing durable
    complete-line discipline, and `scripts/telemetry_report.py` reads
    the result unchanged: its final-metrics view keeps the LAST record
    per name, so intermediate stream records simply become the coarse
    time series.

    A flush that raises (injected ``telemetry.write`` fault, transient
    ENOSPC) leaves its events pending in the writer and the streamer
    keeps ticking — the next interval retries them.
    """

    def __init__(self, flush_fn, interval_s, name="telemetry-stream"):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self._flush_fn = flush_fn
        self._interval = interval_s
        self._stop = threading.Event()
        self.flushes = 0
        self.errors = 0
        self.thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )

    def start(self):
        self.thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._flush_fn()
                self.flushes += 1
            except Exception:  # nclint: disable=swallowed-exception -- counted and retried next tick: the writer keeps un-flushed events pending, and a telemetry hiccup must never kill the stream (or the server it observes)
                self.errors += 1

    def stop(self, join_timeout=1.0):
        """Idempotent; joins the thread bounded."""
        self._stop.set()
        if self.thread.is_alive():
            self.thread.join(join_timeout)


def write_prometheus(path, registry):
    """Durably write the registry's text exposition; returns bytes
    written."""
    blob = registry.to_prometheus().encode("utf-8")
    durable.durable_write_bytes(
        path, blob,
        write_point="telemetry.write",
        rename_point=None,
        bytes_point=None,
    )
    return len(blob)
