"""Span tracer: nested, thread-safe timing regions on monotonic clocks.

Usage at an instrumentation site::

    from ncnet_tpu.telemetry import trace
    with trace.span("step/device_compute"):
        state, loss = train_step(state, dbatch)

Contract:

  * **Disabled is free.** When tracing is off, ``span()`` returns ONE
    cached no-op singleton — no allocation, no clock read; the call pays
    a single attribute lookup on the tracer (the same contract as
    `resilience.faultinject.fire` and `analysis.sanitizer`). Hot paths
    (the serving prep/dispatch/readout loops, the per-step training
    loop) keep their spans unconditionally.
  * **Monotonic clocks.** Durations come from ``time.perf_counter``
    deltas, never wall clock (NTP steps make ``time.time`` run
    backwards; the `wall-clock-timing` nclint rule enforces this
    repo-wide). The wall-clock ``ts`` field on each event is a
    TIMESTAMP — an epoch anchor captured once at enable time plus a
    monotonic offset — not a duration operand.
  * **Nestable + thread-safe.** Each thread keeps its own span stack;
    an event's ``path`` joins the enclosing names with ``>``
    ("serve/dispatch>serve/device"), which is what the report's span
    tree and self-time accounting key on. The separator is NOT ``/``
    because span names use ``/`` for their surface prefix
    ("step/loss_sync") — nesting must stay unambiguous.

Events are dicts ``{type, name, path, ts, dur_s, thread, ok}`` delivered
to the enabled sink (a `telemetry.export.JsonlWriter.write`, usually) or
buffered in memory for tests.
"""

import threading
import time


class _NullSpan:
    """The disabled-mode span: one shared instance, no state, no clock."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "_t0")

    def __init__(self, tracer, name):
        self._tracer = tracer
        self.name = name

    def __enter__(self):
        stack = self._tracer._stack()
        stack.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        tracer = self._tracer
        stack = tracer._stack()
        path = ">".join(stack)
        if stack:
            stack.pop()
        event = {
            "type": "span",
            "name": self.name,
            "path": path,
            "ts": tracer._wall0 + (self._t0 - tracer._perf0),
            "dur_s": t1 - self._t0,
            "thread": threading.get_ident(),
            "ok": exc_type is None,
        }
        tags = getattr(tracer._local, "tags", None)
        if tags:
            event.update(tags)
        tracer._emit(event)
        return False


class Tracer:
    def __init__(self):
        self._enabled = False
        self._sink = None
        self._lock = threading.Lock()
        self._buffer = []
        self._local = threading.local()
        self._wall0 = time.time()  # epoch anchor for ts, not a duration
        self._perf0 = time.perf_counter()

    def span(self, name):
        if not self._enabled:  # nclint: disable=unguarded-shared-state -- the "disabled is free" contract: one racy bool read IS the hot path; a span that races enable() is simply attributed to the old state
            return _NULL_SPAN
        return _Span(self, name)

    def set_thread_tag(self, key, value):
        """Attach ``key: value`` to every span THIS thread emits from now
        on (e.g. the serving fleet tags each replica's worker threads
        ``replica=R`` so one merged report can tell them apart). Tags
        ride on the event dict next to the standard fields; reserved
        field names are rejected. Costs nothing while tracing is
        disabled and one ``getattr`` per span while enabled."""
        if key in ("type", "name", "path", "ts", "dur_s", "thread", "ok"):
            raise ValueError(f"{key!r} is a reserved span field")
        tags = getattr(self._local, "tags", None)
        if tags is None:
            tags = self._local.tags = {}
        tags[key] = value

    def clear_thread_tags(self):
        self._local.tags = None

    def is_enabled(self):
        return self._enabled  # nclint: disable=unguarded-shared-state -- benign racy read of the enable flag; callers use it as a hint, never for mutual exclusion

    def enable(self, sink=None):
        """Turn tracing on. ``sink(event)`` receives each completed span;
        without one, events buffer in memory (drain with `drain`)."""
        with self._lock:
            self._sink = sink
            self._wall0 = time.time()  # re-anchor the epoch mapping
            self._perf0 = time.perf_counter()
            self._enabled = True

    def disable(self):
        with self._lock:
            self._enabled = False
            self._sink = None

    def drain(self):
        """Return and clear the in-memory event buffer."""
        with self._lock:
            events, self._buffer = self._buffer, []
        return events

    # ------------------------------------------------------------------

    def _stack(self):
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _emit(self, event):
        sink = self._sink  # nclint: disable=unguarded-shared-state -- single racy snapshot of the sink reference: a span completing across disable() delivers to the old sink or the buffer, both safe; locking every emit would serialize all traced threads
        if sink is not None:
            sink(event)
        else:
            with self._lock:
                self._buffer.append(event)


_TRACER = Tracer()

# Module-level API: `trace.span(...)` at every instrumentation site.
# Bound once so the disabled hot path is one attribute load + the
# tracer's single `_enabled` check.
span = _TRACER.span
is_enabled = _TRACER.is_enabled
enable = _TRACER.enable
disable = _TRACER.disable
drain = _TRACER.drain
set_thread_tag = _TRACER.set_thread_tag
clear_thread_tags = _TRACER.clear_thread_tags
