"""Unified telemetry: tracing, metrics, and profiling for every surface.

The one observability plane over the five instrumented surfaces — train
loop, serving engine, eval pipelines, feature extraction, and
checkpointing — replacing their per-surface ad-hoc dicts (production TPU
stacks report throughput/latency/utilization side by side; PAPERS.md,
the Gemma-on-TPU serving study):

  * `registry` — counters / gauges / explicit-bucket histograms
    (`MetricsRegistry`), plus `percentiles` / `summarize_latencies`
    as THE latency-summary implementation (``benchmarks/timing.py``
    re-exports them);
  * `trace` — ``with trace.span("step/device_compute"):`` spans on
    monotonic clocks, thread-safe and nestable, an exact no-op
    singleton when disabled;
  * `export` — append-only JSONL event log (durable-append discipline,
    ``telemetry.write`` fault point) and Prometheus text snapshots;
  * `session` — ``start(dir)`` / ``stop()``, the ``--telemetry DIR``
    contract: one run produces ``events_proc<P>.jsonl`` +
    ``metrics_proc<P>.prom`` PER PROCESS (multihost runs share one dir
    without clobbering), rendered by ``scripts/telemetry_report.py``,
    which also still reads the legacy single ``events.jsonl`` layout;
  * `profiler` — the `jax.profiler` capture window
    (``--profile-dir DIR --profile-steps A:B``).

Import-light by contract (stdlib + numpy; jax only inside `profiler`
methods): hot paths import it at instrumentation points and the report
CLI imports it without a device runtime.
"""

from ncnet_tpu.telemetry import export, profiler, registry, session, trace
from ncnet_tpu.telemetry.export import (
    JsonlWriter,
    MetricStreamer,
    read_events,
    write_prometheus,
)
from ncnet_tpu.telemetry.profiler import ProfileWindow, parse_steps
from ncnet_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    percentiles,
    summarize_latencies,
)
from ncnet_tpu.telemetry.session import TelemetrySession, active, start, stop

__all__ = [
    "DEFAULT_LATENCY_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlWriter",
    "MetricStreamer",
    "MetricsRegistry",
    "ProfileWindow",
    "TelemetrySession",
    "active",
    "default_registry",
    "export",
    "parse_steps",
    "percentiles",
    "profiler",
    "read_events",
    "registry",
    "session",
    "start",
    "stop",
    "summarize_latencies",
    "trace",
    "write_prometheus",
]
