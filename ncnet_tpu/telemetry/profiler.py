"""`jax.profiler` capture hook: a programmatic trace window over steps.

`ProfileWindow` owns the start/stop logic the training loop used to
inline: trace steps ``[start, stop)`` into ``profile_dir`` (viewable
with tensorboard/xprof), syncing the device before the trace closes —
``block_until_ready`` does not block on the tunneled platform (see
``bench.py``), so the caller supplies a D2H ``sync`` callable and the
window runs it before ``stop_trace``.

CLI form: ``--profile-dir DIR --profile-steps A:B`` (parse the window
with `parse_steps`). jax imports stay inside methods so this module —
and the telemetry package — import without jax (the report CLI needs
that).
"""


def parse_steps(spec):
    """``"A:B"`` -> ``(A, B)`` with ``0 <= A < B``."""
    try:
        a_s, b_s = str(spec).split(":")
        a, b = int(a_s), int(b_s)
    except ValueError:
        raise ValueError(
            f"--profile-steps wants 'A:B' (e.g. '3:8'), got {spec!r}"
        ) from None
    if a < 0 or b <= a:
        raise ValueError(f"--profile-steps window must have 0 <= A < B, got {spec!r}")
    return (a, b)


class ProfileWindow:
    """Start/stop one `jax.profiler` trace over a step interval.

    ``on_step(i, sync=...)`` is called once per step with the global step
    index; the window opens at ``steps[0]``, closes at ``steps[1]``, and
    captures at most once per process. With ``profile_dir=None`` every
    call is a no-op, so the loop keeps the hook unconditionally.
    """

    def __init__(self, profile_dir, steps=(3, 8)):
        self.profile_dir = profile_dir
        self.start_step, self.stop_step = steps
        self._active = False
        self._done = profile_dir is None

    @property
    def active(self):
        return self._active

    def on_step(self, step, sync=None):
        if self._done:
            return
        if not self._active:
            if step == self.start_step:
                import jax

                jax.profiler.start_trace(self.profile_dir)
                self._active = True
        elif step >= self.stop_step:
            self.close(sync)

    def close(self, sync=None):
        """Stop an open trace (idempotent); runs ``sync`` first so the
        device finishes the profiled steps before the trace file closes."""
        if self._active:
            if sync is not None:
                sync()
            import jax

            jax.profiler.stop_trace()
            self._active = False
            print(
                f"profile trace written to {self.profile_dir}", flush=True
            )
        self._done = True
