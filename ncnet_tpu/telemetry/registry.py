"""Metrics registry: counters, gauges, explicit-bucket histograms.

One metric vocabulary for every surface (train loop, serving engine,
eval pipelines, feature extraction, checkpointing), replacing the
per-surface ad-hoc dicts. Three Prometheus-shaped metric kinds:

  * `Counter` — monotonically non-decreasing totals (``inc`` rejects
    negative deltas by contract, so a counter can never run backwards);
  * `Gauge` — a point-in-time value, either ``set`` explicitly or backed
    by a callback (``set_fn``) sampled at read time — the queue-depth
    idiom, where the truth lives in the queue, not in the metric;
  * `Histogram` — explicit upper-bound buckets (``le``-inclusive, the
    Prometheus convention) PLUS retained raw samples, so snapshots carry
    exact p50/p95/p99 instead of bucket-interpolated estimates. Latency
    histograms default to `DEFAULT_LATENCY_BUCKETS` (seconds).

`percentiles` / `summarize_latencies` here are THE implementation — the
microbenchmarks' ``benchmarks/timing.py`` re-exports them as shims.

Like `resilience` and `analysis`, this module must stay import-light
(stdlib + numpy, no jax): the report CLI and the hot paths that import
it cannot afford a jax import.
"""

import bisect
import math
import re
import threading

from ncnet_tpu.analysis import concurrency

# Upper bounds in seconds for request/step latencies: sub-ms host work up
# through multi-second cold paths. The +Inf bucket is implicit.
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def percentiles(samples, ps=(50, 95, 99)):
    """``{'p50': ..., 'p95': ..., 'p99': ...}`` over ``samples`` (seconds
    or any unit — values pass through), linear interpolation. Empty input
    gives NaNs rather than raising: a benchmark that timed nothing should
    still emit a well-formed report."""
    import numpy as np

    if len(samples) == 0:
        return {f"p{p}": float("nan") for p in ps}
    arr = np.asarray(samples, dtype=np.float64)
    return {f"p{p}": float(np.percentile(arr, p)) for p in ps}


def summarize_latencies(samples, ps=(50, 95, 99)):
    """``{'count', 'mean', 'p50', 'p95', 'p99'}`` over latency samples.

    Unit-preserving like `percentiles`; empty input yields count 0 and
    NaN statistics.
    """
    import numpy as np

    out = {"count": int(len(samples))}
    out["mean"] = (
        float(np.mean(np.asarray(samples, dtype=np.float64)))
        if len(samples)
        else float("nan")
    )
    out.update(percentiles(samples, ps))
    return out


class Counter:
    """A monotonically non-decreasing total."""

    kind = "counter"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError(
                f"counter {self.name}: negative increment {n!r} "
                "(counters are monotonic; use a gauge)"
            )
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def snapshot(self):
        with self._lock:
            return {"kind": self.kind, "value": self._value}


class Gauge:
    """A point-in-time value; optionally backed by a sampling callback."""

    kind = "gauge"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._fn = None

    def set(self, value):
        self._fn = None
        self._value = value

    def set_fn(self, fn):
        """Back the gauge by ``fn()`` sampled at read time (queue depths,
        occupancy: the truth lives in the structure, not the metric)."""
        self._fn = fn

    @property
    def value(self):
        fn = self._fn
        if fn is not None:
            try:
                return fn()
            except Exception:  # nclint: disable=swallowed-exception -- a dead gauge callback must read as NaN, never kill a scrape
                return float("nan")
        return self._value

    def snapshot(self):
        return {"kind": self.kind, "value": self.value}


class Histogram:
    """Explicit-bucket histogram with retained raw samples.

    ``buckets`` are strictly increasing finite upper bounds; an implicit
    +Inf bucket catches the tail. Bucket membership is ``value <= le``
    (inclusive upper bound, the Prometheus convention). Raw samples are
    retained so percentiles are exact, not bucket-interpolated.
    """

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_LATENCY_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if not bounds or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ) or not all(math.isfinite(b) for b in bounds):
            raise ValueError(
                f"histogram {name}: buckets must be strictly increasing "
                f"finite upper bounds, got {buckets!r}"
            )
        self.name = name
        self.help = help
        self.buckets = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)  # last slot is +Inf
        self._sum = 0.0
        self._samples = []

    def observe(self, value):
        v = float(value)
        idx = bisect.bisect_left(self.buckets, v)  # v <= le is inclusive
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._samples.append(v)

    @property
    def count(self):
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self):
        with self._lock:
            return self._sum

    @property
    def samples(self):
        with self._lock:
            return list(self._samples)

    def bucket_counts(self):
        """``[(le, cumulative_count), ...]`` ending with (+Inf, count)."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for le, c in zip(self.buckets + (math.inf,), counts):
            cum += c
            out.append((le, cum))
        return out

    def percentiles(self, ps=(50, 95, 99)):
        return percentiles(self.samples, ps)

    def snapshot(self):
        with self._lock:
            counts = list(self._counts)
            total = self._sum
            samples = list(self._samples)
        cum, buckets = 0, []
        for le, c in zip(self.buckets + (math.inf,), counts):
            cum += c
            buckets.append([le, cum])
        snap = {
            "kind": self.kind,
            "count": cum,
            "sum": total,
            "buckets": buckets,
        }
        snap.update(percentiles(samples))
        return snap


class MetricsRegistry:
    """Named metrics with get-or-create semantics.

    Re-requesting a name returns the SAME metric object (instrumentation
    sites in different modules share totals by name); re-requesting it as
    a different kind raises — a name means one thing.
    """

    def __init__(self):
        self._lock = concurrency.make_lock("telemetry.registry")
        self._metrics = {}

    def _get_or_create(self, cls, name, help, **kwargs):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_LATENCY_BUCKETS):
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def _items(self):
        """Name-sorted ``(name, metric)`` pairs copied under the lock, so
        iteration never races a concurrent registration; each metric's
        own snapshot then locks itself OUTSIDE the registry lock (no
        nesting)."""
        with self._lock:
            return sorted(self._metrics.items())

    def snapshot(self):
        """``{name: metric.snapshot()}`` for every registered metric."""
        return {name: m.snapshot() for name, m in self._items()}

    def to_prometheus(self):
        """Prometheus text exposition (version 0.0.4) of the registry."""
        lines = []
        for name, m in self._items():
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, Histogram):
                for le, cum in m.bucket_counts():
                    lines.append(
                        f'{name}_bucket{{le="{_fmt_le(le)}"}} {cum}'
                    )
                lines.append(f"{name}_sum {_fmt(m.sum)}")
                lines.append(f"{name}_count {m.count}")
            else:
                lines.append(f"{name} {_fmt(m.value)}")
        return "\n".join(lines) + "\n"


def _fmt_le(le):
    return "+Inf" if math.isinf(le) else _fmt(le)


def _fmt(v):
    if isinstance(v, bool):
        return str(int(v))
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15 and not math.isnan(f):
        return str(int(f))
    return repr(f)


# The process-global default registry: train/eval/features/checkpoint
# instrumentation lands here; the serving engine defaults to a private
# registry per engine (see ServeEngine(registry=...)).
_DEFAULT = MetricsRegistry()


def default_registry():
    return _DEFAULT
