"""Static analysis + runtime numerical sanitizers for the framework.

Complementary halves (rule catalog and usage: README.md next to this
file):

  * `engine` / `rules` / `cli` — an AST lint suite encoding the JAX/TPU
    hazards this project has been bitten by (bare contract asserts, host
    syncs inside compiled regions, eps-less divisions, unstable exp,
    Python branches on traced values, mutable defaults). CI gate:
    ``python scripts/lint.py ncnet_tpu scripts benchmarks``.
  * `concurrency` — the lock-discipline prong: three AST rules over the
    threaded serve/telemetry modules (registered into nclint via
    `rules`) plus the opt-in ``NCNET_LOCK_AUDIT=1`` runtime audit
    (`make_lock` / `OrderedLock` acquisition-graph cycle detection,
    `ScheduleFuzzer` seeded interleaving perturbation). CI gate:
    ``python scripts/lock_drill.py``.
  * `sanitizer` — per-stage finiteness / bf16-range probes behind
    ``--sanitize`` on scripts/train.py and bench.py; localizes a NaN to
    the first non-finite stage instead of a dead training run.

The subpackage is import-light on purpose: `sanitizer` is imported by the
model/training modules at instrumentation points, so it must not drag the
lint machinery (or anything heavier than jax itself) along.
"""

from ncnet_tpu.analysis import sanitizer

__all__ = ["sanitizer"]
