"""Concurrency auditing — the fourth audit level, two prongs.

The first three audit levels (AST lint, jaxpr IR, post-fusion HLO) are
blind to the most bug-dense layer of the system: the threaded serving
stack. PRs 10/11 each shipped hand-found races (the ``Watchdog.stop``
self-join, the ``_warm_specs`` dict-changed-size race, the
``MicroBatcher`` lost-request hang). This module attacks that class from
both sides:

**Static prong** — three nclint rules over the threaded modules:

  * ``unguarded-shared-state``: per class, infer which ``self._*``
    attributes are lock-guarded (written inside ``with self._lock:``, or
    inside a helper documented ``# guarded-by: <lock>`` on its ``def``
    line), then flag any read/write of a guarded attribute outside every
    lock scope. One-level interprocedural reach through the PR-9
    `ProjectIndex`: a call site that passes ``self`` into another
    module's helper is flagged when that helper writes a guarded
    attribute and the call site holds no lock.
  * ``lock-order-annotation``: a class holding >= 2 locks must declare
    its acquisition order in a ``# lock-order: _a -> _b`` comment inside
    the class body, and the comment must name exactly the class's lock
    attributes (stale annotations are findings too).
  * ``unjoined-thread``: a ``threading.Thread`` constructed without
    ``daemon=True`` in a scope that never calls ``.join`` leaks at
    shutdown; join it or register it in a thread ledger.

All three honour the engine's suppression-with-reason discipline
(``# nclint: disable=<rule> -- <why>``).

**Runtime prong** — opt-in instrumented locks behind ``NCNET_LOCK_AUDIT=1``
(same env-gated discipline as `resilience.faultinject`; exact no-op when
disabled):

  * `make_lock(name)` is the factory every audited module uses. Disabled
    (the default) it returns a BARE ``threading.Lock``/``RLock`` — zero
    wrapper, zero overhead, byte-identical behaviour (the <= 5% overhead
    acceptance bar is met by construction; `benchmarks/micro_lock_audit.py`
    measures it anyway). Enabled, it returns an `OrderedLock` that records
    the per-thread lock-acquisition graph, detects lock-order cycles
    (potential deadlock) and held-lock wall-time outliers, and reports
    through the shared `findings.py` model (pseudo-path ``lock:<name>``,
    like the auditor's ``jaxpr:<program>``).
  * `ScheduleFuzzer` inserts randomized-but-SEEDED yields at every
    instrumented lock boundary, so chaos drills (`tests/test_fleet.py`
    kill/rejoin/drain) double as schedule-exploration runs and
    interleaving regressions (the PR-11 lost-request bug) get replayable
    coverage instead of one lucky schedule.

Because `make_lock` decides at CONSTRUCTION time, enabling the audit
mid-run only instruments locks created afterwards — enable before
building the engine/fleet under test (the chaos drills and
`scripts/lock_drill.py` do).
"""

import ast
import itertools
import os
import random
import re
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ncnet_tpu.analysis.engine import ModuleContext, rule
from ncnet_tpu.analysis.findings import Finding

# ---------------------------------------------------------------------------
# Static prong: lock-discipline AST rules
# ---------------------------------------------------------------------------

#: canonical callables whose result is a lock attribute
_LOCK_FACTORY_NAMES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "ncnet_tpu.analysis.concurrency.make_lock",
}

#: ``.append`` etc. on a guarded container counts as a WRITE to it
_MUTATOR_METHODS = {
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "pop", "popleft", "popitem", "remove", "setdefault",
    "update",
}

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z0-9_,\s]+)")
_LOCK_ORDER_RE = re.compile(r"#\s*lock-order:\s*([A-Za-z0-9_>\s\-]+)")

STATIC_RULE_IDS = (
    "unguarded-shared-state",
    "lock-order-annotation",
    "unjoined-thread",
)


def _is_lock_factory(ctx: ModuleContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = ctx.canonical(node.func)
    if not name:
        return False
    return name in _LOCK_FACTORY_NAMES or name.endswith(".make_lock")


def _self_attr(node: ast.AST) -> Optional[str]:
    """Attribute name when ``node`` is ``self.<attr>``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _class_locks(ctx: ModuleContext, cls: ast.ClassDef) -> Set[str]:
    """Attribute names assigned a lock-factory call anywhere in ``cls``."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_lock_factory(ctx, node.value):
            for t in node.targets:
                attr = _self_attr(t)
                if attr:
                    locks.add(attr)
    return locks


def _direct_methods(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _guarded_by_annotation(
    lines: List[str], meth: ast.AST
) -> Optional[Set[str]]:
    """Lock names from a ``# guarded-by: <lock>`` comment on the def line."""
    line = lines[meth.lineno - 1] if meth.lineno - 1 < len(lines) else ""
    m = _GUARDED_BY_RE.search(line)
    if not m:
        return None
    return {s.strip() for s in m.group(1).split(",") if s.strip()}


class _Access:
    __slots__ = ("node", "attr", "is_write", "held", "method")

    def __init__(self, node, attr, is_write, held, method):
        self.node = node
        self.attr = attr
        self.is_write = is_write
        self.held = held
        self.method = method


def _scan_method(meth, locks: Set[str], seed_held: Set[str]):
    """Walk the EXECUTED body of ``meth`` tracking the held-lock set.

    Nested FunctionDef/AsyncFunctionDef/Lambda subtrees are pruned
    entirely — an inner def (a worker target, a gauge lambda) runs on its
    own schedule, so neither its accesses nor its lock scopes say
    anything about the enclosing method. Returns ``(accesses, calls)``
    where ``calls`` carries each Call node with the held set at the call
    site (for the one-level interprocedural pass).
    """
    accesses: List[_Access] = []
    calls: List[Tuple[ast.Call, frozenset]] = []
    name = meth.name

    def record(node, attr, is_write, held):
        accesses.append(_Access(node, attr, is_write, frozenset(held), name))

    def walk(node, held):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        if isinstance(node, ast.With):
            acquired = set()
            for item in node.items:
                walk(item.context_expr, held)
                attr = _self_attr(item.context_expr)
                if attr and attr in locks:
                    acquired.add(attr)
            inner = held | acquired
            for stmt in node.body:
                walk(stmt, inner)
            return
        if isinstance(node, ast.Call):
            calls.append((node, frozenset(held)))
            fattr = node.func
            if isinstance(fattr, ast.Attribute):
                recv = _self_attr(fattr.value)
                if recv and fattr.attr in _MUTATOR_METHODS:
                    record(fattr.value, recv, True, held)
        elif isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            attr = _self_attr(node.value)
            if attr:
                record(node.value, attr, True, held)
        elif isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr:
                record(node, attr, isinstance(node.ctx, (ast.Store, ast.Del)), held)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in meth.body:
        walk(stmt, set(seed_held))
    return accesses, calls


def _interproc_guarded_writes(ctx, call, guarded):
    """Guarded attrs a resolved cross-module callee writes via ``self``.

    Only fires when the call passes a bare ``self`` positionally and the
    callee is a top-level function of another indexed module (one level,
    same reach contract as every other interprocedural rule).
    """
    from ncnet_tpu.analysis.rules import _resolve_foreign_call, _walk_executed

    self_pos = None
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Name) and arg.id == "self":
            self_pos = i
            break
    if self_pos is None:
        return ()
    _name, info = _resolve_foreign_call(ctx, call)
    if info is None:
        return ()
    params = [a.arg for a in info.node.args.args]
    if self_pos >= len(params):
        return ()
    pname = params[self_pos]
    written = set()
    for node in _walk_executed(info.node):
        target = None
        if isinstance(node, ast.Attribute) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            target = node
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr in _MUTATOR_METHODS:
            target = node.func.value
        if (
            target is not None
            and isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == pname
            and target.attr in guarded
        ):
            written.add(target.attr)
    if not written:
        return ()
    return sorted(written), info.module


@rule(
    "unguarded-shared-state",
    "warning",
    doc="A `self._*` attribute this class writes under a lock is read or "
        "written elsewhere with NO lock held — a data race unless the "
        "access is intentionally racy (then suppress with the reason). "
        "Guardedness is inferred from `with self._lock:` bodies and "
        "`# guarded-by: <lock>` method annotations.",
)
def unguarded_shared_state(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    if ctx.is_test:
        return
    lines = ctx.source.splitlines()
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _class_locks(ctx, cls)
        if not locks:
            continue

        per_method = []  # (meth, accesses, calls)
        for meth in _direct_methods(cls):
            ann = _guarded_by_annotation(lines, meth)
            if ann is not None:
                unknown = ann - locks
                if unknown:
                    yield meth, (
                        f"# guarded-by: names {sorted(unknown)} but "
                        f"{cls.name} has no such lock attribute(s) "
                        f"(locks: {sorted(locks)})"
                    )
                ann &= locks
            accesses, calls = _scan_method(meth, locks, ann or set())
            per_method.append((meth, accesses, calls))

        # evidence: attr -> (locks seen held at writes, first witness)
        guarded: Dict[str, Set[str]] = {}
        witness: Dict[str, Tuple[str, str]] = {}
        for meth, accesses, _calls in per_method:
            if meth.name == "__init__":
                continue
            for a in accesses:
                if (
                    a.is_write
                    and a.held
                    and a.attr.startswith("_")
                    and a.attr not in locks
                ):
                    guarded.setdefault(a.attr, set()).update(a.held)
                    witness.setdefault(a.attr, (sorted(a.held)[0], a.method))

        if not guarded:
            continue

        flagged = set()
        for meth, accesses, calls in per_method:
            if meth.name == "__init__":
                continue
            for a in accesses:
                g = guarded.get(a.attr)
                if g is None or a.held & g:
                    continue
                key = (a.node.lineno, a.attr)
                if key in flagged:
                    continue
                flagged.add(key)
                lock_name, where = witness[a.attr]
                kind = "written" if a.is_write else "read"
                yield a.node, (
                    f"self.{a.attr} {kind} without holding "
                    f"self.{lock_name} (written under it in "
                    f"{cls.name}.{where}); take the lock or suppress "
                    f"with the reason the race is benign"
                )
            if ctx.project is not None:
                for call, held in calls:
                    if held:
                        continue
                    hit = _interproc_guarded_writes(ctx, call, set(guarded))
                    if not hit:
                        continue
                    attrs, mod = hit
                    key = (call.lineno, tuple(attrs))
                    if key in flagged:
                        continue
                    flagged.add(key)
                    yield call, (
                        f"call passes self into {mod} which writes "
                        f"guarded attribute(s) {attrs} — no lock held "
                        f"at this call site"
                    )


@rule(
    "lock-order-annotation",
    "warning",
    doc="A class holding >= 2 locks must declare its acquisition order "
        "with a `# lock-order: _a -> _b` comment in the class body, and "
        "the comment must name exactly the class's lock attributes. The "
        "runtime OrderedLock audit verifies the declared order is the "
        "observed one.",
)
def lock_order_annotation(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    if ctx.is_test:
        return
    lines = ctx.source.splitlines()
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _class_locks(ctx, cls)
        if len(locks) < 2:
            continue
        end = getattr(cls, "end_lineno", None) or len(lines)
        declared = None
        for lineno in range(cls.lineno, min(end, len(lines)) + 1):
            m = _LOCK_ORDER_RE.search(lines[lineno - 1])
            if m:
                declared = [
                    s.strip() for s in m.group(1).split("->") if s.strip()
                ]
                break
        if declared is None:
            yield cls, (
                f"{cls.name} holds {len(locks)} locks "
                f"({', '.join(sorted(locks))}) but declares no "
                f"acquisition order; add '# lock-order: "
                f"{' -> '.join(sorted(locks))}' (in the true order)"
            )
        elif set(declared) != locks or len(declared) != len(set(declared)):
            yield cls, (
                f"{cls.name} lock-order annotation is stale: declares "
                f"({', '.join(declared)}) but the class's locks are "
                f"({', '.join(sorted(locks))})"
            )


@rule(
    "unjoined-thread",
    "warning",
    doc="`threading.Thread` constructed without daemon=True in a scope "
        "that never calls `.join` — the thread outlives shutdown and "
        "leaks. Join it (the serve stack's thread-ledger pattern), make "
        "it a daemon, or suppress with the reason.",
)
def unjoined_thread(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    if ctx.is_test:
        return
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if ctx.canonical(node.func) != "threading.Thread":
            continue
        daemon = next(
            (kw.value for kw in node.keywords if kw.arg == "daemon"), None
        )
        if isinstance(daemon, ast.Constant) and daemon.value is True:
            continue
        # nearest enclosing function; widen to the class for methods so a
        # start-in-one-method / join-in-shutdown split is not a finding
        scope: ast.AST = ctx.tree
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = cur
                holder = parents.get(cur)
                if isinstance(holder, ast.ClassDef):
                    scope = holder
                break
            cur = parents.get(cur)
        joins = any(
            isinstance(n, ast.Attribute) and n.attr == "join"
            for n in ast.walk(scope)
        )
        if not joins:
            scope_name = getattr(scope, "name", "<module>")
            yield node, (
                f"Thread created without daemon=True and never joined in "
                f"{scope_name}; join it at shutdown, register it in a "
                f"thread ledger, or mark it daemon"
            )


# ---------------------------------------------------------------------------
# Runtime prong: OrderedLock / ScheduleFuzzer behind NCNET_LOCK_AUDIT=1
# ---------------------------------------------------------------------------

ENV_VAR = "NCNET_LOCK_AUDIT"

RUNTIME_RULE_IDS = ("lock-order-cycle", "lock-held-outlier")

_DEFAULT_OUTLIER_S = 0.5
_OUTLIER_CAP_PER_LOCK = 3

_meta_lock = threading.Lock()
_enabled = False
_env_loaded = False
_default_outlier_s = _DEFAULT_OUTLIER_S
#: (held_name, acquired_name) -> observation count
_edges: Dict[Tuple[str, str], int] = {}
#: name -> [acquire_count, total_held_s, max_held_s]
_held: Dict[str, List[float]] = {}
_outliers: List[Finding] = []
_outlier_counts: Dict[str, int] = {}
_fuzzer: Optional["ScheduleFuzzer"] = None
_tls = threading.local()


def _held_stack() -> List[Tuple[str, float]]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _ensure_env_loaded():
    global _env_loaded, _enabled
    if _env_loaded:
        return
    with _meta_lock:
        if _env_loaded:
            return
        _enabled = os.environ.get(ENV_VAR, "") == "1"
        _env_loaded = True


def is_enabled() -> bool:
    _ensure_env_loaded()
    return _enabled


def enable(held_outlier_s: Optional[float] = None):
    """Turn the lock audit on for locks created AFTER this call."""
    global _enabled, _env_loaded, _default_outlier_s
    with _meta_lock:
        _enabled = True
        _env_loaded = True
        if held_outlier_s is not None:
            _default_outlier_s = float(held_outlier_s)


def disable():
    global _enabled, _env_loaded
    with _meta_lock:
        _enabled = False
        _env_loaded = True


def clear():
    """Reset graph + findings and disable (beats a stale env var, same
    contract as `faultinject.clear`)."""
    global _enabled, _env_loaded, _fuzzer, _default_outlier_s
    with _meta_lock:
        _enabled = False
        _env_loaded = True
        _default_outlier_s = _DEFAULT_OUTLIER_S
        _edges.clear()
        _held.clear()
        _outliers.clear()
        _outlier_counts.clear()
        _fuzzer = None


def make_lock(
    name: str,
    reentrant: bool = False,
    held_outlier_s: Optional[float] = None,
):
    """The lock constructor every audited module uses.

    Disabled (default): returns a BARE ``threading.Lock``/``RLock`` —
    the audit costs nothing because there is nothing there. Enabled:
    returns an `OrderedLock` recording the acquisition graph.
    ``held_outlier_s`` overrides the outlier threshold for locks that
    legitimately block for long stretches (e.g. the engine's compile
    lock, held across multi-second AOT compiles).
    """
    _ensure_env_loaded()
    if not _enabled:
        return threading.RLock() if reentrant else threading.Lock()
    return OrderedLock(name, reentrant=reentrant, held_outlier_s=held_outlier_s)


class OrderedLock:
    """Instrumented lock: per-thread acquisition-order edges + held time.

    Wraps a real ``threading.Lock``/``RLock``; the audit state (edge
    graph, held-time stats, outlier findings) is module-global so cycles
    ACROSS locks and threads are visible. Lock NAMES aggregate across
    instances — every replica's ``serve.engine.gen`` is one graph node —
    which is what makes order inversions between two code paths visible
    no matter which instances they ran on. Reentrant re-acquisition adds
    no self-edges.
    """

    __slots__ = ("name", "_lock", "_outlier_s", "reentrant")

    def __init__(self, name, reentrant=False, held_outlier_s=None):
        self.name = name
        self.reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._outlier_s = held_outlier_s

    def acquire(self, blocking=True, timeout=-1):
        fz = _fuzzer
        if fz is not None:
            fz.maybe_yield()
        stack = _held_stack()
        if _enabled and stack:
            with _meta_lock:
                for held_name, _t0 in stack:
                    if held_name != self.name:
                        edge = (held_name, self.name)
                        _edges[edge] = _edges.get(edge, 0) + 1
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            stack.append((self.name, time.perf_counter()))
        return ok

    def release(self):
        stack = _held_stack()
        t0 = None
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == self.name:
                t0 = stack.pop(i)[1]
                break
        self._lock.release()
        if t0 is not None and _enabled:
            dt = time.perf_counter() - t0
            threshold = (
                self._outlier_s
                if self._outlier_s is not None
                else _default_outlier_s
            )
            with _meta_lock:
                st = _held.setdefault(self.name, [0, 0.0, 0.0])
                st[0] += 1
                st[1] += dt
                st[2] = max(st[2], dt)
                if dt > threshold:
                    n = _outlier_counts.get(self.name, 0)
                    if n < _OUTLIER_CAP_PER_LOCK:
                        _outlier_counts[self.name] = n + 1
                        _outliers.append(
                            Finding(
                                f"lock:{self.name}", 1, 0,
                                "lock-held-outlier", "warning",
                                f"lock {self.name!r} held for {dt:.3f}s "
                                f"(threshold {threshold:.3f}s) — a long "
                                f"critical section starves every waiter",
                                detail={"held_s": round(dt, 6),
                                        "threshold_s": threshold},
                            )
                        )
        fz = _fuzzer
        if fz is not None:
            fz.maybe_yield()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self):
        try:
            return self._lock.locked()
        except AttributeError:  # RLock pre-3.12 has no locked()
            return False


class ScheduleFuzzer:
    """Seeded random yields at instrumented-lock boundaries.

    Each thread derives its own ``random.Random`` from ``(seed, k)``
    where ``k`` is the order the thread first hit a boundary — the
    schedule PERTURBATION is deterministic per (seed, thread-arrival
    order) even though the OS schedule underneath is not, which is
    enough to replay an interleaving class (the PR-11 MicroBatcher
    lost-request scenario) rather than one lucky schedule. Install via
    ``with ScheduleFuzzer(seed=...):`` or install()/uninstall().
    """

    def __init__(self, seed: int, p: float = 0.25, max_sleep_s: float = 1e-4):
        self.seed = int(seed)
        self.p = float(p)
        self.max_sleep_s = float(max_sleep_s)
        self._counter = itertools.count()
        self._local = threading.local()

    def _rng(self) -> random.Random:
        rng = getattr(self._local, "rng", None)
        if rng is None:
            # int mix (not a tuple: hash-based Random seeding is
            # deprecated); the odd multiplier keeps streams disjoint
            rng = self._local.rng = random.Random(
                self.seed * 1_000_003 + next(self._counter)
            )
        return rng

    def maybe_yield(self):
        rng = self._rng()
        if rng.random() < self.p:
            time.sleep(rng.random() * self.max_sleep_s)

    def install(self) -> "ScheduleFuzzer":
        global _fuzzer
        with _meta_lock:
            _fuzzer = self
        return self

    def uninstall(self):
        global _fuzzer
        with _meta_lock:
            if _fuzzer is self:
                _fuzzer = None

    def __enter__(self):
        return self.install()

    def __exit__(self, exc_type, exc, tb):
        self.uninstall()
        return False


def acquisition_edges() -> Dict[Tuple[str, str], int]:
    """Copy of the observed (held -> acquired) edge counts."""
    with _meta_lock:
        return dict(_edges)


def held_stats() -> Dict[str, dict]:
    with _meta_lock:
        return {
            name: {
                "acquires": int(st[0]),
                "total_held_s": st[1],
                "max_held_s": st[2],
            }
            for name, st in sorted(_held.items())
        }


def find_cycles() -> List[List[str]]:
    """Cycles in the acquisition graph, each a canonicalized lock-name
    path (rotated to start at its smallest name); deterministic order.
    A cycle means two code paths acquire the same locks in opposite
    orders — a deadlock waiting for the right interleaving."""
    with _meta_lock:
        edges = list(_edges)
    adj: Dict[str, Set[str]] = defaultdict(set)
    for a, b in edges:
        adj[a].add(b)

    # iterative Tarjan SCC
    index_of: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = itertools.count()

    for root in sorted(adj):
        if root in index_of:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index_of[root] = low[root] = next(counter)
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index_of:
                    index_of[w] = low[w] = next(counter)
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index_of[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index_of[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(scc)
            if work:
                u = work[-1][0]
                low[u] = min(low[u], low[v])

    cycles: List[List[str]] = []
    edge_set = set(edges)
    for scc in sccs:
        members = set(scc)
        start = min(members)
        # shortest concrete cycle back to `start` inside the SCC (BFS)
        prev = {start: None}
        frontier = [start]
        found = None
        while frontier and found is None:
            nxt = []
            for u in frontier:
                for w in sorted(adj.get(u, ())):
                    if w == start:
                        found = u
                        break
                    if w in members and w not in prev:
                        prev[w] = u
                        nxt.append(w)
                if found is not None:
                    break
            frontier = nxt
        if found is None:  # defensive: SCC guarantee says unreachable
            continue
        path = [found]
        while prev[path[-1]] is not None:
            path.append(prev[path[-1]])
        path.reverse()
        if any((a, b) not in edge_set for a, b in zip(path, path[1:])):
            continue  # defensive: BFS inside an SCC only walks real edges
        cycles.append(path)
    cycles.sort()
    return cycles


def lock_findings() -> List[Finding]:
    """Cycle + outlier findings in the shared `Finding` model."""
    findings: List[Finding] = []
    with _meta_lock:
        edge_counts = dict(_edges)
        findings.extend(_outliers)
    for cycle in find_cycles():
        loop = cycle + [cycle[0]]
        arrows = " -> ".join(loop)
        obs = sum(
            edge_counts.get((a, b), 0) for a, b in zip(loop, loop[1:])
        )
        findings.append(
            Finding(
                f"lock:{cycle[0]}", 1, 0, "lock-order-cycle", "error",
                f"lock-order cycle: {arrows} (potential deadlock; "
                f"{obs} edge observation(s)) — pick one order and fix "
                f"the inverted acquisition",
                detail={"cycle": list(cycle), "observations": obs},
            )
        )
    findings.sort(key=lambda f: (f.path, f.rule, f.message))
    return findings


def runtime_rules_meta() -> Dict[str, dict]:
    """Rule metadata for SARIF emission (same shape as `lint_rules_meta`)."""
    return {
        "lock-order-cycle": {
            "severity": "error",
            "doc": "Two threads acquired the same locks in opposite "
                   "orders during an audited run — a deadlock under the "
                   "right interleaving.",
        },
        "lock-held-outlier": {
            "severity": "warning",
            "doc": "An audited lock was held longer than its outlier "
                   "threshold; long critical sections starve waiters and "
                   "hide in p99 latency.",
        },
    }


def report() -> dict:
    """One-call summary: enabled flag, per-lock stats, edges, cycles."""
    return {
        "enabled": is_enabled(),
        "locks": held_stats(),
        "edges": {
            f"{a} -> {b}": n
            for (a, b), n in sorted(acquisition_edges().items())
        },
        "cycles": find_cycles(),
        "findings": [f.to_dict() for f in lock_findings()],
    }
