"""JAX/TPU-aware lint rules over the hazards this codebase has actually hit.

Each rule encodes a failure class from the project record (VERDICT/ADVICE
rounds 1-5) or the TPU-compilation literature (arXiv:1810.09868 catalogs
host-sync and shape-driven-recompile trace hazards; the sparse-NCNet line,
arXiv:2004.10566, the low-precision normalization fragility):

  bare-assert               contracts stripped under ``python -O``
  host-sync-in-jit          host synchronization reachable inside compiled code
  unguarded-division        ``x / reduction(..)`` without an epsilon guard
  unstable-exp              ``jnp.exp`` without max-subtraction (bf16 overflow)
  traced-python-branch      Python control flow on a traced jnp value
  mutable-default-arg       shared mutable default arguments
  non-atomic-artifact-write checkpoint/metrics artifacts written with a bare
                            ``open(path, "wb")`` (torn by preemption) instead
                            of the durable temp+fsync+rename helper
  unchecked-gather          ``jnp.take``/``take_along_axis``/``.at[...].get()``
                            without an explicit ``mode=`` (the silent clamp
                            default masks out-of-range index bugs)
  process-zero-only-io      O(state) I/O (``jax.device_get`` of param/state
                            trees, artifact writes) funneled through a
                            ``jax.process_index() == 0`` guard — the
                            single-host serialization bottleneck the sharded
                            checkpoint layout exists to remove
  recompile-hazard          ``jax.jit``/``jax.pmap`` wrappers constructed on
                            per-call paths (inside loop bodies, or
                            immediately invoked inside a function): each
                            wrapper owns a FRESH compile cache, so the
                            program retraces/recompiles every iteration —
                            the jit-cache-churn hazard the serving engine's
                            warm AOT executables exist to avoid
  wall-clock-timing         durations computed by subtracting ``time.time()``
                            readings: wall clock is not monotonic (NTP
                            steps/slews), so logged latencies can go
                            negative — use ``time.perf_counter`` (the
                            telemetry tracer's contract); wall time is for
                            TIMESTAMP fields only
  swallowed-exception       a broad ``except`` (bare/Exception/BaseException)
                            in library code that neither re-raises nor uses
                            the caught exception: the failure vanishes —
                            the anti-pattern the serving engine's typed
                            failures + stage supervision exist to prevent

All rules are intentionally conservative: a finding should mean something;
the escape hatch for justified exceptions is the mandatory-reason inline
suppression. In project runs (`lint_paths` builds a `ProjectIndex`),
`host-sync-in-jit`, `recompile-hazard` and `process-zero-only-io`
additionally follow a resolved call ONE level into its defining module —
the callee's executed body is scanned (nested defs/lambdas pruned: they run
on their own schedule), and the finding is reported at the CALLER's call
site so the suppression lives where the decision is made.
"""

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ncnet_tpu.analysis.engine import ModuleContext, rule

# --- shared helpers ---------------------------------------------------------

#: canonical prefixes that mean "this value lives on device / is traced"
_JNP_ROOTS = ("jax.numpy.", "jax.nn.", "jax.lax.", "jax.scipy.")

#: callables whose function argument is traced/compiled (the argument's body
#: runs under jit/pallas-like constraints even though the outer file doesn't
#: say ``@jax.jit`` anywhere near it)
_COMPILING_CALLS = {
    "jax.jit",
    "jax.pmap",
    "jax.checkpoint",
    "jax.remat",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.lax.map",
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.switch",
}

#: calls that force a device->host synchronization (or fail outright on a
#: tracer) when reached inside a compiled region
_HOST_SYNC_CALLS = {
    "print": "host print inside compiled code runs at trace time only (or "
             "forces a callback); use jax.debug.print",
    "float": "float() on a traced value syncs the device (or raises "
             "TracerError); keep scalars on device or sync outside jit",
    "int": "int() on a traced value syncs the device (or raises "
           "TracerError); use static shapes / sync outside jit",
    "bool": "bool() on a traced value raises TracerError (concretization); "
            "use lax.cond / jnp.where",
    "numpy.asarray": "np.asarray on a traced value forces a device->host "
                     "transfer; stay in jnp inside compiled code",
    "numpy.array": "np.array on a traced value forces a device->host "
                   "transfer; stay in jnp inside compiled code",
    "jax.device_get": "device_get inside compiled code is a host sync",
}

_HOST_SYNC_METHODS = {
    "item": ".item() is a blocking device->host sync",
    "tolist": ".tolist() is a blocking device->host sync",
    "block_until_ready": ".block_until_ready() inside compiled code is a "
                         "host sync",
}

_REDUCTION_FNS = {
    "max", "min", "sum", "prod", "mean", "std", "var", "median",
    "nansum", "nanmax", "nanmin", "logsumexp",
}
_REDUCTION_PREFIXES = ("jax.numpy.", "jax.numpy.linalg.", "jax.lax.",
                       "jax.scipy.special.", "jax.nn.")

_GUARD_CALLS = {
    "jax.numpy.maximum", "jax.numpy.clip", "jax.numpy.where",
    "jax.lax.max", "jax.lax.clamp",
}


def _func_nodes(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            yield node


def _walk_executed(fn: ast.AST) -> Iterator[ast.AST]:
    """Nodes that execute when ``fn`` is CALLED: its body, with nested
    FunctionDef/Lambda subtrees pruned (an inner def — a callback handed to
    `jax.debug.callback`, a worker target — runs on its own schedule, so
    its contents say nothing about the call itself)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _resolve_foreign_call(ctx: ModuleContext, node: ast.Call):
    """(canonical_name, FunctionInfo) when ``node`` calls a top-level
    function of ANOTHER indexed module; (name, None) otherwise. Same-module
    calls stay with the intra-module reasoning of each rule."""
    name = ctx.canonical(node.func)
    project = ctx.project
    if project is None:
        return name, None
    info = project.resolve(name)
    if info is None or os.path.abspath(info.path) == os.path.abspath(ctx.path):
        return name, None
    return name, info


def _is_jnp_call(ctx: ModuleContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = ctx.canonical(node.func)
    return bool(name) and name.startswith(_JNP_ROOTS)


def _assignments(fn: ast.AST) -> Dict[str, ast.AST]:
    """name -> last assigned expression, for simple ``name = expr`` inside
    ``fn`` (one level of expansion for the division rule)."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                out[t.id] = node.value
    return out


# --- bare-assert ------------------------------------------------------------


@rule(
    "bare-assert",
    "warning",
    doc="`assert` used for API/contract validation in non-test code is "
        "stripped under `python -O`, silently disabling the check; raise "
        "ValueError/TypeError instead (ADVICE r5, eval/inloc.py:223).",
)
def bare_assert(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    if ctx.is_test:
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assert):
            yield node, (
                "bare assert vanishes under python -O; raise "
                "ValueError/TypeError for contract checks (or suppress "
                "with a reason for debug-only invariants)"
            )


# --- host-sync-in-jit -------------------------------------------------------


def _compiled_function_names(ctx: ModuleContext) -> Tuple[Set[ast.AST], Set[str]]:
    """Roots of compiled regions in this module.

    A function body is 'compiled' when the function is (a) decorated with
    jit/pmap (directly or through functools.partial), or (b) passed as an
    argument to one of `_COMPILING_CALLS`. Reasoning is intra-module and
    name-based on purpose — cross-module call graphs would need whole-
    program analysis; conservatism keeps findings trustworthy.
    """
    roots: Set[ast.AST] = set()
    root_names: Set[str] = set()

    def is_compiling_name(expr: ast.AST) -> bool:
        name = ctx.canonical(expr)
        if name in _COMPILING_CALLS:
            return True
        # functools.partial(jax.jit, ...) / partial(jax.checkpoint, ...)
        if isinstance(expr, ast.Call) and ctx.canonical(expr.func) in (
            "functools.partial", "partial"
        ):
            return bool(expr.args) and is_compiling_name(expr.args[0])
        return False

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if is_compiling_name(target) or is_compiling_name(dec):
                    roots.add(node)
                    root_names.add(node.name)
        if isinstance(node, ast.Call) and is_compiling_name(node.func):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    roots.add(arg)
                elif isinstance(arg, ast.Name):
                    root_names.add(arg.id)
    return roots, root_names


def _host_sync_message(ctx: ModuleContext, node: ast.Call) -> Optional[str]:
    """Why this single call is a host sync, or None. ``ctx`` must be the
    module the call is WRITTEN in (its aliases decide canonicalization)."""
    name = ctx.canonical(node.func)
    if name in _HOST_SYNC_CALLS:
        return _HOST_SYNC_CALLS[name]
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr in _HOST_SYNC_METHODS
    ):
        # method call on a VALUE (x.item()), not a module function
        # (some.module.item would resolve through an import alias)
        root = node.func.value
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name) and root.id in ctx.aliases:
            return None
        return _HOST_SYNC_METHODS[node.func.attr]
    return None


@rule(
    "host-sync-in-jit",
    "warning",
    doc="Host-synchronizing calls (print/float/int/bool/np.asarray/.item/"
        ".tolist) reachable inside jit/shard_map/lax-control-flow bodies "
        "either fail on tracers or stall the device pipeline "
        "(arXiv:1810.09868's host-sync trace hazard). Project runs also "
        "follow calls one level into other modules: a compiled body "
        "calling a helper whose executed body syncs is reported at the "
        "call site.",
)
def host_sync_in_jit(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    roots, root_names = _compiled_function_names(ctx)

    # module-local def table + intra-module call graph over function names
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    calls: Dict[str, Set[str]] = {}
    for name, fn in defs.items():
        called: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                if node.func.id in defs:
                    called.add(node.func.id)
        calls[name] = called

    # propagate compiled-ness through local calls to a fixed point
    compiled: Set[str] = {n for n in root_names if n in defs}
    frontier = list(compiled)
    while frontier:
        fn_name = frontier.pop()
        for callee in calls.get(fn_name, ()):
            if callee not in compiled:
                compiled.add(callee)
                frontier.append(callee)

    bodies = list(roots) + [defs[n] for n in compiled]
    seen: Set[int] = set()
    for body in bodies:
        for node in ast.walk(body):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            msg = _host_sync_message(ctx, node)
            if msg is not None:
                yield node, f"{msg} (inside a compiled region)"
                continue
            # interprocedural step: a compiled body calling a top-level
            # function of ANOTHER module whose executed body syncs. The
            # concretization builtins (int/float/bool) are excluded here:
            # one call away from the trace they are overwhelmingly static
            # shape/config casts, and flagging them would bury the
            # high-signal syncs (.item/.tolist/device_get/np.asarray).
            callee_name, info = _resolve_foreign_call(ctx, node)
            if info is None:
                continue
            for sub in _walk_executed(info.node):
                if not isinstance(sub, ast.Call):
                    continue
                if info.ctx.canonical(sub.func) in ("int", "float", "bool"):
                    continue
                callee_msg = _host_sync_message(info.ctx, sub)
                if callee_msg is not None:
                    yield node, (
                        f"call to {callee_name} (defined at "
                        f"{os.path.basename(info.path)}:{sub.lineno}) "
                        f"reaches a host sync inside this compiled region: "
                        f"{callee_msg}"
                    )
                    break


# --- unguarded-division -----------------------------------------------------


def _contains_reduction(ctx: ModuleContext, expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = ctx.canonical(node.func)
            if not name:
                continue
            if name in _GUARD_CALLS:
                continue
            if any(name.startswith(p) for p in _REDUCTION_PREFIXES) and (
                name.rsplit(".", 1)[-1] in _REDUCTION_FNS
            ):
                return True
    return False


def _is_guarded(ctx: ModuleContext, expr: ast.AST) -> bool:
    """True when the denominator carries an epsilon guard somewhere: an
    added positive constant, a name containing 'eps', or a flooring call
    (maximum/clip/where)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            for side in (node.left, node.right):
                if isinstance(side, ast.Constant) and isinstance(
                    side.value, (int, float)
                ) and side.value > 0:
                    return True
                if isinstance(side, ast.Name) and "eps" in side.id.lower():
                    return True
                if (
                    isinstance(side, ast.Attribute)
                    and "eps" in side.attr.lower()
                ):
                    return True
        if isinstance(node, ast.Call):
            if ctx.canonical(node.func) in _GUARD_CALLS:
                return True
        if isinstance(node, ast.Name) and "eps" in node.id.lower():
            return True
    return False


@rule(
    "unguarded-division",
    "warning",
    doc="Division whose denominator is a jnp reduction (max/sum/norm/...) "
        "with no epsilon guard: an all-zero slice yields inf/NaN, and bf16 "
        "makes exact zeros more likely (the `corr/(max+eps)` hazard class "
        "of the mutual-matching ratios).",
)
def unguarded_division(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    seen: Set[int] = set()  # functions nest; report each division once
    for fn in list(_func_nodes(ctx.tree)) + [ctx.tree]:
        local = _assignments(fn) if not isinstance(fn, ast.Module) else {}

        def expand(expr: ast.AST) -> ast.AST:
            if isinstance(expr, ast.Name) and expr.id in local:
                return local[expr.id]
            return expr

        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div)
            ):
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            denom = node.right
            # one level of name expansion: `m = jnp.max(x); y = x / m`
            candidates = [denom, expand(denom)]
            if isinstance(denom, ast.BinOp):
                candidates += [expand(denom.left), expand(denom.right)]
            if not any(_contains_reduction(ctx, c) for c in candidates):
                continue
            if any(_is_guarded(ctx, c) for c in candidates):
                continue
            yield node, (
                "division by a reduction without an epsilon guard; an "
                "all-zero (or bf16-flushed) slice produces inf/NaN — add "
                "`+ eps` or clamp with jnp.maximum"
            )


# --- unstable-exp -----------------------------------------------------------


@rule(
    "unstable-exp",
    "warning",
    doc="`jnp.exp` whose argument is not max-subtracted overflows for "
        "logits >= ~89 (both bf16 and f32 share the 8-bit exponent); use "
        "jax.nn.softmax / logsumexp or subtract the max first.",
)
def unstable_exp(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    def has_max_subtraction(expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                for sub in ast.walk(node.right):
                    if isinstance(sub, ast.Call):
                        name = ctx.canonical(sub.func) or ""
                        if name.rsplit(".", 1)[-1] in ("max", "stop_gradient",
                                                       "logsumexp"):
                            return True
            if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
                return True  # exp(-x): decaying direction, no overflow
        return False

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.canonical(node.func)
        if name not in ("jax.numpy.exp", "jax.numpy.exp2"):
            continue
        if node.args and has_max_subtraction(node.args[0]):
            continue
        yield node, (
            "exp without max-subtraction: overflows to inf at ~89 for "
            "softmax-style logits (the 625-cell softmax hazard); use "
            "jax.nn.softmax/logsumexp or subtract jnp.max first"
        )


# --- traced-python-branch ---------------------------------------------------


@rule(
    "traced-python-branch",
    "warning",
    doc="Python `if`/`while` on the result of a jnp call: under jit this "
        "raises TracerBoolConversionError, and outside jit it hides a "
        "host sync and bakes data-dependent control flow into retraces "
        "(shape/value-driven recompilation, arXiv:1810.09868). Use "
        "jnp.where / lax.cond / lax.while_loop.",
)
def traced_python_branch(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    _META_ATTRS = ("dtype", "shape", "ndim", "size")
    _META_FNS = ("result_type", "issubdtype", "iinfo", "finfo", "dtype")

    def traced_calls(node):
        """jnp calls in the subtree, pruning static-metadata access: the
        value of ``jnp.asarray(x).dtype`` (or .shape/.ndim/.size) is known
        at trace time, so branching on it is legal and common."""
        if isinstance(node, ast.Attribute) and node.attr in _META_ATTRS:
            return
        if _is_jnp_call(ctx, node):
            name = ctx.canonical(node.func)
            if name.rsplit(".", 1)[-1] not in _META_FNS:
                yield name
            return  # a traced call's arguments need no separate report
        for child in ast.iter_child_nodes(node):
            yield from traced_calls(child)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
            continue
        for name in traced_calls(node.test):
            yield node, (
                f"Python control flow on `{name}(...)`: traced values "
                "cannot drive `if`/`while` under jit (and force a host "
                "sync outside it); use jnp.where or lax.cond"
            )
            break


# --- non-atomic-artifact-write ----------------------------------------------

#: substrings that mark a write target as a resume/metrics artifact — the
#: class of file whose torn-write loses a training run, not just an output
_ARTIFACT_HINTS = (
    "checkpoint", "ckpt", "metrics", "msgpack", "weights", "model_best",
    "best_",
)


@rule(
    "non-atomic-artifact-write",
    "warning",
    doc="A checkpoint/metrics artifact written with a bare `open(path, "
        "\"wb\")` is torn by a preemption landing mid-write — the resume "
        "point is lost. Route it through "
        "`ncnet_tpu.resilience.durable.durable_write_bytes` "
        "(temp + fsync + atomic rename + sidecar digest).",
)
def non_atomic_artifact_write(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    if ctx.is_test:
        return
    # artifact-ness is judged from the names in scope: string constants and
    # identifiers in the path expression, plus enclosing function names —
    # conservative on purpose (a PNG/tmp-file writer should not be flagged)
    parents: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or ctx.canonical(node.func) != "open":
            continue
        mode = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if mode != "wb":
            continue
        hay: List[str] = []
        if node.args:
            for sub in ast.walk(node.args[0]):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    hay.append(sub.value)
                elif isinstance(sub, ast.Name):
                    hay.append(sub.id)
                elif isinstance(sub, ast.Attribute):
                    hay.append(sub.attr)
        p: ast.AST = node
        while p in parents:
            p = parents[p]
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                hay.append(p.name)
        text = " ".join(hay).lower()
        if any(h in text for h in _ARTIFACT_HINTS):
            yield node, (
                "non-atomic binary write of a resume-critical artifact: a "
                "kill mid-write tears the file; use resilience.durable."
                "durable_write_bytes (temp + fsync + rename + digest)"
            )


# --- unchecked-gather -------------------------------------------------------

#: jnp gather entry points whose ``mode`` argument selects the out-of-bounds
#: semantics (None defaults to silent clamping under jit)
_GATHER_CALLS = {
    "jax.numpy.take",
    "jax.numpy.take_along_axis",
}


@rule(
    "unchecked-gather",
    "warning",
    doc="`jnp.take`/`jnp.take_along_axis`/`x.at[...].get()` without an "
        "explicit `mode=`: under jit, out-of-bounds indices are silently "
        "CLAMPED to the edge — a wrong band/gather index reads a plausible "
        "value instead of failing, masking the bug (the sparse-band "
        "pointer-table hazard class). Pass mode= ('fill' / 'clip' / "
        "'promise_in_bounds') chosen on purpose.",
)
def unchecked_gather(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    def has_mode(call: ast.Call) -> bool:
        return any(kw.arg == "mode" for kw in call.keywords)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.canonical(node.func)
        if name in _GATHER_CALLS:
            if not has_mode(node):
                short = name.rsplit(".", 1)[-1]
                yield node, (
                    f"jnp.{short} without an explicit mode=: out-of-bounds "
                    "indices clamp silently under jit, masking index bugs; "
                    "state the intended semantics ('fill', 'clip', or "
                    "'promise_in_bounds')"
                )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Subscript)
            and isinstance(node.func.value.value, ast.Attribute)
            and node.func.value.value.attr == "at"
        ):
            # x.at[...].get(...) — the indexed-read form of the same gather
            if not has_mode(node):
                yield node, (
                    ".at[...].get() without an explicit mode=: "
                    "out-of-bounds indices clamp silently under jit, "
                    "masking index bugs; state the intended semantics "
                    "('fill', 'clip', or 'promise_in_bounds')"
                )


# --- process-zero-only-io ---------------------------------------------------

#: argument-name substrings that mark a device_get target as O(state) — a
#: whole parameter/optimizer tree, not a scalar metric
_STATE_HINTS = ("param", "opt_state", "state", "weights", "grads", "tree")


def _is_process_zero_test(ctx: ModuleContext, test: ast.AST):
    """Classify a guard expression: returns ``"eq"`` when it contains a
    ``jax.process_index() == 0`` comparison (the body is process-0-only),
    ``"ne"`` for ``jax.process_index() != 0`` (an early-exit guard: the
    FOLLOWING statements are process-0-only), else None. The comparison is
    found anywhere inside the test (``if flag and process_index() != 0:``
    still gates the legacy path on process 0)."""
    for node in ast.walk(test):
        if not (
            isinstance(node, ast.Compare)
            and len(node.ops) == 1
            and len(node.comparators) == 1
        ):
            continue
        sides = (node.left, node.comparators[0])
        has_zero = any(
            isinstance(s, ast.Constant) and s.value == 0 for s in sides
        )
        has_pidx = any(
            isinstance(s, ast.Call)
            and ctx.canonical(s.func)
            in ("jax.process_index", "jax.distributed.process_index")
            for s in sides
        )
        if not (has_zero and has_pidx):
            continue
        if isinstance(node.ops[0], ast.Eq):
            return "eq"
        if isinstance(node.ops[0], ast.NotEq):
            return "ne"
    return None


def _exits_scope(stmt: ast.AST) -> bool:
    body = getattr(stmt, "body", None) or []
    return any(
        isinstance(s, (ast.Return, ast.Continue, ast.Break, ast.Raise))
        for s in body
    )


def _open_mode(node: ast.Call) -> Optional[str]:
    mode = None
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return mode


def _is_o_state_device_get(ctx: ModuleContext, node: ast.Call) -> bool:
    """``jax.device_get`` whose argument names look like a whole
    parameter/optimizer tree (not a scalar metric)."""
    if ctx.canonical(node.func) != "jax.device_get":
        return False
    hay = []
    for arg in node.args:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name):
                hay.append(sub.id)
            elif isinstance(sub, ast.Attribute):
                hay.append(sub.attr)
    text = " ".join(hay).lower()
    return any(h in text for h in _STATE_HINTS)


def _is_artifact_wb_open(ctx: ModuleContext, node: ast.Call) -> bool:
    """Bare ``open(path, "wb")`` whose path expression smells like a
    resume-critical artifact (checkpoint/metrics/weights)."""
    if ctx.canonical(node.func) != "open" or _open_mode(node) != "wb":
        return False
    hay: List[str] = []
    if node.args:
        for sub in ast.walk(node.args[0]):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                hay.append(sub.value)
            elif isinstance(sub, ast.Name):
                hay.append(sub.id)
            elif isinstance(sub, ast.Attribute):
                hay.append(sub.attr)
    text = " ".join(hay).lower()
    return any(h in text for h in _ARTIFACT_HINTS)


@rule(
    "process-zero-only-io",
    "warning",
    doc="O(state) I/O funneled through a `jax.process_index() == 0` guard "
        "(`jax.device_get` of a param/opt_state tree, or a binary artifact "
        "write): at pod scale one host serializes ALL state over DCN and "
        "becomes the sole preemption window. Use the per-host sharded "
        "layout (resilience.distributed / --distributed-checkpoints) where "
        "every process writes only its own shards; suppress with a reason "
        "where a legacy single-file path is kept deliberately.",
)
def process_zero_only_io(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    if ctx.is_test:
        return
    parts = os.path.normpath(ctx.path).split(os.sep)
    if "resilience" in parts:
        return  # the package that IMPLEMENTS the discipline is exempt

    # collect every statement that executes under process-0-only control:
    # bodies of `== 0` ifs, and the statements FOLLOWING a `!= 0` early exit
    guarded: List[ast.stmt] = []
    for node in ast.walk(ctx.tree):
        body = getattr(node, "body", None)
        if not isinstance(body, list):
            continue
        for i, stmt in enumerate(body):
            if not isinstance(stmt, ast.If):
                continue
            kind = _is_process_zero_test(ctx, stmt.test)
            if kind == "eq":
                guarded.extend(stmt.body)
            elif kind == "ne" and _exits_scope(stmt):
                guarded.extend(body[i + 1:])

    seen: Set[int] = set()
    for region in guarded:
        for node in ast.walk(region):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            if _is_o_state_device_get(ctx, node):
                yield node, (
                    "O(state) jax.device_get behind a process-0 guard: "
                    "one host gathers the full tree over DCN; write "
                    "per-host shards instead (resilience.distributed / "
                    "--distributed-checkpoints)"
                )
                continue
            if _is_artifact_wb_open(ctx, node):
                yield node, (
                    "binary artifact write behind a process-0 guard: "
                    "the whole save funnels through one host; use the "
                    "per-host sharded layout (resilience.distributed)"
                )
                continue
            # interprocedural step: the guarded region calling a function
            # of ANOTHER module whose executed body does the O(state) I/O
            callee_name, info = _resolve_foreign_call(ctx, node)
            if info is None or info.ctx.is_test:
                continue
            if "resilience" in os.path.normpath(info.path).split(os.sep):
                continue  # callee implements the sharded discipline
            for sub in _walk_executed(info.node):
                if not isinstance(sub, ast.Call):
                    continue
                if _is_o_state_device_get(info.ctx, sub) or \
                        _is_artifact_wb_open(info.ctx, sub):
                    yield node, (
                        f"call to {callee_name} (defined at "
                        f"{os.path.basename(info.path)}:{sub.lineno}) does "
                        "O(state) I/O behind this process-0 guard: one "
                        "host funnels the full state; use the per-host "
                        "sharded layout (resilience.distributed)"
                    )
                    break


# --- recompile-hazard -------------------------------------------------------

#: wrapper constructors whose RESULT owns the compile cache — building one
#: per call/iteration throws that cache away every time
_JIT_CONSTRUCTORS = ("jax.jit", "jax.pmap")

_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While)
_COMPREHENSION_NODES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_FUNC_BOUNDARY = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@rule(
    "recompile-hazard",
    "warning",
    doc="`jax.jit(...)`/`jax.pmap(...)` constructed on a per-call path — "
        "inside a loop body, or immediately invoked (`jax.jit(f)(x)`) "
        "inside a function: every jit() call returns a wrapper with its "
        "OWN empty compile cache, so the program retraces and recompiles "
        "on each iteration/call (jit-cache churn; the shape-driven "
        "recompile hazard of arXiv:1810.09868, and exactly what "
        "ncnet_tpu.serve's warm AOT executables exist to prevent). Hoist "
        "the jit to module scope, a factory return, or a one-time "
        "assignment; for deliberate per-shape compiles (benchmark sweeps) "
        "suppress with a reason. Project runs also flag a loop-body call "
        "to a FACTORY in another module whose executed body constructs "
        "jit/pmap (e.g. `make_train_step` called per iteration).",
)
def recompile_hazard(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    if ctx.is_test:
        return
    parents: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent

    def in_loop(node: ast.AST) -> bool:
        """Lexically inside a loop/comprehension body WITHOUT crossing a
        function boundary (a def nested in a loop runs on its own
        schedule; a factory called in a loop is the caller's finding)."""
        p = parents.get(node)
        while p is not None:
            if isinstance(p, _FUNC_BOUNDARY):
                return False
            if isinstance(p, _LOOP_NODES + _COMPREHENSION_NODES):
                return True
            p = parents.get(p)
        return False

    def in_function(node: ast.AST) -> bool:
        p = parents.get(node)
        while p is not None:
            if isinstance(p, _FUNC_BOUNDARY):
                return True
            p = parents.get(p)
        return False

    def jit_construction_in(info) -> Optional[ast.AST]:
        """First jit/pmap construction in a callee's EXECUTED body (nested
        defs pruned: `jax.jit(step_fn)` at the factory's own level counts,
        a jit inside a function the factory merely defines does not)."""
        for sub in _walk_executed(info.node):
            if isinstance(sub, ast.Call) and (
                info.ctx.canonical(sub.func) in _JIT_CONSTRUCTORS
            ):
                return sub
        return None

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = ctx.canonical(node.func)
        if name not in _JIT_CONSTRUCTORS:
            # interprocedural step: a loop body calling a foreign factory
            # that constructs its own jit/pmap wrapper each call
            if in_loop(node):
                callee_name, info = _resolve_foreign_call(ctx, node)
                if info is not None and not info.ctx.is_test:
                    site = jit_construction_in(info)
                    if site is not None:
                        yield node, (
                            f"{callee_name} (defined at "
                            f"{os.path.basename(info.path)}:{site.lineno}) "
                            "constructs a jit/pmap wrapper, and this call "
                            "sits inside a loop body: every iteration gets "
                            "a fresh compile cache and retraces; hoist the "
                            "factory call out of the loop (or suppress "
                            "with a reason for deliberate per-shape "
                            "compile sweeps)"
                        )
            continue
        short = name.rsplit(".", 1)[-1]
        parent = parents.get(node)
        immediately_invoked = (
            isinstance(parent, ast.Call) and parent.func is node
        )
        if in_loop(node):
            yield node, (
                f"jax.{short}(...) constructed inside a loop body: each "
                "iteration builds a wrapper with a fresh compile cache and "
                "retraces from scratch; hoist the wrapper out of the loop "
                "(or suppress with a reason for deliberate per-shape "
                "compile sweeps)"
            )
        elif immediately_invoked and in_function(node):
            yield node, (
                f"jax.{short}(f)(...) immediately invoked inside a "
                "function: the wrapper (and its compile cache) is thrown "
                "away after one call, so every call retraces and "
                "recompiles; bind the jitted fn once (module scope, "
                "factory, or a local reused across calls)"
            )


# --- mutable-default-arg ----------------------------------------------------


@rule(
    "mutable-default-arg",
    "warning",
    doc="Mutable default argument ([]/{}//set()): shared across calls, a "
        "classic aliasing bug; default to None and create inside.",
)
def mutable_default_arg(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    for fn in _func_nodes(ctx.tree):
        if isinstance(fn, ast.Lambda):
            args = fn.args
        else:
            args = fn.args
        for default in list(args.defaults) + [
            d for d in args.kw_defaults if d is not None
        ]:
            bad = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
                and not default.args
                and not default.keywords
            )
            if bad:
                yield default, (
                    "mutable default argument is shared across calls; "
                    "default to None and construct inside the function"
                )


# --- wall-clock-timing ------------------------------------------------------


def _is_wall_clock_call(ctx: ModuleContext, expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Call)
        and ctx.canonical(expr.func) == "time.time"
    )


@rule(
    "wall-clock-timing",
    "warning",
    doc="Duration computed by subtracting `time.time()` readings: the wall "
        "clock is not monotonic — an NTP step between the two reads "
        "produces a negative or wildly wrong latency that then lands in "
        "logs and percentile reports. Use `time.perf_counter()` (the "
        "`ncnet_tpu.telemetry` tracer's clock contract). `time.time()` is "
        "for TIMESTAMP fields (epoch anchors, event `ts`), never a "
        "duration operand; genuine wall-time arithmetic gets a reasoned "
        "suppression.",
)
def wall_clock_timing(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    if ctx.is_test:
        return
    # one `seen` across scopes: the module walk revisits every function
    # body, and a BinOp must report once no matter which scope finds it
    seen: Set[ast.AST] = set()
    for fn in list(_func_nodes(ctx.tree)) + [ctx.tree]:
        names = _assignments(fn)

        def expand(expr: ast.AST) -> ast.AST:
            # one level of `t0 = time.time()` name expansion, the same
            # conservatism as unguarded-division
            if isinstance(expr, ast.Name) and expr.id in names:
                return names[expr.id]
            return expr

        for node in ast.walk(fn):
            if (
                not isinstance(node, ast.BinOp)
                or not isinstance(node.op, ast.Sub)
                or node in seen
            ):
                continue
            seen.add(node)
            if _is_wall_clock_call(ctx, expand(node.left)) or \
                    _is_wall_clock_call(ctx, expand(node.right)):
                yield node, (
                    "duration from time.time() subtraction: wall clock "
                    "is not monotonic (NTP steps make latencies negative); "
                    "time with time.perf_counter(), keep time.time() for "
                    "timestamp fields only"
                )


# --- swallowed-exception ----------------------------------------------------


_BROAD_EXC_NAMES = ("Exception", "BaseException")


def _is_broad_handler(ctx: ModuleContext, handler: ast.ExceptHandler) -> bool:
    """Bare ``except:``, ``except Exception``/``BaseException``, or a tuple
    containing either — the handlers wide enough to eat bugs."""
    if handler.type is None:
        return True
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        name = ctx.canonical(t) or ""
        if name in _BROAD_EXC_NAMES or name.startswith("builtins."):
            if name.rsplit(".", 1)[-1] in _BROAD_EXC_NAMES:
                return True
    return False


@rule(
    "swallowed-exception",
    "warning",
    doc="A broad `except` (bare / Exception / BaseException) that neither "
        "re-raises nor uses the caught exception: the failure vanishes — "
        "no typed error on a future, no log line, no counter — which is "
        "exactly how a resilience path rots into decoration (the serving "
        "engine's stage supervisors exist because swallowed worker "
        "exceptions silently shrink the pool). Handle it (route the "
        "exception somewhere: a typed failure, a log, a metric), narrow "
        "the except, or re-raise; a deliberate capability probe or "
        "best-effort fallback gets a reasoned suppression.",
)
def swallowed_exception(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    if ctx.is_test:
        return  # tests legitimately assert "does not raise"
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad_handler(ctx, node):
            continue
        handled = False
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Raise):
                    handled = True  # re-raises (possibly conditionally)
                elif (
                    node.name is not None
                    and isinstance(sub, ast.Name)
                    and sub.id == node.name
                ):
                    handled = True  # the exception is routed somewhere
            if handled:
                break
        if not handled:
            what = (
                "bare except" if node.type is None
                else f"except {ast.unparse(node.type)}"
            )
            yield node, (
                f"{what} swallows the exception: nothing re-raises and "
                "the caught error is never used, so the failure "
                "disappears without a trace; narrow the handler, "
                "re-raise, or route the exception (typed error / log / "
                "metric) — deliberate best-effort probes need a "
                "reasoned suppression"
            )


# --- concurrency rules (fourth audit level) ---------------------------------
# Importing registers `unguarded-shared-state`, `lock-order-annotation`
# and `unjoined-thread`; the module also carries the runtime OrderedLock
# prong (see its docstring). Kept at the bottom: concurrency.py imports
# helpers from THIS module lazily inside its rule bodies.
from ncnet_tpu.analysis import concurrency  # noqa: E402,F401
