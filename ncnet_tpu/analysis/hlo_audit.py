"""HLO-level audit pass: inspect the COMPILED modules of the registered
entry programs.

The jaxpr pass (`jaxpr_audit`) sees the program XLA is asked to compile;
this pass sees what XLA actually made of it — the answer to "where does
the remaining utilization go" that no jaxpr walk can give:

  fusion-fragmentation   the entry computation launches many kernels per
                         contraction: the NC stack lowering as a long
                         chain of small fusions with HBM round-trips
                         between them is exactly the MFU plateau's
                         signature
  layout-churn           transpose/copy ops surviving in the ENTRY
                         computation (not fused into a consumer): each
                         one is a full HBM round-trip that moves bytes
                         without computing anything
  memory-highwater       a linear-scan buffer-liveness estimate over the
                         traced jaxpr exceeds the program's budget:
                         catches residual-stacking / gather-inflation
                         regressions long before an OOM on hardware

Statistics come from two sources, both recorded in the report row:

  * the optimized HLO text (``jit(f).lower(args).compile().as_text()``):
    an opcode census of the ENTRY computation — ops inside fusion bodies
    are NOT counted as launches (a fused transpose is a register
    relayout, a top-level one is an HBM round-trip);
  * a buffer-liveness walk over the traced jaxpr: allocate at the
    defining equation, free after the last use, carry sub-jaxpr peaks as
    transients. An ESTIMATE — XLA's buffer assignment aliases donated
    inputs and reuses dead buffers, so the walk upper-bounds the
    un-aliased live set rather than reproducing XLA's number (the
    compiled module's own ``temp_size_in_bytes`` rides along in the
    report for cross-reference).

Budgets are regression tripwires, not absolute judgments: set from the
measured seed values with ~3x headroom so the gate stays at zero
findings until a change actually regresses the lowering.
"""

import dataclasses
import re
import time
from collections import Counter
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ncnet_tpu.analysis.findings import SEVERITY_ORDER, Finding
from ncnet_tpu.analysis.jaxpr_audit import (
    BuiltProgram,
    TracedProgram,
    _aval_bytes,
    _iter_sub_jaxprs,
    _leaf_bytes,
    iter_eqns,
)

# --- budgets (module-level so the golden tests can monkeypatch them) ---------

#: entry-computation kernel launches per jaxpr contraction before
#: fusion-fragmentation fires. Calibration (CPU, audit geometry, PR-18
#: program table): serve/eval 6.8-7.4, corr/stream 5.6, train/dense
#: 10.2, train/sparse 11.5, train/refine 12.2, train/sparse-stream 13.1
#: — and corr/dense 20.0, the new worst: a deliberately selection-heavy
#: single-GEMM program (one correlation einsum + mutual ranking + top-K)
#: whose launches/contraction is high BY DESIGN, not by fragmentation.
#: The budget keeps the historical 36 rather than loosening; the
#: effective headroom tightened from ~3x (old worst 11.7) to ~1.8x.
FRAGMENTATION_OPS_PER_CONTRACTION = 36.0

#: minimum entry-computation size for the fragmentation ratio to be
#: meaningful (tiny programs divide by almost nothing)
FRAGMENTATION_MIN_OPS = 24

#: un-fused transpose+copy ops tolerated in the entry computation before
#: layout-churn fires: max(MIN_OPS, FRACTION * entry ops). Calibration
#: (PR-18 table): dense programs 0-3 churn ops, the sparse band's
#: scatter/gather lowering 23-25 of ~395 entry ops (6.4%), and
#: train/sparse-stream — whose scan-carried merge adds tile
#: re-layouts — 35 of 473 (7.4%, the worst). The fraction budget stays
#: 0.15, ~2x the worst measured; MIN_OPS only shields tiny programs.
LAYOUT_CHURN_MIN_OPS = 24
LAYOUT_CHURN_FRACTION = 0.15

#: liveness-estimate budget: max(ABS floor, RATIO * program input bytes).
#: Calibration (PR-18 table) peak/input ratios: dense 1.02-1.08,
#: train/dense-bf16 1.45, train/sparse and train/sparse-stream 1.70
#: worst among ratio-governed programs — RATIO tightened 6.0 -> 4.0
#: (~2.3x the worst) now that the streamed band proves selection can
#: run without volume-sized transients. The floor shields small-input
#: programs (localize/ransac 37x on 6 KiB of inputs; corr/stream 3.3x)
#: — and corr/dense, the streaming memory BASELINE, sits at 3.5 MiB,
#: deliberately just 1.14x under it: the dense volume is the cost the
#: stream program exists to avoid, and if it grows past the floor the
#: audit should say so rather than have the floor chase it.
MEM_HIGHWATER_ABS_FLOOR = 4 * 1024 * 1024
MEM_HIGHWATER_INPUT_RATIO = 4.0

#: opcodes that are bookkeeping, not kernel launches
_FREE_OPCODES = frozenset(
    {"parameter", "constant", "tuple", "get-tuple-element", "bitcast"}
)
_CONTRACTION_PRIMS = ("dot_general", "conv_general_dilated")


# --- compiled-module model ---------------------------------------------------


@dataclasses.dataclass
class HloProgram:
    """One entry program compiled to an optimized HLO module."""

    name: str
    built: BuiltProgram
    entry_ops: Dict[str, int]  # opcode -> count, ENTRY computation only
    contractions: int  # dot/conv eqns in the traced jaxpr (scan-multiplied)
    peak_bytes_est: int  # jaxpr buffer-liveness highwater
    bytes_in: int
    hlo_temp_bytes: Optional[int]  # XLA's own temp allocation, if exposed
    compile_seconds: float = 0.0

    @property
    def entry_total(self) -> int:
        return sum(self.entry_ops.values())

    @property
    def entry_launches(self) -> int:
        return sum(
            n for op, n in self.entry_ops.items() if op not in _FREE_OPCODES
        )

    @property
    def fusions(self) -> int:
        return self.entry_ops.get("fusion", 0)

    @property
    def churn_ops(self) -> int:
        return self.entry_ops.get("transpose", 0) + self.entry_ops.get(
            "copy", 0
        )


_OPCODE_RE = re.compile(r"=\s+(?:\([^)]*\)|\S+)\s+([\w-]+)\(")


def parse_entry_opcodes(hlo_text: str) -> Dict[str, int]:
    """Opcode census of the ENTRY computation of an HLO module dump.

    Nested (fusion-body) computations are excluded: an op inside a
    fusion is part of one launch, not a launch of its own.
    """
    m = re.search(r"^ENTRY ", hlo_text, re.M)
    if not m:
        raise ValueError("no ENTRY computation in HLO text")
    entry = hlo_text[m.start():]
    end = entry.find("\n}")
    if end != -1:
        entry = entry[: end + 2]
    return dict(Counter(_OPCODE_RE.findall(entry)))


def _sub_jaxpr_input_bytes(jaxpr) -> int:
    return sum(
        _aval_bytes(v.aval)
        for v in list(jaxpr.invars) + list(jaxpr.constvars)
        if hasattr(getattr(v, "aval", None), "dtype")
    )


def jaxpr_memory_highwater(jaxpr) -> int:
    """Linear-scan buffer-liveness estimate of peak live bytes.

    Allocate every equation's outputs at its program point, free each
    value after its last use (program outputs live to the end), and
    carry each sub-jaxpr's own peak (minus its inputs, which alias the
    caller's live buffers) as a transient at the calling equation. No
    donation/aliasing model — this upper-bounds XLA's assignment; use it
    for RELATIVE regression tracking, not absolute HBM sizing.
    """
    from jax.core import Literal

    def var_ok(v):
        return not isinstance(v, Literal) and hasattr(
            getattr(v, "aval", None), "dtype"
        )

    last_use: Dict[Any, int] = {}
    for i, e in enumerate(jaxpr.eqns):
        for v in e.invars:
            if var_ok(v):
                last_use[v] = i
    n = len(jaxpr.eqns)
    for v in jaxpr.outvars:
        if var_ok(v):
            last_use[v] = n

    alloc: Dict[Any, int] = {}
    live = 0
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if var_ok(v) and v not in alloc:
            alloc[v] = _aval_bytes(v.aval)
            live += alloc[v]
    peak = live
    for i, e in enumerate(jaxpr.eqns):
        sub_extra = 0
        for val in e.params.values():
            for sub in _iter_sub_jaxprs(val):
                sub_extra = max(
                    sub_extra,
                    jaxpr_memory_highwater(sub)
                    - _sub_jaxpr_input_bytes(sub),
                )
        out_bytes = 0
        for v in e.outvars:
            if var_ok(v) and v not in alloc:
                b = _aval_bytes(v.aval)
                alloc[v] = b
                out_bytes += b
        live += out_bytes
        peak = max(peak, live + max(sub_extra, 0))
        for v in list(e.invars) + list(e.outvars):
            if var_ok(v) and v in alloc and last_use.get(v, i) <= i:
                live -= alloc.pop(v)
    return peak


def compile_program(name: str, built: BuiltProgram,
                    traced: TracedProgram) -> HloProgram:
    """Compile ``built.fn`` and collect the HLO/memory statistics."""
    t0 = time.perf_counter()
    compiled = built.fn.lower(*built.args).compile()
    dt = time.perf_counter() - t0
    entry_ops = parse_entry_opcodes(compiled.as_text())
    temp = None
    try:
        stats = compiled.memory_analysis()
        if stats is not None:
            temp = int(stats.temp_size_in_bytes)
    except Exception:  # nclint: disable=swallowed-exception -- capability probe: some backends have no memory_analysis(); hlo_temp_bytes stays None and the liveness estimate still gates
        pass
    contractions = sum(
        m
        for e, m in iter_eqns(traced.jaxpr)
        if e.primitive.name in _CONTRACTION_PRIMS
    )
    bytes_in = sum(
        _leaf_bytes(leaf)
        for leaves in traced.arg_leaves
        for leaf in leaves
    )
    return HloProgram(
        name=name,
        built=built,
        entry_ops=entry_ops,
        contractions=int(contractions),
        peak_bytes_est=jaxpr_memory_highwater(traced.jaxpr),
        bytes_in=bytes_in,
        hlo_temp_bytes=temp,
        compile_seconds=dt,
    )


# --- HLO rule registry -------------------------------------------------------

HloRuleFn = Callable[[HloProgram], Iterator[Tuple[str, Optional[dict]]]]

HLO_RULES: Dict[str, "HloRule"] = {}


@dataclasses.dataclass(frozen=True)
class HloRule:
    rule_id: str
    severity: str
    doc: str
    fn: HloRuleFn


def hlo_rule(rule_id: str, severity: str = "warning", doc: str = ""):
    if severity not in SEVERITY_ORDER:
        raise ValueError(f"unknown severity {severity!r}")

    def wrap(fn: HloRuleFn) -> HloRuleFn:
        if rule_id in HLO_RULES:
            raise ValueError(f"duplicate hlo rule id {rule_id!r}")
        HLO_RULES[rule_id] = HloRule(
            rule_id, severity, doc or (fn.__doc__ or ""), fn
        )
        return fn

    return wrap


@hlo_rule(
    "fusion-fragmentation",
    "warning",
    doc="The entry computation launches many kernels per contraction: "
        "the program lowered as a long chain of small fusions with HBM "
        "round-trips between them — the compiled-side signature of the "
        "MFU plateau. Budget: launches/contraction <= "
        "FRAGMENTATION_OPS_PER_CONTRACTION (regression tripwire, set "
        "from seed measurements with headroom).",
)
def fusion_fragmentation(hp: HloProgram) -> Iterator[Tuple[str, Optional[dict]]]:
    launches = hp.entry_launches
    if launches < FRAGMENTATION_MIN_OPS:
        return
    per = launches / max(hp.contractions, 1)
    if per > FRAGMENTATION_OPS_PER_CONTRACTION:
        yield (
            f"{launches} entry-computation launches for "
            f"{hp.contractions} contraction(s) ({per:.1f}/contraction, "
            f"budget {FRAGMENTATION_OPS_PER_CONTRACTION:.0f}): the "
            "lowering fragmented — look for new layout breaks between "
            "the NC layers",
            {
                "launches": launches,
                "contractions": hp.contractions,
                "per_contraction": round(per, 2),
                "budget": FRAGMENTATION_OPS_PER_CONTRACTION,
            },
        )


@hlo_rule(
    "layout-churn",
    "warning",
    doc="transpose/copy ops surviving at the top of the entry "
        "computation: each is a kernel launch that moves bytes through "
        "HBM without computing anything (fused transposes are free and "
        "not counted). Budget: max(LAYOUT_CHURN_MIN_OPS, "
        "LAYOUT_CHURN_FRACTION of entry ops).",
)
def layout_churn(hp: HloProgram) -> Iterator[Tuple[str, Optional[dict]]]:
    budget = max(
        LAYOUT_CHURN_MIN_OPS, int(LAYOUT_CHURN_FRACTION * hp.entry_total)
    )
    churn = hp.churn_ops
    if churn > budget:
        yield (
            f"{churn} un-fused transpose/copy op(s) in the entry "
            f"computation (budget {budget}): layout churn between "
            "stages is back — check dimension orders at the producer/"
            "consumer boundary",
            {
                "transpose": hp.entry_ops.get("transpose", 0),
                "copy": hp.entry_ops.get("copy", 0),
                "entry_ops": hp.entry_total,
                "budget": budget,
            },
        )


@hlo_rule(
    "memory-highwater",
    "warning",
    doc="The buffer-liveness estimate of peak live bytes exceeds the "
        "program's budget (max(MEM_HIGHWATER_ABS_FLOOR, "
        "MEM_HIGHWATER_INPUT_RATIO * input bytes)): residual stacking "
        "or gather inflation crept in — catch it here, not as an OOM "
        "on hardware.",
)
def memory_highwater(hp: HloProgram) -> Iterator[Tuple[str, Optional[dict]]]:
    budget = max(
        MEM_HIGHWATER_ABS_FLOOR,
        int(MEM_HIGHWATER_INPUT_RATIO * hp.bytes_in),
    )
    if hp.peak_bytes_est > budget:
        yield (
            f"estimated memory highwater {hp.peak_bytes_est:,} bytes "
            f"exceeds the budget {budget:,} (inputs {hp.bytes_in:,}): "
            "the live set blew up — check for stacked residuals or an "
            "unbounded gather",
            {
                "peak_bytes_est": hp.peak_bytes_est,
                "bytes_in": hp.bytes_in,
                "budget": budget,
                "hlo_temp_bytes": hp.hlo_temp_bytes,
            },
        )


def run_hlo_rules(
    hp: HloProgram,
    waivers: Optional[Dict[str, str]] = None,
    rules: Optional[List[str]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Run (selected) HLO rules over one compiled program.

    Same waiver discipline as the jaxpr pass; bad-waiver errors are
    emitted THERE (the specs share one waiver dict), so this only
    splits waived findings out.
    """
    waivers = dict(waivers or {})
    path = f"hlo:{hp.name}"
    findings: List[Finding] = []
    waived: List[Finding] = []
    selected = (
        list(HLO_RULES.values()) if rules is None
        else [HLO_RULES[r] for r in rules if r in HLO_RULES]
    )
    for r in selected:
        for message, detail in r.fn(hp):
            f = Finding(path, 1, 0, r.rule_id, r.severity, message, detail)
            if r.rule_id in waivers and (waivers[r.rule_id] or "").strip():
                waived.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (SEVERITY_ORDER[f.severity], f.rule),
                  reverse=True)
    return findings, waived


def hlo_report(hp: HloProgram) -> Dict[str, Any]:
    """The HLO columns merged into the program's report row."""
    return {
        "hlo_entry_ops": hp.entry_total,
        "hlo_fusions": hp.fusions,
        "hlo_churn": hp.churn_ops,
        "hlo_contractions": hp.contractions,
        "mem_highwater_est": hp.peak_bytes_est,
        "hlo_temp_bytes": hp.hlo_temp_bytes,
        "compile_seconds": round(hp.compile_seconds, 3),
    }


def hlo_rules_meta() -> Dict[str, dict]:
    return {
        r.rule_id: {"severity": r.severity, "doc": r.doc}
        for r in HLO_RULES.values()
    }
