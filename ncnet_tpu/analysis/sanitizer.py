"""Runtime numerical sanitizer: per-stage finiteness + bf16-range probes.

The static rules catch *mechanically detectable* hazards; this module
catches the ones that only exist at runtime — the class of failure PERF.md
records as "stepped around, not understood": a config whose loss wanders
and then NaNs with no indication of WHERE the first non-finite value was
born. `tap(name, x)` instruments a stage boundary; when the sanitizer is
enabled every tap emits a `jax.debug.callback` that records, per stage and
per step, the finite fraction, the max finite |x|, and whether the value
range exceeds what bfloat16 can represent. `first_nonfinite()` then names
the earliest stage (in dataflow/trace order) that ever produced a
non-finite value — turning "it NaN'd" into "stage X went non-finite first".

Zero-cost when disabled: `tap` checks the enable flag at TRACE time and
returns its argument untouched, so the instrumented model compiles to
exactly the same XLA program unless `--sanitize` was passed. Consequences:

  * enable() must run BEFORE the instrumented function is first traced
    (a jit cache hit bypasses tracing; the CLI flags do this correctly);
  * under rematerialization the backward pass re-runs the forward, so each
    remat'd stage reports twice per step — harmless for finiteness;
  * callbacks are unordered across stages; dataflow ordering comes from
    the trace-order index recorded when each tap first traces, not from
    callback arrival time.

The probes are cheap (two reductions per tap) but they do add device work
and host callbacks: ~10-30% step overhead at synthetic-config scale, fine
for debugging runs, not for production training.

Coverage under lax.map (measured, jax 0.4.37): `jax.debug.callback` fires
inside a `lax.map`/`scan` body under jit, and in eager/forward-only runs —
but when the map is DIFFERENTIATED, callbacks staged in the primal pass
are dropped (ordered=True and custom_vjp identities do not help; the
effects only re-fire when a `jax.checkpoint`-remat'd backward re-runs the
body). Consequence for the chunked training loss: on the no-remat chunk
path the per-stage probes inside each chunk go silent under grad, and with
`loss_chunk_remat=True` they report via the backward recompute instead.
The chunk OUTPUTS (`score_pos_chunks`/`score_neg_chunks`, tapped outside
the map in train/loss.py), the loss, and every grad/update leaf always
report. The unchunked paths — including the PERF.md "Not shipped" NaN
config, which runs chunk == batch == unchunked — have full per-stage
coverage.
"""

import functools
import threading

import numpy as np

import jax
import jax.numpy as jnp

#: largest finite bfloat16 (same exponent range as f32; an overflow here
#: means the value is inf in BOTH dtypes — the probe mainly catches
#: exp/product blowups on their way up)
BF16_MAX = 3.3895313892515355e38

_lock = threading.Lock()
_enabled = False
_reports = []  # dicts appended by the debug callbacks, host side
_stage_order = []  # stage names in first-trace order (= dataflow order)
_verbose_nonfinite = True


def enable(on=True):
    """Turn the sanitizer on/off. Must be called before the instrumented
    functions are first traced (see module docstring)."""
    global _enabled
    _enabled = bool(on)


def is_enabled():
    return _enabled


def clear(stage_order=False):
    """Drop recorded reports (e.g. between runs or test cases).

    The stage ORDER is kept by default: it is trace-time metadata, and an
    already-compiled function will not re-trace to rebuild it — clearing
    it between runs of the same jitted step would break `first_nonfinite`
    dataflow ordering. Pass ``stage_order=True`` only when the next run
    re-traces from scratch (e.g. a fresh test case with new functions).
    """
    with _lock:
        _reports.clear()
        if stage_order:
            _stage_order.clear()


def reports():
    """All per-stage records so far: list of dicts with ``stage``,
    ``finite_frac``, ``absmax``, ``bf16_overflow``."""
    with _lock:
        return list(_reports)


def _record(stage, finite_frac, absmax):
    rec = {
        "stage": stage,
        "finite_frac": float(finite_frac),
        "absmax": float(absmax),
        "bf16_overflow": bool(float(absmax) > BF16_MAX),
    }
    with _lock:
        _reports.append(rec)
    if rec["finite_frac"] < 1.0 and _verbose_nonfinite:
        print(
            f"[sanitize] NON-FINITE at stage '{stage}': "
            f"finite_frac={rec['finite_frac']:.6f} "
            f"absmax(finite)={rec['absmax']:.3e}",
            flush=True,
        )


def tap(stage, x):
    """Probe one array at a named stage boundary; returns ``x`` unchanged.

    No-op (identity, zero trace residue) when the sanitizer is disabled.
    Non-floating inputs (ints, bools) pass through unprobed — finiteness
    is vacuous for them.
    """
    if not _enabled:
        return x
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        return x
    with _lock:
        if stage not in _stage_order:
            _stage_order.append(stage)
    xf = jnp.asarray(x).astype(jnp.float32)
    finite = jnp.isfinite(xf)
    finite_frac = jnp.mean(finite.astype(jnp.float32))
    absmax = jnp.max(jnp.where(finite, jnp.abs(xf), 0.0))
    jax.debug.callback(
        functools.partial(_record, stage), finite_frac, absmax
    )
    return x


# the single-array probe is also the right shape for scan/vmap carries;
# export the name the harness docs use
tap_finite = tap


def sanitize_pytree(stage, tree):
    """`tap` every floating leaf of a pytree, naming leaves by key path.

    Returns the tree unchanged (identity when disabled).
    """
    if not _enabled:
        return tree

    def probe(path, leaf):
        name = f"{stage}{jax.tree_util.keystr(path)}"
        return tap(name, leaf)

    return jax.tree_util.tree_map_with_path(probe, tree)


def first_nonfinite():
    """Name of the earliest stage (dataflow order) that ever went
    non-finite, or None. The per-stage record of its FIRST non-finite
    observation rides along as the second tuple element."""
    with _lock:
        bad = {}
        for rec in _reports:
            if rec["finite_frac"] < 1.0 and rec["stage"] not in bad:
                bad[rec["stage"]] = rec
        for stage in _stage_order:
            if stage in bad:
                return stage, bad[stage]
        # non-finite at a stage we never saw trace (shouldn't happen)
        for stage, rec in bad.items():
            return stage, rec
    return None


def summary():
    """Per-stage aggregate in dataflow order: observation count, non-finite
    count, running max |x|, and whether bf16 range was ever exceeded."""
    with _lock:
        agg = {}
        for rec in _reports:
            s = agg.setdefault(
                rec["stage"],
                {"stage": rec["stage"], "observations": 0, "nonfinite": 0,
                 "absmax": 0.0, "bf16_overflow": False},
            )
            s["observations"] += 1
            s["nonfinite"] += rec["finite_frac"] < 1.0
            s["absmax"] = max(s["absmax"], rec["absmax"])
            s["bf16_overflow"] |= rec["bf16_overflow"]
        order = [s for s in _stage_order if s in agg]
        order += [s for s in agg if s not in order]
        return [agg[s] for s in order]


def report_text():
    """Human-readable per-stage table (dataflow order)."""
    rows = summary()
    if not rows:
        return "[sanitize] no observations (sanitizer disabled or no taps ran)"
    w = max(len(r["stage"]) for r in rows)
    lines = [
        f"[sanitize] {'stage'.ljust(w)}  obs  nonfinite  absmax      bf16_ovf"
    ]
    for r in rows:
        lines.append(
            f"[sanitize] {r['stage'].ljust(w)}  "
            f"{r['observations']:>3}  {r['nonfinite']:>9}  "
            f"{r['absmax']:<10.3e}  {'YES' if r['bf16_overflow'] else 'no'}"
        )
    fnf = first_nonfinite()
    if fnf:
        lines.append(
            f"[sanitize] first non-finite stage (dataflow order): {fnf[0]}"
        )
    else:
        lines.append("[sanitize] all observed stages finite")
    return "\n".join(lines)


def check_finite_or_report(loss_value, context=""):
    """Host-side guard for training loops: if ``loss_value`` is non-finite,
    print the per-stage report and raise FloatingPointError naming the
    first non-finite stage."""
    if np.isfinite(loss_value):
        return
    print(report_text(), flush=True)
    fnf = first_nonfinite()
    where = f"; first non-finite stage: {fnf[0]}" if fnf else ""
    raise FloatingPointError(
        f"non-finite loss {loss_value}{' at ' + context if context else ''}"
        f"{where}"
    )
