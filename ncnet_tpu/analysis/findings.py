"""Shared findings model for the static analyzers (nclint + the jaxpr
auditor), with text / JSON / SARIF emitters.

One `Finding` shape for both engines means one gate contract: CI consumes
`--format json` with a single schema, and `--format sarif` uploads to code
scanning for inline annotations, regardless of whether the producer was the
AST linter (`ncnet_tpu.analysis.engine`) or the program-level jaxpr auditor
(`ncnet_tpu.analysis.jaxpr_audit`). The AST engine addresses findings as
``path:line:col``; the auditor uses the pseudo-path ``jaxpr:<program>`` —
SARIF treats both as artifact URIs.
"""

import dataclasses
import json
from typing import Dict, Iterable, List, Optional

SEVERITY_ORDER = {"info": 0, "warning": 1, "error": 2}

#: finding severity -> SARIF result level
_SARIF_LEVEL = {"info": "note", "warning": "warning", "error": "error"}

SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer finding, addressable as ``path:line:col``.

    ``detail`` carries rule-specific structured data (e.g. the auditor's
    wasted-HBM byte counts or FLOP mismatch numbers) — optional, and
    omitted from ``to_dict`` when empty so the JSON schema stays stable
    for consumers that predate it.
    """

    path: str
    line: int
    col: int
    rule: str
    severity: str
    message: str
    detail: Optional[dict] = None

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.severity} [{self.rule}] {self.message}"
        )

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if d.get("detail") is None:
            d.pop("detail", None)
        return d


def max_severity(findings: Iterable[Finding]) -> int:
    return max((SEVERITY_ORDER[f.severity] for f in findings), default=-1)


def format_text(findings: List[Finding]) -> str:
    lines = [f.format() for f in findings]
    lines.append(
        f"{len(findings)} finding(s)" if findings else "clean: 0 findings"
    )
    return "\n".join(lines)


def format_json(findings: List[Finding], tool: Optional[str] = None) -> str:
    payload = {
        "findings": [f.to_dict() for f in findings],
        "count": len(findings),
    }
    if tool is not None:
        payload["tool"] = tool
        payload["schema_version"] = SCHEMA_VERSION
    return json.dumps(payload, indent=2)


def format_sarif(
    findings: List[Finding],
    tool_name: str,
    rules_meta: Optional[Dict[str, dict]] = None,
    tool_version: str = "0",
) -> str:
    """SARIF 2.1.0 for GitHub code scanning upload.

    ``rules_meta``: ``{rule_id: {"severity": ..., "doc": ...}}`` — rules
    referenced by findings but absent here still get a bare descriptor, so
    the document always validates.
    """
    rules_meta = dict(rules_meta or {})
    for f in findings:
        rules_meta.setdefault(f.rule, {"severity": f.severity, "doc": ""})
    rule_ids = sorted(rules_meta)
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    descriptors = [
        {
            "id": rid,
            "shortDescription": {"text": " ".join(
                (rules_meta[rid].get("doc") or rid).split()
            )[:512]},
            "defaultConfiguration": {
                "level": _SARIF_LEVEL.get(
                    rules_meta[rid].get("severity", "warning"), "warning"
                )
            },
        }
        for rid in rule_ids
    ]
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "ruleIndex": rule_index[f.rule],
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace("\\", "/"),
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {
                            "startLine": max(f.line, 1),
                            "startColumn": max(f.col, 0) + 1,
                        },
                    }
                }
            ],
        }
        if f.detail:
            result["properties"] = f.detail
        results.append(result)
    doc = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": tool_name,
                        "version": tool_version,
                        "informationUri": (
                            "https://github.com/GrumpyZhou/ncnet"
                        ),
                        "rules": descriptors,
                    }
                },
                "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)
