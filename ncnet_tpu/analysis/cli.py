"""`nclint` — run the JAX-aware static lint suite over source trees.

Exit status is 0 only when no unsuppressed finding at or above
``--fail-on`` severity remains — the CI gate is simply

    python scripts/lint.py ncnet_tpu scripts benchmarks

(or ``nclint ...`` once the package is pip-installed; see pyproject.toml's
``[project.scripts]``).
"""

import argparse
import sys

from ncnet_tpu.analysis import rules  # noqa: F401  (registers the rule set)
from ncnet_tpu.analysis.engine import (
    RULES,
    SEVERITY_ORDER,
    format_json,
    format_sarif,
    format_text,
    lint_paths,
)

#: engine-level findings that carry no registered Rule (SARIF descriptors)
ENGINE_PSEUDO_RULES = {
    "syntax-error": {
        "severity": "error",
        "doc": "the file cannot be parsed as Python",
    },
    "bad-suppression": {
        "severity": "error",
        "doc": "an inline nclint suppression without a reason: every "
               "silenced finding must say why the exception is safe",
    },
}


def lint_rules_meta():
    """{rule_id: {severity, doc}} over the full AST rule set, for SARIF."""
    meta = {
        r.rule_id: {"severity": r.severity, "doc": r.doc}
        for r in RULES.values()
    }
    meta.update(ENGINE_PSEUDO_RULES)
    return meta


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="nclint",
        description="JAX/TPU-aware static lint (rule catalog: "
                    "ncnet_tpu/analysis/README.md)",
    )
    p.add_argument("paths", nargs="*", default=["."],
                   help="files or directories to lint (default: .)")
    p.add_argument("--format", choices=["text", "json", "sarif"],
                   default="text", dest="fmt",
                   help="output format (default: human-readable text; "
                        "json/sarif share the audit CLI's schema)")
    p.add_argument("--json", action="store_true",
                   help="alias for --format json (back-compat)")
    p.add_argument("--fail-on", choices=sorted(SEVERITY_ORDER),
                   default="warning",
                   help="lowest severity that fails the run (default: "
                        "warning). Findings below it are still printed.")
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in sorted(RULES.values(), key=lambda r: r.rule_id):
            print(f"{r.rule_id} ({r.severity}): {' '.join(r.doc.split())}")
        return 0

    selected = None
    if args.select:
        selected = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = [s for s in selected if s not in RULES]
        if unknown:
            p.error(f"unknown rule id(s): {', '.join(unknown)} "
                    f"(see --list-rules)")

    findings = lint_paths(args.paths or ["."], selected)
    fmt = "json" if args.json else args.fmt
    if fmt == "json":
        print(format_json(findings, tool="nclint"))
    elif fmt == "sarif":
        print(format_sarif(findings, "nclint", lint_rules_meta()))
    else:
        print(format_text(findings))
    threshold = SEVERITY_ORDER[args.fail_on]
    gating = [f for f in findings if SEVERITY_ORDER[f.severity] >= threshold]
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
