"""AST lint engine: file walker, rule registry, findings, suppressions.

The engine is deliberately small and dependency-free (stdlib ``ast`` only):
rules are plain functions registered with the `rule` decorator, each
receiving a parsed module plus a `ModuleContext` with resolved import
aliases, and yielding ``(node, message)`` pairs. The JAX/TPU-specific rule
set lives in `ncnet_tpu.analysis.rules`; importing it populates the
registry as a side effect.

Interprocedural mode (the default for `lint_paths`, i.e. for the CI gate):
before linting, every file in the run is parsed once into a
`ProjectIndex` — a project-wide symbol table mapping dotted module names
(derived from ``__init__.py`` package chains) to their top-level function
definitions. Rules reach it as ``ctx.project`` and may follow a resolved
call ONE level into another module (e.g. a compiled region calling a
helper whose body hides a host sync). Single-file `lint_source` calls have
``ctx.project = None`` and stay intra-module, so snippet-level golden
tests and editor integrations are unchanged.

Suppression contract (enforced, not advisory): a finding is silenced only
by an inline directive ON THE FLAGGED LINE of the form

    # nclint: disable=<rule-id>[,<rule-id>...] -- <reason>

and the reason is MANDATORY — a directive without one is itself reported
as a `bad-suppression` error, so every silenced finding carries a written
justification next to the code it excuses.
"""

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, Iterable, Iterator, List, NamedTuple, Optional, Tuple

from ncnet_tpu.analysis.findings import (  # noqa: F401  (re-exported API)
    SEVERITY_ORDER,
    Finding,
    format_json,
    format_sarif,
    format_text,
    max_severity,
)

_SUPPRESS_RE = re.compile(
    r"#\s*nclint:\s*disable=([A-Za-z0-9_,\-]+)\s*(?:--\s*(\S.*))?"
)


def module_name_for_path(path: str) -> str:
    """Dotted module name for a file, walking the ``__init__.py`` chain.

    ``.../ncnet_tpu/train/step.py`` -> ``ncnet_tpu.train.step`` because
    every directory up to ``ncnet_tpu`` holds an ``__init__.py``;
    ``scripts/train.py`` (no package) -> ``train``. This is what makes a
    caller-side canonical name like ``ncnet_tpu.train.loss.weak_loss``
    resolvable against the index regardless of where the lint run was
    started from.
    """
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    if parts[0] == "__init__":
        parts = parts[1:] or [parts[0]]
    return ".".join(reversed(parts))


class FunctionInfo(NamedTuple):
    """One indexed top-level function: where it lives + its parsed body."""

    module: str
    path: str
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    ctx: "ModuleContext"


class ProjectIndex:
    """Project-wide symbol table: dotted function name -> `FunctionInfo`.

    Built once per lint run over every file in the run; rules use
    `resolve` to follow a call site's canonical dotted name into the
    defining module (one level deep — the callee's OWN calls are not
    followed further, keeping findings explainable).
    """

    def __init__(self):
        self.modules: Dict[str, "ModuleContext"] = {}
        self.functions: Dict[str, FunctionInfo] = {}

    @classmethod
    def build(cls, files: Iterable[str]) -> "ProjectIndex":
        idx = cls()
        for path in files:
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError):
                continue  # unreadable/unparseable files get their own finding
            ctx = ModuleContext(tree, path, source)
            mod = module_name_for_path(path)
            idx.modules[mod] = ctx
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    idx.functions[f"{mod}.{node.name}"] = FunctionInfo(
                        mod, path, node, ctx
                    )
        return idx

    def resolve(self, canonical: Optional[str]) -> Optional[FunctionInfo]:
        """`FunctionInfo` for a caller-side canonical dotted name, if the
        name points at a top-level function of an indexed module."""
        if not canonical:
            return None
        return self.functions.get(canonical)


class ModuleContext:
    """Per-module facts shared by rules: import aliases + test-ness.

    ``canonical(node)`` resolves an ``ast.Name``/``ast.Attribute`` chain to
    its canonical dotted path through the module's imports, so rules match
    ``jax.numpy.max`` whether the source spells it ``jnp.max``,
    ``jax.numpy.max`` or ``from jax import numpy; numpy.max``.

    ``project`` is the run-wide `ProjectIndex` in interprocedural runs
    (`lint_paths`), else None — rules must degrade gracefully.
    """

    def __init__(self, tree: ast.Module, path: str, source: str,
                 project: Optional[ProjectIndex] = None):
        self.tree = tree
        self.path = path
        self.source = source
        self.project = project
        base = os.path.basename(path)
        parts = os.path.normpath(path).split(os.sep)
        self.is_test = (
            base.startswith("test_")
            or base == "conftest.py"
            or "tests" in parts
        )
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:  # relative imports: not external libraries
                    continue
                for a in node.names:
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )

    def canonical(self, node: ast.AST) -> Optional[str]:
        """Dotted canonical name for a Name/Attribute chain, else None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))


RuleFn = Callable[[ModuleContext], Iterator[Tuple[ast.AST, str]]]

RULES: Dict[str, "Rule"] = {}


@dataclasses.dataclass(frozen=True)
class Rule:
    rule_id: str
    severity: str
    doc: str
    fn: RuleFn


def rule(rule_id: str, severity: str = "warning", doc: str = ""):
    """Register a rule function; ``fn(ctx)`` yields ``(node, message)``."""
    if severity not in SEVERITY_ORDER:
        raise ValueError(f"unknown severity {severity!r}")

    def wrap(fn: RuleFn) -> RuleFn:
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        RULES[rule_id] = Rule(rule_id, severity, doc or (fn.__doc__ or ""), fn)
        return fn

    return wrap


def _parse_suppressions(source: str, path: str):
    """Per-line suppression sets + findings for malformed directives."""
    suppressed: Dict[int, set] = {}
    bad: List[Finding] = []
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = (m.group(2) or "").strip()
        if not reason:
            # the directive still suppresses (so the ONE actionable error
            # is the missing reason, not a duplicate of the silenced
            # finding) but fails the gate until a reason is written
            bad.append(
                Finding(
                    path, lineno, line.index("#"), "bad-suppression", "error",
                    "suppression without a reason: use "
                    "'# nclint: disable=<rule> -- <why this is safe>'",
                )
            )
        suppressed[lineno] = suppressed.get(lineno, set()) | rules
    return suppressed, bad


def lint_source(
    source: str,
    path: str,
    rules: Optional[Iterable[str]] = None,
    project: Optional[ProjectIndex] = None,
) -> List[Finding]:
    """Lint one module's source text; returns unsuppressed findings."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                path, e.lineno or 1, e.offset or 0, "syntax-error", "error",
                f"cannot parse: {e.msg}",
            )
        ]
    ctx = ModuleContext(tree, path, source, project=project)
    suppressed, findings = _parse_suppressions(source, path)
    selected = (
        RULES.values() if rules is None
        else [RULES[r] for r in rules]
    )
    for r in selected:
        for node, message in r.fn(ctx):
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
            if r.rule_id in suppressed.get(line, ()):
                continue
            findings.append(
                Finding(path, line, col, r.rule_id, r.severity, message)
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(
    path: str,
    rules: Optional[Iterable[str]] = None,
    project: Optional[ProjectIndex] = None,
) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path, rules, project=project)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into sorted .py paths (dirs recursively)."""
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def lint_paths(
    paths: Iterable[str],
    rules: Optional[Iterable[str]] = None,
    interprocedural: bool = True,
) -> List[Finding]:
    """Lint files/directories; multi-file runs get a shared `ProjectIndex`
    so rules can follow calls across modules (disable with
    ``interprocedural=False`` for strictly per-file behaviour)."""
    files = list(iter_python_files(paths))
    project = ProjectIndex.build(files) if interprocedural else None
    findings: List[Finding] = []
    for path in files:
        findings.extend(lint_file(path, rules, project=project))
    return findings
