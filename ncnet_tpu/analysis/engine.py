"""AST lint engine: file walker, rule registry, findings, suppressions.

The engine is deliberately small and dependency-free (stdlib ``ast`` only):
rules are plain functions registered with the `rule` decorator, each
receiving a parsed module plus a `ModuleContext` with resolved import
aliases, and yielding ``(node, message)`` pairs. The JAX/TPU-specific rule
set lives in `ncnet_tpu.analysis.rules`; importing it populates the
registry as a side effect.

Suppression contract (enforced, not advisory): a finding is silenced only
by an inline directive ON THE FLAGGED LINE of the form

    # nclint: disable=<rule-id>[,<rule-id>...] -- <reason>

and the reason is MANDATORY — a directive without one is itself reported
as a `bad-suppression` error, so every silenced finding carries a written
justification next to the code it excuses.
"""

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

SEVERITY_ORDER = {"info": 0, "warning": 1, "error": 2}

_SUPPRESS_RE = re.compile(
    r"#\s*nclint:\s*disable=([A-Za-z0-9_,\-]+)\s*(?:--\s*(\S.*))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, addressable as ``path:line:col``."""

    path: str
    line: int
    col: int
    rule: str
    severity: str
    message: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.severity} [{self.rule}] {self.message}"
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ModuleContext:
    """Per-module facts shared by rules: import aliases + test-ness.

    ``canonical(node)`` resolves an ``ast.Name``/``ast.Attribute`` chain to
    its canonical dotted path through the module's imports, so rules match
    ``jax.numpy.max`` whether the source spells it ``jnp.max``,
    ``jax.numpy.max`` or ``from jax import numpy; numpy.max``.
    """

    def __init__(self, tree: ast.Module, path: str, source: str):
        self.tree = tree
        self.path = path
        self.source = source
        base = os.path.basename(path)
        parts = os.path.normpath(path).split(os.sep)
        self.is_test = (
            base.startswith("test_")
            or base == "conftest.py"
            or "tests" in parts
        )
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:  # relative imports: not external libraries
                    continue
                for a in node.names:
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )

    def canonical(self, node: ast.AST) -> Optional[str]:
        """Dotted canonical name for a Name/Attribute chain, else None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))


RuleFn = Callable[[ModuleContext], Iterator[Tuple[ast.AST, str]]]

RULES: Dict[str, "Rule"] = {}


@dataclasses.dataclass(frozen=True)
class Rule:
    rule_id: str
    severity: str
    doc: str
    fn: RuleFn


def rule(rule_id: str, severity: str = "warning", doc: str = ""):
    """Register a rule function; ``fn(ctx)`` yields ``(node, message)``."""
    if severity not in SEVERITY_ORDER:
        raise ValueError(f"unknown severity {severity!r}")

    def wrap(fn: RuleFn) -> RuleFn:
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        RULES[rule_id] = Rule(rule_id, severity, doc or (fn.__doc__ or ""), fn)
        return fn

    return wrap


def _parse_suppressions(source: str, path: str):
    """Per-line suppression sets + findings for malformed directives."""
    suppressed: Dict[int, set] = {}
    bad: List[Finding] = []
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = (m.group(2) or "").strip()
        if not reason:
            # the directive still suppresses (so the ONE actionable error
            # is the missing reason, not a duplicate of the silenced
            # finding) but fails the gate until a reason is written
            bad.append(
                Finding(
                    path, lineno, line.index("#"), "bad-suppression", "error",
                    "suppression without a reason: use "
                    "'# nclint: disable=<rule> -- <why this is safe>'",
                )
            )
        suppressed[lineno] = suppressed.get(lineno, set()) | rules
    return suppressed, bad


def lint_source(
    source: str, path: str, rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    """Lint one module's source text; returns unsuppressed findings."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                path, e.lineno or 1, e.offset or 0, "syntax-error", "error",
                f"cannot parse: {e.msg}",
            )
        ]
    ctx = ModuleContext(tree, path, source)
    suppressed, findings = _parse_suppressions(source, path)
    selected = (
        RULES.values() if rules is None
        else [RULES[r] for r in rules]
    )
    for r in selected:
        for node, message in r.fn(ctx):
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
            if r.rule_id in suppressed.get(line, ()):
                continue
            findings.append(
                Finding(path, line, col, r.rule_id, r.severity, message)
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str, rules: Optional[Iterable[str]] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path, rules)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into sorted .py paths (dirs recursively)."""
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def lint_paths(
    paths: Iterable[str], rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(lint_file(path, rules))
    return findings


def max_severity(findings: Iterable[Finding]) -> int:
    return max(
        (SEVERITY_ORDER[f.severity] for f in findings), default=-1
    )


def format_text(findings: List[Finding]) -> str:
    lines = [f.format() for f in findings]
    lines.append(
        f"{len(findings)} finding(s)" if findings else "clean: 0 findings"
    )
    return "\n".join(lines)


def format_json(findings: List[Finding]) -> str:
    return json.dumps(
        {"findings": [f.to_dict() for f in findings], "count": len(findings)},
        indent=2,
    )
