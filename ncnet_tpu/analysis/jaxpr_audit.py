"""Program-level auditing: trace the repo's REAL entry programs to jaxprs
and statically check the IR for the hazards source-level linting cannot see.

`ncnet_tpu.analysis.engine` (nclint) reasons about source text; everything
it can say stops at the trace boundary. This module picks up on the other
side: each registered `ProgramSpec` builds one of the repo's actual entry
programs — the jitted train step (dense / feature-cached / sparse-band),
the serving engine's bucket program, the eval match fn — traces it with
`jax.make_jaxpr`, and runs jaxpr rules over the resulting IR:

  f64-leak               any float64/complex128 value in the program: on
                         TPU f64 is emulated (orders of magnitude slower),
                         and a leak usually means a numpy scalar promoted
                         the whole chain
  bf16-promotion-drift   f32 dot/conv ops inside a program whose config
                         declares the bf16 compute path: each one silently
                         gives back the bf16 win it was supposed to get
  host-callback-in-jit   callback primitives (pure_callback /
                         debug_callback / io_callback) compiled into the
                         program: every execution round-trips to the host
  missing-donation       declared-donatable args (carried train state, the
                         serving batch) whose buffers are NOT donated —
                         flagged with the wasted HBM bytes
  oversized-constant     closure-captured arrays baked into the program as
                         constants (weights captured instead of passed):
                         they bloat the executable and dodge donation
  flop-accounting-drift  an analytic FLOP walk over the jaxpr (dot_general
                         + conv_general_dilated, recursing through
                         scan/cond/remat sub-jaxprs) cross-checked against
                         `ops.accounting.train_step_flops_for_batch`: a
                         mismatch beyond tolerance means the telemetry MFU
                         numerator (PR 7) has rotted

Findings use the shared `analysis.findings.Finding` model with the
pseudo-path ``jaxpr:<program>`` — `scripts/audit.py` emits them through
the same text/JSON/SARIF formatters as nclint.

Waivers are the auditor's suppression mechanism (same discipline as
nclint's inline directives): a `ProgramSpec` may waive a rule with a
MANDATORY reason; an empty reason is itself an error finding.
"""

import dataclasses
import time
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ncnet_tpu.analysis.findings import SEVERITY_ORDER, Finding

# --- traced-program model ----------------------------------------------------


@dataclasses.dataclass
class BuiltProgram:
    """A concrete, traceable entry program.

    ``fn`` must be a jit-wrapped callable (the trace looks for its pjit
    equation); ``args`` are small-but-real example arguments.
    ``declared_dtype`` names the compute dtype the config promises
    ("bfloat16" enables the drift rule). ``donate_expect`` maps argnums
    that SHOULD be donated to a human label for the finding.
    ``expected_flops`` (when set) arms the accounting cross-check.
    """

    fn: Callable
    args: Tuple[Any, ...]
    declared_dtype: Optional[str] = None
    donate_expect: Dict[int, str] = dataclasses.field(default_factory=dict)
    expected_flops: Optional[float] = None
    flop_tol: float = 0.02


@dataclasses.dataclass
class TracedProgram:
    """One entry program traced to its compiled-side ClosedJaxpr."""

    name: str
    built: BuiltProgram
    closed: Any  # inner ClosedJaxpr (the pjit body)
    donated_invars: Tuple[bool, ...]
    arg_leaves: List[List[Any]]  # per-argnum flattened concrete leaves
    trace_seconds: float = 0.0

    @property
    def jaxpr(self):
        return self.closed.jaxpr

    def leaf_slice(self, argnum: int) -> Tuple[int, int]:
        """[start, stop) positions of ``argnum``'s leaves in the flat
        invar order (= the `donated_invars` index space)."""
        start = sum(len(ls) for ls in self.arg_leaves[:argnum])
        return start, start + len(self.arg_leaves[argnum])


def _leaf_bytes(leaf) -> int:
    arr = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
    return int(np.prod(arr.shape, dtype=np.int64)) * arr.dtype.itemsize if (
        arr.shape
    ) else arr.dtype.itemsize


def _aval_bytes(aval) -> int:
    size = int(np.prod(aval.shape, dtype=np.int64)) if aval.shape else 1
    try:
        itemsize = np.dtype(aval.dtype).itemsize
    except TypeError:
        # extended dtypes (the typed PRNG-key avals a threaded
        # jax.random key introduces, e.g. key<fry>) are not numpy
        # dtypes but know their physical width
        itemsize = int(aval.dtype.itemsize)
    return size * itemsize


def trace_program(name: str, built: BuiltProgram) -> TracedProgram:
    """Trace ``built.fn(*built.args)`` and unwrap the pjit equation.

    The wrapper lambda keeps the jitted fn a CALL inside the outer trace,
    so the jaxpr contains one ``pjit`` eqn whose params carry both the
    inner ClosedJaxpr and ``donated_invars`` (aligned 1:1 with the
    flattened argument leaves, in argument order). Closure-captured
    arrays appear as the INNER jaxpr's consts — which is exactly what the
    oversized-constant rule inspects.
    """
    import jax

    t0 = time.perf_counter()
    outer = jax.make_jaxpr(lambda *a: built.fn(*a))(*built.args)
    dt = time.perf_counter() - t0
    pjit_eqns = [e for e in outer.jaxpr.eqns if e.primitive.name == "pjit"]
    if not pjit_eqns:
        raise ValueError(
            f"program {name!r}: no pjit equation in the trace — is "
            "built.fn actually jit-wrapped?"
        )
    eqn = pjit_eqns[0]
    arg_leaves = [list(jax.tree_util.tree_leaves(a)) for a in built.args]
    return TracedProgram(
        name=name,
        built=built,
        closed=eqn.params["jaxpr"],
        donated_invars=tuple(eqn.params.get("donated_invars", ())),
        arg_leaves=arg_leaves,
        trace_seconds=dt,
    )


# --- generic IR walkers ------------------------------------------------------


def _iter_sub_jaxprs(value) -> Iterator[Any]:
    """Yield every Jaxpr inside an eqn param value (ClosedJaxpr unwrapped,
    tuples/lists of branches — e.g. cond — walked)."""
    if hasattr(value, "jaxpr") and hasattr(value, "consts"):  # ClosedJaxpr
        yield value.jaxpr
    elif hasattr(value, "eqns") and hasattr(value, "invars"):  # Jaxpr
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _iter_sub_jaxprs(v)


def iter_eqns(jaxpr) -> Iterator[Tuple[Any, int]]:
    """Every equation in the program, recursively, with the execution
    multiplier its nesting implies (scan bodies run ``length`` times)."""
    for e in jaxpr.eqns:
        yield e, 1
        mult = int(e.params.get("length", 1)) if (
            e.primitive.name == "scan"
        ) else 1
        for v in e.params.values():
            for sub in _iter_sub_jaxprs(v):
                for inner_e, inner_m in iter_eqns(sub):
                    yield inner_e, mult * inner_m


def _prod(xs) -> float:
    out = 1.0
    for x in xs:
        out *= float(x)
    return out


def eqn_flops(eqn) -> float:
    """Analytic FLOPs (2*MACs) of ONE equation; 0 for non-contraction ops.

    Elementwise/reduction work is deliberately excluded — it is noise
    next to the contractions for every program in this repo, and
    `ops.accounting` counts the same way, so the cross-check compares
    like with like.
    """
    p = eqn.primitive.name
    if p == "dot_general":
        (lc, _rc), _batch = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval
        out = eqn.outvars[0].aval
        return 2.0 * _prod(out.shape) * _prod(lhs.shape[d] for d in lc)
    if p == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        rhs = eqn.invars[1].aval
        out = eqn.outvars[0].aval
        k_spatial = _prod(rhs.shape[d] for d in dn.rhs_spec[2:])
        cin = rhs.shape[dn.rhs_spec[1]]  # per-group input channels
        return 2.0 * _prod(out.shape) * k_spatial * cin
    return 0.0


def jaxpr_flops(jaxpr) -> float:
    """Analytic FLOP walk over the whole program (scan-multiplied)."""
    return sum(eqn_flops(e) * m for e, m in iter_eqns(jaxpr))


def _iter_avals(jaxpr) -> Iterator[Any]:
    """Every array type the program touches: inputs, consts, and each
    equation output, recursively."""
    seen = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for v in list(j.invars) + list(j.constvars) + list(j.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                yield aval
        for e in j.eqns:
            for v in e.outvars:
                aval = getattr(v, "aval", None)
                if aval is not None and hasattr(aval, "dtype"):
                    yield aval
            for val in e.params.values():
                stack.extend(_iter_sub_jaxprs(val))


# --- jaxpr rule registry -----------------------------------------------------

JaxprRuleFn = Callable[[TracedProgram], Iterator[Tuple[str, Optional[dict]]]]

JAXPR_RULES: Dict[str, "JaxprRule"] = {}


@dataclasses.dataclass(frozen=True)
class JaxprRule:
    rule_id: str
    severity: str
    doc: str
    fn: JaxprRuleFn


def jaxpr_rule(rule_id: str, severity: str = "warning", doc: str = ""):
    """Register a jaxpr rule; ``fn(traced)`` yields ``(message, detail)``."""
    if severity not in SEVERITY_ORDER:
        raise ValueError(f"unknown severity {severity!r}")

    def wrap(fn: JaxprRuleFn) -> JaxprRuleFn:
        if rule_id in JAXPR_RULES:
            raise ValueError(f"duplicate jaxpr rule id {rule_id!r}")
        JAXPR_RULES[rule_id] = JaxprRule(
            rule_id, severity, doc or (fn.__doc__ or ""), fn
        )
        return fn

    return wrap


_WIDE_DTYPES = ("float64", "complex128")


@jaxpr_rule(
    "f64-leak",
    "error",
    doc="A float64/complex128 value inside the compiled program: TPUs "
        "emulate f64 in software (orders of magnitude slower), and the "
        "usual cause — an unannotated numpy scalar or np.float64 literal "
        "— silently promotes everything downstream of it.",
)
def f64_leak(tp: TracedProgram) -> Iterator[Tuple[str, Optional[dict]]]:
    hits: Dict[str, int] = {}
    for aval in _iter_avals(tp.jaxpr):
        dt = str(aval.dtype)
        if dt in _WIDE_DTYPES:
            hits[dt] = hits.get(dt, 0) + 1
    for dt, n in sorted(hits.items()):
        yield (
            f"{n} {dt} value(s) in the program: f64 is software-emulated "
            "on TPU — find the promoting literal/scalar and pin the dtype",
            {"dtype": dt, "count": n},
        )


@jaxpr_rule(
    "bf16-promotion-drift",
    "warning",
    doc="f32 dot/conv contractions inside a program whose config declares "
        "the bf16 compute path (half_precision=True): each one runs at "
        "the f32 rate and gives back the bf16 throughput the config "
        "promised. f32 ELEMENTWISE ops are by design (final readout "
        "cast, optimizer math) and not flagged.",
)
def bf16_promotion_drift(tp: TracedProgram) -> Iterator[Tuple[str, Optional[dict]]]:
    if tp.built.declared_dtype != "bfloat16":
        return
    f32_heavy = 0
    total_heavy = 0
    for e, _m in iter_eqns(tp.jaxpr):
        if e.primitive.name not in ("dot_general", "conv_general_dilated"):
            continue
        total_heavy += 1
        if str(e.outvars[0].aval.dtype) == "float32":
            f32_heavy += 1
    if f32_heavy:
        yield (
            f"{f32_heavy}/{total_heavy} dot/conv op(s) run in float32 in a "
            "declared-bf16 program: a promotion upstream is eating the "
            "bf16 win — chase the first f32 operand",
            {"f32_contractions": f32_heavy, "contractions": total_heavy},
        )


@jaxpr_rule(
    "host-callback-in-jit",
    "error",
    doc="A callback primitive (pure_callback / debug_callback / "
        "io_callback) compiled into the program: every execution "
        "round-trips device->host->device, serializing the pipeline — "
        "the compiled-side twin of nclint's host-sync-in-jit.",
)
def host_callback_in_jit(tp: TracedProgram) -> Iterator[Tuple[str, Optional[dict]]]:
    hits: Dict[str, int] = {}
    for e, _m in iter_eqns(tp.jaxpr):
        if "callback" in e.primitive.name:
            hits[e.primitive.name] = hits.get(e.primitive.name, 0) + 1
    for prim, n in sorted(hits.items()):
        yield (
            f"{n} `{prim}` op(s) compiled into the program: each "
            "execution stalls on a host round-trip; move the callback "
            "outside jit or behind a debug flag",
            {"primitive": prim, "count": n},
        )


@jaxpr_rule(
    "missing-donation",
    "warning",
    doc="An argument the program's contract marks single-use (the carried "
        "train state, the serving engine's padded batch) is NOT in "
        "donate_argnums: XLA must allocate fresh output buffers while the "
        "dead input still holds HBM — the flagged byte count is paid "
        "every step.",
)
def missing_donation(tp: TracedProgram) -> Iterator[Tuple[str, Optional[dict]]]:
    donated = tp.donated_invars
    for argnum, label in sorted(tp.built.donate_expect.items()):
        start, stop = tp.leaf_slice(argnum)
        if stop > len(donated):
            # donated_invars misaligned with the arg leaves (layout change
            # upstream): surface it rather than silently passing
            yield (
                f"arg {argnum} ({label}): donation flags unavailable for "
                "its leaves — pjit invar layout changed; audit needs "
                "updating",
                {"argnum": argnum},
            )
            continue
        flags = donated[start:stop]
        if all(flags):
            continue
        undonated = [
            leaf for leaf, flag in zip(tp.arg_leaves[argnum], flags)
            if not flag
        ]
        wasted = sum(_leaf_bytes(leaf) for leaf in undonated)
        yield (
            f"arg {argnum} ({label}) is not donated: "
            f"{len(undonated)}/{len(flags)} leaf buffer(s), "
            f"{wasted:,} wasted HBM bytes held across every call — add "
            "it to donate_argnums",
            {
                "argnum": argnum,
                "label": label,
                "undonated_leaves": len(undonated),
                "leaves": len(flags),
                "wasted_bytes": wasted,
            },
        )


#: constants below this size are legitimate program data (iotas, masks,
#: norm epsilons); above it they are almost certainly captured weights
OVERSIZED_CONST_BYTES = 1 << 20


@jaxpr_rule(
    "oversized-constant",
    "warning",
    doc="A large array captured by closure and baked into the program as "
        "a constant (>= 1 MiB): captured weights bloat the serialized "
        "executable, recompile on every value change, and can never be "
        "donated — pass them as arguments instead.",
)
def oversized_constant(tp: TracedProgram) -> Iterator[Tuple[str, Optional[dict]]]:
    for i, const in enumerate(tp.closed.consts):
        if not hasattr(const, "dtype"):
            continue
        nbytes = _leaf_bytes(const)
        if nbytes < OVERSIZED_CONST_BYTES:
            continue
        yield (
            f"const #{i} ({tuple(const.shape)} {const.dtype}, "
            f"{nbytes:,} bytes) is baked into the program: a closure "
            "captured what should be an argument — weights passed as "
            "args stay donatable and don't trigger recompiles",
            {
                "const_index": i,
                "shape": list(const.shape),
                "dtype": str(const.dtype),
                "bytes": nbytes,
            },
        )


@jaxpr_rule(
    "flop-accounting-drift",
    "warning",
    doc="The analytic FLOP walk over the traced program disagrees with "
        "`ops.accounting`'s closed-form count beyond tolerance: the "
        "telemetry MFU gauge and bench.py report against the closed "
        "form, so drift here means the utilization numbers are wrong.",
)
def flop_accounting_drift(tp: TracedProgram) -> Iterator[Tuple[str, Optional[dict]]]:
    expected = tp.built.expected_flops
    if not expected:
        return
    walked = jaxpr_flops(tp.jaxpr)
    rel = abs(walked - expected) / expected
    if rel > tp.built.flop_tol:
        yield (
            f"jaxpr FLOP walk {walked:,.0f} vs ops.accounting "
            f"{expected:,.0f} ({rel:+.1%} drift, tol "
            f"{tp.built.flop_tol:.0%}): the MFU numerator has rotted — "
            "re-derive the closed form against this program",
            {
                "walked_flops": walked,
                "expected_flops": expected,
                "relative_drift": rel,
                "tolerance": tp.built.flop_tol,
            },
        )


# --- running rules over a traced program -------------------------------------


def run_jaxpr_rules(
    tp: TracedProgram,
    waivers: Optional[Dict[str, str]] = None,
    rules: Optional[Iterable[str]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Run (selected) jaxpr rules over one traced program.

    Returns ``(findings, waived)``. A waiver with an empty reason is
    converted into a `bad-waiver` error — the same mandatory-reason
    discipline as nclint's inline suppressions.
    """
    waivers = dict(waivers or {})
    path = f"jaxpr:{tp.name}"
    findings: List[Finding] = []
    waived: List[Finding] = []
    for rule_id, reason in sorted(waivers.items()):
        if not (reason or "").strip():
            findings.append(
                Finding(
                    path, 1, 0, "bad-waiver", "error",
                    f"waiver for {rule_id!r} has no reason: every waived "
                    "rule must say why the exception is safe",
                )
            )
    selected = (
        list(JAXPR_RULES.values()) if rules is None
        else [JAXPR_RULES[r] for r in rules]
    )
    for r in selected:
        for message, detail in r.fn(tp):
            f = Finding(path, 1, 0, r.rule_id, r.severity, message, detail)
            if r.rule_id in waivers and (waivers[r.rule_id] or "").strip():
                waived.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (SEVERITY_ORDER[f.severity], f.rule),
                  reverse=True)
    return findings, waived


def program_report(tp: TracedProgram) -> Dict[str, Any]:
    """Per-program audit statistics (the human table's row)."""
    n_eqns = sum(1 for _ in iter_eqns(tp.jaxpr))
    bytes_in = sum(
        _leaf_bytes(leaf) for leaves in tp.arg_leaves for leaf in leaves
    )
    flat = [leaf for leaves in tp.arg_leaves for leaf in leaves]
    bytes_donated = sum(
        _leaf_bytes(leaf)
        for leaf, flag in zip(flat, tp.donated_invars)
        if flag
    )
    bytes_out = sum(_aval_bytes(v.aval) for v in tp.jaxpr.outvars
                    if hasattr(getattr(v, "aval", None), "dtype"))
    bytes_const = sum(
        _leaf_bytes(c) for c in tp.closed.consts if hasattr(c, "dtype")
    )
    walked = jaxpr_flops(tp.jaxpr)
    report = {
        "program": tp.name,
        "eqns": n_eqns,
        "flops_walked": walked,
        "flops_expected": tp.built.expected_flops,
        "bytes_in": bytes_in,
        "bytes_out": bytes_out,
        "bytes_const": bytes_const,
        "bytes_donated": bytes_donated,
        "trace_seconds": round(tp.trace_seconds, 3),
    }
    return report


# --- the real entry-program registry -----------------------------------------


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One auditable entry program: a name, what it is, how to build it,
    and any waived rules (reason mandatory)."""

    name: str
    description: str
    build: Callable[[], BuiltProgram]
    waivers: Dict[str, str] = dataclasses.field(default_factory=dict)


#: audit-sized geometry: patch16 trunk (exact analytic FLOPs), 64x64
#: images -> 4x4 feature grid, batch 2 — every program traces in <2 s on
#: CPU, and every hazard class the rules check is shape-independent
_IMAGE_SIDE = 64
_GRID = _IMAGE_SIDE // 16
_BATCH = 2
_FEAT_CH = 256  # patch16 trunk channels (models/patch.py)


def _audit_config(**overrides):
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig

    return ImMatchNetConfig(
        feature_extraction_cnn="patch16",
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(4, 1),
        **overrides,
    )


def _audit_params(config):
    import jax

    from ncnet_tpu.models.immatchnet import init_immatchnet

    return init_immatchnet(jax.random.PRNGKey(0), config)


def _image_batch():
    rng = np.random.default_rng(0)
    img = rng.standard_normal(
        (_BATCH, _IMAGE_SIDE, _IMAGE_SIDE, 3)
    ).astype(np.float32)
    return {"source_image": img, "target_image": img.copy()}


def _feature_batch():
    rng = np.random.default_rng(0)
    feat = rng.standard_normal(
        (_BATCH, _GRID, _GRID, _FEAT_CH)
    ).astype(np.float32)
    return {"source_features": feat, "target_features": feat.copy()}


def _build_train(nc_topk=0, from_features=False, half_precision=False,
                 refine=False, corr_stream=False):
    from ncnet_tpu.ops.accounting import train_step_flops_for_batch
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    refine_overrides = (
        # coarse-to-fine geometry at audit size: 4x4 fine grid pooled by
        # 2 -> 2x2 coarse, the full 4-wide coarse band, radius 0
        {"refine_factor": 2, "refine_topk": 4} if refine else {}
    )
    stream_overrides = (
        # the default tile (128) clamps to the 16-cell audit B grid, so
        # the streamed GEMMs pad nothing and walk==form pins the streamed
        # count EQUAL to train/sparse's — streaming buys memory, not FLOPs
        {"corr_impl": "stream"} if corr_stream else {}
    )
    config = _audit_config(
        nc_topk=nc_topk, half_precision=half_precision,
        **refine_overrides, **stream_overrides,
    )
    params = _audit_params(config)
    optimizer = make_optimizer()
    state = create_train_state(params, optimizer)
    step = make_train_step(config, optimizer, from_features=from_features)
    batch = _feature_batch() if from_features else _image_batch()
    # the closed form counts contraction shapes, which are dtype-
    # independent: the bf16 programs run the SAME dots/convs as their
    # f32 twins, so walk==form is armed on both (a bf16-only extra
    # contraction — a stray promotion cast re-contracting, say — shows
    # up here as drift)
    expected = train_step_flops_for_batch(
        config, batch, from_features=from_features, trunk_trainable=False
    )
    return BuiltProgram(
        fn=step,
        args=(state, batch),
        declared_dtype="bfloat16" if half_precision else None,
        donate_expect={0: "carried TrainState (params/opt_state/step)"},
        expected_flops=expected,
    )


#: dedicated correlation->band geometry (the ``corr/*`` programs): a
#: 16x16 grid (256 cells/side) is large enough that the dense volume and
#: its rank tensors dominate the dense program's highwater — measured
#: 3.50 MiB dense vs 0.82 MiB stream (ratio 0.235), which is what the
#: streaming ratio gate (tests/test_corr_stream.py: stream <= 0.35x
#: dense) proves — while the dense program still clears the 4 MiB
#: memory-highwater budget floor (hlo_audit.MEM_HIGHWATER_ABS_FLOOR).
#: Tile 32 divides 256, so the streamed GEMM count is EXACTLY the dense
#: count (walk==form with zero padding term)
_CORR_GRID = 16
_CORR_FEAT_CH = 64
_CORR_TOPK = 12
_CORR_TILE = 32


def _build_corr(impl):
    import jax

    from ncnet_tpu.ops.accounting import corr_select_flops
    from ncnet_tpu.ops.band import topk_band
    from ncnet_tpu.ops.corr_stream import corr_stream_band
    from ncnet_tpu.ops.correlation import correlation_4d
    from ncnet_tpu.ops.matching import mutual_matching

    k = _CORR_TOPK
    if impl == "stream":

        def select(fa, fb):
            return corr_stream_band(
                fa, fb, k, mutual=True, tile=_CORR_TILE
            )

    else:

        def select(fa, fb):
            corr = correlation_4d(fa, fb)
            return topk_band(
                corr, k, values_from=mutual_matching(corr), mutual=True
            )

    rng = np.random.default_rng(0)
    shape = (_BATCH, _CORR_GRID, _CORR_GRID, _CORR_FEAT_CH)
    fa = rng.standard_normal(shape).astype(np.float32)
    fb = rng.standard_normal(shape).astype(np.float32)
    n = _CORR_GRID * _CORR_GRID
    return BuiltProgram(
        fn=jax.jit(select),
        args=(fa, fb),
        expected_flops=corr_select_flops(
            _BATCH, n, n, _CORR_FEAT_CH, corr_impl=impl,
            corr_tile=_CORR_TILE,
        ),
    )


def _build_serve():
    import jax

    from ncnet_tpu.serve.engine import (
        SERVE_DONATE_ARGNUMS,
        make_serve_match_step,
    )

    config = _audit_config()
    params = _audit_params(config)
    apply_fn = make_serve_match_step(config)
    # the same jit the engine builds in __init__ (minus the trace counter)
    fn = jax.jit(apply_fn, donate_argnums=SERVE_DONATE_ARGNUMS)
    return BuiltProgram(
        fn=fn,
        args=(params, _image_batch()),
        donate_expect={
            argnum: "single-use padded request batch"
            for argnum in SERVE_DONATE_ARGNUMS
        },
    )


def _build_refine_serve():
    import jax

    from ncnet_tpu.ops.accounting import refine_match_flops
    from ncnet_tpu.serve.engine import (
        SERVE_DONATE_ARGNUMS,
        make_serve_match_step,
    )

    # the refined quality tier (ncnet_tpu.refine): the third pre-warmed
    # program family the engine's QualityLadder dispatches to
    config = _audit_config(refine_factor=2, refine_topk=4)
    params = _audit_params(config)
    fn = jax.jit(
        make_serve_match_step(config), donate_argnums=SERVE_DONATE_ARGNUMS
    )
    return BuiltProgram(
        fn=fn,
        args=(params, _image_batch()),
        donate_expect={
            argnum: "single-use padded request batch"
            for argnum in SERVE_DONATE_ARGNUMS
        },
        expected_flops=refine_match_flops(
            _BATCH,
            config.ncons_kernel_sizes,
            config.ncons_channels,
            grid_hi=_GRID,
            factor=2,
            nc_topk=4,
            feat_ch=_FEAT_CH,
            image=_IMAGE_SIDE,
            cnn="patch16",
        ),
    )


def _build_serve_sharded():
    import jax

    from ncnet_tpu.parallel.mesh import make_batch_sharded_apply, make_mesh
    from ncnet_tpu.serve.engine import (
        SERVE_DONATE_ARGNUMS,
        make_serve_match_step,
    )

    config = _audit_config()
    params = _audit_params(config)
    # mesh over whatever devices this process has (1 in plain CI, 8 on
    # the virtual-device harness): the shard_map eqn and the donation
    # plumbing the rules check are present either way, and the batch is
    # sized to the mesh so the leading dim always divides
    mesh = make_mesh()
    fn = jax.jit(
        make_batch_sharded_apply(make_serve_match_step(config), mesh),
        donate_argnums=SERVE_DONATE_ARGNUMS,
    )
    rng = np.random.default_rng(0)
    img = rng.standard_normal(
        (mesh.size, _IMAGE_SIDE, _IMAGE_SIDE, 3)
    ).astype(np.float32)
    batch = {"source_image": img, "target_image": img.copy()}
    return BuiltProgram(
        fn=fn,
        args=(params, batch),
        donate_expect={
            argnum: "single-use padded request batch (mesh-sharded)"
            for argnum in SERVE_DONATE_ARGNUMS
        },
    )


def _build_localize():
    import jax

    from ncnet_tpu.localize.request import make_pose_apply
    from ncnet_tpu.ops.accounting import pose_ransac_flops
    from ncnet_tpu.serve.engine import SERVE_DONATE_ARGNUMS

    # audit-sized pose geometry: the smallest bucket at a degraded-rung
    # hypothesis count — every hazard the rules check is shape-blind
    n_pad, n_hyp, lo_iters = 128, 8, 2
    fn = jax.jit(
        make_pose_apply(n_hypotheses=n_hyp, lo_iters=lo_iters),
        donate_argnums=SERVE_DONATE_ARGNUMS,
    )
    rng = np.random.default_rng(0)
    rays = rng.standard_normal((_BATCH, n_pad, 3)).astype(np.float32)
    rays[:, :, 2] = np.abs(rays[:, :, 2]) + 1.0  # in front of the camera
    batch = {
        "rays": rays,
        "points": rng.standard_normal((_BATCH, n_pad, 3)).astype(
            np.float32
        ),
        "mask": np.ones((_BATCH, n_pad), bool),
        "seed": np.arange(_BATCH, dtype=np.int32),
    }
    return BuiltProgram(
        fn=fn,
        args=({}, batch),
        donate_expect={
            argnum: "single-use padded match buffer"
            for argnum in SERVE_DONATE_ARGNUMS
        },
        expected_flops=pose_ransac_flops(_BATCH, n_pad, n_hyp, lo_iters),
    )


def _build_eval_match():
    import jax

    from ncnet_tpu.eval.inloc import make_match_fn

    config = _audit_config()
    params = _audit_params(config)
    fn = jax.jit(make_match_fn(config))
    rng = np.random.default_rng(0)
    src = rng.standard_normal(
        (1, _IMAGE_SIDE, _IMAGE_SIDE, 3)
    ).astype(np.float32)
    return BuiltProgram(fn=fn, args=(params, src, src.copy()))


PROGRAMS: Dict[str, ProgramSpec] = {
    spec.name: spec
    for spec in [
        ProgramSpec(
            "train/dense",
            "dense NC training step (patch16 trunk, donated state)",
            lambda: _build_train(),
        ),
        ProgramSpec(
            "train/cached",
            "feature-cache training step (zero trunk ops)",
            lambda: _build_train(from_features=True),
        ),
        ProgramSpec(
            "train/sparse",
            "sparse-band (nc_topk) training step from cached features",
            lambda: _build_train(nc_topk=4, from_features=True),
        ),
        ProgramSpec(
            "train/sparse-stream",
            "sparse-band training step with the streamed tiled "
            "correlation (corr_impl='stream', ops/corr_stream.py)",
            lambda: _build_train(
                nc_topk=4, from_features=True, corr_stream=True
            ),
        ),
        ProgramSpec(
            "corr/dense",
            "standalone dense correlation->mutual-band selection at the "
            "16x16 corr geometry (the streaming memory baseline)",
            lambda: _build_corr("dense"),
        ),
        ProgramSpec(
            "corr/stream",
            "streamed tiled correlation->mutual-band selection — same "
            "band bitwise, highwater gated <= 0.35x corr/dense "
            "(tests/test_corr_stream.py)",
            lambda: _build_corr("stream"),
        ),
        ProgramSpec(
            "train/dense-bf16",
            "dense training step on the declared-bf16 compute path",
            lambda: _build_train(half_precision=True),
        ),
        ProgramSpec(
            "train/sparse-bf16",
            "sparse-band training step on the declared-bf16 compute path "
            "(cached features cast at the loss boundary)",
            lambda: _build_train(
                nc_topk=4, from_features=True, half_precision=True
            ),
        ),
        ProgramSpec(
            "train/refine",
            "coarse-to-fine (refine_factor) training step from cached "
            "features",
            lambda: _build_train(refine=True, from_features=True),
        ),
        ProgramSpec(
            "serve/bucket",
            "serving engine bucket program (the warmup-compiled apply)",
            _build_serve,
        ),
        ProgramSpec(
            "refine/rescore",
            "refined serving program: coarse band + high-res window "
            "rescore (the quality ladder's top rung)",
            _build_refine_serve,
        ),
        ProgramSpec(
            "serve/sharded",
            "batch-axis shard_map variant of the serving bucket program",
            _build_serve_sharded,
        ),
        ProgramSpec(
            "eval/match",
            "eval per-pair match fn (the InLoc dump's jitted forward)",
            _build_eval_match,
        ),
        ProgramSpec(
            "localize/ransac",
            "batched PnP-RANSAC pose program (the pose-bucket apply)",
            _build_localize,
        ),
    ]
}


@dataclasses.dataclass
class AuditResult:
    findings: List[Finding]
    waived: List[Finding]
    reports: List[Dict[str, Any]]
    errors: List[Finding]  # programs that failed to build/trace

    @property
    def all_findings(self) -> List[Finding]:
        return self.errors + self.findings


def audit(
    programs: Optional[Iterable[str]] = None,
    rules: Optional[Iterable[str]] = None,
    hlo: bool = False,
) -> AuditResult:
    """Build, trace, and rule-check the registered entry programs.

    A program that fails to build or trace is itself an error finding
    (``audit-trace-failure``) — the gate must not silently skip a broken
    entry point.

    With ``hlo=True`` each successfully traced program is ALSO compiled
    and the HLO-level pass (`ncnet_tpu.analysis.hlo_audit`: fusion
    fragmentation, layout churn, memory highwater) runs over the
    optimized module; its statistics merge into the same report row and
    a compile failure is an ``audit-compile-failure`` error finding.
    ``rules`` selects across BOTH registries (a selection naming only
    jaxpr rules simply runs no HLO rules).
    """
    names = list(programs) if programs is not None else sorted(PROGRAMS)
    unknown = [n for n in names if n not in PROGRAMS]
    if unknown:
        raise KeyError(f"unknown audit program(s): {unknown}")
    result = AuditResult([], [], [], [])
    for name in names:
        spec = PROGRAMS[name]
        try:
            built = spec.build()
            traced = trace_program(name, built)
        except Exception as e:  # build/trace failure IS a finding
            result.errors.append(
                Finding(
                    f"jaxpr:{name}", 1, 0, "audit-trace-failure", "error",
                    f"program failed to build/trace: {type(e).__name__}: {e}",
                )
            )
            continue
        jaxpr_rule_sel = rules
        if rules is not None:
            jaxpr_rule_sel = [r for r in rules if r in JAXPR_RULES]
        findings, waived = run_jaxpr_rules(traced, spec.waivers,
                                           jaxpr_rule_sel)
        result.findings.extend(findings)
        result.waived.extend(waived)
        report = program_report(traced)
        if hlo:
            from ncnet_tpu.analysis import hlo_audit

            try:
                hp = hlo_audit.compile_program(name, built, traced)
            except Exception as e:
                result.errors.append(
                    Finding(
                        f"hlo:{name}", 1, 0, "audit-compile-failure",
                        "error",
                        "program traced but failed to compile for the "
                        f"HLO pass: {type(e).__name__}: {e}",
                    )
                )
            else:
                hlo_rule_sel = None
                if rules is not None:
                    hlo_rule_sel = [
                        r for r in rules if r in hlo_audit.HLO_RULES
                    ]
                hfindings, hwaived = hlo_audit.run_hlo_rules(
                    hp, spec.waivers, hlo_rule_sel
                )
                result.findings.extend(hfindings)
                result.waived.extend(hwaived)
                report.update(hlo_audit.hlo_report(hp))
        result.reports.append(report)
    return result


def rules_meta() -> Dict[str, dict]:
    """{rule_id: {severity, doc}} for SARIF emission / --list-rules,
    including the HLO pass's rules and the engine-level pseudo-rules."""
    from ncnet_tpu.analysis.hlo_audit import hlo_rules_meta

    meta = {
        r.rule_id: {"severity": r.severity, "doc": r.doc}
        for r in JAXPR_RULES.values()
    }
    meta.update(hlo_rules_meta())
    meta["bad-waiver"] = {
        "severity": "error",
        "doc": "a ProgramSpec waiver without a reason: every waived rule "
               "must say why the exception is safe",
    }
    meta["audit-trace-failure"] = {
        "severity": "error",
        "doc": "a registered entry program failed to build or trace",
    }
    meta["audit-compile-failure"] = {
        "severity": "error",
        "doc": "a registered entry program traced but failed to compile "
               "for the HLO-level pass",
    }
    return meta


def format_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:,.0f} {unit}" if unit == "B" else f"{n:,.1f} {unit}"
        n /= 1024.0
    return f"{n:,.1f} GiB"


def format_flops(n: Optional[float]) -> str:
    if n is None:
        return "-"
    if n >= 1e9:
        return f"{n / 1e9:,.2f} G"
    if n >= 1e6:
        return f"{n / 1e6:,.2f} M"
    return f"{n:,.0f}"


def format_report_table(reports: List[Dict[str, Any]]) -> str:
    """The telemetry_report-style human table over per-program stats.

    When the HLO pass ran (``audit(hlo=True)``), its per-program columns
    — entry-computation fusion count, un-fused transpose/copy churn, and
    the buffer-liveness memory-highwater estimate — extend the table.
    """
    with_hlo = any("hlo_fusions" in r for r in reports)
    headers = [
        "program", "eqns", "flops(walk)", "flops(form)", "in",
        "donated", "out", "const", "trace s",
    ]
    if with_hlo:
        headers += ["fusions", "churn", "mem(hw)", "compile s"]
    rows = []
    for r in reports:
        row = [
            r["program"],
            str(r["eqns"]),
            format_flops(r["flops_walked"]),
            format_flops(r["flops_expected"]),
            format_bytes(r["bytes_in"]),
            format_bytes(r["bytes_donated"]),
            format_bytes(r["bytes_out"]),
            format_bytes(r["bytes_const"]),
            f"{r['trace_seconds']:.2f}",
        ]
        if with_hlo:
            if "hlo_fusions" in r:
                row += [
                    str(r["hlo_fusions"]),
                    str(r["hlo_churn"]),
                    format_bytes(r["mem_highwater_est"]),
                    f"{r['compile_seconds']:.2f}",
                ]
            else:
                row += ["-", "-", "-", "-"]
        rows.append(row)
    widths = [
        max(len(h), *(len(row[i]) for row in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip(),
        "  ".join("-" * w for w in widths),
    ]
    for row in rows:
        lines.append(
            "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        )
    return "\n".join(lines)
