"""Host-side input pipeline: pair datasets, image IO, prefetching."""
