"""Batching loader over a populated :class:`FeatureStore`.

Feature batches ride the SAME machinery as image batches: the store is
adapted into an indexable dataset (`FeaturePairDataset`) and batched by
``ncnet_tpu.data.loader.DataLoader``, so per-sample retry/backoff, the
bounded skip budget, worker backends, per-host sharding, deterministic
absolute-epoch shuffling (``iter_epoch``) and mid-epoch resume all apply
unchanged — a training run resumed from a cursor replays the identical
batch sequence whether it reads images or cached features.

HBM pinning (``pin_hbm=True``): when the whole feature set fits on
device (PF-Pascal train is ~7.6 GB in bf16 against a 16 GB v5e), the
stacked ``[N, h, w, c]`` source/target arrays are device_put ONCE and
every epoch's batches become device-side gathers — zero host decode,
zero H2D traffic on the steady-state step. The fit is checked against
the device's reported memory when available; an over-budget pin raises
instead of OOMing mid-epoch.
"""

import numpy as np

from ncnet_tpu.data.loader import DataLoader


class FeaturePairDataset:
    """A populated feature store as an indexable pair dataset (the shape
    ``ncnet_tpu.data.loader`` batches): shards are digest-verified at
    read, so bitrot surfaces as the loader's retry/skip machinery."""

    def __init__(self, store):
        self.store = store

    def __len__(self):
        return self.store.num_items

    def __getitem__(self, idx):
        src, tgt = self.store.get(int(idx))
        return {"source_features": src, "target_features": tgt}


class FeatureBatchLoader:
    """DataLoader-compatible loader yielding feature batches.

    Exposes the loader surface ``train/loop.py`` drives: ``__len__``,
    ``iter_epoch(epoch, skip_batches)``, ``__iter__``, ``seed``,
    ``close()`` and context management.
    """

    def __init__(
        self,
        store,
        batch_size,
        shuffle=False,
        seed=0,
        num_workers=2,
        drop_last=False,
        prefetch=4,
        host_id=0,
        n_hosts=1,
        backend="thread",
        sample_retries=2,
        retry_backoff=0.05,
        skip_budget=0,
        pin_hbm=False,
        hbm_fit_fraction=0.6,
    ):
        if not store.complete():
            raise ValueError(
                f"feature store at {store.root} is missing "
                f"{len(store.missing())} of {store.num_items} pairs; "
                "populate it first (scripts/extract_features.py or the "
                "train-time lazy fill)"
            )
        self.store = store
        self.seed = seed
        self.batch_size = batch_size
        self.pin_hbm = pin_hbm
        self.hbm_fit_fraction = hbm_fit_fraction
        self._pinned = None
        self._epoch = 0
        self._dl = DataLoader(
            FeaturePairDataset(store),
            batch_size,
            shuffle=shuffle,
            seed=seed,
            num_workers=num_workers,
            drop_last=drop_last,
            prefetch=prefetch,
            host_id=host_id,
            n_hosts=n_hosts,
            backend=backend,
            sample_retries=sample_retries,
            retry_backoff=retry_backoff,
            skip_budget=skip_budget,
        )

    def __len__(self):
        return len(self._dl)

    def close(self):
        self._dl.close()
        self._pinned = None  # release the device references too

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __iter__(self):
        it = self.iter_epoch(self._epoch)
        self._epoch += 1
        return it

    def iter_epoch(self, epoch, skip_batches=0):
        """Batches of ABSOLUTE ``epoch`` — the identical index sequence
        (shuffle, host shard, drop_last) as an image DataLoader with the
        same parameters, so cursor resume and loss trajectories line up
        across the image and feature paths."""
        if not self.pin_hbm:
            return self._dl.iter_epoch(epoch, skip_batches=skip_batches)
        return self._iter_pinned(epoch, skip_batches)

    # -- whole-set device pinning -------------------------------------------

    def _ensure_pinned(self):
        if self._pinned is not None:
            return self._pinned
        import jax
        import jax.numpy as jnp

        n = self.store.num_items
        nbytes = n * self.store.shard_nbytes(0)
        stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
        limit = (stats or {}).get("bytes_limit")
        if limit and nbytes > self.hbm_fit_fraction * limit:
            raise ValueError(
                f"pin_hbm: feature set is ~{nbytes / 1e9:.1f} GB but the "
                f"device reports {limit / 1e9:.1f} GB (budget "
                f"{self.hbm_fit_fraction:.0%}); run without pinning"
            )
        src = np.stack([self.store.get(i)[0] for i in range(n)])
        tgt = np.stack([self.store.get(i)[1] for i in range(n)])
        self._pinned = (jnp.asarray(src), jnp.asarray(tgt))
        return self._pinned

    def _iter_pinned(self, epoch, skip_batches):
        src, tgt = self._ensure_pinned()
        # the DataLoader's OWN index plan (shuffle + shard + drop_last),
        # so pinned and unpinned epochs are batch-for-batch identical
        batches = self._dl._epoch_batches(epoch)[skip_batches:]
        for idx in batches:
            gather = np.asarray(idx)
            yield {
                "source_features": src[gather],
                "target_features": tgt[gather],
            }
