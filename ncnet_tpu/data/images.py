"""Host-side image decode / resize / normalize.

All host-side preprocessing is numpy (the device never sees raw images):
decode with PIL, bilinear align-corners resize (parity with the reference's
identity-affine grid_sample resize, lib/transformation.py:41-63), ImageNet
normalization. A C++ fast path for the resize (native/resize.cpp, built by
native/build.sh) is loaded via ctypes when present and falls back to numpy
otherwise (`ncnet_tpu.data.native`).
"""

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def load_image(path):
    """Decode an image file -> float32 RGB [h, w, 3] in 0..255.

    Grayscale images are stacked to 3 channels (reference
    lib/im_pair_dataset.py:64-65).
    """
    from PIL import Image

    with Image.open(path) as im:
        arr = np.asarray(im)
    if arr.ndim == 2:
        arr = np.stack([arr] * 3, axis=-1)
    if arr.shape[-1] == 4:
        arr = arr[..., :3]
    return arr.astype(np.float32)


def resize_bilinear_np(image, out_h, out_w):
    """Align-corners bilinear resize, numpy, channels-last [h, w, c]."""
    try:
        from ncnet_tpu.data.native import resize_bilinear_native

        out = resize_bilinear_native(image, out_h, out_w)
        if out is not None:
            return out
    except ImportError:
        pass
    h, w = image.shape[:2]
    if (h, w) == (out_h, out_w):
        return image.astype(np.float32)

    def axis_coords(n_in, n_out):
        if n_out == 1:
            return np.zeros(1), np.zeros(1, np.int64), np.zeros(1, np.int64)
        pos = np.linspace(0.0, n_in - 1.0, n_out)
        lo = np.floor(pos).astype(np.int64)
        hi = np.minimum(lo + 1, n_in - 1)
        return pos - lo, lo, hi

    fy, y0, y1 = axis_coords(h, out_h)
    fx, x0, x1 = axis_coords(w, out_w)
    img = image.astype(np.float32)
    top = img[y0] * (1 - fy)[:, None, None] + img[y1] * fy[:, None, None]
    out = (
        top[:, x0] * (1 - fx)[None, :, None]
        + top[:, x1] * fx[None, :, None]
    )
    return out


def to_uint8_image(image):
    """Rounded uint8 of a [0, 255]-range float image — the wire format of
    the device-preprocess paths (train's ``uint8_output`` loader option
    and eval's ``device_normalize``); one definition so train and eval
    quantization can never diverge."""
    return np.rint(np.clip(image, 0.0, 255.0)).astype(np.uint8)


def normalize_image_np(image):
    """0..255 float RGB -> ImageNet-normalized (in place when possible)."""
    return (image / 255.0 - IMAGENET_MEAN) / IMAGENET_STD


def preprocess(path, out_h, out_w):
    """decode -> resize -> normalize. Returns ([h,w,3] float32, orig (h,w))."""
    img = load_image(path)
    orig = img.shape[:2]
    img = resize_bilinear_np(img, out_h, out_w)
    return normalize_image_np(img), orig
