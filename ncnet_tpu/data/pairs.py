"""CSV-driven image-pair datasets (reference schemas, SURVEY.md §2.5).

* Training pairs (`ImagePairDataset`, lib/im_pair_dataset.py:11-93):
  ``source_image,target_image,class,flip`` — weak supervision, optional
  horizontal flip per row, resize to a square training size.
* PF-Pascal eval pairs (`PFPascalDataset`, lib/pf_dataset.py:11-112):
  adds semicolon-separated keypoint columns ``XA;YA;XB;YB`` (up to 20
  points, -1-padded) and the PCK reference length per the 'pf' (max GT
  bbox side) or 'scnet' (rescale to 224) procedure.

Datasets are plain indexable objects returning numpy dicts; batching /
prefetching lives in `ncnet_tpu.data.loader`.
"""

import os

import numpy as np

from ncnet_tpu.data.images import (
    load_image,
    normalize_image_np,
    resize_bilinear_np,
    to_uint8_image,
)

PF_PASCAL_CATEGORIES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)

MAX_KEYPOINTS = 20


def _read_csv(path):
    import csv

    with open(path, newline="") as f:
        rows = list(csv.reader(f))
    return rows[0], rows[1:]


class ImagePairDataset:
    """Weak-supervision training pairs."""

    def __init__(
        self,
        csv_file,
        dataset_path,
        output_size=(400, 400),
        random_crop=False,
        normalize=True,
        seed=0,
        uint8_output=False,
    ):
        """``uint8_output=True`` returns resized images as uint8 WITHOUT
        normalization — 4x less host->device traffic; the train step
        ImageNet-normalizes uint8 batches on device (train/loss.py).
        Numerics differ from the host path only by uint8 rounding of the
        resized pixels."""
        if uint8_output and normalize:
            normalize = False
        self.header, self.rows = _read_csv(csv_file)
        self.dataset_path = dataset_path
        self.out_h, self.out_w = output_size
        self.random_crop = random_crop
        self.normalize = normalize
        self.uint8_output = uint8_output
        self.seed = seed

    def __len__(self):
        return len(self.rows)

    def _load(self, name, flip, crop_rng):
        img = load_image(os.path.join(self.dataset_path, name))
        if crop_rng is not None:
            # reference crop (lib/im_pair_dataset.py:68-74): corners anchored
            # in the outer quarters, so the window is always >= half size
            h, w = img.shape[:2]
            top = crop_rng.randint(max(h // 4, 1))
            bottom = int(3 * h / 4 + crop_rng.randint(max(h // 4, 1)))
            left = crop_rng.randint(max(w // 4, 1))
            right = int(3 * w / 4 + crop_rng.randint(max(w // 4, 1)))
            img = img[top:bottom, left:right]
        if flip:
            img = img[:, ::-1]
        img = resize_bilinear_np(img, self.out_h, self.out_w)
        if self.uint8_output:
            return to_uint8_image(img)
        if self.normalize:
            img = normalize_image_np(img)
        return img

    def __getitem__(self, idx):
        row = self.rows[idx]
        name_a, name_b = row[0], row[1]
        flip = bool(int(float(row[3]))) if len(row) > 3 else False
        # per-sample RNG derived from (seed, idx): thread-safe and identical
        # for any worker count (the invariant data/loader.py relies on)
        crop_rng = (
            np.random.RandomState((self.seed * 100003 + idx) % (2**31))
            if self.random_crop
            else None
        )
        return {
            "source_image": self._load(name_a, flip, crop_rng),
            "target_image": self._load(name_b, flip, crop_rng),
            "set_class": np.float32(float(row[2])) if len(row) > 2 else np.float32(0),
        }


class PFPascalDataset:
    """PF-Pascal keypoint-annotated eval pairs."""

    def __init__(
        self,
        csv_file,
        dataset_path,
        output_size=(400, 400),
        category=None,
        pck_procedure="scnet",
        normalize=True,
    ):
        self.header, rows = _read_csv(csv_file)
        if category is not None:
            rows = [r for r in rows if int(float(r[2])) == int(category)]
        self.rows = rows
        self.dataset_path = dataset_path
        self.out_h, self.out_w = output_size
        self.pck_procedure = pck_procedure
        self.normalize = normalize

    def __len__(self):
        return len(self.rows)

    @staticmethod
    def _points(xs, ys):
        x = np.fromstring(xs, sep=";")
        y = np.fromstring(ys, sep=";")
        pts = -np.ones((2, MAX_KEYPOINTS), np.float32)
        pts[0, : len(x)] = x
        pts[1, : len(y)] = y
        return pts

    def __getitem__(self, idx):
        row = self.rows[idx]
        img_a = load_image(os.path.join(self.dataset_path, row[0]))
        img_b = load_image(os.path.join(self.dataset_path, row[1]))
        size_a = np.asarray(img_a.shape, np.float32)
        size_b = np.asarray(img_b.shape, np.float32)
        pts_a = self._points(row[3], row[4])
        pts_b = self._points(row[5], row[6])
        n_pts = int(np.sum(pts_a[0] != -1))

        if self.pck_procedure == "pf":
            l_pck = np.float32(
                np.max(
                    pts_a[:, :n_pts].max(axis=1) - pts_a[:, :n_pts].min(axis=1)
                )
            )
        elif self.pck_procedure == "scnet":
            # SCNet protocol (lib/pf_dataset.py:66-75): rescale points as if
            # images were 224x224; L_pck = 224.
            pts_a[0, :n_pts] *= 224 / size_a[1]
            pts_a[1, :n_pts] *= 224 / size_a[0]
            pts_b[0, :n_pts] *= 224 / size_b[1]
            pts_b[1, :n_pts] *= 224 / size_b[0]
            size_a[0:2] = 224
            size_b[0:2] = 224
            l_pck = np.float32(224.0)
        else:
            raise ValueError(f"unknown pck procedure {self.pck_procedure!r}")

        def prep(img):
            img = resize_bilinear_np(img, self.out_h, self.out_w)
            return normalize_image_np(img) if self.normalize else img

        return {
            "source_image": prep(img_a),
            "target_image": prep(img_b),
            "source_im_size": size_a[:3],
            "target_im_size": size_b[:3],
            "source_points": pts_a,
            "target_points": pts_b,
            "L_pck": np.asarray([l_pck], np.float32),
        }


class SyntheticPairDataset:
    """Synthetic stand-in when no image data is on disk (CI, benchmarks).

    Target = source warped by a random horizontal roll, so trained models
    have real (cyclic-translation) structure to learn — and a KNOWN dense
    correspondence: source pixel (x, y) appears at target (x + shift mod W,
    y), which `eval.synthetic` uses for a PCK-style transfer metric.
    """

    def __init__(self, n=256, output_size=(400, 400), seed=0,
                 return_shift=False, granularity=8):
        """``granularity``: pixel scale of the noise texture (base noise is
        upsampled by this factor). 8 is the training default; coarser
        textures (e.g. 32) keep patch correlation high under sub-cell
        (non-stride-aligned) shifts — used by the demo figure where a
        CONSTRUCTED (untrained) model must resolve arbitrary shifts."""
        self.n = n
        self.out_h, self.out_w = output_size
        self.seed = seed
        self.return_shift = return_shift
        self.granularity = granularity

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed * 100003 + idx)
        # clamp so tiny output sizes still get a >=1-cell base texture
        g = min(self.granularity, self.out_h, self.out_w)
        base = rng.rand(self.out_h // g, self.out_w // g, 3).astype(np.float32)
        img = resize_bilinear_np(base * 255.0, self.out_h, self.out_w)
        shift = rng.randint(0, self.out_w // 2)
        tgt = np.roll(img, shift, axis=1)
        out = {
            "source_image": normalize_image_np(img),
            "target_image": normalize_image_np(tgt),
            "set_class": np.float32(0),
        }
        if self.return_shift:
            out["shift"] = np.float32(shift)
        return out
