"""Batching + prefetching loader.

Replaces the reference's vendored fork of the PyTorch-0.3 DataLoader
(lib/dataloader.py:1-316, SURVEY.md §2 item 20). Design differences,
TPU-host-first:

* two worker backends behind one API: worker THREADS with a bounded
  prefetch window (at most ``prefetch + num_workers`` batches in flight
  or buffered), and worker PROCESSES (``backend='process'``) for
  multi-core hosts where the GIL would cap the rate. Measured
  (benchmarks/micro_loader.py, PERF.md): one image costs ~14.6 ms of
  host CPU (decode 1.4 + resize 10.7 + normalize 2.5), so a core
  sustains ~68 images/s and the loader ~45 after collate/queue overhead
  — this container exposes ONE core, so that is its ceiling under
  either backend (the process pool only adds IPC there). It covers the
  PF-Pascal device rate (34.9 images/s at 17.4 pairs/s); the IVD
  config's ~240 images/s needs ~5+ cores with the process backend —
  trivial on real TPU hosts (v5e hosts expose >100 vCPUs). The pool is
  spawn-context (fork after jax import can deadlock) with the dataset
  shipped once per worker at startup, not per task;
* the reference's one fix over stock torch — per-worker numpy RNG reseeding
  so augmentation isn't duplicated (lib/dataloader.py:39-43) — is preserved
  by construction: sample RNG is derived from the sample index, so results
  are identical regardless of worker count AND backend;
* deterministic epoch shuffling from a seed, addressable by ABSOLUTE epoch
  (`iter_epoch`) so a mid-epoch resume replays the exact batch sequence;
* graceful degradation (production fleets see bitrot and flaky NFS):
  per-sample retry with exponential backoff, then — within a bounded
  ``skip_budget`` — a deterministic substitute sample instead of killing
  the epoch; exceeding the budget still fails loudly;
* per-host sharding for multi-host data parallelism;
* a context manager (``with DataLoader(...) as dl:``) so the process
  pool is shut down on every exit path, including SIGTERM preemption.
"""

import queue
import threading
import time
import traceback

import numpy as np

from ncnet_tpu.resilience import faultinject

# process-backend worker state: the dataset object, delivered once via the
# pool initializer (pickling it per task would dominate small-task cost)
_WORKER_DATASET = None


def _process_worker_init(dataset):
    global _WORKER_DATASET
    _WORKER_DATASET = dataset


def _process_build_batch(indices, retries, backoff, skip_budget):
    return build_batch(_WORKER_DATASET, indices, retries, backoff, skip_budget)


def collate(samples):
    """Stack a list of numpy dicts into a batched dict."""
    out = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        out[key] = np.stack(vals).astype(vals[0].dtype, copy=False)
    return out


def retry_call(fn, retries=0, backoff=0.05):
    """Call ``fn()`` with per-attempt retry + exponential backoff
    (transient I/O: flaky NFS, racing downloads). The LAST failure
    propagates. The retry primitive under `_load_sample` here and under
    the serving engine's host prep (`ncnet_tpu.serve.engine`)."""
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception:
            if attempt == retries:
                raise
            time.sleep(backoff * (2 ** attempt))


def _load_sample(dataset, idx, retries, backoff):
    """One sample with per-attempt retry (see `retry_call`)."""
    return retry_call(lambda: dataset[int(idx)], retries, backoff)


def build_batch(dataset, indices, retries=0, backoff=0.05, skip_budget=0):
    """Collate ``dataset[indices]`` with retry + bounded substitution.

    A sample that still fails after ``retries`` extra attempts is skipped
    and replaced by the next loadable index (deterministic — depends only
    on the failing index, so batches are identical for any worker count or
    backend). Returns ``(batch, skipped)`` where ``skipped`` lists the
    indices abandoned; at most ``skip_budget`` substitutions happen per
    call before the original exception propagates. Shapes stay constant
    under substitution, so jitted steps do not recompile.
    """
    faultinject.fire("data.batch")
    samples, skipped = [], []
    for idx in indices:
        cur = int(idx)
        while True:
            try:
                samples.append(_load_sample(dataset, cur, retries, backoff))
                break
            except Exception:
                skipped.append(cur)
                if len(skipped) > skip_budget:
                    raise
                print(
                    f"[loader] skipping corrupt sample {cur} "
                    f"(substituting {(cur + 1) % len(dataset)}; "
                    f"{len(skipped)} skipped so far)",
                    flush=True,
                )
                cur = (cur + 1) % len(dataset)
    return collate(samples), skipped


def shard_indices(n, host_id, n_hosts):
    """Contiguous per-host shard of dataset indices.

    Shards are EQUAL-SIZED (the remainder ``n % n_hosts`` is dropped):
    unequal shards give hosts different batch counts, and in multi-host
    training the host with the extra batch blocks forever in its step's
    collective while the others have finished the epoch.
    """
    per = n // n_hosts
    start = host_id * per
    return np.arange(start, start + per)


class DataLoader:
    def __init__(
        self,
        dataset,
        batch_size,
        shuffle=False,
        seed=0,
        num_workers=4,
        drop_last=False,
        prefetch=4,
        host_id=0,
        n_hosts=1,
        backend="thread",
        sample_retries=2,
        retry_backoff=0.05,
        skip_budget=0,
    ):
        """``sample_retries``/``retry_backoff``: extra per-sample attempts
        for transient failures. ``skip_budget``: total corrupt samples this
        loader may substitute (deterministically, shape-preserving) over
        its lifetime before failing loudly; 0 keeps strict
        fail-on-first-error semantics."""
        if backend not in ("thread", "process"):
            raise ValueError(f"unknown loader backend {backend!r}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.drop_last = drop_last
        self.prefetch = prefetch
        self.indices = shard_indices(len(dataset), host_id, n_hosts)
        self.epoch = 0
        self.backend = backend
        self.sample_retries = sample_retries
        self.retry_backoff = retry_backoff
        self.skip_budget = skip_budget
        self.skipped = []  # indices substituted so far (loader lifetime)
        self._pool = None

    def _process_pool(self):
        # lazily created, reused across epochs (spawn startup is ~1 s)
        if self._pool is None:
            import concurrent.futures
            import multiprocessing

            self._pool = concurrent.futures.ProcessPoolExecutor(
                self.num_workers,
                mp_context=multiprocessing.get_context("spawn"),
                initializer=_process_worker_init,
                initargs=(self.dataset,),
            )
        return self._pool

    def close(self):
        """Shut the worker pool down (idempotent). The training path runs
        loaders as context managers so preemption can't leak spawn
        processes."""
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __len__(self):
        n = len(self.indices)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _epoch_indices(self, epoch):
        idx = self.indices.copy()
        if self.shuffle:
            np.random.RandomState(self.seed + epoch).shuffle(idx)
        return idx

    def _epoch_batches(self, epoch):
        idx = self._epoch_indices(epoch)
        batches = [
            idx[i : i + self.batch_size]
            for i in range(0, len(idx), self.batch_size)
        ]
        if self.drop_last and batches and len(batches[-1]) < self.batch_size:
            batches.pop()
        return batches

    def __iter__(self):
        """Legacy auto-advancing iteration: epoch 0, 1, 2, ... per call.
        Resumable training drives `iter_epoch` with the absolute epoch
        instead, so the shuffle does not depend on iterator call count."""
        it = self.iter_epoch(self.epoch)
        self.epoch += 1
        return it

    def iter_epoch(self, epoch, skip_batches=0):
        """Iterate the batches of ABSOLUTE ``epoch``, optionally skipping
        the first ``skip_batches`` (mid-epoch resume: the skipped batches
        are never constructed, so resume costs no wasted decode work)."""
        batches = self._epoch_batches(epoch)[skip_batches:]
        if self.backend == "process":
            return self._iter_process(batches)
        return self._iter_thread(batches)

    def _account_skips(self, skipped, cause=None):
        """Lifetime skip-budget accounting; loud failure past the budget."""
        if not skipped:
            return
        self.skipped.extend(skipped)
        if len(self.skipped) > self.skip_budget:
            raise RuntimeError(
                f"corrupt-sample skip budget exhausted: "
                f"{len(self.skipped)} samples skipped "
                f"(budget {self.skip_budget}); first failures: "
                f"{self.skipped[:8]}"
            ) from cause

    def _iter_process(self, batches):
        import collections

        pool = self._process_pool()
        window = self.prefetch + self.num_workers
        futs = collections.deque()
        bi = 0
        while bi < len(batches) or futs:
            while bi < len(batches) and len(futs) < window:
                futs.append(
                    pool.submit(
                        _process_build_batch,
                        batches[bi],
                        self.sample_retries,
                        self.retry_backoff,
                        self.skip_budget,
                    )
                )
                bi += 1
            # same error contract as the thread backend: wrap the worker
            # exception (its remote traceback rides along as __cause__).
            # An abandoned iterator leaves at most `window` futures to
            # drain quietly in the reused pool.
            # Exception, not BaseException: a KeyboardInterrupt here hits
            # the MAIN thread mid-wait and must keep its own semantics;
            # worker failures always arrive as Exception via the future
            try:
                batch, skipped = futs.popleft().result()
            except Exception as e:
                raise RuntimeError(
                    f"data worker failed on batch construction: {e!r}"
                ) from e
            self._account_skips(skipped)
            yield batch

    def _iter_thread(self, batches):
        task_q = queue.Queue()
        for bi, b in enumerate(batches):
            task_q.put((bi, b))
        results = {}
        lock = threading.Lock()
        stop = threading.Event()
        # Bounds host memory: each in-flight or completed-but-unconsumed
        # batch holds one permit; the consumer releases a permit per yield.
        # Workers pull tasks in order, so the oldest unconsumed batch is
        # always either buffered or in flight — no deadlock.
        inflight = threading.Semaphore(self.prefetch + self.num_workers)

        # First worker exception (with its full traceback) — surfaced to
        # the consumer promptly instead of a late generic error.
        error = []
        error_event = threading.Event()

        def worker():
            while not stop.is_set():
                if not inflight.acquire(timeout=0.1):
                    continue  # re-check stop while waiting for a permit
                try:
                    bi, b = task_q.get_nowait()
                except queue.Empty:
                    inflight.release()
                    return
                try:
                    batch = build_batch(
                        self.dataset, b, self.sample_retries,
                        self.retry_backoff, self.skip_budget,
                    )
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    with lock:
                        if not error:
                            error.append((e, traceback.format_exc()))
                    error_event.set()
                    stop.set()
                    return
                with lock:
                    results[bi] = batch
        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.num_workers)
        ]
        for t in threads:
            t.start()

        def raise_worker_error():
            exc, tb = error[0]
            raise RuntimeError(
                f"data worker failed on batch construction:\n{tb}"
            ) from exc

        try:
            next_bi = 0
            while next_bi < len(batches):
                if error_event.is_set():
                    raise_worker_error()
                with lock:
                    batch = results.pop(next_bi, None)
                if batch is None:
                    if not any(t.is_alive() for t in threads):
                        with lock:
                            batch = results.pop(next_bi, None)
                        if batch is None:
                            if error_event.is_set():
                                raise_worker_error()
                            raise RuntimeError(
                                "data workers exited before producing batch "
                                f"{next_bi}/{len(batches)}"
                            )
                    else:
                        time.sleep(0.002)
                        continue
                batch, skipped = batch
                self._account_skips(skipped)
                yield batch
                inflight.release()
                next_bi += 1
        finally:
            stop.set()
