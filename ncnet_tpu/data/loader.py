"""Batching + prefetching loader.

Replaces the reference's vendored fork of the PyTorch-0.3 DataLoader
(lib/dataloader.py:1-316, SURVEY.md §2 item 20). Design differences,
TPU-host-first:

* two worker backends behind one API: worker THREADS with a bounded
  prefetch window (at most ``prefetch + num_workers`` batches in flight
  or buffered), and worker PROCESSES (``backend='process'``) for
  multi-core hosts where the GIL would cap the rate. Measured
  (benchmarks/micro_loader.py, PERF.md): one image costs ~14.6 ms of
  host CPU (decode 1.4 + resize 10.7 + normalize 2.5), so a core
  sustains ~68 images/s and the loader ~45 after collate/queue overhead
  — this container exposes ONE core, so that is its ceiling under
  either backend (the process pool only adds IPC there). It covers the
  PF-Pascal device rate (34.9 images/s at 17.4 pairs/s); the IVD
  config's ~240 images/s needs ~5+ cores with the process backend —
  trivial on real TPU hosts (v5e hosts expose >100 vCPUs). The pool is
  spawn-context (fork after jax import can deadlock) with the dataset
  shipped once per worker at startup, not per task;
* the reference's one fix over stock torch — per-worker numpy RNG reseeding
  so augmentation isn't duplicated (lib/dataloader.py:39-43) — is preserved
  by construction: sample RNG is derived from the sample index, so results
  are identical regardless of worker count AND backend;
* deterministic epoch shuffling from a seed;
* per-host sharding for multi-host data parallelism.
"""

import queue
import threading
import time
import traceback

import numpy as np

# process-backend worker state: the dataset object, delivered once via the
# pool initializer (pickling it per task would dominate small-task cost)
_WORKER_DATASET = None


def _process_worker_init(dataset):
    global _WORKER_DATASET
    _WORKER_DATASET = dataset


def _process_build_batch(indices):
    ds = _WORKER_DATASET
    return collate([ds[int(i)] for i in indices])


def collate(samples):
    """Stack a list of numpy dicts into a batched dict."""
    out = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        out[key] = np.stack(vals).astype(vals[0].dtype, copy=False)
    return out


def shard_indices(n, host_id, n_hosts):
    """Contiguous per-host shard of dataset indices.

    Shards are EQUAL-SIZED (the remainder ``n % n_hosts`` is dropped):
    unequal shards give hosts different batch counts, and in multi-host
    training the host with the extra batch blocks forever in its step's
    collective while the others have finished the epoch.
    """
    per = n // n_hosts
    start = host_id * per
    return np.arange(start, start + per)


class DataLoader:
    def __init__(
        self,
        dataset,
        batch_size,
        shuffle=False,
        seed=0,
        num_workers=4,
        drop_last=False,
        prefetch=4,
        host_id=0,
        n_hosts=1,
        backend="thread",
    ):
        if backend not in ("thread", "process"):
            raise ValueError(f"unknown loader backend {backend!r}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.drop_last = drop_last
        self.prefetch = prefetch
        self.indices = shard_indices(len(dataset), host_id, n_hosts)
        self.epoch = 0
        self.backend = backend
        self._pool = None

    def _process_pool(self):
        # lazily created, reused across epochs (spawn startup is ~1 s)
        if self._pool is None:
            import concurrent.futures
            import multiprocessing

            self._pool = concurrent.futures.ProcessPoolExecutor(
                self.num_workers,
                mp_context=multiprocessing.get_context("spawn"),
                initializer=_process_worker_init,
                initargs=(self.dataset,),
            )
        return self._pool

    def close(self):
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None

    def __len__(self):
        n = len(self.indices)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _epoch_indices(self):
        idx = self.indices.copy()
        if self.shuffle:
            np.random.RandomState(self.seed + self.epoch).shuffle(idx)
        return idx

    def __iter__(self):
        idx = self._epoch_indices()
        self.epoch += 1
        batches = [
            idx[i : i + self.batch_size]
            for i in range(0, len(idx), self.batch_size)
        ]
        if self.drop_last and batches and len(batches[-1]) < self.batch_size:
            batches.pop()
        if self.backend == "process":
            return self._iter_process(batches)
        return self._iter_thread(batches)

    def _iter_process(self, batches):
        import collections

        pool = self._process_pool()
        window = self.prefetch + self.num_workers
        futs = collections.deque()
        bi = 0
        while bi < len(batches) or futs:
            while bi < len(batches) and len(futs) < window:
                futs.append(pool.submit(_process_build_batch, batches[bi]))
                bi += 1
            # same error contract as the thread backend: wrap the worker
            # exception (its remote traceback rides along as __cause__).
            # An abandoned iterator leaves at most `window` futures to
            # drain quietly in the reused pool.
            # Exception, not BaseException: a KeyboardInterrupt here hits
            # the MAIN thread mid-wait and must keep its own semantics;
            # worker failures always arrive as Exception via the future
            try:
                batch = futs.popleft().result()
            except Exception as e:
                raise RuntimeError(
                    f"data worker failed on batch construction: {e!r}"
                ) from e
            yield batch

    def _iter_thread(self, batches):
        task_q = queue.Queue()
        for bi, b in enumerate(batches):
            task_q.put((bi, b))
        results = {}
        lock = threading.Lock()
        stop = threading.Event()
        # Bounds host memory: each in-flight or completed-but-unconsumed
        # batch holds one permit; the consumer releases a permit per yield.
        # Workers pull tasks in order, so the oldest unconsumed batch is
        # always either buffered or in flight — no deadlock.
        inflight = threading.Semaphore(self.prefetch + self.num_workers)

        # First worker exception (with its full traceback) — surfaced to
        # the consumer promptly instead of a late generic error.
        error = []
        error_event = threading.Event()

        def worker():
            while not stop.is_set():
                if not inflight.acquire(timeout=0.1):
                    continue  # re-check stop while waiting for a permit
                try:
                    bi, b = task_q.get_nowait()
                except queue.Empty:
                    inflight.release()
                    return
                try:
                    batch = collate([self.dataset[int(i)] for i in b])
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    with lock:
                        if not error:
                            error.append((e, traceback.format_exc()))
                    error_event.set()
                    stop.set()
                    return
                with lock:
                    results[bi] = batch

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.num_workers)
        ]
        for t in threads:
            t.start()

        def raise_worker_error():
            exc, tb = error[0]
            raise RuntimeError(
                f"data worker failed on batch construction:\n{tb}"
            ) from exc

        try:
            next_bi = 0
            while next_bi < len(batches):
                if error_event.is_set():
                    raise_worker_error()
                with lock:
                    batch = results.pop(next_bi, None)
                if batch is None:
                    if not any(t.is_alive() for t in threads):
                        with lock:
                            batch = results.pop(next_bi, None)
                        if batch is None:
                            if error_event.is_set():
                                raise_worker_error()
                            raise RuntimeError(
                                "data workers exited before producing batch "
                                f"{next_bi}/{len(batches)}"
                            )
                    else:
                        time.sleep(0.002)
                        continue
                yield batch
                inflight.release()
                next_bi += 1
        finally:
            stop.set()
