"""ctypes loader for the host-side C++ fast paths (native/resize.cpp).

Build with ``native/build.sh`` (g++, no other deps); the library lands in
``ncnet_tpu/data/_native/libncnet_native.so`` or is pointed to by the
``NCNET_NATIVE_LIB`` env var. Every entry point degrades gracefully:
when the library is absent the functions return ``None`` and callers fall
back to their numpy implementations.

Why native: the loader uses worker THREADS (data/loader.py); ctypes calls
release the GIL for the duration of the C call, so resize work in multiple
workers genuinely runs in parallel — the numpy fallback holds the GIL in
its gather/arith steps.
"""

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = os.environ.get("NCNET_NATIVE_LIB") or os.path.join(
        os.path.dirname(__file__), "_native", "libncnet_native.so"
    )
    if not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    lib.ncnet_resize_bilinear_f32.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.ncnet_resize_bilinear_f32.restype = None
    _LIB = lib
    return lib


def native_available():
    return _load() is not None


def resize_bilinear_native(image, out_h, out_w):
    """Align-corners bilinear resize of ``[h, w, c]`` float32.

    Returns the resized array, or ``None`` when the native library is not
    built (callers fall back to numpy).
    """
    lib = _load()
    if lib is None or np.ndim(image) != 3:
        return None
    img = np.ascontiguousarray(image, np.float32)
    h, w, c = img.shape
    if (h, w) == (out_h, out_w):
        return img
    out = np.empty((out_h, out_w, c), np.float32)
    lib.ncnet_resize_bilinear_f32(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        h,
        w,
        c,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out_h,
        out_w,
    )
    return out
