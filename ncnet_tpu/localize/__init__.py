"""Device-native visual localization: batched, jittable PnP-RANSAC.

The seed's `eval/localize.py` is a faithful pure-NumPy port of the
reference's MATLAB L6 stage — it runs one (query, pano) pair at a time
on the host while the accelerator idles. This package is the same math
as a static-shape XLA program:

  * :mod:`ncnet_tpu.localize.solver` — jittable Grunert P3P: quartic
    roots via the 4x4 companion-matrix eigendecomposition, degenerate /
    complex solutions MASKED (never branched), a fixed ``[4, 3, 4]``
    pose slate per minimal sample;
  * :mod:`ncnet_tpu.localize.ransac` — fixed-iteration LO-RANSAC with
    static shapes end to end: matches padded/masked to a bucket size,
    sample indices from a threaded PRNG key, every hypothesis's angular
    inlier count as one masked reduction, ``vmap`` across hypotheses AND
    across a batch of queries — no ``while_loop`` on data, no host sync
    inside the loop;
  * :mod:`ncnet_tpu.localize.request` — ``PoseRequest``: "image pair ->
    pose" as a servable request type through `ServeEngine`/`ServeFleet`,
    with its own bucket family keyed on padded match count and
    hypothesis-count rungs as the degradation knob.

Exactness contract: the jitted solver matches
`eval.localize.p3p_grunert` on the same minimal samples, and with the
same sample sequence the batched RANSAC selects the same best pose as
the NumPy reference on the synthetic InLoc fixtures — the existing
module is the oracle the same way ``gemm4`` anchors the sparse band
(tests/test_localize_jax.py pins both).

Backend note: the quartic eigendecomposition (``jnp.linalg.eigvals`` on
a nonsymmetric matrix) lowers on the CPU backend; on TPU, run this
program on the host-attached CPU device or via the CPU proxy (the same
split the reference makes — L6 never ran on the GPU either). Everything
else (scoring, Kabsch, DLT) lowers everywhere.
"""

from ncnet_tpu.localize.ransac import (
    localize_poses,
    make_ransac_step,
    pose_from_matches,
    ransac_pose,
    ransac_pose_np,
    sample_triplets,
    score_hypotheses,
)
from ncnet_tpu.localize.request import (
    POSE_HYPOTHESIS_RUNGS,
    POSE_MATCH_BUCKETS,
    PoseRequest,
    make_pose_apply,
    make_pose_engine,
    pose_bucket,
    pose_bucket_specs,
    prep_pose_request,
)
from ncnet_tpu.localize.solver import p3p_solve, p3p_solve_batch

__all__ = [
    "POSE_HYPOTHESIS_RUNGS",
    "POSE_MATCH_BUCKETS",
    "PoseRequest",
    "localize_poses",
    "make_pose_apply",
    "make_pose_engine",
    "make_ransac_step",
    "p3p_solve",
    "p3p_solve_batch",
    "pose_bucket",
    "pose_bucket_specs",
    "pose_from_matches",
    "prep_pose_request",
    "ransac_pose",
    "ransac_pose_np",
    "sample_triplets",
    "score_hypotheses",
]
