"""Jittable Grunert P3P: a fixed ``[4, 3, 4]`` pose slate per sample.

The math is `eval.localize.p3p_grunert` (Grunert 1841 via the Haralick
et al. survey), restated for XLA: the NumPy oracle returns a *list* of
0-4 poses and branches on every degeneracy; a compiled program cannot.
Instead every minimal sample always produces the full 4-slot slate plus
a validity mask —

  * the quartic in ``v = s3/s1`` is solved as the eigenvalues of its
    4x4 monic companion matrix (``np.roots`` is exactly this for one
    polynomial), so all four candidate roots exist as array slots;
  * every oracle early-return (short triangle side, vanishing leading
    coefficient, complex root, negative ``v``/``u``/``s1^2``, singular
    denominator, non-finite fit) becomes a mask bit, and the guarded
    denominators are substituted with 1 so the masked lanes still
    compute finite garbage instead of NaN-poisoning the batch;
  * invalid slots are overwritten with the identity pose, so downstream
    scoring reads a well-formed ``[4, 3, 4]`` array unconditionally and
    the RANSAC argmax simply never selects a masked slot (its inlier
    count is forced to -1).

float32 end to end (the jaxpr audit's f64-leak rule is an error
repo-wide): companion eigenvalues in f32 carry ~1e-4 relative error, so
real roots get two Newton polish steps on the quartic before the
back-substitution — that is what buys the tight-parity contract against
the f64 oracle (tests/test_localize_jax.py). The degeneracy cutoffs are
correspondingly wider than the oracle's f64 ones; they are calibrated
so that on *non-degenerate* samples both sides agree on validity and on
clearly-degenerate ones both mask.
"""

import numpy as np

import jax
import jax.numpy as jnp

#: f32-calibrated degeneracy guards (oracle f64 counterparts in parens):
#: minimum triangle side (1e-12), minimum |denominator| in the u / s1^2
#: back-substitution (1e-12), minimum |A4| for a genuine quartic (1e-14),
#: and the relative imaginary tolerance for calling a companion
#: eigenvalue real (1e-8 absolute — f32 eig needs the relative form).
_SIDE_EPS = 1e-6
_DENOM_EPS = 1e-6
_LEAD_EPS = 1e-10
_IMAG_TOL = 1e-3
_NEWTON_STEPS = 2


def _det3(m):
    """Closed-form 3x3 determinant over a leading batch.

    Elementwise on purpose: ``jnp.linalg.det`` lowers through an LU
    custom call, which would add a non-contraction kernel to a program
    whose flop ledger (`ops.accounting.pose_ransac_flops`) counts pure
    dot_generals.
    """
    return (
        m[..., 0, 0] * (m[..., 1, 1] * m[..., 2, 2] - m[..., 1, 2] * m[..., 2, 1])
        - m[..., 0, 1] * (m[..., 1, 0] * m[..., 2, 2] - m[..., 1, 2] * m[..., 2, 0])
        + m[..., 0, 2] * (m[..., 1, 0] * m[..., 2, 1] - m[..., 1, 1] * m[..., 2, 0])
    )


def kabsch(world_pts, cam_pts):
    """Batched Kabsch rigid fit ``x_cam = R x_world + t`` (no scale).

    Args:
      world_pts: ``[s, 3, 3]`` world-point triplets (rows).
      cam_pts: ``[s, 3, 3]`` camera-frame triplets.

    Returns:
      ``[s, 3, 4]`` poses ``P = [R | t]`` — `_absolute_orientation`
      batched, with the reflection fix applied per slot.
    """
    cw = jnp.mean(world_pts, axis=1, keepdims=True)
    cc = jnp.mean(cam_pts, axis=1, keepdims=True)
    h = jnp.einsum("ski,skj->sij", world_pts - cw, cam_pts - cc)
    u, _, vt = jnp.linalg.svd(h)
    d = jnp.sign(_det3(jnp.einsum("sji,skj->sik", vt, u)))
    d = jnp.where(d == 0.0, 1.0, d)
    flip = jnp.concatenate(
        [jnp.ones_like(d)[:, None], jnp.ones_like(d)[:, None], d[:, None]],
        axis=1,
    )
    r = jnp.einsum("sji,skj->sik", vt * flip[:, :, None], u)
    t = cc[:, 0] - jnp.einsum("sij,sj->si", r, cw[:, 0])
    return jnp.concatenate([r, t[:, :, None]], axis=2)


def p3p_solve(rays, points):
    """Absolute pose slate from 3 ray/point correspondences.

    Args:
      rays: ``[3, 3]`` bearing vectors in the camera frame (rows; need
        not be normalized).
      points: ``[3, 3]`` corresponding world points (rows).

    Returns:
      ``(poses, valid)`` — ``poses`` is the fixed ``[4, 3, 4]`` slate of
      ``P = [R | t]`` candidates (``x_cam = R x_world + t``), ``valid``
      the ``[4]`` bool mask of admissible slots. Invalid slots hold the
      identity pose. Matches `eval.localize.p3p_grunert` on the valid
      slots (slate order follows the companion eigenvalue order, which
      differs from ``np.roots`` — compare as sets).
    """
    rays = jnp.asarray(rays, jnp.float32)
    points = jnp.asarray(points, jnp.float32)
    norm = jnp.sqrt(jnp.sum(rays * rays, axis=1, keepdims=True))
    f = rays / jnp.maximum(norm, 1e-12)

    d23 = points[1] - points[2]
    d13 = points[0] - points[2]
    d12 = points[0] - points[1]
    a2 = jnp.sum(d23 * d23)  # side opposite point 1, squared
    b2 = jnp.sum(d13 * d13)
    c2 = jnp.sum(d12 * d12)
    side_ok = jnp.minimum(jnp.minimum(a2, b2), c2) > _SIDE_EPS * _SIDE_EPS
    b2s = jnp.where(side_ok, b2, 1.0)

    cos_a = jnp.sum(f[1] * f[2])
    cos_b = jnp.sum(f[0] * f[2])
    cos_g = jnp.sum(f[0] * f[1])

    # Grunert's quartic in v = s3/s1 — the oracle's coefficients verbatim
    q = (a2 - c2) / b2s
    a4 = (q - 1.0) ** 2 - 4.0 * (c2 / b2s) * cos_a**2
    a3 = 4.0 * (
        q * (1.0 - q) * cos_b
        - (1.0 - (a2 + c2) / b2s) * cos_a * cos_g
        + 2.0 * (c2 / b2s) * cos_a**2 * cos_b
    )
    a2_ = 2.0 * (
        q**2
        - 1.0
        + 2.0 * q**2 * cos_b**2
        + 2.0 * ((b2 - c2) / b2s) * cos_a**2
        - 4.0 * ((a2 + c2) / b2s) * cos_a * cos_b * cos_g
        + 2.0 * ((b2 - a2) / b2s) * cos_g**2
    )
    a1 = 4.0 * (
        -q * (1.0 + q) * cos_b
        + 2.0 * (a2 / b2s) * cos_g**2 * cos_b
        - (1.0 - (a2 + c2) / b2s) * cos_a * cos_g
    )
    a0 = (1.0 + q) ** 2 - 4.0 * (a2 / b2s) * cos_g**2

    coeffs = jnp.stack([a4, a3, a2_, a1, a0])
    lead_ok = jnp.abs(a4) > _LEAD_EPS
    coeffs_ok = side_ok & lead_ok & jnp.all(jnp.isfinite(coeffs))

    # batched np.roots: the monic companion matrix, eigenvalues = roots
    mono = coeffs[1:] / jnp.where(lead_ok, a4, 1.0)
    mono_ok = jnp.all(jnp.isfinite(mono))
    mono = jnp.where(mono_ok, mono, jnp.zeros_like(mono))
    comp = jnp.zeros((4, 4), jnp.float32)
    comp = comp.at[1, 0].set(1.0).at[2, 1].set(1.0).at[3, 2].set(1.0)
    comp = comp.at[0, :].set(-mono)
    roots = jnp.linalg.eigvals(comp)  # [4] complex64; CPU lowering
    v = jnp.real(roots)
    imag_ok = jnp.abs(jnp.imag(roots)) <= _IMAG_TOL * (1.0 + jnp.abs(v))

    # Newton polish: pull f32 eigenvalues onto the quartic's real roots
    for _ in range(_NEWTON_STEPS):
        pv = (((a4 * v + a3) * v + a2_) * v + a1) * v + a0
        dpv = ((4.0 * a4 * v + 3.0 * a3) * v + 2.0 * a2_) * v + a1
        dp_ok = jnp.abs(dpv) > _DENOM_EPS
        v = jnp.where(dp_ok, v - pv / jnp.where(dp_ok, dpv, 1.0), v)

    denom = 2.0 * (cos_g - v * cos_a)
    denom_ok = jnp.abs(denom) > _DENOM_EPS
    u = ((q - 1.0) * v * v - 2.0 * q * cos_b * v + 1.0 + q) / jnp.where(
        denom_ok, denom, 1.0
    )
    s1_den = 1.0 + v * v - 2.0 * v * cos_b
    s1_den_ok = s1_den > _DENOM_EPS
    s1sq = b2 / jnp.where(s1_den_ok, s1_den, 1.0)
    valid = (
        coeffs_ok
        & mono_ok
        & imag_ok
        & (v > 0.0)
        & denom_ok
        & (u > 0.0)
        & s1_den_ok
        & jnp.isfinite(u)
        & jnp.isfinite(s1sq)
    )

    s1 = jnp.sqrt(jnp.maximum(s1sq, 0.0))
    scales = jnp.stack([s1, u * s1, v * s1], axis=1)  # [4, 3]
    cam = scales[:, :, None] * f[None, :, :]  # [4, 3, 3]
    poses = kabsch(jnp.broadcast_to(points[None], (4, 3, 3)), cam)
    valid = valid & jnp.all(jnp.isfinite(poses.reshape(4, 12)), axis=1)

    ident = jnp.concatenate(
        [jnp.eye(3, dtype=jnp.float32), jnp.zeros((3, 1), jnp.float32)],
        axis=1,
    )
    poses = jnp.where(valid[:, None, None], poses, ident[None])
    return poses, valid


def p3p_solve_batch(rays, points):
    """`p3p_solve` vmapped over a leading sample axis.

    ``[s, 3, 3] x 2 -> ([s, 4, 3, 4], [s, 4])``.
    """
    return jax.vmap(p3p_solve)(rays, points)


def p3p_slate_np(rays, points):
    """f64 NumPy mirror of `p3p_solve` for the exactness contract.

    Identical control structure (companion eigenvalues, slate slots,
    mask bits, Newton polish) evaluated at double precision — the bridge
    between the list-shaped oracle `eval.localize.p3p_grunert` and the
    slate-shaped jitted solver: tests check oracle poses appear among
    this mirror's valid slots AND that the jitted slots match the
    mirror's slot-for-slot.

    Returns ``(poses [4, 3, 4], valid [4])`` numpy arrays.
    """
    rays = np.asarray(rays, np.float64)
    points = np.asarray(points, np.float64)
    f = rays / np.maximum(
        np.linalg.norm(rays, axis=1, keepdims=True), 1e-12
    )
    a2 = float(np.sum((points[1] - points[2]) ** 2))
    b2 = float(np.sum((points[0] - points[2]) ** 2))
    c2 = float(np.sum((points[0] - points[1]) ** 2))
    side_ok = min(a2, b2, c2) > _SIDE_EPS * _SIDE_EPS
    b2s = b2 if side_ok else 1.0
    cos_a = float(f[1] @ f[2])
    cos_b = float(f[0] @ f[2])
    cos_g = float(f[0] @ f[1])
    q = (a2 - c2) / b2s
    a4 = (q - 1.0) ** 2 - 4.0 * (c2 / b2s) * cos_a**2
    a3 = 4.0 * (
        q * (1.0 - q) * cos_b
        - (1.0 - (a2 + c2) / b2s) * cos_a * cos_g
        + 2.0 * (c2 / b2s) * cos_a**2 * cos_b
    )
    a2_ = 2.0 * (
        q**2
        - 1.0
        + 2.0 * q**2 * cos_b**2
        + 2.0 * ((b2 - c2) / b2s) * cos_a**2
        - 4.0 * ((a2 + c2) / b2s) * cos_a * cos_b * cos_g
        + 2.0 * ((b2 - a2) / b2s) * cos_g**2
    )
    a1 = 4.0 * (
        -q * (1.0 + q) * cos_b
        + 2.0 * (a2 / b2s) * cos_g**2 * cos_b
        - (1.0 - (a2 + c2) / b2s) * cos_a * cos_g
    )
    a0 = (1.0 + q) ** 2 - 4.0 * (a2 / b2s) * cos_g**2
    coeffs = np.array([a4, a3, a2_, a1, a0])
    lead_ok = abs(a4) > _LEAD_EPS
    coeffs_ok = side_ok and lead_ok and bool(np.all(np.isfinite(coeffs)))
    mono = coeffs[1:] / (a4 if lead_ok else 1.0)
    mono_ok = bool(np.all(np.isfinite(mono)))
    comp = np.zeros((4, 4))
    comp[1, 0] = comp[2, 1] = comp[3, 2] = 1.0
    comp[0, :] = -mono if mono_ok else 0.0
    roots = np.linalg.eigvals(comp)
    v = roots.real.copy()
    imag_ok = np.abs(roots.imag) <= _IMAG_TOL * (1.0 + np.abs(v))
    for _ in range(_NEWTON_STEPS):
        pv = (((a4 * v + a3) * v + a2_) * v + a1) * v + a0
        dpv = ((4.0 * a4 * v + 3.0 * a3) * v + 2.0 * a2_) * v + a1
        dp_ok = np.abs(dpv) > _DENOM_EPS
        v = np.where(dp_ok, v - pv / np.where(dp_ok, dpv, 1.0), v)
    denom = 2.0 * (cos_g - v * cos_a)
    denom_ok = np.abs(denom) > _DENOM_EPS
    with np.errstate(divide="ignore", invalid="ignore"):
        u = ((q - 1.0) * v * v - 2.0 * q * cos_b * v + 1.0 + q) / np.where(
            denom_ok, denom, 1.0
        )
        s1_den = 1.0 + v * v - 2.0 * v * cos_b
        s1_den_ok = s1_den > _DENOM_EPS
        s1sq = b2 / np.where(s1_den_ok, s1_den, 1.0)
    valid = (
        coeffs_ok
        & mono_ok
        & imag_ok
        & (v > 0.0)
        & denom_ok
        & (u > 0.0)
        & s1_den_ok
        & np.isfinite(u)
        & np.isfinite(s1sq)
    )
    s1 = np.sqrt(np.maximum(s1sq, 0.0))
    scales = np.stack([s1, u * s1, v * s1], axis=1)
    cam = scales[:, :, None] * f[None, :, :]
    cw = points.mean(axis=0)
    poses = np.zeros((4, 3, 4))
    poses[:, :, :3] = np.eye(3)
    for i in range(4):
        if not valid[i]:
            continue
        cc = cam[i].mean(axis=0)
        h = (points - cw).T @ (cam[i] - cc)
        uu, _, vt = np.linalg.svd(h)
        d = np.sign(np.linalg.det(vt.T @ uu.T))
        r = vt.T @ np.diag([1.0, 1.0, d if d != 0 else 1.0]) @ uu.T
        t = cc - r @ cw
        p = np.concatenate([r, t[:, None]], axis=1)
        if np.all(np.isfinite(p)):
            poses[i] = p
        else:
            valid[i] = False
            poses[i, :, :3] = np.eye(3)
            poses[i, :, 3] = 0.0
    return poses, valid
