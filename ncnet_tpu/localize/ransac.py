"""Fixed-iteration, static-shape LO-RANSAC over the P3P slate.

The oracle (`eval.localize.lo_ransac_p3p`) is an adaptive while-loop:
draw a chunk, early-exit on the confidence rule, locally optimize the
incumbent — every decision a host-side branch. The compiled form trades
the adaptive schedule for a *fixed* hypothesis budget so the whole
solve is one static-shape program:

  * matches arrive padded to a bucket size with a validity mask — the
    pad rows carry zero weight through sampling, scoring and the refit,
    so padding NEVER perturbs the result;
  * ``sample_triplets`` draws all ``H`` index-triplets up front from a
    threaded PRNG key, sampling only among valid rows (valid-first
    stable argsort + uniform draw over ``n_valid``); a duplicate-bearing
    triplet is masked, not resampled — at the reference's tentative
    counts the loss is ~3/n of the budget;
  * every hypothesis slate slot (``4 * H`` poses) is scored in ONE
    masked reduction (`score_hypotheses` — the oracle's sign-safe
    ``dot^2 > cos^2 ||Xc||^2`` comparison, batched); invalid slots score
    -1 so the argmax can never pick them;
  * local optimization is ``lo_iters`` *unrolled* masked DLT refits
    (`eval.localize.dlt_pnp` with the inlier subset expressed as 0/1 row
    weights on the normal matrix, cheirality as a positive-depth
    majority — the jittable equivalent of the oracle's median test); a
    refit is accepted only where it does not lose inliers, mirroring the
    oracle's keep-while-improving rule.

No ``while_loop`` on data, no host sync inside the loop; `vmap` lifts
the solve across hypotheses (inside `pose_from_matches`) and across a
batch of queries (`make_ransac_step`). `ransac_pose_np` is the f64
NumPy reference for the exactness contract, built directly on
`eval.localize`'s building blocks and consuming the SAME sample-index
sequence, so fixed-seed tests can demand best-pose agreement rather
than merely statistical equivalence.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ncnet_tpu.localize.solver import _det3, p3p_solve
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import default_registry

#: identity pose — what "no model found" reports instead of None (a
#: compiled program has no optional return; check ``found``)
_IDENT_POSE = np.concatenate(
    [np.eye(3, dtype=np.float32), np.zeros((3, 1), np.float32)], axis=1
)


def unit_rays(rays):
    """Normalize bearing vectors once, guarded (pad rows are zero)."""
    norm = jnp.sqrt(jnp.sum(rays * rays, axis=-1, keepdims=True))
    return rays / jnp.maximum(norm, 1e-12)


def sample_triplets(key, mask, n_hypotheses):
    """Draw ``[H, 3]`` index-triplets among the VALID rows only.

    Valid rows are compacted to the front by a stable argsort on the
    mask, then each slot draws uniformly over ``n_valid`` — so the
    distribution over valid rows is independent of how the padding is
    laid out, and the same ``(key, n_valid)`` yields the same triplets
    at every bucket size (the pad-invariance contract).
    """
    order = jnp.argsort(jnp.where(mask, 0, 1).astype(jnp.int32), stable=True)
    n_valid = jnp.sum(mask.astype(jnp.int32))
    hi = jnp.maximum(n_valid, 1)
    u = jax.random.uniform(key, (n_hypotheses, 3), dtype=jnp.float32)
    r = jnp.minimum((u * hi.astype(jnp.float32)).astype(jnp.int32), hi - 1)
    return jnp.take(order, r, axis=0, mode="clip")


def score_hypotheses(poses, rays, points, mask, cos_thr):
    """Masked angular-inlier counts for ``[M, 3, 4]`` poses at once.

    One batched contraction + one reduction — the RANSAC scoring loop
    with no loop. ``rays`` must be pre-normalized (`unit_rays`). The
    comparison is the oracle's sign-safe form: ``cos > thr`` iff
    ``dot > 0 and dot^2 > thr^2 ||Xc||^2`` — no divide, no sqrt.
    """
    xc = jnp.einsum("mij,nj->mni", poses[:, :, :3], points)
    xc = xc + poses[:, None, :, 3]
    dots = jnp.einsum("mni,ni->mn", xc, rays)
    sq = jnp.sum(xc * xc, axis=2)
    inl = (dots > 0.0) & (dots * dots > (cos_thr * cos_thr) * sq)
    inl = inl & mask[None, :]
    return jnp.sum(inl.astype(jnp.int32), axis=1)


def _inlier_mask(pose, rays, points, mask, cos_thr):
    """[n] bool angular-inlier mask of one pose (rays pre-normalized)."""
    xc = points @ pose[:, :3].T + pose[:, 3]
    dots = jnp.sum(xc * rays, axis=1)
    sq = jnp.sum(xc * xc, axis=1)
    return (dots > 0.0) & (dots * dots > (cos_thr * cos_thr) * sq) & mask


def _dlt_refit(rays, points, weights):
    """Masked DLT PnP (`eval.localize.dlt_pnp` with 0/1 row weights).

    The oracle slices the inlier subset; a static-shape program cannot,
    so the two row families of the 2n x 12 design matrix are weighted
    and collapsed straight into the 12x12 normal matrix (binary weights:
    ``w^2 = w``). Returns ``(pose [3, 4], ok)`` where ``ok`` folds the
    oracle's rejections: < 6 inliers, vanishing scale, cheirality (a
    positive-depth majority over the inliers — the jittable stand-in
    for the oracle's ``median > 0``), non-finite output.
    """
    n = points.shape[0]
    xh = jnp.concatenate([points, jnp.ones((n, 1), jnp.float32)], axis=1)
    zeros = jnp.zeros((n, 4), jnp.float32)
    a_even = jnp.concatenate(
        [-rays[:, 2:3] * xh, zeros, rays[:, 0:1] * xh], axis=1
    )
    a_odd = jnp.concatenate(
        [zeros, -rays[:, 2:3] * xh, rays[:, 1:2] * xh], axis=1
    )
    w = weights[:, None]
    ata = a_even.T @ (w * a_even) + a_odd.T @ (w * a_odd)
    _, evec = jnp.linalg.eigh(ata)
    p = evec[:, 0].reshape(3, 4)
    # null-vector sign is arbitrary; resolve BEFORE the SO(3) projection
    p = jnp.where(_det3(p[:, :3]) < 0.0, -p, p)
    u, s, vt = jnp.linalg.svd(p[:, :3])
    r = u @ vt
    scale = jnp.mean(s)
    t = p[:, 3] / jnp.maximum(scale, 1e-12)
    xc = points @ r.T + t
    dots = jnp.sum(xc * rays, axis=1)
    n_inl = jnp.sum(weights)
    n_pos = jnp.sum(jnp.where(dots > 0.0, weights, 0.0))
    pose = jnp.concatenate([r, t[:, None]], axis=1)
    ok = (
        (scale > 1e-10)
        & (n_inl >= 6.0)
        & (2.0 * n_pos > n_inl)
        & jnp.all(jnp.isfinite(pose))
    )
    return pose, ok


def ransac_pose(rays, points, mask, sample_idx, *, cos_thr, lo_iters=2):
    """LO-RANSAC best pose from a precomputed sample-index sequence.

    Args:
      rays: ``[n, 3]`` camera-frame bearings (normalized internally).
      points: ``[n, 3]`` world points (pad rows: zeros).
      mask: ``[n]`` bool validity of each row.
      sample_idx: ``[H, 3]`` int triplet indices (`sample_triplets`).
      cos_thr: cosine of the angular inlier threshold (static).
      lo_iters: unrolled local-optimization refits (static).

    Returns:
      dict of ``P [3, 4]``, ``inliers [n]`` bool, ``n_inliers`` int32,
      ``found`` bool, ``best_hyp`` int32 (flat slate index). ``P`` is
      the identity pose when ``found`` is False.
    """
    rays = unit_rays(jnp.asarray(rays, jnp.float32))
    points = jnp.asarray(points, jnp.float32)
    h = sample_idx.shape[0]

    tri_f = jnp.take(rays, sample_idx, axis=0, mode="clip")  # [H, 3, 3]
    tri_x = jnp.take(points, sample_idx, axis=0, mode="clip")
    poses, valid = jax.vmap(p3p_solve)(tri_f, tri_x)  # [H,4,3,4], [H,4]

    dup = (
        (sample_idx[:, 0] == sample_idx[:, 1])
        | (sample_idx[:, 0] == sample_idx[:, 2])
        | (sample_idx[:, 1] == sample_idx[:, 2])
    )
    n_valid = jnp.sum(mask.astype(jnp.int32))
    valid = valid & (~dup)[:, None] & (n_valid >= 3)

    flat_p = poses.reshape(h * 4, 3, 4)
    flat_ok = valid.reshape(h * 4)
    counts = score_hypotheses(flat_p, rays, points, mask, cos_thr)
    counts = jnp.where(flat_ok, counts, -1)
    best = jnp.argmax(counts).astype(jnp.int32)
    best_pose = jnp.take(flat_p, best[None], axis=0, mode="clip")[0]
    best_count = jnp.take(counts, best[None], axis=0, mode="clip")[0]
    found = best_count > 0

    for _ in range(lo_iters):
        inl = _inlier_mask(best_pose, rays, points, mask, cos_thr)
        pose_lo, ok = _dlt_refit(rays, points, inl.astype(jnp.float32))
        cnt_lo = score_hypotheses(
            pose_lo[None], rays, points, mask, cos_thr
        )[0]
        accept = ok & found & (cnt_lo >= best_count)
        best_pose = jnp.where(accept, pose_lo, best_pose)
        best_count = jnp.where(accept, cnt_lo, best_count)

    best_pose = jnp.where(found, best_pose, jnp.asarray(_IDENT_POSE))
    inliers = _inlier_mask(best_pose, rays, points, mask, cos_thr) & found
    return {
        "P": best_pose,
        "inliers": inliers,
        "n_inliers": jnp.maximum(best_count, 0).astype(jnp.int32),
        "found": found,
        "best_hyp": best,
    }


def pose_from_matches(
    rays, points, mask, seed, *, n_hypotheses, cos_thr, lo_iters=2
):
    """One query's full solve: threaded PRNG sampling + `ransac_pose`.

    ``seed`` is a traced int32, so the whole thing jits and vmaps with
    per-query seeds (the serve path batches exactly this function).
    """
    key = jax.random.PRNGKey(seed)
    idx = sample_triplets(key, mask, n_hypotheses)
    return ransac_pose(
        rays, points, mask, idx, cos_thr=cos_thr, lo_iters=lo_iters
    )


@functools.lru_cache(maxsize=None)
def make_ransac_step(n_hypotheses=64, thr_deg=0.2, lo_iters=2):
    """Jitted batched solver ``step(rays, points, mask, seeds)``.

    ``[b, n, 3] x 2 + [b, n] + [b] -> dict of [b, ...]`` — `vmap` across
    queries of `pose_from_matches`. Memoized so repeated calls at one
    geometry share a single jit wrapper (the `recompile-hazard`
    discipline, same shape as ``make_train_step``).
    """
    cos_thr = float(np.cos(np.deg2rad(thr_deg)))
    fn = functools.partial(
        pose_from_matches,
        n_hypotheses=n_hypotheses,
        cos_thr=cos_thr,
        lo_iters=lo_iters,
    )
    return jax.jit(jax.vmap(fn))


# ------------------------------------------------------- staged host driver


@functools.lru_cache(maxsize=None)
def _sample_stage(n_hypotheses):
    def stage(seeds, mask):
        keys = jax.vmap(jax.random.PRNGKey)(seeds)
        return jax.vmap(
            functools.partial(sample_triplets, n_hypotheses=n_hypotheses)
        )(keys, mask)

    return jax.jit(stage)


@functools.lru_cache(maxsize=None)
def _solve_stage():
    def stage(rays, points, idx):
        rays = unit_rays(jnp.asarray(rays, jnp.float32))
        points = jnp.asarray(points, jnp.float32)
        # per-query gather: vmap keeps the [H, 3] indices local to a row
        tri_f = jax.vmap(
            lambda r, i: jnp.take(r, i, axis=0, mode="clip")
        )(rays, idx)
        tri_x = jax.vmap(
            lambda p, i: jnp.take(p, i, axis=0, mode="clip")
        )(points, idx)
        return jax.vmap(jax.vmap(p3p_solve))(tri_f, tri_x)

    return jax.jit(stage)


@functools.lru_cache(maxsize=None)
def _score_stage(cos_thr, lo_iters):
    def one(rays, points, mask, idx):
        return ransac_pose(
            rays, points, mask, idx, cos_thr=cos_thr, lo_iters=lo_iters
        )

    return jax.jit(jax.vmap(one))


def localize_poses(
    rays, points, mask, seeds, *, n_hypotheses=64, thr_deg=0.2, lo_iters=2
):
    """Host driver with per-stage telemetry spans.

    Runs the batched solve as three jitted stages — ``localize/sample``
    (index generation), ``localize/solve`` (the P3P slates, traced for
    span attribution only; the fused score stage re-derives them so its
    program stays self-contained), ``localize/score`` (scoring + LO) —
    and bumps ``localize_poses_total``. The serve path compiles the SAME
    math as one fused program (`localize.request.make_pose_apply`);
    this staged variant exists for the CLI and benchmarks, where stage
    timing is the thing being measured.
    """
    m_poses = default_registry().counter(
        "localize_poses_total",
        "camera poses estimated by the batched JAX localizer",
    )
    seeds = jnp.asarray(seeds, jnp.int32)
    mask = jnp.asarray(mask, bool)
    with trace.span("localize/sample"):
        idx = _sample_stage(n_hypotheses)(seeds, mask)
        jax.block_until_ready(idx)
    with trace.span("localize/solve"):
        slates = _solve_stage()(rays, points, idx)
        jax.block_until_ready(slates)
    cos_thr = float(np.cos(np.deg2rad(thr_deg)))
    with trace.span("localize/score"):
        out = _score_stage(cos_thr, lo_iters)(rays, points, mask, idx)
        jax.block_until_ready(out)
    m_poses.inc(int(seeds.shape[0]))
    return out


# ------------------------------------------------------- NumPy reference


def ransac_pose_np(rays, points, mask, sample_idx, *, thr_rad, lo_iters=2):
    """f64 NumPy reference of `ransac_pose`, built on the oracle.

    Consumes the SAME ``[H, 3]`` sample-index sequence as the jitted
    path and mirrors its fixed schedule (score-all-then-argmax,
    ``lo_iters`` accept-if-no-worse refits), but every building block is
    `eval.localize`'s own: `_p3p_grunert_batch`, `_count_inliers_batch`,
    `_angular_inliers`, `dlt_pnp`. This is the exactness-contract
    anchor: with a fixed seed the batched program must select the same
    best pose this reference does (tests/test_localize_jax.py).

    Returns the same dict shape as `ransac_pose` (numpy arrays; ``P``
    is the identity pose when not found).
    """
    from ncnet_tpu.eval import localize as oracle

    rays = np.asarray(rays, np.float64)
    points = np.asarray(points, np.float64)
    mask = np.asarray(mask, bool)
    sel = np.asarray(sample_idx, int)
    n = len(points)
    cos_thr = float(np.cos(thr_rad))
    unit = rays / np.maximum(
        np.linalg.norm(rays, axis=1, keepdims=True), 1e-12
    )

    out = {
        "P": _IDENT_POSE.astype(np.float64),
        "inliers": np.zeros(n, bool),
        "n_inliers": 0,
        "found": False,
    }
    if int(mask.sum()) < 3:
        return out

    dup = (
        (sel[:, 0] == sel[:, 1])
        | (sel[:, 0] == sel[:, 2])
        | (sel[:, 1] == sel[:, 2])
    )
    keep = ~dup
    if not keep.any():
        return out
    cand_p, owner = oracle._p3p_grunert_batch(
        unit[sel[keep]], points[sel[keep]]
    )
    if len(cand_p) == 0:
        return out
    counts = oracle._count_inliers_batch(
        cand_p, unit[mask], points[mask], cos_thr
    )
    best = int(np.argmax(counts))
    best_pose = cand_p[best]
    best_count = int(counts[best])
    if best_count <= 0:
        return out

    for _ in range(lo_iters):
        inl = oracle._angular_inliers(best_pose, unit, points, cos_thr)
        inl = inl & mask
        if inl.sum() < 6:
            continue
        pose_lo = oracle.dlt_pnp(unit[inl], points[inl])
        if pose_lo is None:
            continue
        cnt_lo = int(
            oracle._count_inliers_batch(
                pose_lo[None], unit[mask], points[mask], cos_thr
            )[0]
        )
        if cnt_lo >= best_count:
            best_pose, best_count = pose_lo, cnt_lo

    inliers = (
        oracle._angular_inliers(best_pose, unit, points, cos_thr) & mask
    )
    out.update(
        P=best_pose, inliers=inliers, n_inliers=best_count, found=True
    )
    return out
