"""``PoseRequest``: "image pair -> camera pose" as a servable request.

The serve engine is generic over ``apply_fn(params, batch)`` — turning
localization into a product request type needs exactly three pieces,
all here:

  * a bucket family keyed on PADDED MATCH COUNT: ``("pose", n_pad)``
    with ``n_pad`` drawn from `POSE_MATCH_BUCKETS`. Tentative sets vary
    per query; padding to the next rung keeps every compiled program's
    shapes static, the same quantized-bucketing discipline as the match
    path's resize rule;
  * `prep_pose_request` — the host-side prep: score-threshold already
    applied upstream (tentatives in hand), subsample above the largest
    bucket (deterministic, seeded — mirrors ``n_subsample``), zero-pad
    + mask to the bucket size;
  * `make_pose_apply` — the fused device program (`vmap` of
    `ransac_pose` across the batch) at a STATIC hypothesis count. The
    degradation knob falls out of the engine's existing two-variant
    slot: build the engine with ``apply_fn`` at the primary rung and
    ``degraded_apply_fn`` at the degraded rung (`POSE_HYPOTHESIS_RUNGS`)
    and `warmup` AOT-compiles BOTH at every (bucket, batch size) — so
    the PR-10 hysteresis controller degrades ``n_hypotheses`` exactly
    like it degrades ``nc_topk``, at zero recompiles
    (tests/test_localize_serve.py drills the flip).

``params`` is an empty dict — the solver has no weights — kept so the
pose program satisfies the universal serving contract, including the
batch-donation spec (`SERVE_DONATE_ARGNUMS`: argnum 1, the single-use
padded match buffer, audited as ``localize/ransac``).
"""

import functools
from dataclasses import dataclass

import numpy as np

from ncnet_tpu.localize.ransac import pose_from_matches

#: padded-match-count rungs of the pose bucket family. The reference
#: caps tentatives via params.ncnet.N_subsample (typically <= 2k); one
#: doubling ladder covers sparse panoramas up to that cap.
POSE_MATCH_BUCKETS = (128, 256, 512, 1024, 2048)

#: (primary, degraded) hypothesis counts — the SLO/degradation rungs.
#: 64 fixed hypotheses resolve the synthetic fixtures' ~70% inlier rate
#: with large margin (p_fail ~ (1 - w^3)^64 < 1e-10 at w = 0.7); the
#: degraded rung keeps p_fail < 1e-3 down to w ~ 0.5.
POSE_HYPOTHESIS_RUNGS = (64, 16)

#: angular inlier threshold, degrees (reference params.ncnet.pnp_thr)
POSE_THR_DEG = 0.2


@dataclass(frozen=True)
class PoseRequest:
    """One query's localization request: tentative 2D-3D matches.

    ``rays``: ``[n, 3]`` camera-frame bearing vectors (query pixels
    through ``K^-1``); ``points``: ``[n, 3]`` world points (DB cutout
    back-projection, already alignment-transformed, NaNs dropped);
    ``seed``: the RANSAC sample seed (per-request, so a replayed
    request is bit-reproducible).
    """

    rays: np.ndarray
    points: np.ndarray
    seed: int = 0

    @classmethod
    def from_tentatives(cls, tentatives_3d, seed=0):
        """From `eval.localize.pnp_localize_pair`'s ``tentatives_3d``
        layout (``[6, n]``: ray rows then point rows)."""
        t = np.asarray(tentatives_3d, np.float32)
        return cls(rays=t[:3].T.copy(), points=t[3:].T.copy(), seed=seed)


def pose_bucket(n_matches):
    """Bucket key for a tentative count: ``("pose", n_pad)``."""
    for n_pad in POSE_MATCH_BUCKETS:
        if n_matches <= n_pad:
            return ("pose", n_pad)
    return ("pose", POSE_MATCH_BUCKETS[-1])


def prep_pose_request(req):
    """Host prep: ``PoseRequest -> (bucket_key, payload)``.

    Above the largest bucket the tentatives are subsampled (seeded
    permutation — the oracle's ``n_subsample`` rule); below, zero-padded
    with a mask. Payload arrays are per-sample (the micro-batcher stacks
    the batch axis).
    """
    rays = np.asarray(req.rays, np.float32)
    points = np.asarray(req.points, np.float32)
    if rays.shape != points.shape or rays.ndim != 2 or rays.shape[1] != 3:
        raise ValueError(
            f"PoseRequest wants [n, 3] rays and points, got "
            f"{rays.shape} / {points.shape}"
        )
    n = len(rays)
    key = pose_bucket(n)
    n_pad = key[1]
    if n > n_pad:
        sel = np.random.RandomState(int(req.seed)).permutation(n)[:n_pad]
        rays, points, n = rays[sel], points[sel], n_pad
    pad = n_pad - n
    payload = {
        "rays": np.concatenate(
            [rays, np.zeros((pad, 3), np.float32)], axis=0
        ),
        "points": np.concatenate(
            [points, np.zeros((pad, 3), np.float32)], axis=0
        ),
        "mask": np.concatenate(
            [np.ones(n, bool), np.zeros(pad, bool)], axis=0
        ),
        "seed": np.int32(req.seed),
    }
    return key, payload


def pose_payload_spec(n_pad):
    """`payload_spec`-shaped per-sample spec of one pose bucket."""
    return {
        "rays": ((n_pad, 3), np.dtype(np.float32)),
        "points": ((n_pad, 3), np.dtype(np.float32)),
        "mask": ((n_pad,), np.dtype(bool)),
        "seed": ((), np.dtype(np.int32)),
    }


def pose_bucket_specs(buckets=POSE_MATCH_BUCKETS):
    """Warmup spec list: every pose bucket's ``(key, per-sample spec)``."""
    return [(("pose", n), pose_payload_spec(n)) for n in buckets]


def make_pose_apply(n_hypotheses=None, thr_deg=POSE_THR_DEG, lo_iters=2):
    """The fused serving program: ``apply(params, batch) -> pose dict``.

    ``batch``: ``{"rays": [b, n, 3], "points": [b, n, 3], "mask":
    [b, n], "seed": [b]}``; returns ``{"P": [b, 3, 4], "inliers":
    [b, n], "n_inliers": [b], "found": [b], "best_hyp": [b]}`` — every
    leaf batch-first, per the engine's readout contract. The hypothesis
    count is STATIC: one apply per rung, warmed as the engine's
    primary/degraded program pair.
    """
    import jax

    if n_hypotheses is None:
        n_hypotheses = POSE_HYPOTHESIS_RUNGS[0]
    cos_thr = float(np.cos(np.deg2rad(thr_deg)))
    fn = functools.partial(
        pose_from_matches,
        n_hypotheses=int(n_hypotheses),
        cos_thr=cos_thr,
        lo_iters=int(lo_iters),
    )
    batched = jax.vmap(fn)

    def apply(params, batch):
        del params  # the solver has no weights; kept for the contract
        return batched(
            batch["rays"], batch["points"], batch["mask"], batch["seed"]
        )

    return apply


def make_pose_engine(
    *,
    n_hypotheses=POSE_HYPOTHESIS_RUNGS[0],
    degraded_hypotheses=POSE_HYPOTHESIS_RUNGS[1],
    thr_deg=POSE_THR_DEG,
    lo_iters=2,
    **engine_kwargs,
):
    """A `ServeEngine` serving `PoseRequest`s with hypothesis rungs.

    ``prep_fn`` is wired to `prep_pose_request`, the degraded program is
    the same solver at the lower rung; call
    ``engine.warmup(pose_bucket_specs(...))`` before traffic for the
    zero-recompile guarantee. Extra kwargs pass through to the engine
    (``max_batch``, ``batch_sizes``, ``registry``, ...).
    """
    from ncnet_tpu.serve.engine import ServeEngine

    if not degraded_hypotheses < n_hypotheses:
        raise ValueError(
            f"degraded rung must be below primary, got "
            f"{degraded_hypotheses} >= {n_hypotheses}"
        )
    return ServeEngine(
        make_pose_apply(n_hypotheses, thr_deg, lo_iters),
        {},
        prep_fn=prep_pose_request,
        degraded_apply_fn=make_pose_apply(
            degraded_hypotheses, thr_deg, lo_iters
        ),
        **engine_kwargs,
    )
