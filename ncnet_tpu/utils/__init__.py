"""Utilities: torch checkpoint conversion, profiling, misc helpers."""

from ncnet_tpu.utils import convert_torch

__all__ = ["convert_torch"]
