"""Match/keypoint visualization — reference lib/plot.py:6-29 (un-normalize
+ imshow, tight savefig) plus a side-by-side match drawing equivalent to
the demo notebook cells 3-7 and lib_matlab/show_matches2_horizontal.m.

matplotlib is imported lazily with the Agg backend so headless
environments work.
"""

import numpy as np

from ncnet_tpu.data.images import IMAGENET_MEAN, IMAGENET_STD


def unnormalize_image_np(image):
    """ImageNet-normalized [h, w, 3] -> displayable float RGB in [0, 1]."""
    img = np.asarray(image, np.float32)
    return np.clip(img * IMAGENET_STD + IMAGENET_MEAN, 0.0, 1.0)


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def plot_image(image, normalized=True, ax=None):
    """imshow an image tensor ([h, w, 3] or [1, h, w, 3]), un-normalizing
    if needed (reference plot_image, lib/plot.py:6-19)."""
    plt = _plt()
    img = np.asarray(image)
    if img.ndim == 4:
        img = img[0]
    if normalized:
        img = unnormalize_image_np(img)
    else:
        img = np.clip(img / 255.0, 0, 1) if img.max() > 2 else np.clip(img, 0, 1)
    if ax is None:
        ax = plt.gca()
    ax.imshow(img)
    ax.axis("off")
    return ax


def save_plot(filename, fig=None, dpi=150):
    """Tight savefig (reference save_plot, lib/plot.py:22-29)."""
    plt = _plt()
    if fig is None:
        fig = plt.gcf()
    fig.savefig(filename, dpi=dpi, bbox_inches="tight", pad_inches=0.05)


def plot_loss_curves(train_hist, val_hist):
    """Train/val loss per epoch — the persisted form of the loss arrays
    the reference only stores inside its checkpoints (train.py:203-204)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(6, 4))
    epochs = np.arange(1, len(train_hist) + 1)
    ax.plot(epochs, train_hist, "-o", ms=3, label="train")
    if val_hist is not None and len(val_hist):
        ax.plot(epochs[: len(val_hist)], val_hist, "-s", ms=3, label="val")
    ax.set_xlabel("Epoch")
    ax.set_ylabel("Weak-supervision loss")
    ax.grid(True, alpha=0.3)
    ax.legend()
    return fig


def plot_localization_curve(thresholds_m, rate_percent, label="ncnet_tpu"):
    """Localization-rate curve figure — % correctly localized queries vs
    distance threshold, the reference's final InLoc deliverable
    (ht_plotcurve_WUSTL.m:95-111, axes/ticks matched; PNG instead of
    .fig/.eps)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(5, 5))
    ax.plot(thresholds_m, rate_percent, "-", linewidth=2, label=label)
    ax.set_xlabel("Distance threshold [meters]")
    ax.set_ylabel("Correctly localized queries [%]")
    ax.set_xticks(np.arange(0, 2.0 + 1e-9, 0.25))
    ax.set_xlim(0, 2.0)
    ax.set_ylim(0, 100)
    ax.grid(True, alpha=0.3)
    ax.legend(loc="lower right", fontsize=10)
    return fig


def draw_point_transfer(
    source_image,
    target_image,
    source_points,
    warped_points,
    target_points,
    out_path,
    normalized=True,
    title=None,
):
    """Side-by-side keypoint-transfer figure.

    Left: source image with ground-truth source keypoints (green) and the
    model-warped target keypoints (red x) joined by error lines. Right:
    target image with the query keypoints. Equivalent information to the
    reference demo's final cell.

    Args:
      source_points, warped_points: ``[2, N]`` pixel coords in the source.
      target_points: ``[2, N]`` pixel coords in the target.
    """
    plt = _plt()
    fig, axes = plt.subplots(1, 2, figsize=(11, 5))
    plot_image(source_image, normalized, ax=axes[0])
    plot_image(target_image, normalized, ax=axes[1])
    sp = np.asarray(source_points)
    wp = np.asarray(warped_points)
    tp = np.asarray(target_points)
    valid = (sp[0] != -1) & (sp[1] != -1)
    for i in np.nonzero(valid)[0]:
        axes[0].plot(
            [sp[0, i], wp[0, i]], [sp[1, i], wp[1, i]], "-", color="yellow", lw=1
        )
    axes[0].plot(sp[0, valid], sp[1, valid], "o", color="lime", ms=5, label="GT")
    axes[0].plot(wp[0, valid], wp[1, valid], "x", color="red", ms=6, label="warped")
    axes[0].legend(loc="lower right", fontsize=8)
    axes[1].plot(tp[0, valid], tp[1, valid], "o", color="cyan", ms=5)
    if title:
        fig.suptitle(title)
    save_plot(out_path, fig)
    plt.close(fig)
    return out_path


def draw_matches(
    source_image,
    target_image,
    matches_xyxy,
    scores,
    out_path,
    top_k=100,
    normalized=True,
):
    """Horizontal side-by-side match-line plot
    (lib_matlab/show_matches2_horizontal.m equivalent).

    Args:
      matches_xyxy: ``[N, 4]`` of (xA, yA, xB, yB) in [0, 1] normalized
        image coordinates (the InLoc dump convention).
      scores: ``[N]`` match scores; the top_k by score are drawn.
    """
    plt = _plt()
    src = unnormalize_image_np(source_image) if normalized else source_image
    tgt = unnormalize_image_np(target_image) if normalized else target_image
    h = max(src.shape[0], tgt.shape[0])

    def padto(img):
        if img.shape[0] < h:
            img = np.pad(img, ((0, h - img.shape[0]), (0, 0), (0, 0)))
        return img

    canvas = np.concatenate([padto(src), padto(tgt)], axis=1)
    fig, ax = plt.subplots(figsize=(12, 5))
    ax.imshow(canvas)
    ax.axis("off")
    m = np.asarray(matches_xyxy)
    s = np.asarray(scores)
    order = np.argsort(-s)[:top_k]
    for i in order:
        xa = m[i, 0] * src.shape[1]
        ya = m[i, 1] * src.shape[0]
        xb = m[i, 2] * tgt.shape[1] + src.shape[1]
        yb = m[i, 3] * tgt.shape[0]
        ax.plot([xa, xb], [ya, yb], "-", lw=0.6, alpha=0.7)
    save_plot(out_path, fig)
    plt.close(fig)
    return out_path
