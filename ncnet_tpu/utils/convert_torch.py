"""Convert reference PyTorch checkpoints (.pth.tar) to ncnet_tpu params.

The reference checkpoint schema (train.py:197-205) is
``{epoch, args, state_dict, best_test_loss, optimizer, train_loss,
test_loss}`` with state-dict keys like
``FeatureExtraction.model.<idx>...`` (torchvision Sequential indices:
0=conv1, 1=bn1, 4=layer1, 5=layer2, 6=layer3 for the resnet101 trunk) and
``NeighConsensus.conv.<2*i>.{weight,bias}`` for the Conv4d layers.

Conv4d weights are stored PRE-PERMUTED by the reference constructor
(lib/conv4d.py:72-77): ``[k1, c_out, c_in, k2, k3, k4]`` instead of torch's
native ``[c_out, c_in, k1, k2, k3, k4]``.

torch is only needed inside these functions (CPU-only is fine); the rest of
the framework never imports it.
"""

import re

import numpy as np


def _np(t):
    return np.asarray(t.detach().cpu().numpy(), dtype=np.float32)


def _conv2d_kernel(t):
    # torch [cout, cin, kh, kw] -> HWIO
    return _np(t).transpose(2, 3, 1, 0)


def _bn(sd, prefix):
    return {
        "scale": _np(sd[prefix + ".weight"]),
        "offset": _np(sd[prefix + ".bias"]),
        "mean": _np(sd[prefix + ".running_mean"]),
        "var": _np(sd[prefix + ".running_var"]),
    }


def _normalize_seq_keys(state_dict, prefix, seq_map):
    """Strip ``prefix`` and rename leading Sequential indices per ``seq_map``
    (the reference saves truncated trunks as ``nn.Sequential``, so keys are
    ``0.weight`` etc.; raw torchvision checkpoints use attribute names)."""
    sd = {k[len(prefix):]: v for k, v in state_dict.items() if k.startswith(prefix)}
    if not sd:
        raise ValueError(f"no keys under prefix {prefix!r}")
    norm = {}
    for k, v in sd.items():
        head, _, rest = k.partition(".")
        if head in seq_map:
            k = seq_map[head] + ("." + rest if rest else "")
        norm[k] = v
    return norm


def convert_resnet101_trunk(state_dict, prefix="FeatureExtraction.model."):
    """torchvision-style resnet state dict -> `models.resnet` param tree.

    Accepts either Sequential-index keys (``0.weight`` .. ``6.<block>...``,
    as saved by the reference's truncated model) or attribute keys
    (``conv1.weight``, ``layer1.0...``, as in raw torchvision checkpoints).
    """
    sd = _normalize_seq_keys(
        state_dict,
        prefix,
        {"0": "conv1", "1": "bn1", "4": "layer1", "5": "layer2", "6": "layer3"},
    )

    from ncnet_tpu.models.resnet import RESNET101_STAGES

    params = {
        "conv1": {"kernel": _conv2d_kernel(sd["conv1.weight"])},
        "bn1": _bn(sd, "bn1"),
    }
    for si, (n_blocks, _, _) in enumerate(RESNET101_STAGES):
        layer = f"layer{si + 1}"
        blocks = []
        for bi in range(n_blocks):
            p = f"{layer}.{bi}."
            block = {}
            for ci in (1, 2, 3):
                block[f"conv{ci}"] = {
                    "kernel": _conv2d_kernel(sd[p + f"conv{ci}.weight"])
                }
                block[f"bn{ci}"] = _bn(sd, p + f"bn{ci}")
            if p + "downsample.0.weight" in sd:
                block["downsample_conv"] = {
                    "kernel": _conv2d_kernel(sd[p + "downsample.0.weight"])
                }
                block["downsample_bn"] = _bn(sd, p + "downsample.1")
            blocks.append(block)
        params[layer] = blocks
    return params


def convert_vgg16_trunk(state_dict, prefix="FeatureExtraction.model."):
    """torchvision vgg16.features state dict (conv layers only, in order)."""
    sd = {k[len(prefix):]: v for k, v in state_dict.items() if k.startswith(prefix)}
    weights = sorted(
        (int(k.split(".")[0]), k) for k in sd if k.endswith(".weight")
    )
    params = []
    for idx, wkey in weights:
        params.append(
            {
                "kernel": _conv2d_kernel(sd[wkey]),
                "bias": _np(sd[f"{idx}.bias"]),
            }
        )
    return params


def convert_densenet201_trunk(state_dict, prefix="FeatureExtraction.model."):
    """torchvision-style densenet201 state dict -> `models.densenet` tree.

    Accepts Sequential-index keys (``0.weight`` = conv0 .. ``7.`` =
    transition2, as saved by the reference's ``features.children()[:-4]``
    truncation, lib/model.py:74) or attribute keys (``conv0.weight``,
    ``denseblock1.denselayer1.norm1.weight``, as in raw torchvision
    checkpoints, with or without a leading ``features.``), including the
    legacy zoo-file names ``denselayer*.norm.1`` / ``conv.2`` that
    torchvision itself regex-remaps at load time.
    """
    sd = _normalize_seq_keys(
        state_dict,
        prefix,
        {
            "0": "conv0",
            "1": "norm0",
            "4": "denseblock1",
            "5": "transition1",
            "6": "denseblock2",
            "7": "transition2",
        },
    )
    # legacy torchvision zoo naming: 'denselayerN.norm.1.weight' etc.
    sd = {
        re.sub(
            r"(denselayer\d+\.(?:norm|conv))\.(\d)\.", r"\1\2.", k
        ): v
        for k, v in sd.items()
    }

    from ncnet_tpu.models.densenet import TRUNK_BLOCKS

    params = {
        "conv0": {"kernel": _conv2d_kernel(sd["conv0.weight"])},
        "norm0": _bn(sd, "norm0"),
    }
    for bi, n_layers in enumerate(TRUNK_BLOCKS):
        block = []
        for li in range(n_layers):
            p = f"denseblock{bi + 1}.denselayer{li + 1}."
            block.append(
                {
                    "norm1": _bn(sd, p + "norm1"),
                    "conv1": {"kernel": _conv2d_kernel(sd[p + "conv1.weight"])},
                    "norm2": _bn(sd, p + "norm2"),
                    "conv2": {"kernel": _conv2d_kernel(sd[p + "conv2.weight"])},
                }
            )
        params[f"denseblock{bi + 1}"] = block
        t = f"transition{bi + 1}."
        params[f"transition{bi + 1}"] = {
            "norm": _bn(sd, t + "norm"),
            "conv": {"kernel": _conv2d_kernel(sd[t + "conv.weight"])},
        }
    return params


def convert_neigh_consensus(state_dict, prefix="NeighConsensus.conv.", pre_permuted=True):
    """Conv4d stack -> list of {'kernel': [k,k,k,k,cin,cout], 'bias': [cout]}."""
    sd = {k[len(prefix):]: v for k, v in state_dict.items() if k.startswith(prefix)}
    indices = sorted({int(k.split(".")[0]) for k in sd})
    params = []
    for idx in indices:
        w = _np(sd[f"{idx}.weight"])
        if pre_permuted:
            # [k1, cout, cin, k2, k3, k4] -> [cout, cin, k1, k2, k3, k4]
            w = w.transpose(1, 2, 0, 3, 4, 5)
        # [cout, cin, k1, k2, k3, k4] -> [k1, k2, k3, k4, cin, cout]
        w = w.transpose(2, 3, 4, 5, 1, 0)
        params.append({"kernel": w, "bias": _np(sd[f"{idx}.bias"])})
    return params


def load_trunk_weights(path, cnn="resnet101"):
    """Load backbone trunk weights from any supported source file.

    Accepts:
      * a reference ``.pth.tar`` training checkpoint (keys under
        ``FeatureExtraction.model.``, possibly legacy ``vgg.``-prefixed);
      * a raw torchvision state dict (``.pth``, keys like ``conv1.weight``,
        ``layer1.0.conv1.weight`` / ``features.0.weight``);
      * an ncnet_tpu msgpack checkpoint (takes its
        ``params['feature_extraction']``).

    Returns the ``feature_extraction`` param tree for ``cnn``.
    """
    if path.endswith(".msgpack"):
        from ncnet_tpu.train.checkpoint import load_checkpoint

        return load_checkpoint(path).params["feature_extraction"]

    import torch

    blob = torch.load(path, map_location="cpu", weights_only=False)
    sd = blob.get("state_dict", blob) if isinstance(blob, dict) else blob
    sd = {k.replace("vgg", "model"): v for k, v in sd.items()}
    prefix = (
        "FeatureExtraction.model."
        if any(k.startswith("FeatureExtraction.model.") for k in sd)
        else ""
    )
    if cnn == "resnet101":
        return convert_resnet101_trunk(sd, prefix=prefix)
    if cnn == "vgg":
        if prefix == "" and any(k.startswith("features.") for k in sd):
            prefix = "features."
        return convert_vgg16_trunk(sd, prefix=prefix)
    if cnn == "densenet201":
        if prefix == "" and any(k.startswith("features.") for k in sd):
            prefix = "features."
        return convert_densenet201_trunk(sd, prefix=prefix)
    raise ValueError(f"unsupported backbone for trunk conversion: {cnn!r}")


def convert_checkpoint(path):
    """Load a reference .pth.tar and return ``(config, params)``.

    Applies the reference's legacy key rename ``'vgg' -> 'model'``
    (lib/model.py:214) and reads the architecture from the embedded args,
    preserving the self-describing-checkpoint property.
    """
    import torch

    from ncnet_tpu.models.immatchnet import ImMatchNetConfig

    ckpt = torch.load(path, map_location="cpu", weights_only=False)
    sd = {k.replace("vgg", "model"): v for k, v in ckpt["state_dict"].items()}
    args = ckpt.get("args")
    cnn = getattr(args, "fe_arch", None) or getattr(
        args, "feature_extraction_cnn", "resnet101"
    )
    config = ImMatchNetConfig(
        feature_extraction_cnn=cnn,
        ncons_kernel_sizes=tuple(args.ncons_kernel_sizes),
        ncons_channels=tuple(args.ncons_channels),
    )
    if cnn == "resnet101":
        fe = convert_resnet101_trunk(sd)
    elif cnn == "vgg":
        fe = convert_vgg16_trunk(sd)
    elif cnn == "densenet201":
        fe = convert_densenet201_trunk(sd)
    else:
        raise ValueError(f"unsupported backbone in checkpoint: {cnn!r}")
    params = {
        "feature_extraction": fe,
        "neigh_consensus": convert_neigh_consensus(sd),
    }
    return config, params
