"""JAX persistent compilation cache, one call to enable.

The conv4d NC stack takes minute-scale XLA compiles (benchmarks/PERF.md);
without a persistent cache every process pays them again. Enabling
``jax_compilation_cache_dir`` makes compiles a once-per-machine cost:
identical (program, flags, platform) lookups hit the disk cache across
runs, restarts, and preemption resumes.

Entry point for the ``--compile-cache`` flag of ``scripts/train.py`` and
``bench.py``: call BEFORE the first jit tracing.
"""

import os

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "ncnet_tpu", "xla"
)


def enable_compile_cache(cache_dir=None):
    """Point JAX's persistent compilation cache at ``cache_dir`` (default
    ``~/.cache/ncnet_tpu/xla``); returns the directory used, or ``None``
    when ``cache_dir`` is an empty/'none' sentinel (explicitly disabled).

    The min-compile-time threshold is lowered to 1 s so the many small
    per-shape entry points cache too, not just the big NC stack.
    """
    if cache_dir is not None and str(cache_dir).lower() in ("", "none", "off"):
        return None
    import jax

    cache_dir = os.path.abspath(cache_dir or DEFAULT_CACHE_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return cache_dir
