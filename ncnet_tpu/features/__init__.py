"""Epoch-persistent feature cache for frozen-trunk training and eval.

With the backbone frozen (the reference default), its forward pass over a
fixed dataset is deterministic and parameter-constant — the fastest trunk
pass is the one that never runs. This package extracts trunk features
ONCE into a durable digest-guarded on-disk store (`store`), fills it
lazily or via ``scripts/extract_features.py`` (`extract`), and the
training stack consumes it through ``ncnet_tpu.data.features_loader``
plus the ``from_features`` modes of ``train/loss.py`` and
``train/step.py``.
"""

from ncnet_tpu.features.extract import (
    make_batch_extractor,
    make_multires_batch_extractor,
    populate_store,
    populate_store_multires,
)
from ncnet_tpu.features.store import (
    FeatureCacheMismatch,
    FeatureStore,
    GalleryFeatureStore,
    MultiResFeatureStore,
    MultiResGalleryFeatureStore,
    feature_dtype_name,
    pooled_digest,
    trunk_digest,
)

__all__ = [
    "FeatureCacheMismatch",
    "FeatureStore",
    "GalleryFeatureStore",
    "MultiResFeatureStore",
    "MultiResGalleryFeatureStore",
    "feature_dtype_name",
    "make_batch_extractor",
    "make_multires_batch_extractor",
    "pooled_digest",
    "populate_store",
    "populate_store_multires",
    "trunk_digest",
]
