"""Durable on-disk store of precomputed trunk features.

The default training config freezes the backbone (train/step.py), so the
ResNet-101 forward over a fixed dataset is a parameter-constant
computation — yet the reference re-runs it on every image at every step
of every epoch. This store makes the trunk pass a one-time cost: one
shard per image (source and target of each pair are separate shards),
bf16 or f32, written with the ``resilience.durable`` discipline
(temp + fsync + atomic rename + a ``<path>.sha256`` sidecar verified at
read), so a preemption mid-extraction never leaves a torn shard and
bitrot is detected instead of silently training on garbage features.

Staleness is the failure mode that matters: features extracted by a
DIFFERENT trunk (other weights, other backbone, other image size, other
dtype, centering or normalization toggled) correlate like noise and
training "works" while learning nothing. The manifest therefore records
a digest over (trunk params bytes, cnn name, image size, feature dtype,
normalize/center flags); opening a store with a non-matching digest
raises :class:`FeatureCacheMismatch` — a stale cache is rejected, never
silently reused.

Disk math (PF-Pascal train, 400x400 resnet101): 25x25x1024 features are
1.28 MB/image in bf16; ~2940 pairs x 2 images ~= 7.6 GB (2x in f32).
"""

import hashlib
import json
import os

import numpy as np

from ncnet_tpu.resilience import durable

MANIFEST_NAME = "manifest.json"
STORE_VERSION = 1

#: dtypes a store may hold; bf16 numpy arrays come from ml_dtypes (a jax
#: dependency), so shards round-trip bit-exactly without torch/jax imports
_DTYPE_NAMES = ("float32", "bfloat16")


class FeatureCacheMismatch(RuntimeError):
    """The cache on disk was extracted under a different trunk/config."""


def np_dtype(name):
    if name == "float32":
        return np.dtype(np.float32)
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    raise ValueError(
        f"unsupported feature dtype {name!r}; have {_DTYPE_NAMES}"
    )


def feature_dtype_name(config):
    """The on-disk dtype a config's extraction produces (models/immatchnet
    ``extract_features``: bf16 under ``half_precision``, else f32)."""
    return "bfloat16" if config.half_precision else "float32"


def trunk_digest(fe_params, config, image_size):
    """Digest of everything that determines the extracted feature bytes.

    Covers the trunk parameter VALUES (not just the architecture name —
    re-extracting after loading different pretrained weights must miss)
    plus the extraction-relevant config: cnn name, input image size,
    feature dtype, and the normalize/center toggles that run inside
    ``feature_extraction_apply``.

    ``image_size=None`` means size-agnostic: gallery stores
    (:class:`GalleryFeatureStore`) hold images of heterogeneous resized
    shapes, each shard self-describing its own — the digest then pins
    everything EXCEPT the size.
    """
    import jax
    from flax import serialization

    state = serialization.to_state_dict(
        jax.tree.map(lambda x: np.asarray(x), fe_params)
    )
    h = hashlib.sha256(serialization.msgpack_serialize(state))
    h.update(
        json.dumps(
            {
                "cnn": config.feature_extraction_cnn,
                "image_size": (
                    None if image_size is None
                    else [int(s) for s in image_size]
                ),
                "feature_dtype": feature_dtype_name(config),
                "normalize_features": bool(config.normalize_features),
                "center_features": bool(config.center_features),
            },
            sort_keys=True,
        ).encode("ascii")
    )
    return h.hexdigest()


def _encode_shard(arr, dtype_name):
    """Self-describing shard bytes: a tiny JSON header (shape + dtype)
    then the raw feature bytes. Exact non-multiple-of-stride image sizes
    make the feature shape awkward to predict, so each shard carries its
    own; uniformity across a store is enforced by `get` callers stacking."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype != np_dtype(dtype_name):
        raise ValueError(
            f"shard dtype {arr.dtype} does not match the store's "
            f"{dtype_name!r}; extract with the matching config instead of "
            "casting (a silent cast would hide config drift)"
        )
    head = json.dumps(
        {"shape": list(arr.shape), "dtype": dtype_name}
    ).encode("ascii")
    return len(head).to_bytes(4, "little") + head + arr.tobytes()


def _decode_shard(blob, dtype_name):
    hlen = int.from_bytes(blob[:4], "little")
    head = json.loads(blob[4 : 4 + hlen].decode("ascii"))
    if head["dtype"] != dtype_name:
        raise FeatureCacheMismatch(
            f"shard dtype {head['dtype']!r} does not match the manifest's "
            f"{dtype_name!r}"
        )
    arr = np.frombuffer(
        blob, dtype=np_dtype(dtype_name), offset=4 + hlen
    )
    return arr.reshape(head["shape"])


class FeatureStore:
    """One directory of per-image feature shards plus a digest manifest.

    Construct through `create` / `open_store` / `open_or_create`; the
    manifest and every shard go through ``resilience.durable`` writes.
    """

    def __init__(self, root, manifest):
        self.root = os.path.abspath(root)
        self.manifest = manifest

    # -- construction --------------------------------------------------------

    @classmethod
    def create(cls, root, digest, config, image_size, num_items):
        manifest = {
            "version": STORE_VERSION,
            "digest": str(digest),
            "cnn": config.feature_extraction_cnn,
            "image_size": [int(s) for s in image_size],
            "feature_dtype": feature_dtype_name(config),
            "normalize_features": bool(config.normalize_features),
            "center_features": bool(config.center_features),
            "num_items": int(num_items),
        }
        np_dtype(manifest["feature_dtype"])  # validates the name
        durable.durable_write_bytes(
            os.path.join(os.path.abspath(root), MANIFEST_NAME),
            json.dumps(manifest, sort_keys=True, indent=1).encode("ascii"),
        )
        return cls(root, manifest)

    @classmethod
    def open_store(cls, root, expected_digest=None, num_items=None):
        """Open an existing store, REJECTING digest / size mismatches.

        Raises ``FileNotFoundError`` when there is no manifest,
        :class:`FeatureCacheMismatch` when the manifest was written under a
        different trunk/config digest or for a different dataset size, and
        ``resilience.durable.IntegrityError`` on manifest corruption.
        """
        path = os.path.join(os.path.abspath(root), MANIFEST_NAME)
        if not os.path.exists(path):
            raise FileNotFoundError(f"no feature-cache manifest at {path}")
        manifest = json.loads(
            durable.read_verified_bytes(path).decode("ascii")
        )
        if expected_digest is not None and manifest.get("digest") != str(
            expected_digest
        ):
            raise FeatureCacheMismatch(
                f"feature cache at {root} was extracted under digest "
                f"{manifest.get('digest')!r}, but the current trunk/config "
                f"digests to {expected_digest!r} (trunk weights, backbone, "
                "image size, feature dtype, or normalize/center flags "
                "changed). Re-extract into a fresh directory — training on "
                "stale features would silently learn noise."
            )
        if num_items is not None and int(manifest.get("num_items", -1)) != int(
            num_items
        ):
            raise FeatureCacheMismatch(
                f"feature cache at {root} holds {manifest.get('num_items')} "
                f"items but the dataset has {num_items}; the cache belongs "
                "to a different dataset"
            )
        return cls(root, manifest)

    @classmethod
    def open_or_create(cls, root, digest, config, image_size, num_items):
        """Open a matching store, or create an empty one when absent.

        An EXISTING manifest with a different digest still raises — only
        a missing manifest falls through to creation."""
        try:
            return cls.open_store(
                root, expected_digest=digest, num_items=num_items
            )
        except FileNotFoundError:
            return cls.create(root, digest, config, image_size, num_items)

    # -- shard IO ------------------------------------------------------------

    @property
    def num_items(self):
        return int(self.manifest["num_items"])

    @property
    def dtype(self):
        return np_dtype(self.manifest["feature_dtype"])

    def shard_path(self, idx, role):
        if role not in ("source", "target"):
            raise ValueError(f"unknown shard role {role!r}")
        return os.path.join(self.root, f"{int(idx):08d}.{role}.feat")

    def has(self, idx):
        return all(
            os.path.exists(self.shard_path(idx, r))
            for r in ("source", "target")
        )

    def missing(self):
        """Indices without both shards — the lazy-fill worklist."""
        return [i for i in range(self.num_items) if not self.has(i)]

    def complete(self):
        return not self.missing()

    def put(self, idx, source_features, target_features):
        """Durably write one pair's feature shards (idempotent rewrite)."""
        name = self.manifest["feature_dtype"]
        for role, arr in (
            ("source", source_features),
            ("target", target_features),
        ):
            durable.durable_write_bytes(
                self.shard_path(idx, role), _encode_shard(arr, name)
            )

    def get(self, idx):
        """Read one pair's ``(source, target)`` features, digest-verified
        (raises ``durable.IntegrityError`` on bitrot)."""
        name = self.manifest["feature_dtype"]
        return tuple(
            _decode_shard(
                durable.read_verified_bytes(self.shard_path(idx, role)), name
            )
            for role in ("source", "target")
        )

    def shard_nbytes(self, idx=0):
        """On-disk payload size of one pair (both shards), for fit math."""
        return sum(
            os.path.getsize(self.shard_path(idx, r))
            for r in ("source", "target")
        )


class GalleryFeatureStore:
    """Path-keyed trunk-feature store for retrieval galleries (InLoc).

    The pair store above is index-keyed against a fixed dataset; a
    retrieval gallery is the opposite shape: an open-ended set of
    database images, each revisited by MANY queries (the InLoc shortlist
    shows every pano to ~tens of queries — the same trunk GFLOPs
    recomputed per query-pano pair). Here each image's features are one
    durable shard keyed by a digest of its PATH, under a manifest pinned
    to the trunk digest (weights + cnn + dtype + normalize/center; image
    size excluded — gallery images resize per their own aspect, and each
    shard self-describes its shape). Opening with a different trunk
    digest raises :class:`FeatureCacheMismatch`: stale features are
    rejected, never silently matched against.

    Shards use the same durable write/read discipline as the pair store
    (temp + fsync + atomic rename + sha256 sidecar verified at read), so
    a killed dump never leaves a torn shard and bitrot is detected.
    """

    def __init__(self, root, manifest):
        self.root = os.path.abspath(root)
        self.manifest = manifest

    @classmethod
    def create(cls, root, digest, config):
        manifest = {
            "version": STORE_VERSION,
            "kind": "gallery",
            "digest": str(digest),
            "cnn": config.feature_extraction_cnn,
            "feature_dtype": feature_dtype_name(config),
            "normalize_features": bool(config.normalize_features),
            "center_features": bool(config.center_features),
        }
        np_dtype(manifest["feature_dtype"])  # validates the name
        durable.durable_write_bytes(
            os.path.join(os.path.abspath(root), MANIFEST_NAME),
            json.dumps(manifest, sort_keys=True, indent=1).encode("ascii"),
        )
        return cls(root, manifest)

    @classmethod
    def open_store(cls, root, expected_digest=None):
        path = os.path.join(os.path.abspath(root), MANIFEST_NAME)
        if not os.path.exists(path):
            raise FileNotFoundError(f"no feature-cache manifest at {path}")
        manifest = json.loads(
            durable.read_verified_bytes(path).decode("ascii")
        )
        if manifest.get("kind") != "gallery":
            raise FeatureCacheMismatch(
                f"feature cache at {root} is a "
                f"{manifest.get('kind', 'pair')!r} store, not a gallery "
                "store; point --feature-store at its own directory"
            )
        if expected_digest is not None and manifest.get("digest") != str(
            expected_digest
        ):
            raise FeatureCacheMismatch(
                f"gallery feature cache at {root} was extracted under "
                f"digest {manifest.get('digest')!r}, but the current "
                f"trunk/config digests to {expected_digest!r} (trunk "
                "weights, backbone, feature dtype, or normalize/center "
                "flags changed). Re-extract into a fresh directory — "
                "matching against stale features would silently produce "
                "noise."
            )
        return cls(root, manifest)

    @classmethod
    def open_or_create(cls, root, digest, config):
        """Open a matching store, or create an empty one when absent.
        An EXISTING manifest with a different digest still raises."""
        try:
            return cls.open_store(root, expected_digest=digest)
        except FileNotFoundError:
            return cls.create(root, digest, config)

    def shard_path(self, image_path):
        key = hashlib.sha256(str(image_path).encode("utf-8")).hexdigest()
        return os.path.join(self.root, f"{key[:32]}.feat")

    def has(self, image_path):
        return os.path.exists(self.shard_path(image_path))

    def put(self, image_path, features):
        """Durably write one image's features (idempotent rewrite)."""
        durable.durable_write_bytes(
            self.shard_path(image_path),
            _encode_shard(features, self.manifest["feature_dtype"]),
        )

    def get(self, image_path):
        """Read one image's features, digest-verified (raises
        ``durable.IntegrityError`` on bitrot)."""
        return _decode_shard(
            durable.read_verified_bytes(self.shard_path(image_path)),
            self.manifest["feature_dtype"],
        )


# ---------------------------------------------------------------------------
# multi-resolution stores (ncnet_tpu.refine): one trunk, two resolutions


def pooled_digest(base_digest, factor):
    """Digest of the POOLED tier derived from a high-res tier's digest.

    The low-res features are a pure function of the high-res ones
    (``refine.pool.pool_features``: r x r mean + re-L2-norm), so their
    identity is exactly (high-res identity, pool factor). Deriving the
    digest this way makes staleness transitive BY CONSTRUCTION: any
    change that re-digests the high-res tier (new trunk weights, other
    dtype, flags) re-digests the pooled tier too, and a leftover pooled
    directory from an older trunk refuses to open — there is no way to
    pair fresh high-res shards with stale coarse ones.
    """
    if int(factor) < 1:
        raise ValueError(f"pool factor must be >= 1, got {factor}")
    h = hashlib.sha256(str(base_digest).encode("ascii"))
    h.update(f":avgpool{int(factor)}".encode("ascii"))
    return h.hexdigest()


class MultiResFeatureStore:
    """Two digest-linked pair stores: the trunk features and their pool.

    Layout: ``root/hi`` holds the full-resolution `FeatureStore` under
    the trunk digest; ``root/lo{factor}`` holds the pooled tier under
    `pooled_digest`. Both tiers of a pair are written together by one
    `put` (the extractor pools on device in the same jitted pass), and
    `missing` reports a pair until BOTH tiers hold it — a crash between
    the two writes re-extracts that pair instead of serving a torn
    resolution ladder. Opening either tier stale raises
    :class:`FeatureCacheMismatch` exactly like the single-tier stores.
    """

    def __init__(self, hi, lo, factor):
        self.hi = hi
        self.lo = lo
        self.factor = int(factor)

    @staticmethod
    def _roots(root, factor):
        root = os.path.abspath(root)
        return (
            os.path.join(root, "hi"),
            os.path.join(root, f"lo{int(factor)}"),
        )

    @classmethod
    def create(cls, root, digest, config, image_size, num_items, factor):
        hi_root, lo_root = cls._roots(root, factor)
        hi = FeatureStore.create(
            hi_root, digest, config, image_size, num_items
        )
        lo = FeatureStore.create(
            lo_root, pooled_digest(digest, factor), config, image_size,
            num_items,
        )
        return cls(hi, lo, factor)

    @classmethod
    def open_store(cls, root, factor, expected_digest=None, num_items=None):
        hi_root, lo_root = cls._roots(root, factor)
        hi = FeatureStore.open_store(
            hi_root, expected_digest=expected_digest, num_items=num_items
        )
        lo = FeatureStore.open_store(
            lo_root,
            expected_digest=(
                None
                if expected_digest is None
                else pooled_digest(expected_digest, factor)
            ),
            num_items=num_items,
        )
        return cls(hi, lo, factor)

    @classmethod
    def open_or_create(cls, root, digest, config, image_size, num_items,
                       factor):
        """Open a matching two-tier store, or create an empty one. An
        EXISTING manifest with a different digest (either tier) raises."""
        try:
            return cls.open_store(
                root, factor, expected_digest=digest, num_items=num_items
            )
        except FileNotFoundError:
            return cls.create(
                root, digest, config, image_size, num_items, factor
            )

    @property
    def num_items(self):
        return self.hi.num_items

    @property
    def dtype(self):
        return self.hi.dtype

    def has(self, idx):
        return self.hi.has(idx) and self.lo.has(idx)

    def missing(self):
        return [i for i in range(self.num_items) if not self.has(i)]

    def complete(self):
        return not self.missing()

    def put(self, idx, source_hi, target_hi, source_lo, target_lo):
        """Durably write one pair at BOTH resolutions (idempotent)."""
        self.hi.put(idx, source_hi, target_hi)
        self.lo.put(idx, source_lo, target_lo)

    def get(self, idx):
        """``((source_hi, target_hi), (source_lo, target_lo))``."""
        return self.hi.get(idx), self.lo.get(idx)


class MultiResGalleryFeatureStore:
    """`GalleryFeatureStore` parity for the two-tier layout (InLoc)."""

    def __init__(self, hi, lo, factor):
        self.hi = hi
        self.lo = lo
        self.factor = int(factor)

    @classmethod
    def open_or_create(cls, root, digest, config, factor):
        hi_root, lo_root = MultiResFeatureStore._roots(root, factor)
        hi = GalleryFeatureStore.open_or_create(hi_root, digest, config)
        lo = GalleryFeatureStore.open_or_create(
            lo_root, pooled_digest(digest, factor), config
        )
        return cls(hi, lo, factor)

    def has(self, image_path):
        return self.hi.has(image_path) and self.lo.has(image_path)

    def put(self, image_path, features_hi, features_lo):
        self.hi.put(image_path, features_hi)
        self.lo.put(image_path, features_lo)

    def get(self, image_path):
        """``(features_hi, features_lo)``, each digest-verified."""
        return self.hi.get(image_path), self.lo.get(image_path)
