"""Populate a :class:`FeatureStore` by running the frozen trunk once.

Used by the ``scripts/extract_features.py`` CLI and by the lazy
fill-on-first-epoch path in ``scripts/train.py`` (--feature-cache): only
the MISSING shards are extracted, so an interrupted extraction resumes
where it stopped and a populated cache costs one directory scan.
"""

import time

import numpy as np

from ncnet_tpu.models.immatchnet import extract_features
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import default_registry


def make_batch_extractor(params, config):
    """Jitted ``[b, h, w, 3] image batch -> feature batch`` for the
    config's trunk; uint8 batches are ImageNet-normalized on device, the
    same dtype keying as the training loss (train/loss.py)."""
    import jax
    import jax.numpy as jnp

    def _extract(images):
        if images.dtype == jnp.uint8:
            from ncnet_tpu.ops.image import imagenet_normalize

            images = imagenet_normalize(images.astype(jnp.float32))
        return extract_features(params, config, images)

    return jax.jit(_extract)


def make_multires_batch_extractor(params, config, factor):
    """Jitted ``image batch -> (hi, lo) feature batches``: ONE trunk
    forward per image, the pooled tier derived on device in the same
    program (``refine.pool.pool_features`` — the two tiers can never
    come from different trunks because they come from the same pass)."""
    import jax
    import jax.numpy as jnp

    from ncnet_tpu.refine.pool import pool_features

    def _extract(images):
        if images.dtype == jnp.uint8:
            from ncnet_tpu.ops.image import imagenet_normalize

            images = imagenet_normalize(images.astype(jnp.float32))
        hi = extract_features(params, config, images)
        return hi, pool_features(
            hi, factor, normalize=config.normalize_features
        )

    return jax.jit(_extract)


def populate_store_multires(store, params, config, dataset, batch_size=8,
                            log_every=0):
    """`populate_store` for a :class:`MultiResFeatureStore`: every
    missing pair gets BOTH resolution tiers from one trunk pass per
    image. Returns the count of pairs extracted."""
    if len(dataset) != store.num_items:
        raise ValueError(
            f"dataset has {len(dataset)} items but the store manifest "
            f"records {store.num_items}"
        )
    missing = store.missing()
    if not missing:
        return 0
    extractor = make_multires_batch_extractor(params, config, store.factor)
    out_dtype = store.dtype
    metrics = default_registry()
    m_shards = metrics.counter(
        "feature_shards_written_total", "feature shards durably written"
    )
    m_bytes = metrics.counter(
        "feature_shard_bytes_total", "feature payload bytes written"
    )
    t0 = time.perf_counter()
    done = 0
    for lo in range(0, len(missing), batch_size):
        group = missing[lo : lo + batch_size]
        with trace.span("features/extract_batch"):
            samples = [dataset[i] for i in group]
            pad = batch_size - len(group)
            if pad:
                samples = samples + [samples[-1]] * pad
            src = np.stack([s["source_image"] for s in samples])
            tgt = np.stack([s["target_image"] for s in samples])
            hi, low = extractor(np.concatenate([src, tgt], axis=0))
            hi, low = np.asarray(hi), np.asarray(low)
        if hi.dtype != out_dtype:
            raise RuntimeError(
                f"extractor produced {hi.dtype} but the store holds "
                f"{out_dtype}; the config does not match the manifest"
            )
        with trace.span("features/store_put"):
            for j, idx in enumerate(group):
                store.put(
                    idx,
                    hi[j], hi[batch_size + j],
                    low[j], low[batch_size + j],
                )
                m_shards.inc(2)
                m_bytes.inc(
                    int(hi[j].nbytes) + int(hi[batch_size + j].nbytes)
                    + int(low[j].nbytes) + int(low[batch_size + j].nbytes)
                )
        done += len(group)
        if log_every and (done // batch_size) % log_every == 0:
            rate = done / max(time.perf_counter() - t0, 1e-9)
            print(
                f"[features] {done}/{len(missing)} pairs extracted "
                f"({rate:.1f} pairs/s, 2 resolutions)",
                flush=True,
            )
    return done


def populate_store(store, params, config, dataset, batch_size=8,
                   log_every=0):
    """Extract and durably write every missing shard; returns the count
    of pairs extracted (0 when the store was already complete).

    Source and target images of each chunk run as ONE double-batch trunk
    application, and the final partial chunk is padded by repetition so
    the jitted extractor compiles exactly once per store.
    """
    if len(dataset) != store.num_items:
        # belt alongside the manifest's num_items check: a store populated
        # from a different dataset must not be silently topped up
        raise ValueError(
            f"dataset has {len(dataset)} items but the store manifest "
            f"records {store.num_items}"
        )
    missing = store.missing()
    if not missing:
        return 0
    extractor = make_batch_extractor(params, config)
    out_dtype = store.dtype
    metrics = default_registry()
    m_shards = metrics.counter(
        "feature_shards_written_total", "feature shards durably written"
    )
    m_bytes = metrics.counter(
        "feature_shard_bytes_total", "feature payload bytes written"
    )
    t0 = time.perf_counter()
    done = 0
    for lo in range(0, len(missing), batch_size):
        group = missing[lo : lo + batch_size]
        with trace.span("features/extract_batch"):
            samples = [dataset[i] for i in group]
            pad = batch_size - len(group)
            if pad:
                samples = samples + [samples[-1]] * pad
            src = np.stack([s["source_image"] for s in samples])
            tgt = np.stack([s["target_image"] for s in samples])
            feats = np.asarray(
                extractor(np.concatenate([src, tgt], axis=0))
            )
        if feats.dtype != out_dtype:
            raise RuntimeError(
                f"extractor produced {feats.dtype} but the store holds "
                f"{out_dtype}; the config does not match the manifest"
            )
        feats_src, feats_tgt = feats[:batch_size], feats[batch_size:]
        with trace.span("features/store_put"):
            for j, idx in enumerate(group):
                store.put(idx, feats_src[j], feats_tgt[j])
                m_shards.inc()
                m_bytes.inc(
                    int(feats_src[j].nbytes) + int(feats_tgt[j].nbytes)
                )
        done += len(group)
        if log_every and (done // batch_size) % log_every == 0:
            rate = done / max(time.perf_counter() - t0, 1e-9)
            print(
                f"[features] {done}/{len(missing)} pairs extracted "
                f"({rate:.1f} pairs/s)",
                flush=True,
            )
    return done
