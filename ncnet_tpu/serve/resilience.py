"""SLO-aware serving resilience: typed outcomes, admission control,
overload degradation, and stage supervision primitives.

PR 6's `ServeEngine` had a happy-path story only: bounded queues give
backpressure, but a request with a latency budget could still be accepted
and then miss it, a crashed prep worker silently shrank the worker pool,
a hung dispatch wedged the whole pipeline, and overload had no knob other
than "queue up". This module holds the policy pieces the engine now wires
together:

* **Typed outcomes** — every accepted future resolves with either a
  result or one of the exception types below; callers can branch on type
  instead of parsing tracebacks:

  - :class:`RequestShed` — the engine *chose* not to serve the request
    (admission-control shed, or a drain deadline expired first). Carries
    the estimate/deadline that drove the decision and a ``retry_after_s``
    hint.
  - :class:`DeadlineExceeded` — the request was accepted but its deadline
    expired in-pipeline; it is dropped at the prep / dispatch / readout
    stage named by ``stage`` rather than occupying a device slot.
  - :class:`AdmissionRejected` — ``submit`` refused the request because
    the bounded queue is full. Subclasses ``queue.Full`` (callers that
    handled backpressure before this PR keep working) but adds the
    ``retry_after_s`` hint.
  - :class:`ReplicaDown` — the fleet replica holding the request died
    (killed / hung past the fleet watchdog). ``dispatched=False`` means
    the request never reached the device and the fleet requeues it onto
    a survivor; ``dispatched=True`` means the in-flight batch is lost
    and the caller sees this typed failure (PR 11, `ncnet_tpu.serve.fleet`).
  - :class:`StageFailure` — the request was in flight on a pipeline stage
    that crashed or hung; ONLY in-flight requests fail this way, the
    stage restarts, and the warm compile cache survives
    (``recompiles_after_warmup`` stays 0 — drilled in
    tests/test_serve_resilience.py).

* :class:`LatencyEstimator` — per-bucket EWMA of batch latency (the same
  samples the telemetry latency histograms retain), the completion-time
  estimate admission control sheds against.

* :class:`HysteresisController` — the overload -> degraded-program
  controller: queue pressure above ``high`` for ``up_count`` consecutive
  observations flips dispatch to the cheap pre-warmed ``nc_topk`` band
  program; pressure below ``low`` for ``down_count`` observations flips
  back. The two thresholds plus the dwell counts are the hysteresis —
  pressure oscillating around one threshold cannot make the controller
  thrash programs.

* :func:`run_supervised` / :class:`Watchdog` — the supervision
  primitives: a stage loop that restarts after a crash (after the
  engine's ``on_crash`` fails the in-flight futures with
  :class:`StageFailure`), and a heartbeat watchdog that detects a hung
  dispatch (a thread stuck inside a device call cannot be killed in
  Python, so recovery is: fail its in-flight batch, bump the dispatch
  generation so the wedged thread discards its work when it wakes, and
  start a fresh dispatch thread).

* :func:`drain_on_preemption` — SIGTERM (via the existing
  `resilience.signals.PreemptionGuard`) -> stop admission and drain
  under a deadline, resolving every accepted future with a result or a
  typed :class:`RequestShed`.

Import-light by contract (stdlib only): the engine imports this on every
serving path.
"""

import queue
import threading
import time

from ncnet_tpu.analysis import concurrency


class ServeResilienceError(RuntimeError):
    """Base of every typed serving-resilience outcome."""


class RequestShed(ServeResilienceError):
    """The engine declined to serve the request (load shedding).

    ``reason`` is machine-readable: ``"admission"`` (estimated completion
    would miss the deadline — shed before occupying any queue slot) or
    ``"drain"`` (a drain deadline expired with the request unresolved).
    """

    def __init__(self, message, *, reason, estimated_s=None,
                 deadline_s=None, retry_after_s=None):
        super().__init__(message)
        self.reason = reason
        self.estimated_s = estimated_s
        self.deadline_s = deadline_s
        self.retry_after_s = retry_after_s


class DeadlineExceeded(RequestShed):
    """An accepted request's deadline expired in-pipeline; it was dropped
    at ``stage`` ('prep', 'dispatch', or 'readout') instead of wasting a
    device slot on a result nobody is waiting for."""

    def __init__(self, message, *, stage, deadline_s=None):
        super().__init__(
            message, reason="deadline", deadline_s=deadline_s
        )
        self.stage = stage


class ReplicaDown(ServeResilienceError):
    """The replica holding this request died (killed, crashed, or declared
    hung by the fleet watchdog) before the request completed.

    ``replica`` names the dead replica; ``dispatched`` distinguishes the
    two fates the fleet contract assigns: ``False`` means the request was
    still queued (never on the device) — the fleet REQUEUES it onto a
    surviving replica, so callers normally never see this value —
    ``True`` means the batch was already dispatched to the device when
    the replica died, so the result is unrecoverable and the future
    fails with THIS exception (typed, never silently dropped).
    """

    def __init__(self, message, *, replica=None, dispatched=False):
        super().__init__(message)
        self.replica = replica
        self.dispatched = dispatched


class AdmissionRejected(ServeResilienceError, queue.Full):
    """``submit`` refused the request: the bounded submit queue is full.

    Subclasses ``queue.Full`` so pre-existing backpressure handling keeps
    working; ``retry_after_s`` is the engine's estimate of when a slot is
    likely to free up (one batch latency), the hint a client or an HTTP
    front end maps to ``Retry-After``.
    """

    def __init__(self, message, *, retry_after_s=None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class StageFailure(ServeResilienceError):
    """A pipeline stage crashed or hung while this request was in flight
    on it. Only in-flight requests fail this way; the stage restarted and
    subsequent requests are served from the intact warm compile cache."""

    def __init__(self, stage, message, *, hang=False):
        super().__init__(f"serve {stage} stage "
                         f"{'hang' if hang else 'failure'}: {message}")
        self.stage = stage
        self.hang = hang


# ----------------------------------------------------------------------
# admission control: the completion-time estimate


class LatencyEstimator:
    """EWMA of per-bucket batch latency (dispatch -> readout complete).

    ``observe(key, s)`` feeds one batch's latency (the engine calls it at
    readout, alongside the telemetry histogram's ``observe``);
    ``estimate(key)`` returns the per-key EWMA, falling back to the
    cross-bucket EWMA when the key is unknown (the ``prep_fn`` path
    cannot know its bucket at submit time), and None before any
    observation — admission control admits blind until the first batch
    has been measured rather than shedding on a guess.
    """

    def __init__(self, alpha=0.25):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self._lock = concurrency.make_lock("serve.latency_estimator")
        self._per_key = {}
        self._global = None

    def observe(self, key, seconds):
        s = float(seconds)
        with self._lock:
            prev = self._per_key.get(key)
            self._per_key[key] = (
                s if prev is None else prev + self.alpha * (s - prev)
            )
            self._global = (
                s if self._global is None
                else self._global + self.alpha * (s - self._global)
            )

    def estimate(self, key=None):
        with self._lock:
            if key is not None and key in self._per_key:
                return self._per_key[key]
            return self._global


# ----------------------------------------------------------------------
# overload degradation: the hysteresis controller


class HysteresisController:
    """Queue-pressure -> degraded-mode controller with hysteresis.

    ``update(pressure)`` is called by the engine's dispatch thread (every
    loop iteration, so it keeps observing while idle and can flip BACK
    when pressure clears) and returns the current mode. ``pressure`` is
    the engine's queued-work fraction (queued requests / queue limit).

    Flip up: ``pressure >= high`` for ``up_count`` consecutive updates.
    Flip down: ``pressure <= low`` for ``down_count`` consecutive
    updates. Readings in the dead band (low, high) reset both streaks —
    mid-band noise keeps the current mode, which is the point of the
    hysteresis.
    """

    def __init__(self, high=0.75, low=0.25, up_count=2, down_count=4):
        if not low < high:
            raise ValueError(
                f"hysteresis needs low < high, got low={low} high={high}"
            )
        if up_count < 1 or down_count < 1:
            raise ValueError("up_count and down_count must be >= 1")
        self.high = high
        self.low = low
        self.up_count = up_count
        self.down_count = down_count
        self.degraded = False
        self.flips = 0
        self.last_pressure = 0.0
        self._above = 0
        self._below = 0

    def update(self, pressure):
        p = float(pressure)
        self.last_pressure = p
        if p >= self.high:
            self._above += 1
            self._below = 0
        elif p <= self.low:
            self._below += 1
            self._above = 0
        else:
            self._above = 0
            self._below = 0
        if not self.degraded and self._above >= self.up_count:
            self.degraded = True
            self.flips += 1
            self._above = 0
        elif self.degraded and self._below >= self.down_count:
            self.degraded = False
            self.flips += 1
            self._below = 0
        return self.degraded


class QualityLadder:
    """Queue-pressure -> quality-rung controller (multi-level hysteresis).

    The generalization of `HysteresisController` from two modes to an
    ordered ladder of program variants, richest first — e.g.
    ``("refined", "standard", "degraded")`` (scripts/serve.py's default
    with ``--refine``). The engine's dispatch thread calls
    ``update(pressure)`` every loop iteration; sustained high pressure
    climbs ONE rung toward cheaper per flip, sustained low pressure
    steps back toward richer, and dead-band readings reset both streaks
    — exactly the two-mode controller's discipline, applied per rung, so
    a pressure spike cannot leap from refined straight to degraded and a
    recovering queue re-earns each quality level one flip at a time.

    Every rung must name a program family the engine actually warmed
    ("standard" plus any of "refined"/"degraded"); the engine clamps an
    unservable rung to "standard" rather than crash mid-dispatch.
    """

    def __init__(self, rungs=("refined", "standard", "degraded"),
                 start="standard", high=0.75, low=0.25,
                 up_count=2, down_count=4):
        rungs = tuple(rungs)
        if len(rungs) < 2:
            raise ValueError(f"a ladder needs >= 2 rungs, got {rungs!r}")
        if len(set(rungs)) != len(rungs):
            raise ValueError(f"duplicate rungs: {rungs!r}")
        if start not in rungs:
            raise ValueError(f"start rung {start!r} not in {rungs!r}")
        if not low < high:
            raise ValueError(
                f"hysteresis needs low < high, got low={low} high={high}"
            )
        if up_count < 1 or down_count < 1:
            raise ValueError("up_count and down_count must be >= 1")
        self.rungs = rungs
        self.high = high
        self.low = low
        self.up_count = up_count
        self.down_count = down_count
        self.flips = 0
        self.last_pressure = 0.0
        self._above = 0
        self._below = 0
        self._i = rungs.index(start)

    @property
    def variant(self):
        """The current rung's program-variant name."""
        return self.rungs[self._i]

    @property
    def rung(self):
        """Current position, 0 = richest."""
        return self._i

    @property
    def degraded(self):
        # NAMED-rung semantics, not position: a ("refined", "standard")
        # ladder never reports degraded — its cheapest rung is the
        # standard program, and metrics/report() must say so.
        return self.variant == "degraded"

    def update(self, pressure):
        p = float(pressure)
        self.last_pressure = p
        if p >= self.high:
            self._above += 1
            self._below = 0
        elif p <= self.low:
            self._below += 1
            self._above = 0
        else:
            self._above = 0
            self._below = 0
        if self._above >= self.up_count and self._i < len(self.rungs) - 1:
            self._i += 1
            self.flips += 1
            self._above = 0
        elif self._below >= self.down_count and self._i > 0:
            self._i -= 1
            self.flips += 1
            self._below = 0
        return self.variant


# ----------------------------------------------------------------------
# supervision: restart-on-crash stage loops + the dispatch watchdog


def run_supervised(loop_fn, *, on_crash, stopping=None):
    """Run a pipeline-stage loop under crash supervision.

    ``loop_fn()`` returning normally ends the stage (it saw its shutdown
    sentinel). An escaped exception is a STAGE crash — request-level
    failures are caught inside the loop and fail only their own future —
    so ``on_crash(exc)`` runs (the engine fails the in-flight future with
    a typed :class:`StageFailure` and counts the restart) and the loop
    re-enters: the restart. ``stopping()`` (optional) short-circuits the
    restart when the stage has been superseded (a stale dispatch
    generation) or the engine is tearing down.
    """
    while True:
        try:
            loop_fn()
            return
        except BaseException as exc:  # noqa: BLE001 — supervision boundary
            on_crash(exc)
            if stopping is not None and stopping():
                return


class Watchdog:
    """Heartbeat watchdog for the dispatch stage.

    Polls every ``timeout / 4`` seconds; when ``busy_fn()`` reports
    in-flight work AND ``clock() - beat_fn()`` exceeds ``timeout``, calls
    ``on_hang()`` once per hang (the engine fails the in-flight batch,
    bumps the dispatch generation, and starts a fresh dispatch thread —
    the next poll then sees the new thread's heartbeat).

    ``timeout`` must exceed the worst-case single-batch latency
    (including any live compile of an unwarmed bucket), or a legitimately
    long device call reads as a hang.
    """

    def __init__(self, timeout, *, beat_fn, busy_fn, on_hang,
                 clock=time.monotonic):
        if timeout <= 0:
            raise ValueError(f"watchdog timeout must be > 0, got {timeout}")
        self.timeout = timeout
        self._beat_fn = beat_fn
        self._busy_fn = busy_fn
        self._on_hang = on_hang
        self._clock = clock
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="serve-watchdog", daemon=True
        )

    def start(self):
        self._thread.start()
        return self

    @property
    def thread(self):
        """The watchdog's poll thread — for the owner's thread ledger."""
        return self._thread

    def _loop(self):
        poll = self.timeout / 4.0
        while not self._stop.wait(poll):
            if not self._busy_fn():
                continue
            if self._clock() - self._beat_fn() > self.timeout:
                self._on_hang()

    def stop(self, join_timeout=None):
        self._stop.set()
        # on_hang may stop its own watchdog (a fleet killing a hung
        # replica); joining the current thread would raise and kill the
        # hang-handler mid-flight — _stop alone already ends the loop.
        if (self._thread.is_alive()
                and self._thread is not threading.current_thread()):
            self._thread.join(join_timeout)


# ----------------------------------------------------------------------
# graceful drain on preemption


def drain_on_preemption(engine, guard, *, timeout=None, poll_s=0.05):
    """Watch a `PreemptionGuard`; when it trips (SIGTERM/SIGINT), stop
    admission and drain the engine under ``timeout`` seconds — every
    accepted future resolves with its result or a typed
    :class:`RequestShed`. Returns the watcher thread; the caller joins it
    (or simply exits — it is a daemon)."""

    def _watch():
        while not engine.closed:
            if guard.requested:
                engine.drain(timeout=timeout)
                return
            time.sleep(poll_s)

    t = threading.Thread(
        target=_watch, name="serve-preemption-drain", daemon=True
    )
    t.start()
    return t
