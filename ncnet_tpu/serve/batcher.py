"""Dynamic micro-batching: coalesce concurrent requests per shape bucket.

The batcher is deliberately PASSIVE — a lock-protected data structure
with ``add() / ready() / drain()`` — and takes an injectable clock, so
deadline behavior is deterministically testable with a fake clock and no
real sleeps (tests/test_serve.py). The engine's dispatcher thread drives
it.

Policy (the ISSUE's contract):

* requests group by an opaque ``key`` (the `buckets.pair_bucket` of the
  request; only same-key requests may share a compiled program) and by
  their pinned quality ``variant`` (a rung-pinned request must never be
  coalesced into a batch that will run at a different rung);
* a group flushes when it reaches ``max_batch`` (cap) or when its OLDEST
  request has waited ``max_wait`` seconds (deadline) — latency is bounded
  by max_wait even at low traffic, and a lone request never waits behind
  a full batch;
* DEADLINE-AWARE flush (ISSUE 17): when an ``estimate_fn`` is supplied,
  a group also flushes early once its tightest member's remaining budget
  drops below ``max_wait`` plus the bucket's EWMA service estimate —
  waiting any longer would spend batching headroom the request no longer
  has. Without ``estimate_fn`` the batcher is the fixed-wait baseline
  (the A/B arm of benchmarks/micro_http.py);
* each flushed group becomes a :class:`MicroBatch` padded UP to the
  smallest allowed batch size (powers of two by default, so the warmup
  shape set stays small). Padding replicates a real request's arrays and
  is masked at readout by the engine (only real slots are sliced out),
  so padding never perturbs real results (see the engine's numerical
  contract).

Backpressure is the ENGINE's job (its bounded submit queue); the batcher
itself never blocks.
"""

import dataclasses
import time

from ncnet_tpu.analysis import concurrency
from typing import Callable, List, Optional, Sequence


def default_batch_sizes(max_batch):
    """Powers of two up to and including ``max_batch`` (plus ``max_batch``
    itself when it is not a power of two): the allowed PADDED sizes, i.e.
    the per-bucket shape set warmup must compile."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return tuple(sizes)


def pad_size(n, batch_sizes):
    """Smallest allowed batch size >= ``n``."""
    for b in batch_sizes:
        if b >= n:
            return b
    raise ValueError(
        f"group of {n} exceeds the largest allowed batch size "
        f"{batch_sizes[-1]} (the batcher caps groups at max_batch)"
    )


class Request:
    """One queued request: a bucket key, named per-sample arrays, and the
    future its result resolves. ``t_submit`` feeds latency accounting;
    ``deadline`` (absolute, on the engine clock, None = no SLO) lets the
    pipeline drop the request at any stage once it can no longer be
    served in time (engine's deadline contract, PR 10); ``variant``
    (None = let the engine's controller choose) pins the quality rung the
    request must run at (``X-Quality``, ISSUE 17)."""

    __slots__ = ("key", "payload", "future", "t_submit", "deadline", "variant")

    def __init__(self, key, payload, future, t_submit, deadline=None, variant=None):
        self.key = key
        self.payload = payload
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline
        self.variant = variant


@dataclasses.dataclass
class MicroBatch:
    """A flushed group: ``len(requests)`` real samples to be stacked and
    padded to ``pad_to`` rows (the engine replicates the last real
    payload into the padding slots and discards them at readout).
    ``variant`` is the members' pinned rung (None: controller's pick)."""

    key: object
    requests: List[Request]
    pad_to: int
    variant: Optional[str] = None

    @property
    def occupancy(self):
        """Real-sample fraction of the padded batch (1.0 = no padding)."""
        return len(self.requests) / self.pad_to


class _Group:
    """One open coalescing group: add time of the oldest member, the
    tightest member deadline (None: no member carries one), requests."""

    __slots__ = ("t0", "deadline", "requests")

    def __init__(self, t0, deadline, requests):
        self.t0 = t0
        self.deadline = deadline
        self.requests = requests


class MicroBatcher:
    """Per-(key, variant) request coalescing under a deadline and a cap.

    Thread-safe; all methods are non-blocking. ``clock`` must be a
    monotonic ``() -> float`` (seconds); tests pass a fake. The batcher
    TOLERATES a clock that violates the contract and jumps backwards
    (e.g. a buggy injected clock): no group is ever lost or flushed
    early — deadlines simply stretch until the clock passes the add
    time again, and `add`'s cap flush and `drain` are clock-independent
    (pinned in tests/test_serve_resilience.py).

    ``estimate_fn(bucket_key) -> Optional[float]`` enables deadline-aware
    flushing (see module docstring); None disables it (fixed-wait).
    """

    def __init__(
        self,
        max_batch: int = 8,
        max_wait: float = 0.005,
        batch_sizes: Optional[Sequence[int]] = None,
        clock: Callable[[], float] = time.monotonic,
        estimate_fn: Optional[Callable[[object], Optional[float]]] = None,
    ):
        if max_wait < 0:
            raise ValueError(f"max_wait must be >= 0, got {max_wait}")
        self.max_batch = max_batch
        self.max_wait = max_wait
        self.batch_sizes = (
            tuple(sorted(batch_sizes))
            if batch_sizes is not None
            else default_batch_sizes(max_batch)
        )
        if self.batch_sizes[-1] < max_batch:
            raise ValueError(
                f"batch_sizes {self.batch_sizes} cannot hold a full "
                f"max_batch={max_batch} group"
            )
        self._clock = clock
        self._estimate_fn = estimate_fn
        self._lock = concurrency.make_lock("serve.batcher")
        # (key, variant) -> _Group; insertion-ordered so deadline scans
        # see oldest groups first
        self._groups = {}

    @property
    def deadline_aware(self):
        """Whether the deadline-aware early-flush policy is active."""
        return self._estimate_fn is not None

    def _make_batch(self, key, reqs, variant):
        return MicroBatch(
            key, reqs, pad_size(len(reqs), self.batch_sizes), variant
        )

    def _flush_at(self, key, grp):
        """Absolute time this group should flush: the fixed max_wait
        deadline, pulled earlier when the tightest member's remaining
        budget would drop below max_wait + the bucket's service
        estimate (deadline-aware policy; only with an estimate_fn)."""
        at = grp.t0 + self.max_wait
        if grp.deadline is not None and self._estimate_fn is not None:
            est = self._estimate_fn(key)
            at = min(at, grp.deadline - self.max_wait - (est or 0.0))
        return at

    def add(self, request: Request) -> Optional[MicroBatch]:
        """Queue a request; returns a full MicroBatch if this add filled
        its group to ``max_batch``, else None."""
        gkey = (request.key, request.variant)
        with self._lock:
            grp = self._groups.get(gkey)
            if grp is None:
                if self.max_batch <= 1:
                    # a fresh group already AT the cap (max_batch=1, the
                    # fleet scaling benchmark's no-coalescing mode) must
                    # flush now: parking it would let the next add grow
                    # the group past batch_sizes[-1]
                    return self._make_batch(
                        request.key, [request], request.variant
                    )
                self._groups[gkey] = _Group(
                    self._clock(), request.deadline, [request]
                )
                return None
            grp.requests.append(request)
            if request.deadline is not None and (
                grp.deadline is None or request.deadline < grp.deadline
            ):
                grp.deadline = request.deadline
            if len(grp.requests) >= self.max_batch:
                del self._groups[gkey]
                return self._make_batch(request.key, grp.requests, request.variant)
            return None

    def ready(self, now: Optional[float] = None) -> List[MicroBatch]:
        """Pop every group whose flush time has arrived (oldest request
        waited >= max_wait, or — deadline-aware — the tightest member
        budget no longer covers further waiting). Full groups never sit
        here — `add` returns them immediately."""
        if now is None:
            now = self._clock()
        out = []
        with self._lock:
            expired = [
                gkey
                for gkey, grp in self._groups.items()
                if now >= self._flush_at(gkey[0], grp)
            ]
            for gkey in expired:
                grp = self._groups.pop(gkey)
                out.append(self._make_batch(gkey[0], grp.requests, gkey[1]))
        return out

    def drain(self) -> List[MicroBatch]:
        """Pop everything regardless of deadline (shutdown flush)."""
        out = []
        with self._lock:
            for gkey, grp in self._groups.items():
                out.append(self._make_batch(gkey[0], grp.requests, gkey[1]))
            self._groups.clear()
        return out

    def next_deadline(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds until the next pending group flushes (<= 0: already
        due), or None when empty — the dispatcher's wait timeout. This
        is the EARLIEST of each group's fixed max_wait deadline and its
        deadline-aware pull-forward, so the dispatcher wakes in time for
        tight budgets instead of sleeping through them (ISSUE 17's
        batcher/engine seam fix)."""
        if now is None:
            now = self._clock()
        with self._lock:
            if not self._groups:
                return None
            at = min(
                self._flush_at(gkey[0], grp)
                for gkey, grp in self._groups.items()
            )
        return at - now

    def pending(self) -> int:
        """Number of queued (not yet flushed) requests."""
        with self._lock:
            return sum(len(grp.requests) for grp in self._groups.values())

    def keys(self):
        """Bucket keys with queued (not yet flushed) requests — the
        fleet router's bucket-affinity signal: a replica already holding
        half a batch of key K is the cheapest place to send one more K.
        Deduplicated across variants (affinity is per compiled bucket)."""
        with self._lock:
            return tuple(dict.fromkeys(gkey[0] for gkey in self._groups))
