"""Serving engine: warm AOT-compiled shape buckets + a pipelined request path.

Three stages, each on its own thread(s), bounded queues between them
(double-buffered in the style of `train.loop._prefetch_device_batches`):

1. **host prep** — ``host_workers`` threads pop raw requests from a
   BOUNDED submit queue (backpressure: `submit` blocks or raises a typed
   `AdmissionRejected`, a ``queue.Full`` subclass), hit the
   ``serve.request`` fault point (`resilience.faultinject` — tests
   inject slow/failed requests here without stalling the pipeline), run
   ``prep_fn`` (decode/resize/normalize, or a feature-store lookup)
   under the data loader's per-attempt retry + exponential backoff
   (``prep_retries`` — the same `data.loader.retry_call` the training
   loaders use for transient I/O), and feed the micro-batcher;
2. **device dispatch** — one thread drives `MicroBatcher` (cap +
   deadline flushes), stacks each flushed group into a padded
   fixed-shape batch, runs the AOT-compiled executable for
   ``(bucket key, padded size)``, and starts the result's D2H via
   ``copy_to_host_async`` the moment compute is dispatched;
3. **readout** — one thread converts device results to numpy (the only
   blocking sync), slices out the REAL rows (padding masked here: a
   served batch is bitwise the same program on the same padded array,
   and a lone bs-1 request is bitwise the per-pair pipeline — across
   batch sizes XLA's codegen may differ by ulps, never by padding),
   and resolves per-request futures. The readout queue depth of 2 means
   the device computes batch i+1 while batch i is being read out.

Compile discipline: `warmup` AOT-compiles every (bucket, batch-size)
shape up front via ``jit(...).lower(...).compile()`` — reusing the
persistent compilation cache when ``compile_cache_dir`` is set
(`utils.compile_cache`) — and serving then calls the compiled
executables directly, never the jit wrapper, so the steady state cannot
retrace. A trace-time counter inside the wrapped apply fn counts every
real compile (the counting-jit assertion in tests/test_serve.py), and
any compile triggered by a LIVE request after warmup is reported as
``recompiles_after_warmup`` (the number `scripts/serve.py` must show as
zero).

SLO + resilience layer (PR 10, `serve.resilience`):

* **Deadlines & shedding** — ``submit(..., deadline_s=)`` stamps an
  absolute deadline on the engine clock. Admission control sheds
  requests whose deadline would expire before the estimated completion
  (`LatencyEstimator` EWMA of per-bucket batch latency, fed at readout)
  with a typed `RequestShed` on the returned future — no queue slot is
  occupied. Requests whose deadline expires IN pipeline are dropped at
  the prep / dispatch / readout stage with `DeadlineExceeded` rather
  than wasting a device slot.
* **Overload degradation** — with a ``degraded_apply_fn`` (the
  pre-warmed `nc_topk` band program), a `HysteresisController` watches
  the queued-work fraction and flips per-bucket dispatch to the cheaper
  program under sustained pressure, back when it clears. Both variants
  are AOT-compiled at `warmup()`; flip events, degraded-batch counts,
  and the mode/pressure gauges all export through the registry.
* **Supervision** — every stage loop runs under `run_supervised`: a
  stage crash fails ONLY its in-flight request(s) with a typed
  `StageFailure` and the stage restarts with the warm compile cache
  intact (``recompiles_after_warmup`` stays 0). A hung dispatch (a
  Python thread wedged in a device call cannot be killed) is detected
  by a heartbeat `Watchdog` (``hang_timeout``): the in-flight batch
  fails typed, the dispatch GENERATION is bumped so the wedged thread
  discards its work when it wakes, and a fresh dispatch thread takes
  over.
* **Graceful drain** — `shutdown(timeout=)` / `drain()` stop admission
  and drain the pipeline under a deadline; every accepted future
  resolves with a result or a typed shed. `close()` is
  ``shutdown(None)`` (blocking, the pre-PR-10 semantics);
  `resilience.drain_on_preemption` ties this to the SIGTERM
  `PreemptionGuard`.
"""

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError

import numpy as np

import jax

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.data.loader import retry_call
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.serve.batcher import (
    MicroBatch,
    MicroBatcher,
    Request,
    default_batch_sizes,
    pad_size,
)
from ncnet_tpu.serve.resilience import (
    AdmissionRejected,
    DeadlineExceeded,
    HysteresisController,
    LatencyEstimator,
    QualityLadder,
    ReplicaDown,
    RequestShed,
    StageFailure,
    Watchdog,
    run_supervised,
)
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    percentiles,
)

_SENTINEL = object()


def payload_spec(payload):
    """Per-sample ``{name: (shape, dtype)}`` of a payload dict — the
    warmup-time description of a bucket's arrays."""
    return {
        name: (tuple(np.shape(arr)), np.asarray(arr).dtype)
        for name, arr in payload.items()
    }


#: donation spec for every serving program: argnum 1 is the padded input
#: batch — single-use per dispatch (`_dispatch_inner` stacks fresh numpy
#: each time, and nothing reads it after the call), so XLA may reuse its
#: HBM for outputs. Argnum 0 (params) is reused across every dispatch and
#: must NEVER be donated. `ncnet_tpu.analysis.jaxpr_audit` checks the
#: compiled programs against this spec.
SERVE_DONATE_ARGNUMS = (1,)


def make_serve_match_step(config, softmax=True, from_features=False):
    """The serving apply fn for the correspondence workload:
    ``apply(params, batch) -> {'matches': [b, 5, n]}``.

    Wraps `eval.inloc.make_match_fn(concat_directions=True)` — the same
    forward the InLoc dump jits — so serving is the per-pair pipeline,
    just batched: trunk (or, with ``from_features=True``, a feature-store
    lookup upstream feeding ``[b, fh, fw, c]`` feature maps straight in),
    dense or ``nc_topk`` sparse NC, both-direction `corr_to_matches`
    fused into one output array. The direction concat stays inside the
    compiled program; the batch axis is moved first so readout slices
    one ``[5, n]`` block per request.

    The degraded serving program is this same constructor at a sparse
    geometry: ``make_serve_match_step(replace(config, nc_topk=K))``.
    """
    import jax.numpy as jnp

    # lazy: eval.inloc imports serve.buckets, so a module-level import
    # here would be a cycle through ncnet_tpu.serve.__init__
    from ncnet_tpu.eval.inloc import make_match_fn

    fn = make_match_fn(
        config, softmax=softmax, concat_directions=True,
        from_features=from_features,
    )

    def apply(params, batch):
        out = fn(params, batch["source_image"], batch["target_image"])
        return {"matches": jnp.moveaxis(out, 1, 0)}  # [5,b,n] -> [b,5,n]

    return apply


class ServeEngine:
    """Batched, warm, overlapped serving of ``apply_fn(params, batch)``.

    ``apply_fn`` takes ``(params, {name: [b, ...]})`` and returns a
    pytree whose every leaf has the batch as axis 0 (per-request results
    are sliced out along it). ``prep_fn(raw) -> (bucket_key, payload)``
    runs on the host workers; without one, `submit` takes ``(key,
    payload)`` directly (payload: ``{name: per-sample array}``). Requests
    sharing a key are batched together, padded up to the next allowed
    batch size by replicating the last real payload, and the padding rows
    are discarded at readout — padding never perturbs real rows (bitwise
    vs the same program unpadded; vs a different-batch-size program the
    results agree to XLA codegen ulps, tests/test_serve.py).

    Resilience knobs (all optional — defaults preserve the PR 6
    behavior):

    * ``degraded_apply_fn`` — the cheaper program (same signature as
      ``apply_fn``) the `HysteresisController` flips dispatch to under
      sustained queue pressure; pass ``degrade_controller=`` to tune the
      thresholds. Both variants compile at `warmup()`.
    * ``refined_apply_fn`` — the RICHER program (PR 14,
      `ncnet_tpu.refine`): a third pre-warmed family per bucket above
      the standard one. With it, dispatch is steered by a
      `QualityLadder` (pass ``quality_controller=`` to tune/replace)
      walking refined <-> standard <-> degraded one rung per sustained
      pressure change — quality itself becomes the SLO knob, at zero
      recompiles because every rung's programs compile at `warmup()`.
    * ``hang_timeout`` — enable the dispatch heartbeat `Watchdog`. Must
      exceed the worst-case single-batch latency INCLUDING any live
      compile of an unwarmed bucket, or a legitimately long device call
      reads as a hang; None (default) disables it.
    * ``deadline_margin`` — safety factor on the EWMA latency estimate
      admission control sheds against.
    * ``deadline_flush`` (default True) — deadline-aware micro-batch
      flushing: a group flushes early once its tightest member's
      remaining budget drops below ``max_wait`` plus the bucket's EWMA
      service estimate. False restores the fixed-wait policy (the
      goodput A/B baseline in benchmarks/micro_http.py).
    * ``per_bucket_quality`` — cost-aware per-bucket degradation: each
      bucket gets its own `QualityLadder` fed (dispatch ETA / tightest
      queued budget), so rung choice tracks each bucket's own cost
      instead of one global queue signal; ``bucket_ladder=`` injects a
      custom ladder factory. Per-request ``submit(variant=)`` pins
      override any controller.
    * ``clock`` — injectable monotonic clock shared with the batcher
      (tests pass a fake).

    Fleet knobs (PR 11, `ncnet_tpu.serve.fleet`):

    * ``device`` — pin the engine to ONE device: params are
      ``device_put`` there at construction and every compiled program's
      input specs carry that device's sharding, so co-resident engines
      (one per device, the fleet topology) never cross-dispatch through
      the process-global default device.
    * ``shard_mesh`` / ``shard_min_batch`` — the batch-axis `shard_map`
      dispatch variant (`parallel.mesh.make_batch_sharded_apply`): when
      a padded batch is at least ``shard_min_batch`` rows AND divides
      evenly over the mesh, dispatch runs the mesh-sharded program on
      replicated params instead of the single-device one. Bitwise
      contract: the sharded result equals the single-device program
      applied per shard, concatenated. Mutually exclusive with
      ``device`` (a pinned engine owns one chip; the sharded program
      owns the mesh).
    * ``replica_tag`` — the fleet's replica index: stamps this engine's
      worker-thread spans with a ``replica`` tag
      (`telemetry.trace.set_thread_tag`) so one fleet-wide report can
      tell the replicas apart, and names the replica in `kill`'s typed
      `ReplicaDown` outcomes.
    * `kill()` — abrupt replica death (the chaos-drill verb): every
      unresolved future fails with `ReplicaDown`, ``dispatched=True``
      for batches already on the device (unrecoverable, typed — never
      silent), ``False`` for queued-but-undispatched requests (the
      fleet requeues exactly these onto survivors).

    Use as a context manager; `close` drains in-flight work, resolves
    every accepted future, and joins all threads; `shutdown(timeout=)`
    is the bounded-drain variant (leftover futures resolve with a typed
    `RequestShed`).
    """

    def __init__(
        self,
        apply_fn,
        params,
        *,
        max_batch=8,
        max_wait=0.005,
        batch_sizes=None,
        queue_limit=64,
        host_workers=2,
        prep_fn=None,
        prep_retries=0,
        retry_backoff=0.05,
        readout_depth=2,
        compile_cache_dir=None,
        registry=None,
        degraded_apply_fn=None,
        degrade_controller=None,
        refined_apply_fn=None,
        quality_controller=None,
        deadline_margin=1.0,
        deadline_flush=True,
        per_bucket_quality=False,
        bucket_ladder=None,
        hang_timeout=None,
        estimator=None,
        clock=time.monotonic,
        device=None,
        shard_mesh=None,
        shard_min_batch=0,
        replica_tag=None,
    ):
        if compile_cache_dir is not None:
            from ncnet_tpu.utils.compile_cache import enable_compile_cache

            enable_compile_cache(compile_cache_dir)
        if device is not None and shard_mesh is not None:
            raise ValueError(
                "device= pins the engine to one chip; shard_mesh= spans "
                "the mesh — pick one"
            )
        self._device = device
        self._shard_mesh = shard_mesh
        self._shard_min_batch = max(int(shard_min_batch), 1)
        self.replica_tag = replica_tag
        # pin params to the engine's device NOW: a fleet builds one
        # engine per device in one process, and placement via the
        # process-global default device would cross-dispatch them all
        # onto device 0
        if device is not None:
            params = jax.device_put(params, device)
        self._params = params
        self._params_sharded = None
        if shard_mesh is not None:
            from ncnet_tpu.parallel.mesh import replicate

            self._params_sharded = replicate(shard_mesh, params)
        self._prep_fn = prep_fn
        self._prep_retries = prep_retries
        self._retry_backoff = retry_backoff
        self._clock = clock
        self._queue_limit = queue_limit
        self._deadline_margin = deadline_margin
        self.batch_sizes = (
            tuple(sorted(batch_sizes))
            if batch_sizes is not None
            else default_batch_sizes(max_batch)
        )
        self.estimator = (
            estimator if estimator is not None else LatencyEstimator()
        )
        # deadline-aware flush (ISSUE 17): the batcher pulls a group's
        # flush forward once its tightest member's remaining budget drops
        # below max_wait + the bucket's EWMA service estimate. OFF
        # (deadline_flush=False) is the fixed-wait baseline arm of
        # benchmarks/micro_http.py's goodput A/B.
        self._batcher = MicroBatcher(
            max_batch=max_batch, max_wait=max_wait,
            batch_sizes=self.batch_sizes, clock=clock,
            estimate_fn=(self.estimator.estimate if deadline_flush else None),
        )

        # one jit wrapper per program variant (standard, plus degraded
        # and/or refined when configured); the
        # jit caches are NEVER hit in steady state (serving calls the
        # AOT executables below) — they exist to lower/compile and to
        # count traces: the increment is a Python side effect that runs
        # only when JAX actually retraces. ALL wrappers share ONE
        # counter, so `compile_count` covers every variant's programs.
        self._trace_count = 0

        def _counted_apply(p, batch):
            self._trace_count += 1
            return apply_fn(p, batch)

        self._jit = jax.jit(_counted_apply, donate_argnums=SERVE_DONATE_ARGNUMS)
        self._jit_sharded = None
        if shard_mesh is not None:
            from ncnet_tpu.parallel.mesh import make_batch_sharded_apply

            sharded_apply = make_batch_sharded_apply(apply_fn, shard_mesh)

            def _counted_sharded(p, batch):
                self._trace_count += 1
                return sharded_apply(p, batch)

            self._jit_sharded = jax.jit(
                _counted_sharded, donate_argnums=SERVE_DONATE_ARGNUMS
            )
        self._jit_degraded = None
        if degraded_apply_fn is not None:

            def _counted_degraded(p, batch):
                self._trace_count += 1
                return degraded_apply_fn(p, batch)

            self._jit_degraded = jax.jit(
                _counted_degraded, donate_argnums=SERVE_DONATE_ARGNUMS
            )
        self._jit_refined = None
        if refined_apply_fn is not None:

            def _counted_refined(p, batch):
                self._trace_count += 1
                return refined_apply_fn(p, batch)

            self._jit_refined = jax.jit(
                _counted_refined, donate_argnums=SERVE_DONATE_ARGNUMS
            )
        # controller precedence: an injected quality_controller wins,
        # then an injected degrade_controller; else a refined program
        # auto-builds a QualityLadder over exactly the variants this
        # engine can serve, and a degraded-only engine keeps the PR-8
        # two-mode HysteresisController (both expose .degraded and
        # .update(pressure); the ladder adds .variant, which
        # `_variant_now` prefers when present)
        if quality_controller is not None:
            self.controller = quality_controller
        elif degrade_controller is not None:
            self.controller = degrade_controller
        elif refined_apply_fn is not None:
            rungs = (
                ("refined", "standard", "degraded")
                if degraded_apply_fn is not None
                else ("refined", "standard")
            )
            self.controller = QualityLadder(rungs=rungs)
        elif degraded_apply_fn is not None:
            self.controller = HysteresisController()
        else:
            self.controller = None
        # per-bucket cost-aware degradation (ISSUE 17): one QualityLadder
        # PER BUCKET, fed the ratio of the bucket's dispatch ETA
        # (max_wait + EWMA estimate) to the tightest queued budget, so a
        # heavy bucket can step down a rung while a light one stays rich.
        # Effective only when a cheaper/richer program exists; the global
        # controller then becomes the no-deadline fallback signal only.
        self._per_bucket = bool(per_bucket_quality) and (
            self._jit_degraded is not None or self._jit_refined is not None
        )
        self._bucket_ladder_fn = (
            bucket_ladder
            if bucket_ladder is not None
            else self._default_bucket_ladder
        )
        self._bucket_ladders = {}
        self._bucket_lock = concurrency.make_lock("serve.engine.buckets")
        # lock-order: _close_lock -> _gen_lock -> _compile_lock -> _bucket_lock -> _pending_lock
        # (no pair is ever truly nested today; the declared order is the
        # one any future nesting must follow, and the NCNET_LOCK_AUDIT=1
        # drills verify the observed graph stays acyclic)
        self._compiled = {}  # (key, padded size, variant, sharded) -> exe
        # held across multi-second AOT compiles by design, hence the
        # raised held-time outlier threshold
        self._compile_lock = concurrency.make_lock(
            "serve.engine.compile", held_outlier_s=300.0
        )
        self._warm = False
        # every (key, per-sample spec) warmup has seen: the fleet re-warms
        # a rejoining replica from exactly this set, so
        # recompiles_after_warmup == 0 holds across a kill + rejoin
        self.warmed_specs = {}

        self._submit_q = queue.Queue(maxsize=queue_limit)
        self._batch_q = queue.Queue()
        self._readout_q = queue.Queue(maxsize=readout_depth)
        self._closed = False
        # held across the drain wait in kill() on the already-closed
        # path, so its outlier threshold tracks a full drain
        self._close_lock = concurrency.make_lock(
            "serve.engine.close", held_outlier_s=60.0
        )
        self._drained = threading.Event()
        self._stop_dispatch = threading.Event()

        # every accepted, unresolved future — the drain contract's
        # ledger: whatever is still here when the drain deadline expires
        # is failed with a typed shed, so 100% of accepted futures
        # resolve before shutdown returns
        self._pending = set()
        self._pending_lock = concurrency.make_lock("serve.engine.pending")

        # Engine stats live in a telemetry metrics registry; `report()`
        # is a VIEW over it. Private per engine by default (co-resident
        # engines and tests must not share totals); pass ``registry=``
        # (e.g. the telemetry session's) to publish into a shared one.
        self.metrics = registry if registry is not None else MetricsRegistry()
        m = self.metrics
        self._m_submitted = m.counter(
            "serve_requests_submitted_total",
            "requests accepted by submit()",
        )
        self._m_completed = m.counter(
            "serve_requests_completed_total",
            "requests resolved with a result",
        )
        self._m_failed = m.counter(
            "serve_requests_failed_total",
            "requests resolved with an exception",
        )
        self._m_shed = m.counter(
            "serve_requests_shed_total",
            "requests shed by admission control or an expired drain",
        )
        self._m_deadline = m.counter(
            "serve_deadline_exceeded_total",
            "accepted requests dropped in-pipeline on an expired deadline",
        )
        self._m_rejected = m.counter(
            "serve_admission_rejected_total",
            "submits refused on a full queue (AdmissionRejected)",
        )
        self._m_pinned = m.counter(
            "serve_requests_pinned_total",
            "requests submitted with a pinned quality variant",
        )
        self._m_batches = m.counter(
            "serve_batches_total", "device batches dispatched"
        )
        self._m_real = m.counter(
            "serve_samples_real_total", "real rows across served batches"
        )
        self._m_padded = m.counter(
            "serve_samples_padded_total",
            "padded rows across served batches",
        )
        self._m_recompiles = m.counter(
            "serve_recompiles_after_warmup_total",
            "live-request compiles after warmup (must stay 0)",
        )
        self._m_degraded_batches = m.counter(
            "serve_batches_degraded_total",
            "batches served by the degraded program",
        )
        self._m_refined_batches = m.counter(
            "serve_batches_refined_total",
            "batches served by the refined (coarse-to-fine) program",
        )
        self._m_sharded_batches = m.counter(
            "serve_batches_sharded_total",
            "batches served by the mesh-sharded (shard_map) program",
        )
        self._m_replica_down = m.counter(
            "serve_replica_down_total",
            "requests failed or requeued because this replica was killed",
        )
        self._m_flips = m.counter(
            "serve_degrade_flips_total",
            "degradation controller mode changes (either direction)",
        )
        self._m_hangs = m.counter(
            "serve_dispatch_hangs_total",
            "dispatch heartbeat timeouts detected by the watchdog",
        )
        self._m_prep_restarts = m.counter(
            "serve_prep_restarts_total", "prep worker stage restarts"
        )
        self._m_dispatch_restarts = m.counter(
            "serve_dispatch_restarts_total", "dispatch stage restarts"
        )
        self._m_readout_restarts = m.counter(
            "serve_readout_restarts_total", "readout stage restarts"
        )
        self._m_latency = m.histogram(
            "serve_request_latency_seconds",
            "submit-to-result latency",
            buckets=DEFAULT_LATENCY_BUCKETS,
        )
        self._m_batch_size = m.histogram(
            "serve_batch_real_size",
            "real rows per dispatched batch",
            buckets=tuple(float(b) for b in self.batch_sizes),
        )
        # Sampled gauges: the truth lives in the queue / the counters /
        # the controller, the gauges read it at scrape time.
        m.gauge(
            "serve_submit_queue_depth",
            "requests waiting in the bounded submit queue",
        ).set_fn(self._submit_q.qsize)
        m.gauge(
            "serve_mean_occupancy",
            "cumulative real/padded row ratio across served batches",
        ).set_fn(self._mean_occupancy)
        m.gauge(
            "serve_degraded_mode",
            "1 when dispatch is flipped to the degraded program",
        ).set_fn(lambda: 1.0 if self._degraded_now() else 0.0)
        m.gauge(
            "serve_quality_rung",
            "current dispatch variant: 0 degraded, 1 standard, 2 refined",
        ).set_fn(
            lambda: {"degraded": 0.0, "standard": 1.0, "refined": 2.0}[
                self._variant_now()
            ]
        )
        m.gauge(
            "serve_pressure",
            "queued-work fraction the degradation controller last saw",
        ).set_fn(
            lambda: (
                self.controller.last_pressure
                if self.controller is not None
                else 0.0
            )
        )

        self._workers = [
            threading.Thread(
                target=self._prep_worker, name=f"serve-prep-{i}", daemon=True
            )
            for i in range(host_workers)
        ]
        # dispatch runs under a GENERATION: hang recovery bumps the
        # generation and starts a fresh thread; the wedged one discards
        # its work when it wakes (a Python thread cannot be killed)
        self._dispatch_gen = 0
        self._gen_lock = concurrency.make_lock("serve.engine.gen")
        self._inflight_dispatch = {}  # gen -> the batch on the device
        self._dispatch_beat = clock()
        self._reader = threading.Thread(
            target=self._readout_worker, name="serve-readout", daemon=True
        )
        # ledger of EVERY thread the engine ever started (prep workers,
        # each dispatcher generation, readout, watchdog): shutdown joins
        # the whole list under a bounded budget and report() names the
        # stragglers. Append-only from the starting thread; list.append
        # is atomic under the GIL.
        self._thread_ledger = list(self._workers) + [self._reader]
        for t in self._workers:
            t.start()
        self._start_dispatcher()
        self._reader.start()
        self._watchdog = None
        if hang_timeout is not None:
            self._watchdog = Watchdog(
                hang_timeout,
                beat_fn=lambda: self.heartbeat,
                busy_fn=lambda: self.busy,
                on_hang=self._on_dispatch_hang,
                clock=clock,
            ).start()
            self._thread_ledger.append(self._watchdog.thread)

    # ------------------------------------------------------------------
    # compile management

    def _specs(self, key, bs, pspec, sharded=False):
        del key  # the bucket key is already encoded in the shapes
        sharding = None
        if sharded:
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(self._shard_mesh, PartitionSpec("data"))
        elif self._device is not None:
            from jax.sharding import SingleDeviceSharding

            # the pinning half of the contract: inputs compile AGAINST
            # this device, so the executable can never be fed through
            # another engine's placement
            sharding = SingleDeviceSharding(self._device)
        return {
            name: jax.ShapeDtypeStruct(
                (bs,) + tuple(shape), dtype, sharding=sharding
            )
            for name, (shape, dtype) in pspec.items()
        }

    def _shardable(self, pad_to):
        """Whether a padded batch takes the mesh-sharded program: large
        enough to span the mesh AND divides evenly over it (the batcher's
        power-of-two pad sizes make every size >= mesh.size divide a
        power-of-two mesh)."""
        return (
            self._jit_sharded is not None
            and pad_to >= self._shard_min_batch
            and pad_to % self._shard_mesh.size == 0
        )

    def _program_params(self, sharded):
        return self._params_sharded if sharded else self._params

    def _executable(self, key, bs, pspec, live, variant="standard",
                    sharded=False):
        ck = (key, bs, variant, sharded)
        exe = self._compiled.get(ck)  # nclint: disable=unguarded-shared-state -- double-checked fast path: dict.get is atomic under the GIL and a miss re-checks under _compile_lock below
        if exe is not None:
            return exe
        if sharded:
            jit = self._jit_sharded
        else:
            jit = {
                "standard": self._jit,
                "degraded": self._jit_degraded,
                "refined": self._jit_refined,
            }[variant]
        if jit is None:
            raise ValueError(
                f"{variant} dispatch requested but the engine has no "
                f"{variant}_apply_fn"
            )
        with self._compile_lock:
            exe = self._compiled.get(ck)
            if exe is None:
                if live and self._warm:
                    self._m_recompiles.inc()
                exe = jit.lower(
                    self._program_params(sharded),
                    self._specs(key, bs, pspec, sharded=sharded),
                ).compile()
                self._compiled[ck] = exe
        return exe

    def warmup(self, bucket_specs):
        """AOT-compile every (bucket, batch size) pair up front.

        ``bucket_specs``: iterable of ``(key, per-sample spec)`` where the
        spec is `payload_spec`-shaped (``{name: (shape, dtype)}``). Each
        key is compiled at EVERY allowed padded batch size — and in
        EVERY configured program variant (standard, plus degraded and/or
        refined) — so a warmed engine serves any traffic mix over those
        buckets with zero compiles even across quality-ladder flips.
        Incremental: may be called again for newly-discovered buckets;
        warmup compiles are never counted as recompiles. Returns the
        number of compiled programs now cached.
        """
        for key, pspec in bucket_specs:
            self.warmed_specs[key] = pspec
            for bs in self.batch_sizes:
                self._executable(key, bs, pspec, live=False)
                if self._jit_degraded is not None:
                    self._executable(
                        key, bs, pspec, live=False, variant="degraded"
                    )
                if self._jit_refined is not None:
                    self._executable(
                        key, bs, pspec, live=False, variant="refined"
                    )
                if self._shardable(bs):
                    self._executable(key, bs, pspec, live=False,
                                     sharded=True)
        self._warm = True
        with self._compile_lock:
            return len(self._compiled)

    @property
    def compile_count(self):
        """Number of real traces so far (the counting-jit assertion)."""
        return self._trace_count

    # ------------------------------------------------------------------
    # request path

    def _check_variant(self, variant):
        """Validate a per-request quality pin against the programs this
        engine actually warmed — a typo or an unservable rung must fail
        at submit time (HTTP 400), never mid-dispatch."""
        jits = {
            "standard": self._jit,
            "degraded": self._jit_degraded,
            "refined": self._jit_refined,
        }
        if variant not in jits:
            raise ValueError(
                f"unknown quality variant {variant!r} "
                f"(expected one of {sorted(jits)})"
            )
        if jits[variant] is None:
            raise ValueError(
                f"variant {variant!r} pinned but the engine has no "
                f"{variant} program configured"
            )

    def submit(self, raw=None, *, key=None, payload=None, timeout=None,
               deadline_s=None, variant=None):
        """Queue one request; returns a `concurrent.futures.Future`.

        With a ``prep_fn``: pass ``raw`` (whatever the prep fn consumes).
        Without one: pass ``key=``/``payload=``. The submit queue is
        BOUNDED (``queue_limit``): when it is full, ``timeout=None``
        blocks (natural backpressure), ``timeout=0`` raises a typed
        `AdmissionRejected` (a ``queue.Full`` subclass, with a
        retry-after hint) immediately, and a positive timeout raises
        after waiting that long.

        ``deadline_s`` (relative seconds) sets the request's SLO. When
        the EWMA latency estimate says completion would miss it, the
        request is SHED at admission: the returned future already holds
        a `RequestShed` (no queue slot occupied, counted in
        ``serve_requests_shed_total``). An accepted request whose
        deadline expires in-pipeline resolves with `DeadlineExceeded`.

        ``variant`` pins the quality rung ("refined" / "standard" /
        "degraded") this request must be served at (the ``X-Quality``
        header contract): it bypasses the degradation controller, joins
        only same-rung micro-batches, and raises `ValueError` at submit
        when the engine has no such program. None (default) lets the
        controller choose.
        """
        if variant is not None:
            self._check_variant(variant)
        if self._closed:  # nclint: disable=unguarded-shared-state -- benign racy read of the monotonic close flag: kill() holds _close_lock across the drain wait, so a locked read here would block every submitter for a full drain
            raise RuntimeError("submit on a closed ServeEngine")
        if raw is None:
            if key is None or payload is None:
                raise ValueError(
                    "submit needs either raw (with a prep_fn) or "
                    "key= and payload="
                )
            raw = (key, payload)
        now = self._clock()
        deadline = None if deadline_s is None else now + deadline_s
        fut = Future()
        if deadline is not None:
            est = self.estimator.estimate(key)
            if est is not None:
                # the fixed-wait batcher makes a tight request pay up to
                # max_wait of coalescing before service; the deadline-
                # aware batcher flushes a tight group early, so charging
                # max_wait here would shed requests it CAN serve
                wait = (
                    0.0 if self._batcher.deadline_aware
                    else self._batcher.max_wait
                )
                eta = wait + est * self._deadline_margin
                if now + eta > deadline:
                    # shed BEFORE occupying a queue slot: the future is
                    # returned pre-resolved with the typed shed
                    self._m_submitted.inc()
                    self._fail(
                        fut,
                        RequestShed(
                            f"estimated completion {eta * 1e3:.1f}ms "
                            f"exceeds deadline {deadline_s * 1e3:.1f}ms",
                            reason="admission",
                            estimated_s=eta,
                            deadline_s=deadline_s,
                            retry_after_s=est,
                        ),
                    )
                    return fut
        item = (raw, fut, now, deadline, variant)
        try:
            if timeout == 0:
                self._submit_q.put_nowait(item)
            else:
                self._submit_q.put(item, timeout=timeout)
        except queue.Full:
            self._m_rejected.inc()
            est = self.estimator.estimate(key)
            raise AdmissionRejected(
                f"submit queue full ({self._queue_limit} waiting)",
                retry_after_s=(
                    est if est is not None else self._batcher.max_wait
                ),
            ) from None
        self._track(fut)
        self._m_submitted.inc()
        if variant is not None:
            self._m_pinned.inc()
        return fut

    # -- prep stage ----------------------------------------------------

    def _tag_thread(self):
        # fleet telemetry: worker-thread spans carry the replica index so
        # one merged report can tell the fleet's replicas apart
        if self.replica_tag is not None:
            trace.set_thread_tag("replica", self.replica_tag)

    def _prep_worker(self):
        self._tag_thread()
        # single-slot in-flight ledger shared with the supervisor: when
        # the loop crashes, ONLY the request left here fails
        inflight = {}

        def on_crash(exc):
            fut = inflight.pop("fut", None)
            if fut is not None:
                self._fail(fut, StageFailure("prep", repr(exc)))
            self._m_prep_restarts.inc()

        # always restart: close() leaves this worker's sentinel in the
        # queue, so a post-crash re-entry still terminates promptly
        run_supervised(lambda: self._prep_loop(inflight), on_crash=on_crash)

    def _prep_loop(self, inflight):
        while True:
            item = self._submit_q.get()
            if item is _SENTINEL:
                return
            raw, fut, t_submit, deadline, variant = item
            inflight["fut"] = fut
            # a STAGE crash (vs a request failure below) escapes this
            # loop to the supervisor, which fails only `inflight`
            faultinject.fire("serve.worker.crash")
            if deadline is not None and self._clock() > deadline:
                self._fail(fut, DeadlineExceeded(
                    "deadline expired while queued for prep",
                    stage="prep", deadline_s=deadline,
                ))
                inflight.pop("fut", None)
                continue
            try:
                with trace.span("serve/prep"):
                    # the fault point fires ONCE per request (never
                    # retried: an injected crash must fail
                    # deterministically); the prep itself gets the
                    # loader's transient-I/O retry
                    faultinject.fire("serve.request")
                    key, payload = retry_call(
                        lambda: (
                            self._prep_fn(raw)
                            if self._prep_fn is not None
                            else raw
                        ),
                        self._prep_retries,
                        self._retry_backoff,
                    )
            except BaseException as exc:  # a failed request fails ALONE
                self._fail(fut, exc)
                inflight.pop("fut", None)
                continue
            # the future stays in the in-flight ledger until the request
            # is safely parked in the batcher (or its batch enqueued): a
            # crash in add/put then fails THIS request instead of losing
            # it silently (double-settle is impossible — settling is
            # InvalidStateError-guarded)
            batch = self._batcher.add(
                Request(key, payload, fut, t_submit, deadline, variant)
            )
            if batch is not None:  # the add filled a group to max_batch
                self._batch_q.put(batch)
            inflight.pop("fut", None)

    # -- dispatch stage ------------------------------------------------

    def _start_dispatcher(self):
        with self._gen_lock:
            gen = self._dispatch_gen
        self._dispatcher = threading.Thread(
            target=self._dispatch_worker, args=(gen,),
            name=f"serve-dispatch-{gen}", daemon=True,
        )
        self._thread_ledger.append(self._dispatcher)
        self._dispatcher.start()

    def _dispatch_worker(self, gen):
        self._tag_thread()

        def on_crash(exc):
            with self._gen_lock:
                batch = self._inflight_dispatch.pop(gen, None)
            if batch is not None:
                for r in batch.requests:
                    self._fail(r.future, StageFailure("dispatch", repr(exc)))
            self._m_dispatch_restarts.inc()

        run_supervised(
            lambda: self._dispatch_loop(gen),
            on_crash=on_crash,
            stopping=lambda: self._dispatch_gen != gen,
        )

    def _on_dispatch_hang(self):
        """Watchdog verdict: the dispatch thread stopped heartbeating
        with a batch on the device. Fail that batch typed, supersede the
        wedged thread (generation bump — it discards its work when it
        wakes), and take over with a fresh one."""
        with self._gen_lock:
            gen = self._dispatch_gen
            batch = self._inflight_dispatch.pop(gen, None)
            if batch is None:
                return  # raced with a completing dispatch: not a hang
            self._dispatch_gen = gen + 1
            self._dispatch_beat = self._clock()
        self._m_hangs.inc()
        self._m_dispatch_restarts.inc()
        for r in batch.requests:
            self._fail(r.future, StageFailure(
                "dispatch",
                f"no heartbeat for > {self._watchdog.timeout:.3f}s",
                hang=True,
            ))
        self._start_dispatcher()

    def _dispatch_loop(self, gen):
        while True:
            if self._dispatch_gen != gen:  # nclint: disable=unguarded-shared-state -- advisory lock-free generation check: the authoritative check runs under _gen_lock in _dispatch, and settlement is InvalidStateError-guarded
                return  # superseded by hang recovery
            with self._gen_lock:
                self._dispatch_beat = self._clock()
            self._update_degrade()
            stopping = self._stop_dispatch.is_set()
            nd = self._batcher.next_deadline()
            wait = 0.0 if stopping else min(
                0.05, max(0.0, nd) if nd is not None else 0.05
            )
            try:
                batch = self._batch_q.get(timeout=wait)
            except queue.Empty:
                batch = None
            if self._dispatch_gen != gen:  # nclint: disable=unguarded-shared-state -- advisory lock-free generation check: the authoritative check runs under _gen_lock in _dispatch, and settlement is InvalidStateError-guarded
                if batch is not None:
                    self._batch_q.put(batch)  # hand back to the successor
                return
            if batch is not None:
                self._dispatch(batch, gen)
            for b in self._batcher.ready():
                self._dispatch(b, gen)
            if stopping and batch is None and self._batch_q.empty():
                # prep workers are already joined: nothing new can
                # arrive, so one final drain flushes trailing partials
                for b in self._batcher.drain():
                    self._dispatch(b, gen)
                if self._batch_q.empty():
                    return

    def _dispatch(self, batch, gen):
        with self._gen_lock:
            if self._dispatch_gen != gen:
                self._batch_q.put(batch)
                return
            self._inflight_dispatch[gen] = batch
        # stage-level fault point: delay:<s> wedges the thread here (the
        # hang drill — the watchdog must recover), crash escapes to the
        # stage supervisor. NO try around it: an escape must leave
        # `_inflight_dispatch` set so the supervisor/watchdog can fail
        # exactly the in-flight batch.
        faultinject.fire("serve.dispatch.hang")
        if self._dispatch_gen != gen:  # nclint: disable=unguarded-shared-state -- advisory lock-free generation check: the pop below re-checks under _gen_lock and the watchdog already settled these futures
            # woke from a hang after supersession: the watchdog already
            # failed these futures; discard
            with self._gen_lock:
                self._inflight_dispatch.pop(gen, None)
            return
        with trace.span("serve/dispatch"):
            self._dispatch_inner(batch, gen)
        with self._gen_lock:
            self._inflight_dispatch.pop(gen, None)

    def _dispatch_inner(self, batch, gen):
        # drop requests whose deadline already expired: they would
        # occupy device rows nobody is waiting for
        now = self._clock()
        live, expired = [], []
        for r in batch.requests:
            if r.deadline is not None and now > r.deadline:
                expired.append(r)
            else:
                live.append(r)
        for r in expired:
            self._fail(r.future, DeadlineExceeded(
                "deadline expired before dispatch",
                stage="dispatch", deadline_s=r.deadline,
            ))
        if not live:
            return
        if expired:
            batch = MicroBatch(
                batch.key, live, pad_size(len(live), self.batch_sizes),
                batch.variant,
            )
        # variant precedence: a pinned batch wins (the members asked for
        # exactly this rung), then the per-bucket cost-aware ladder, then
        # the global controller
        if batch.variant is not None:
            variant = batch.variant
        elif self._per_bucket:
            variant = self._bucket_variant(batch, now)
        else:
            variant = self._variant_now()
        # the sharded program is the LARGE-batch fast path for the
        # STANDARD tier only; under pressure the cheaper single-device
        # band program wins, and the refined tier ships as the
        # single-device program it was warmed as
        sharded = variant == "standard" and self._shardable(batch.pad_to)
        try:
            reqs = batch.requests
            names = sorted(reqs[0].payload)
            stacked = {}
            for name in names:
                arrs = [np.asarray(r.payload[name]) for r in reqs]
                # pad by replicating the last REAL sample: the padded
                # rows run through the same program and are discarded at
                # readout, so they only have to be shape/dtype-valid
                arrs.extend([arrs[-1]] * (batch.pad_to - len(arrs)))
                stacked[name] = np.stack(arrs)
            exe = self._executable(
                batch.key, batch.pad_to, payload_spec(reqs[0].payload),
                live=True, variant=variant, sharded=sharded,
            )
            if sharded:
                self._m_sharded_batches.inc()
            t_dispatch = self._clock()
            out = exe(self._program_params(sharded), stacked)
            # start D2H immediately; the readout thread's np.asarray
            # then finds the bytes already on their way
            for leaf in jax.tree_util.tree_leaves(out):
                leaf.copy_to_host_async()
        except BaseException as exc:  # compile/shape/dispatch failure
            for r in batch.requests:
                self._fail(r.future, exc)
            return
        if self._dispatch_gen != gen:  # nclint: disable=unguarded-shared-state -- advisory lock-free generation check: a stale read only delays the discard one step; the watchdog already settled the batch under _gen_lock
            return  # superseded mid-call; the watchdog settled the batch
        self._readout_q.put((batch, out, t_dispatch, variant))

    # -- quality/degradation controller --------------------------------

    def _default_bucket_ladder(self):
        """A fresh per-bucket ladder over exactly the rungs this engine
        can serve. Thresholds are COST-pressure semantics (dispatch ETA /
        remaining budget): >= 1.0 sustained means the bucket is missing
        its budgets — step down a rung immediately (up_count=1, a missed
        SLO should not need two batches of proof); <= 0.5 sustained
        means the budget covers twice the ETA — re-earn richer quality
        after two comfortable batches."""
        rungs = []
        if self._jit_refined is not None:
            rungs.append("refined")
        rungs.append("standard")
        if self._jit_degraded is not None:
            rungs.append("degraded")
        return QualityLadder(
            rungs=tuple(rungs), start="standard",
            high=1.0, low=0.5, up_count=1, down_count=2,
        )

    def _bucket_variant(self, batch, now):
        """Per-bucket cost-aware rung pick (ISSUE 17): feed this bucket's
        ladder the ratio of its dispatch ETA (batcher wait + EWMA
        service estimate) to the tightest remaining budget in the batch.
        Requests without deadlines (or a cold estimator) fall back to
        the global queued-work fraction — the same signal the global
        controller uses."""
        est = self.estimator.estimate(batch.key)
        deadlines = [
            r.deadline for r in batch.requests if r.deadline is not None
        ]
        if est is not None and deadlines:
            remaining = min(deadlines) - now
            eta = self._batcher.max_wait + est * self._deadline_margin
            # expired budgets were already dropped above; clamp anyway
            pressure = min(eta / max(remaining, 1e-6), 1e6)
        else:
            pressure = self.queued_work() / max(1, self._queue_limit)
        with self._bucket_lock:
            ladder = self._bucket_ladders.get(batch.key)
            if ladder is None:
                ladder = self._bucket_ladder_fn()
                self._bucket_ladders[batch.key] = ladder
            was = ladder.variant
            ladder.update(pressure)
            variant = ladder.variant
        if variant != was:
            self._m_flips.inc()
        # a custom bucket_ladder factory may name rungs this engine
        # lacks; clamp like _variant_now rather than crash mid-dispatch
        if variant == "degraded" and self._jit_degraded is None:
            return "standard"
        if variant == "refined" and self._jit_refined is None:
            return "standard"
        return variant

    def _variant_now(self):
        """The program variant dispatch uses RIGHT NOW. Clamps a rung the
        engine cannot serve (controller says refined/degraded but no such
        apply_fn was configured) to the standard program rather than
        crash mid-dispatch."""
        if self.controller is None:
            return "standard"
        variant = getattr(self.controller, "variant", None)
        if variant is None:  # two-mode HysteresisController
            variant = "degraded" if self.controller.degraded else "standard"
        if variant == "degraded" and self._jit_degraded is None:
            return "standard"
        if variant == "refined" and self._jit_refined is None:
            return "standard"
        return variant

    def _degraded_now(self):
        return self._variant_now() == "degraded"

    def _update_degrade(self):
        if self.controller is None or (
            self._jit_degraded is None and self._jit_refined is None
        ):
            return
        if self._per_bucket:
            # rung choice happens per batch in _bucket_variant; driving
            # the global controller too would double-count flips
            return
        pressure = (
            self._submit_q.qsize()
            + self._batcher.pending()
            + self._batch_q.qsize()
        ) / max(1, self._queue_limit)
        was = getattr(self.controller, "variant", self.controller.degraded)
        self.controller.update(pressure)
        now = getattr(self.controller, "variant", self.controller.degraded)
        if now != was:
            self._m_flips.inc()

    # -- readout stage -------------------------------------------------

    def _readout_worker(self):
        self._tag_thread()
        inflight = {}

        def on_crash(exc):
            batch = inflight.pop("batch", None)
            if batch is not None:
                for r in batch.requests:
                    self._fail(r.future, StageFailure("readout", repr(exc)))
            self._m_readout_restarts.inc()

        run_supervised(
            lambda: self._readout_loop(inflight), on_crash=on_crash
        )

    def _readout_loop(self, inflight):
        while True:
            item = self._readout_q.get()
            if item is _SENTINEL:
                return
            batch, out, t_dispatch, variant = item
            inflight["batch"] = batch
            # stage-level fault: delay:<s> models a slow D2H/convert
            # (the readout-deadline drill), crash escapes to the
            # supervisor, which fails only this batch
            faultinject.fire("serve.readout.delay")
            with trace.span("serve/readout"):
                try:
                    host = jax.tree_util.tree_map(np.asarray, out)
                except BaseException as exc:
                    for r in batch.requests:
                        self._fail(r.future, exc)
                    inflight.pop("batch", None)
                    continue
                now = self._clock()
                n = len(batch.requests)
                # feed admission control: per-bucket EWMA of
                # dispatch -> readout-complete latency
                self.estimator.observe(batch.key, max(0.0, now - t_dispatch))
                self._m_batches.inc()
                self._m_real.inc(n)
                self._m_padded.inc(batch.pad_to)
                if variant == "degraded":
                    self._m_degraded_batches.inc()
                elif variant == "refined":
                    self._m_refined_batches.inc()
                self._m_batch_size.observe(n)
                for i, r in enumerate(batch.requests):
                    if r.deadline is not None and now > r.deadline:
                        self._fail(r.future, DeadlineExceeded(
                            "deadline expired before readout completed",
                            stage="readout", deadline_s=r.deadline,
                        ))
                        continue
                    # padding masked here: only rows [0, n) are ever read
                    if self._settle_result(
                        r.future,
                        jax.tree_util.tree_map(lambda a, i=i: a[i], host),
                    ):
                        self._m_completed.inc()
                        self._m_latency.observe(now - r.t_submit)
            inflight.pop("batch", None)

    # -- settlement (every accepted future resolves EXACTLY once) ------

    def _track(self, fut):
        with self._pending_lock:
            self._pending.add(fut)

    def _settle_result(self, fut, value):
        with self._pending_lock:
            self._pending.discard(fut)
        try:
            fut.set_result(value)
            return True
        except InvalidStateError:
            return False  # already settled (watchdog/drain won the race)

    def _settle_exc(self, fut, exc):
        with self._pending_lock:
            self._pending.discard(fut)
        try:
            fut.set_exception(exc)
            return True
        except InvalidStateError:
            return False

    def _fail(self, fut, exc):
        if not self._settle_exc(fut, exc):
            return
        # counters route by outcome TYPE, and only on the settling
        # transition, so submitted == completed + failed + shed +
        # deadline_exceeded holds exactly
        if isinstance(exc, DeadlineExceeded):
            self._m_deadline.inc()
        elif isinstance(exc, RequestShed):
            self._m_shed.inc()
        else:
            self._m_failed.inc()
            if isinstance(exc, ReplicaDown):
                self._m_replica_down.inc()

    # ------------------------------------------------------------------
    # lifecycle / accounting

    def _mean_occupancy(self):
        padded = self._m_padded.value
        return self._m_real.value / padded if padded else float("nan")

    @property
    def closed(self):
        return self._closed  # nclint: disable=unguarded-shared-state -- benign racy read of the monotonic close flag: kill() holds _close_lock across the drain wait, so a locked read could block for a full drain

    # -- the fleet's view of one replica -------------------------------

    @property
    def heartbeat(self):
        """Last dispatch-loop heartbeat on the engine clock — the fleet
        watchdog's ``beat_fn`` (the internal hang watchdog reads the same
        field)."""
        with self._gen_lock:
            return self._dispatch_beat

    @property
    def busy(self):
        """True while a batch is on the device (the watchdog's
        ``busy_fn``: an idle replica that stops beating is not hung)."""
        with self._gen_lock:
            return bool(self._inflight_dispatch)

    @property
    def max_wait(self):
        return self._batcher.max_wait

    @property
    def max_batch(self):
        return self._batcher.max_batch

    def queued_work(self):
        """Requests admitted but not yet dispatched — the router's
        backlog signal for this replica's ETA."""
        return (
            self._submit_q.qsize()
            + self._batcher.pending()
            + self._batch_q.qsize()
        )

    def pending_bucket_keys(self):
        """Bucket keys with half-filled micro-batches — the router's
        bucket-affinity signal (one more same-key request completes a
        batch instead of opening a new group elsewhere)."""
        return self._batcher.keys()

    def kill(self, reason="killed"):
        """Abrupt replica death (the fleet chaos-drill verb; contrast
        `shutdown`, the graceful drain). Admission stops immediately and
        EVERY unresolved future fails with a typed `ReplicaDown`:
        ``dispatched=True`` for requests whose batch was already on the
        device (the result is lost with the replica — typed, never
        silent), ``dispatched=False`` for queued-but-undispatched
        requests, which the fleet requeues onto surviving replicas.
        Worker threads are told to exit best-effort (they are daemons; a
        real preemption would take the whole process). Idempotent."""
        with self._close_lock:
            if self._closed:
                self._drained.wait()
                return
            self._closed = True
        # supersede the dispatcher so an in-progress or wedged dispatch
        # discards its work when it wakes (same mechanism as hang
        # recovery, but no successor thread is started)
        with self._gen_lock:
            self._dispatch_gen += 1
            dispatched = [
                r.future
                for b in self._inflight_dispatch.values()
                for r in b.requests
            ]
            self._inflight_dispatch.clear()
        # batches sitting in the readout queue were dispatched too: their
        # device results die with the replica
        while True:
            try:
                item = self._readout_q.get_nowait()
            except queue.Empty:
                break
            if item is not _SENTINEL:
                dispatched.extend(r.future for r in item[0].requests)
        dispatched = set(dispatched)
        self._stop_dispatch.set()
        # drain the submit queue (frees slots for the worker sentinels;
        # these futures are undispatched and already in the ledger)
        while True:
            try:
                item = self._submit_q.get_nowait()
            except queue.Empty:
                break
        for _ in self._workers:
            try:
                self._submit_q.put_nowait(_SENTINEL)
            except queue.Full:  # worker races refilled it; daemons anyway
                break
        try:
            self._readout_q.put_nowait(_SENTINEL)
        except queue.Full:
            pass
        if self._watchdog is not None:
            self._watchdog.stop(0)
        tag = self.replica_tag if self.replica_tag is not None else "?"
        with self._pending_lock:
            leftovers = list(self._pending)
        for fut in leftovers:
            on_device = fut in dispatched
            self._fail(fut, ReplicaDown(
                f"replica {tag} {reason}: "
                + ("in-flight batch lost with the replica" if on_device
                   else "request was queued, eligible for requeue"),
                replica=self.replica_tag,
                dispatched=on_device,
            ))
        self._drained.set()

    def report(self):
        """Snapshot of serving stats: counts, mean batch occupancy,
        latency percentiles, and the compile accounting. A VIEW over
        ``self.metrics`` — the same totals a telemetry session or a
        Prometheus scrape of the registry sees."""
        lat = self._m_latency.samples
        s = {
            "submitted": self._m_submitted.value,
            "completed": self._m_completed.value,
            "failed": self._m_failed.value,
            "shed": self._m_shed.value,
            "deadline_exceeded": self._m_deadline.value,
            "admission_rejected": self._m_rejected.value,
            "batches": self._m_batches.value,
            "real_samples": self._m_real.value,
            "padded_samples": self._m_padded.value,
            "recompiles_after_warmup": self._m_recompiles.value,
            "sharded_batches": self._m_sharded_batches.value,
            "replica_down": self._m_replica_down.value,
            "degraded_batches": self._m_degraded_batches.value,
            "refined_batches": self._m_refined_batches.value,
            "degrade_flips": self._m_flips.value,
            "degraded_mode": self._degraded_now(),
            "quality_variant": self._variant_now(),
            "pinned": self._m_pinned.value,
            "deadline_flush": self._batcher.deadline_aware,
            "dispatch_hangs": self._m_hangs.value,
            "stage_restarts": {
                "prep": self._m_prep_restarts.value,
                "dispatch": self._m_dispatch_restarts.value,
                "readout": self._m_readout_restarts.value,
            },
        }
        with self._bucket_lock:
            # str() keys: bucket keys are tuples and the report must
            # stay json.dumps-able (scripts/serve*.py print it)
            s["bucket_quality"] = {
                str(key): ladder.variant
                for key, ladder in self._bucket_ladders.items()
            }
        s["mean_occupancy"] = self._mean_occupancy()
        s["compiles"] = self._trace_count
        with self._compile_lock:
            s["compiled_programs"] = len(self._compiled)
        # threads from the ledger still alive after the drain settled —
        # populated only post-close so a live engine's worker pool is
        # not reported as a leak
        s["straggler_threads"] = (
            sorted(t.name for t in self._thread_ledger if t.is_alive())
            if self._drained.is_set() else []
        )
        for p, v in percentiles(lat).items():
            s[f"latency_{p}_ms"] = v * 1e3
        s["latencies_s"] = lat
        return s

    def shutdown(self, timeout=None):
        """Stop admission and drain; EVERY accepted future resolves
        before this returns. With ``timeout=None`` the drain blocks
        until all in-flight work finishes (the pre-PR-10 `close`
        semantics). With a finite timeout, whatever has not resolved
        when it expires is failed with a typed ``RequestShed
        (reason="drain")`` — results for slow stragglers are dropped,
        but no caller is ever left holding an unresolved future.
        Idempotent."""
        with self._close_lock:
            already = self._closed
            self._closed = True
        if already:
            # a concurrent shutdown owns the drain (e.g. the preemption
            # watcher): BLOCK until it finishes so "returned => every
            # accepted future resolved" holds for every caller, not just
            # the first
            self._drained.wait(timeout)
            return
        deadline = (
            None if timeout is None else self._clock() + timeout
        )

        def remaining():
            if deadline is None:
                return None
            return max(0.0, deadline - self._clock())

        for _ in self._workers:
            self._submit_q.put(_SENTINEL)
        for t in self._workers:
            t.join(remaining())
        self._stop_dispatch.set()
        self._dispatcher.join(remaining())
        try:
            self._readout_q.put(_SENTINEL, timeout=remaining())
        except queue.Full:
            pass  # readout wedged; its futures are failed below
        self._reader.join(remaining())
        if self._watchdog is not None:
            self._watchdog.stop(remaining())
        # thread-ledger sweep: join EVERY thread the engine ever started,
        # under a small bounded budget (a superseded dispatch generation
        # may be wedged by design — the watchdog drill leaves one parked
        # on a fault injection); whatever survives shows up in report()'s
        # straggler_threads instead of leaking silently
        ledger_deadline = self._clock() + 0.5
        for t in self._thread_ledger:
            if t is threading.current_thread():
                continue
            budget = ledger_deadline - self._clock()
            r = remaining()
            if r is not None:
                budget = min(budget, r)
            if budget > 0 and t.is_alive():
                t.join(budget)
        # the drain ledger: anything still pending missed the deadline
        with self._pending_lock:
            leftovers = list(self._pending)
        for fut in leftovers:
            self._fail(fut, RequestShed(
                "drain deadline expired before this request resolved",
                reason="drain",
            ))
        self._drained.set()

    def drain(self, timeout=None):
        """Alias for `shutdown` — the name `drain_on_preemption` calls."""
        self.shutdown(timeout=timeout)

    def close(self):
        """Drain in-flight work (every accepted future resolves), then
        join all pipeline threads. Idempotent."""
        self.shutdown(timeout=None)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
