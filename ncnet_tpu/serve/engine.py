"""Serving engine: warm AOT-compiled shape buckets + a pipelined request path.

Three stages, each on its own thread(s), bounded queues between them
(double-buffered in the style of `train.loop._prefetch_device_batches`):

1. **host prep** — ``host_workers`` threads pop raw requests from a
   BOUNDED submit queue (backpressure: `submit` blocks or raises
   ``queue.Full``), hit the ``serve.request`` fault point
   (`resilience.faultinject` — tests inject slow/failed requests here
   without stalling the pipeline), run ``prep_fn`` (decode/resize/
   normalize, or a feature-store lookup) under the data loader's
   per-attempt retry + exponential backoff (``prep_retries`` — the same
   `data.loader.retry_call` the training loaders use for transient
   I/O), and feed the micro-batcher;
2. **device dispatch** — one thread drives `MicroBatcher` (cap +
   deadline flushes), stacks each flushed group into a padded
   fixed-shape batch, runs the AOT-compiled executable for
   ``(bucket key, padded size)``, and starts the result's D2H via
   ``copy_to_host_async`` the moment compute is dispatched;
3. **readout** — one thread converts device results to numpy (the only
   blocking sync), slices out the REAL rows (padding masked here: a
   served batch is bitwise the same program on the same padded array,
   and a lone bs-1 request is bitwise the per-pair pipeline — across
   batch sizes XLA's codegen may differ by ulps, never by padding),
   and resolves per-request futures. The readout queue depth of 2 means
   the device computes batch i+1 while batch i is being read out.

Compile discipline: `warmup` AOT-compiles every (bucket, batch-size)
shape up front via ``jit(...).lower(...).compile()`` — reusing the
persistent compilation cache when ``compile_cache_dir`` is set
(`utils.compile_cache`) — and serving then calls the compiled
executables directly, never the jit wrapper, so the steady state cannot
retrace. A trace-time counter inside the wrapped apply fn counts every
real compile (the counting-jit assertion in tests/test_serve.py), and
any compile triggered by a LIVE request after warmup is reported as
``recompiles_after_warmup`` (the number `scripts/serve.py` must show as
zero).
"""

import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

import jax

from ncnet_tpu.data.loader import retry_call
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.serve.batcher import MicroBatcher, Request, default_batch_sizes
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    percentiles,
)

_SENTINEL = object()


def payload_spec(payload):
    """Per-sample ``{name: (shape, dtype)}`` of a payload dict — the
    warmup-time description of a bucket's arrays."""
    return {
        name: (tuple(np.shape(arr)), np.asarray(arr).dtype)
        for name, arr in payload.items()
    }


#: donation spec for every serving program: argnum 1 is the padded input
#: batch — single-use per dispatch (`_dispatch_inner` stacks fresh numpy
#: each time, and nothing reads it after the call), so XLA may reuse its
#: HBM for outputs. Argnum 0 (params) is reused across every dispatch and
#: must NEVER be donated. `ncnet_tpu.analysis.jaxpr_audit` checks the
#: compiled programs against this spec.
SERVE_DONATE_ARGNUMS = (1,)


def make_serve_match_step(config, softmax=True, from_features=False):
    """The serving apply fn for the correspondence workload:
    ``apply(params, batch) -> {'matches': [b, 5, n]}``.

    Wraps `eval.inloc.make_match_fn(concat_directions=True)` — the same
    forward the InLoc dump jits — so serving is the per-pair pipeline,
    just batched: trunk (or, with ``from_features=True``, a feature-store
    lookup upstream feeding ``[b, fh, fw, c]`` feature maps straight in),
    dense or ``nc_topk`` sparse NC, both-direction `corr_to_matches`
    fused into one output array. The direction concat stays inside the
    compiled program; the batch axis is moved first so readout slices
    one ``[5, n]`` block per request.
    """
    import jax.numpy as jnp

    # lazy: eval.inloc imports serve.buckets, so a module-level import
    # here would be a cycle through ncnet_tpu.serve.__init__
    from ncnet_tpu.eval.inloc import make_match_fn

    fn = make_match_fn(
        config, softmax=softmax, concat_directions=True,
        from_features=from_features,
    )

    def apply(params, batch):
        out = fn(params, batch["source_image"], batch["target_image"])
        return {"matches": jnp.moveaxis(out, 1, 0)}  # [5,b,n] -> [b,5,n]

    return apply


class ServeEngine:
    """Batched, warm, overlapped serving of ``apply_fn(params, batch)``.

    ``apply_fn`` takes ``(params, {name: [b, ...]})`` and returns a
    pytree whose every leaf has the batch as axis 0 (per-request results
    are sliced out along it). ``prep_fn(raw) -> (bucket_key, payload)``
    runs on the host workers; without one, `submit` takes ``(key,
    payload)`` directly (payload: ``{name: per-sample array}``). Requests
    sharing a key are batched together, padded up to the next allowed
    batch size by replicating the last real payload, and the padding rows
    are discarded at readout — padding never perturbs real rows (bitwise
    vs the same program unpadded; vs a different-batch-size program the
    results agree to XLA codegen ulps, tests/test_serve.py).

    Use as a context manager; `close` drains in-flight work, resolves
    every accepted future, and joins all threads.
    """

    def __init__(
        self,
        apply_fn,
        params,
        *,
        max_batch=8,
        max_wait=0.005,
        batch_sizes=None,
        queue_limit=64,
        host_workers=2,
        prep_fn=None,
        prep_retries=0,
        retry_backoff=0.05,
        readout_depth=2,
        compile_cache_dir=None,
        registry=None,
    ):
        if compile_cache_dir is not None:
            from ncnet_tpu.utils.compile_cache import enable_compile_cache

            enable_compile_cache(compile_cache_dir)
        self._params = params
        self._prep_fn = prep_fn
        self._prep_retries = prep_retries
        self._retry_backoff = retry_backoff
        self.batch_sizes = (
            tuple(sorted(batch_sizes))
            if batch_sizes is not None
            else default_batch_sizes(max_batch)
        )
        self._batcher = MicroBatcher(
            max_batch=max_batch, max_wait=max_wait,
            batch_sizes=self.batch_sizes,
        )

        # one jit wrapper per engine; its cache is NEVER hit in steady
        # state (serving calls the AOT executables below), it exists to
        # lower/compile and to count traces: the increment is a Python
        # side effect that runs only when JAX actually retraces
        self._trace_count = 0

        def _counted_apply(p, batch):
            self._trace_count += 1
            return apply_fn(p, batch)

        self._jit = jax.jit(_counted_apply, donate_argnums=SERVE_DONATE_ARGNUMS)
        self._compiled = {}  # (bucket key, padded size) -> executable
        self._compile_lock = threading.Lock()
        self._warm = False

        self._submit_q = queue.Queue(maxsize=queue_limit)
        self._batch_q = queue.Queue()
        self._readout_q = queue.Queue(maxsize=readout_depth)
        self._closed = False
        self._stop_dispatch = threading.Event()

        # Engine stats live in a telemetry metrics registry; `report()`
        # is a VIEW over it. Private per engine by default (co-resident
        # engines and tests must not share totals); pass ``registry=``
        # (e.g. the telemetry session's) to publish into a shared one.
        self.metrics = registry if registry is not None else MetricsRegistry()
        m = self.metrics
        self._m_submitted = m.counter(
            "serve_requests_submitted_total",
            "requests accepted by submit()",
        )
        self._m_completed = m.counter(
            "serve_requests_completed_total",
            "requests resolved with a result",
        )
        self._m_failed = m.counter(
            "serve_requests_failed_total",
            "requests resolved with an exception",
        )
        self._m_batches = m.counter(
            "serve_batches_total", "device batches dispatched"
        )
        self._m_real = m.counter(
            "serve_samples_real_total", "real rows across served batches"
        )
        self._m_padded = m.counter(
            "serve_samples_padded_total",
            "padded rows across served batches",
        )
        self._m_recompiles = m.counter(
            "serve_recompiles_after_warmup_total",
            "live-request compiles after warmup (must stay 0)",
        )
        self._m_latency = m.histogram(
            "serve_request_latency_seconds",
            "submit-to-result latency",
            buckets=DEFAULT_LATENCY_BUCKETS,
        )
        self._m_batch_size = m.histogram(
            "serve_batch_real_size",
            "real rows per dispatched batch",
            buckets=tuple(float(b) for b in self.batch_sizes),
        )
        # Sampled gauges: the truth lives in the queue / the counters,
        # the gauges read it at scrape time.
        m.gauge(
            "serve_submit_queue_depth",
            "requests waiting in the bounded submit queue",
        ).set_fn(self._submit_q.qsize)
        m.gauge(
            "serve_mean_occupancy",
            "cumulative real/padded row ratio across served batches",
        ).set_fn(self._mean_occupancy)

        self._workers = [
            threading.Thread(
                target=self._prep_loop, name=f"serve-prep-{i}", daemon=True
            )
            for i in range(host_workers)
        ]
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serve-dispatch", daemon=True
        )
        self._reader = threading.Thread(
            target=self._readout_loop, name="serve-readout", daemon=True
        )
        for t in self._workers:
            t.start()
        self._dispatcher.start()
        self._reader.start()

    # ------------------------------------------------------------------
    # compile management

    def _specs(self, key, bs, pspec):
        del key  # the bucket key is already encoded in the shapes
        return {
            name: jax.ShapeDtypeStruct((bs,) + tuple(shape), dtype)
            for name, (shape, dtype) in pspec.items()
        }

    def _executable(self, key, bs, pspec, live):
        ck = (key, bs)
        exe = self._compiled.get(ck)
        if exe is not None:
            return exe
        with self._compile_lock:
            exe = self._compiled.get(ck)
            if exe is None:
                if live and self._warm:
                    self._m_recompiles.inc()
                exe = self._jit.lower(
                    self._params, self._specs(key, bs, pspec)
                ).compile()
                self._compiled[ck] = exe
        return exe

    def warmup(self, bucket_specs):
        """AOT-compile every (bucket, batch size) pair up front.

        ``bucket_specs``: iterable of ``(key, per-sample spec)`` where the
        spec is `payload_spec`-shaped (``{name: (shape, dtype)}``). Each
        key is compiled at EVERY allowed padded batch size, so a warmed
        engine serves any traffic mix over those buckets with zero
        compiles. Incremental: may be called again for newly-discovered
        buckets; warmup compiles are never counted as recompiles. Returns
        the number of compiled programs now cached.
        """
        for key, pspec in bucket_specs:
            for bs in self.batch_sizes:
                self._executable(key, bs, pspec, live=False)
        self._warm = True
        return len(self._compiled)

    @property
    def compile_count(self):
        """Number of real traces so far (the counting-jit assertion)."""
        return self._trace_count

    # ------------------------------------------------------------------
    # request path

    def submit(self, raw=None, *, key=None, payload=None, timeout=None):
        """Queue one request; returns a `concurrent.futures.Future`.

        With a ``prep_fn``: pass ``raw`` (whatever the prep fn consumes).
        Without one: pass ``key=``/``payload=``. The submit queue is
        BOUNDED (``queue_limit``): when it is full, ``timeout=None``
        blocks (natural backpressure), ``timeout=0`` raises
        ``queue.Full`` immediately, and a positive timeout raises after
        waiting that long.
        """
        if self._closed:
            raise RuntimeError("submit on a closed ServeEngine")
        if raw is None:
            if key is None or payload is None:
                raise ValueError(
                    "submit needs either raw (with a prep_fn) or "
                    "key= and payload="
                )
            raw = (key, payload)
        fut = Future()
        item = (raw, fut, time.monotonic())
        if timeout == 0:
            self._submit_q.put_nowait(item)  # queue.Full on backpressure
        else:
            self._submit_q.put(item, timeout=timeout)
        self._m_submitted.inc()
        return fut

    def _prep_loop(self):
        while True:
            item = self._submit_q.get()
            if item is _SENTINEL:
                return
            raw, fut, t_submit = item
            try:
                with trace.span("serve/prep"):
                    # the fault point fires ONCE per request (never
                    # retried: an injected crash must fail
                    # deterministically); the prep itself gets the
                    # loader's transient-I/O retry
                    faultinject.fire("serve.request")
                    key, payload = retry_call(
                        lambda: (
                            self._prep_fn(raw)
                            if self._prep_fn is not None
                            else raw
                        ),
                        self._prep_retries,
                        self._retry_backoff,
                    )
            except BaseException as exc:  # a failed request fails ALONE
                self._fail(fut, exc)
                continue
            batch = self._batcher.add(Request(key, payload, fut, t_submit))
            if batch is not None:  # the add filled a group to max_batch
                self._batch_q.put(batch)

    def _dispatch_loop(self):
        while True:
            stopping = self._stop_dispatch.is_set()
            nd = self._batcher.next_deadline()
            wait = 0.0 if stopping else min(
                0.05, max(0.0, nd) if nd is not None else 0.05
            )
            try:
                batch = self._batch_q.get(timeout=wait)
            except queue.Empty:
                batch = None
            if batch is not None:
                self._dispatch(batch)
            for b in self._batcher.ready():
                self._dispatch(b)
            if stopping and batch is None and self._batch_q.empty():
                # prep workers are already joined: nothing new can
                # arrive, so one final drain flushes trailing partials
                for b in self._batcher.drain():
                    self._dispatch(b)
                if self._batch_q.empty():
                    return

    def _dispatch(self, batch):
        with trace.span("serve/dispatch"):
            self._dispatch_inner(batch)

    def _dispatch_inner(self, batch):
        try:
            reqs = batch.requests
            names = sorted(reqs[0].payload)
            stacked = {}
            for name in names:
                arrs = [np.asarray(r.payload[name]) for r in reqs]
                # pad by replicating the last REAL sample: the padded
                # rows run through the same program and are discarded at
                # readout, so they only have to be shape/dtype-valid
                arrs.extend([arrs[-1]] * (batch.pad_to - len(arrs)))
                stacked[name] = np.stack(arrs)
            exe = self._executable(
                batch.key, batch.pad_to, payload_spec(reqs[0].payload),
                live=True,
            )
            out = exe(self._params, stacked)
            # start D2H immediately; the readout thread's np.asarray
            # then finds the bytes already on their way
            for leaf in jax.tree_util.tree_leaves(out):
                leaf.copy_to_host_async()
        except BaseException as exc:  # compile/shape/dispatch failure
            for r in batch.requests:
                self._fail(r.future, exc)
            return
        self._readout_q.put((batch, out))

    def _readout_loop(self):
        while True:
            item = self._readout_q.get()
            if item is _SENTINEL:
                return
            batch, out = item
            with trace.span("serve/readout"):
                try:
                    host = jax.tree_util.tree_map(np.asarray, out)
                except BaseException as exc:
                    for r in batch.requests:
                        self._fail(r.future, exc)
                    continue
                now = time.monotonic()
                n = len(batch.requests)
                self._m_batches.inc()
                self._m_real.inc(n)
                self._m_padded.inc(batch.pad_to)
                self._m_completed.inc(n)
                self._m_batch_size.observe(n)
                for r in batch.requests:
                    self._m_latency.observe(now - r.t_submit)
                for i, r in enumerate(batch.requests):
                    # padding masked here: only rows [0, n) are ever read
                    r.future.set_result(
                        jax.tree_util.tree_map(lambda a: a[i], host)
                    )

    def _fail(self, fut, exc):
        self._m_failed.inc()
        fut.set_exception(exc)

    # ------------------------------------------------------------------
    # lifecycle / accounting

    def _mean_occupancy(self):
        padded = self._m_padded.value
        return self._m_real.value / padded if padded else float("nan")

    def report(self):
        """Snapshot of serving stats: counts, mean batch occupancy,
        latency percentiles, and the compile accounting. A VIEW over
        ``self.metrics`` — the same totals a telemetry session or a
        Prometheus scrape of the registry sees."""
        lat = self._m_latency.samples
        s = {
            "submitted": self._m_submitted.value,
            "completed": self._m_completed.value,
            "failed": self._m_failed.value,
            "batches": self._m_batches.value,
            "real_samples": self._m_real.value,
            "padded_samples": self._m_padded.value,
            "recompiles_after_warmup": self._m_recompiles.value,
        }
        s["mean_occupancy"] = self._mean_occupancy()
        s["compiles"] = self._trace_count
        s["compiled_programs"] = len(self._compiled)
        for p, v in percentiles(lat).items():
            s[f"latency_{p}_ms"] = v * 1e3
        s["latencies_s"] = lat
        return s

    def close(self):
        """Drain in-flight work (every accepted future resolves), then
        join all pipeline threads. Idempotent."""
        if self._closed:
            return
        self._closed = True
        for _ in self._workers:
            self._submit_q.put(_SENTINEL)
        for t in self._workers:
            t.join()
        self._stop_dispatch.set()
        self._dispatcher.join()
        self._readout_q.put(_SENTINEL)
        self._reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
