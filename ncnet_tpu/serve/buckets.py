"""Shape bucketing: the quantized-resize rule as a first-class module.

Serving a jit-compiled model means every distinct input shape is a
compiled program, so the resize policy IS the compile-cache policy.
`eval/inloc.py` has always quantized its aspect-preserving resize so the
feature grid divides the relocalization ``k_size`` — and leaned on the
jit cache as an accidental shape-bucketing layer (its module docstring
says as much). This module promotes that rule to a shared primitive:

* :func:`quantized_resize_shape` — THE resize rule, moved verbatim from
  `eval/inloc.py` (which now imports it from here: one formula, two
  consumers, behavior pinned by a parity test in tests/test_serve.py);
* :class:`BucketSpec` — a frozen, hashable description of the bucket
  universe (``image_size``, ``k_size``, ``grid_multiple``) with per-image
  and per-pair bucket keys;
* :func:`request_buckets` — the distinct pair buckets of a request
  sweep, i.e. exactly the shape set a serving engine must AOT-compile at
  warmup.

Buckets are EXACT resized shapes, not padded envelopes: two requests
share a bucket iff their quantized shapes coincide, so batching pairs
within a bucket pads only the BATCH dimension, never the spatial dims —
which is what keeps padding from perturbing results at all (spatial
padding would change the correlation support; batch padding is sliced
away at readout).
"""

import dataclasses
from typing import Optional, Tuple

import numpy as np

SCALE_FACTOR = 0.0625  # 1/backbone stride (reference eval_inloc.py:77)


def quantized_resize_shape(h, w, image_size, k_size, grid_multiple=None):
    """The reference's resize rule (eval_inloc.py:84-89): max side ->
    ``image_size``, then quantize so feature-grid dims divide by
    ``grid_multiple`` (default: ``k_size``; the sharded path additionally
    needs divisibility by the shard count)."""
    m = grid_multiple if grid_multiple is not None else k_size
    ratio = max(h, w) / image_size
    if m <= 1:
        return int(h / ratio), int(w / ratio)
    s = SCALE_FACTOR
    return (
        int(np.floor(h / ratio * s / m) / s * m),
        int(np.floor(w / ratio * s / m) / s * m),
    )


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """The bucket universe: which quantized shape each raw image maps to.

    Frozen/hashable so a spec can key jit-static state. ``k_size`` <= 1
    means no grid quantization beyond the integer resize (matching
    `quantized_resize_shape`); ``grid_multiple`` widens the quantum for
    the spatially-sharded pipeline.
    """

    image_size: int
    k_size: int = 1
    grid_multiple: Optional[int] = None

    def bucket(self, h, w) -> Tuple[int, int]:
        """Quantized (h, w) for a raw image of shape (h, w)."""
        return quantized_resize_shape(
            h, w, self.image_size, self.k_size, self.grid_multiple
        )


def pair_bucket(spec, src_hw, tgt_hw):
    """Bucket key for one (source, target) request: a pair of quantized
    shapes. Requests batch together iff their keys are equal."""
    return (spec.bucket(*src_hw), spec.bucket(*tgt_hw))


def request_buckets(spec, pair_shapes):
    """Sorted distinct `pair_bucket` keys over ``(src_hw, tgt_hw)`` raw
    shape pairs — the exact shape set to AOT-compile at warmup."""
    return sorted({pair_bucket(spec, s, t) for s, t in pair_shapes})
