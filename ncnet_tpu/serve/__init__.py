"""Batched, shape-bucketed correspondence serving.

Turns the per-pair match pipeline into a warm, batched, overlapped
request path:

* :mod:`ncnet_tpu.serve.buckets` — the quantized-resize shape-bucketing
  rule (shared with `eval/inloc.py`), jit-static by construction;
* :mod:`ncnet_tpu.serve.batcher` — dynamic micro-batching: concurrent
  requests coalesced per bucket into padded fixed-shape batches under a
  max-wait deadline and a max-batch cap;
* :mod:`ncnet_tpu.serve.engine` — lifecycle + pipelining: warmup
  AOT-compiles every (bucket, batch-size) shape, then host prep workers
  -> device dispatch -> async D2H readout run double-buffered.

Padding is masked at readout, so padded rows NEVER perturb real results:
a served batch is bitwise identical to the same compiled program on the
same padded array, and a lone request (padded to batch 1) is bitwise the
per-pair pipeline. Across different padded batch sizes, results agree to
the few-ulp float associativity of XLA's batch-size-dependent codegen —
the only permitted difference (tests/test_serve.py pins all three).

SLO + resilience layer (:mod:`ncnet_tpu.serve.resilience`): per-request
deadlines with admission-control shedding (typed `RequestShed` /
`DeadlineExceeded` / `AdmissionRejected` outcomes), hysteresis-controlled
degradation to a pre-warmed sparse program under overload, supervised
stage restarts with a dispatch-hang watchdog (`StageFailure`), and
deadline-bounded graceful drain (`drain_on_preemption` + the SIGTERM
`PreemptionGuard`).

Fleet layer (:mod:`ncnet_tpu.serve.fleet` + :mod:`ncnet_tpu.serve.router`):
one device-pinned, warmed engine per chip behind a bucket-affinity
best-ETA router; fleet-wide admission sheds only when NO replica can
meet the budget; per-replica watchdog supervision with typed
`ReplicaDown`, requeue of a dead replica's queued work onto survivors,
and quarantine/rejoin with re-warmup. Engines also accept
``shard_mesh=`` to run a bucket's batch sharded across the mesh via
`parallel.mesh.make_batch_sharded_apply` (bitwise the single-device
program per shard).
"""

from ncnet_tpu.serve.batcher import MicroBatch, MicroBatcher, default_batch_sizes
from ncnet_tpu.serve.buckets import (
    SCALE_FACTOR,
    BucketSpec,
    pair_bucket,
    quantized_resize_shape,
    request_buckets,
)
from ncnet_tpu.serve.engine import ServeEngine, make_serve_match_step, payload_spec
from ncnet_tpu.serve.fleet import ServeFleet
from ncnet_tpu.serve.http import (
    HttpFrontDoor,
    default_bucket_key,
    make_http_server,
    outcome_status,
    start_http_server,
)
from ncnet_tpu.serve.resilience import (
    AdmissionRejected,
    DeadlineExceeded,
    HysteresisController,
    LatencyEstimator,
    QualityLadder,
    ReplicaDown,
    RequestShed,
    ServeResilienceError,
    StageFailure,
    Watchdog,
    drain_on_preemption,
    run_supervised,
)
from ncnet_tpu.serve.router import FleetRouter, ReplicaView

__all__ = [
    "AdmissionRejected",
    "BucketSpec",
    "DeadlineExceeded",
    "FleetRouter",
    "HttpFrontDoor",
    "HysteresisController",
    "LatencyEstimator",
    "MicroBatch",
    "MicroBatcher",
    "QualityLadder",
    "ReplicaDown",
    "ReplicaView",
    "RequestShed",
    "SCALE_FACTOR",
    "ServeEngine",
    "ServeFleet",
    "ServeResilienceError",
    "StageFailure",
    "Watchdog",
    "default_batch_sizes",
    "default_bucket_key",
    "drain_on_preemption",
    "make_http_server",
    "make_serve_match_step",
    "outcome_status",
    "start_http_server",
    "pair_bucket",
    "payload_spec",
    "quantized_resize_shape",
    "request_buckets",
]
