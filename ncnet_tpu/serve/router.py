"""Fleet request routing: bucket-affinity placement + fleet-wide
admission control.

The single-engine admission story (PR 10) sheds a request when ITS
engine's latency estimate says the deadline will be missed. A fleet must
not shed that eagerly: replica A being backlogged is no reason to drop a
request replica B could serve in time. `FleetRouter.route` therefore
ranks replicas by estimated time-to-completion and sheds ONLY when no
replica can meet the budget — the fleet-wide generalization of the same
SLO contract.

Two signals drive placement, in priority order:

* **ETA** — per-replica `LatencyEstimator` EWMA (each replica feeds its
  own: replicas can sit on heterogeneous devices or carry different
  backlogs, so one fleet-wide EWMA would mis-estimate both) scaled by
  queued work: ``max_wait + est * (1 + queued / max_batch) * margin``.
  A replica whose estimator has no samples yet is BLIND — it is assumed
  fast (effective ETA ``max_wait``, the floor any batch pays) and is
  never shed against: admission control admits blind until measured,
  exactly as the single-engine contract.
* **Bucket affinity** — among replicas whose ETA is within
  ``affinity_slack`` of the best, prefer one that already holds a
  half-filled micro-batch for this request's bucket key
  (`ServeEngine.pending_bucket_keys`): one more same-key request
  completes a batch there instead of opening a fresh group elsewhere,
  which raises occupancy fleet-wide without sacrificing latency (the
  slack bound).

Ties break round-robin so an idle fleet spreads load instead of
hammering replica 0.

Import-light by contract (stdlib only): `ServeFleet` imports this on
every submit.
"""

import threading

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.serve.resilience import RequestShed


class ReplicaView:
    """One replica's routing-relevant surface, decoupled from the engine
    class so the router is testable with plain closures (and so a future
    HTTP front door can route over remote replicas it only knows through
    stats). The fleet builds one per healthy replica on every route."""

    __slots__ = (
        "replica", "estimator", "queued_fn", "keys_fn", "max_wait",
        "max_batch",
    )

    def __init__(self, replica, *, estimator, queued_fn, keys_fn,
                 max_wait, max_batch):
        self.replica = replica
        self.estimator = estimator
        self.queued_fn = queued_fn
        self.keys_fn = keys_fn
        self.max_wait = max_wait
        self.max_batch = max_batch


class FleetRouter:
    """Pick the replica for one request; shed only when NONE can serve
    it in budget.

    ``margin`` scales every ETA (pessimism knob, mirroring the engine's
    ``deadline_margin``); ``affinity_slack`` bounds how much latency the
    bucket-affinity preference may trade for occupancy (affinity only
    wins among replicas with ``eta <= best * affinity_slack``).
    """

    def __init__(self, margin=1.0, affinity_slack=1.5):
        if margin <= 0:
            raise ValueError(f"margin must be > 0, got {margin}")
        if affinity_slack < 1.0:
            raise ValueError(
                f"affinity_slack must be >= 1, got {affinity_slack}"
            )
        self.margin = margin
        self.affinity_slack = affinity_slack
        self._lock = concurrency.make_lock("serve.router")
        self._rr = 0
        # last routing decision, for the fleet report / debugging:
        # {"replica", "eta_s", "affinity"}
        self.last_decision = None

    def eta(self, view, key=None):
        """Estimated time-to-completion on ``view``: None while its
        estimator is blind (no batch measured yet), else batch wait +
        EWMA scaled by how many batches are already queued ahead."""
        est = view.estimator.estimate(key)
        if est is None:
            return None
        backlog = view.queued_fn() / max(view.max_batch, 1)
        return view.max_wait + est * (1.0 + backlog) * self.margin

    def route(self, views, *, key=None, deadline_s=None):
        """Return the chosen `ReplicaView`.

        Raises typed `RequestShed`: ``reason="unavailable"`` when
        ``views`` is empty (every replica dead/quarantined),
        ``reason="admission"`` when a deadline is set, every replica is
        measured, and even the BEST ETA misses it — the fleet-wide shed.
        """
        faultinject.fire("serve.router.route")
        views = list(views)
        if not views:
            raise RequestShed(
                "no live replica (all dead or quarantined)",
                reason="unavailable",
            )
        etas = [self.eta(v, key) for v in views]
        known = [e for e in etas if e is not None]
        if deadline_s is not None and len(known) == len(etas):
            best = min(known)
            if best > deadline_s:
                raise RequestShed(
                    f"no replica can meet deadline: best ETA {best:.4f}s "
                    f"> budget {deadline_s:.4f}s",
                    reason="admission",
                    estimated_s=best,
                    deadline_s=deadline_s,
                    retry_after_s=best,
                )
        # blind replicas compete at the optimistic floor (max_wait): they
        # must attract traffic or their estimator never gets a sample
        eff = [
            v.max_wait if e is None else e for v, e in zip(views, etas)
        ]
        best = min(eff)
        slack = best * self.affinity_slack
        candidates = [
            (v, e) for v, e in zip(views, eff) if e <= slack
        ]
        chosen, chosen_eta, affinity = None, None, False
        if key is not None:
            with_key = [
                (v, e) for v, e in candidates if key in v.keys_fn()
            ]
            if with_key:
                chosen, chosen_eta = min(with_key, key=lambda ve: ve[1])
                affinity = True
        if chosen is None:
            # min-ETA with round-robin tiebreak: an idle fleet (all ETAs
            # equal) spreads instead of always picking index 0
            with self._lock:
                start = self._rr
                self._rr += 1
            n = len(candidates)
            order = [candidates[(start + i) % n] for i in range(n)]
            chosen, chosen_eta = min(order, key=lambda ve: ve[1])
        # written under the lock so a fleet report never sees a decision
        # dict mid-swap relative to the round-robin state it paired with
        with self._lock:
            self.last_decision = {
                "replica": chosen.replica,
                "eta_s": chosen_eta,
                "affinity": affinity,
            }
        return chosen
