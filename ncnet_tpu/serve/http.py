"""HTTP front door: the serving stack's network edge (ISSUE 17).

A thin stdlib-only (``http.server.ThreadingHTTPServer``) layer over
`ServeEngine.submit` / `ServeFleet.submit` that turns the typed outcome
hierarchy into WIRE contracts — the whole point of typed outcomes since
PR 10 was that a load balancer can act on them:

==========================  ======  =======================================
typed outcome               status  extras
==========================  ======  =======================================
result                      200     ``{"result": {name: nested lists}}``
`AdmissionRejected`         429     ``Retry-After`` from the carried hint
`RequestShed` (admission)   429     ``Retry-After``, estimate + budget body
`RequestShed` (drain)       503     server is draining / closed
`DeadlineExceeded`          504     the FAILING STAGE in the body
`ReplicaDown`               502     replica + dispatched flag in the body
`StageFailure` / other      500     stage (+ hang flag) / exception name
bad request (pin, JSON)     400     `ValueError` detail in the body
==========================  ======  =======================================

Endpoints:

* ``POST /v1/match`` — body ``{"payload": {name: nested lists},
  "dtypes": {name: dtype-str} (optional, default float32)}``. Headers:
  ``X-Deadline-Ms`` propagates INTO the stack as ``deadline_s`` (the
  engine's admission control, deadline-aware micro-batch flush, and
  per-bucket cost ladders all run off it); ``X-Quality`` pins the
  quality rung ("refined" / "standard" / "degraded") for this request.
* ``GET /healthz`` — 200 while serving; 503 before warmup finishes and
  from the moment a drain BEGINS (the load balancer stops routing
  before SIGTERM completes), while the listener keeps answering.
* ``GET /metrics`` — the registry's Prometheus text snapshot.

Every response carries exactly one status code counted in
``http_responses_<code>_total``, so the engine/fleet accounting identity
can be reconciled against the HTTP tallies (benchmarks/micro_http.py).

The front door never blocks on a full submit queue (engine submits use
``timeout=0``): backpressure surfaces as 429, not as a wedged handler
thread holding a socket open.
"""

import inspect
import json
import math
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.serve.resilience import (
    AdmissionRejected,
    DeadlineExceeded,
    ReplicaDown,
    RequestShed,
    StageFailure,
)
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import MetricsRegistry

#: status codes pre-registered as counters (anything else falls into
#: http_responses_other_total; the registry has no labels by design)
_KNOWN_CODES = (200, 400, 404, 405, 429, 500, 502, 503, 504)

VALID_QUALITY = ("refined", "standard", "degraded")


def default_bucket_key(payload):
    """Canonical bucket key of a payload: the sorted (name, shape,
    dtype) spec tuple. Server-side and deterministic — the SAME function
    keys warmup and live traffic, so a warmed shape can never miss its
    executable because a client spelled the key differently."""
    return tuple(
        sorted(
            (name, tuple(np.shape(arr)), str(np.asarray(arr).dtype))
            for name, arr in payload.items()
        )
    )


def decode_payload(obj, dtypes=None):
    """JSON body -> ``{name: np.ndarray}``. Arrays default to float32
    (JSON floats would otherwise decode as float64 and miss every warmed
    float32 bucket); ``dtypes`` overrides per name."""
    if not isinstance(obj, dict) or not obj:
        raise ValueError("payload must be a non-empty JSON object")
    dtypes = dtypes or {}
    out = {}
    for name, val in obj.items():
        dt = np.dtype(dtypes.get(name, "float32"))
        out[name] = np.asarray(val, dtype=dt)
    return out


def outcome_status(exc):
    """Map a typed serving outcome to ``(status, retry_after_s, body)``.

    The single source of truth for the wire contract — the table test in
    tests/test_http.py pins every row."""
    if isinstance(exc, AdmissionRejected):
        return 429, exc.retry_after_s, {
            "error": "admission_rejected",
            "detail": str(exc),
        }
    if isinstance(exc, DeadlineExceeded):
        return 504, None, {
            "error": "deadline_exceeded",
            "stage": exc.stage,
            "detail": str(exc),
        }
    if isinstance(exc, RequestShed):
        if exc.reason == "drain":
            return 503, exc.retry_after_s, {
                "error": "draining",
                "detail": str(exc),
            }
        return 429, exc.retry_after_s, {
            "error": "shed",
            "reason": exc.reason,
            "estimated_s": exc.estimated_s,
            "deadline_s": exc.deadline_s,
            "detail": str(exc),
        }
    if isinstance(exc, ReplicaDown):
        return 502, None, {
            "error": "replica_down",
            "replica": exc.replica,
            "dispatched": exc.dispatched,
            "detail": str(exc),
        }
    if isinstance(exc, StageFailure):
        return 500, None, {
            "error": "stage_failure",
            "stage": exc.stage,
            "hang": exc.hang,
            "detail": str(exc),
        }
    return 500, None, {"error": type(exc).__name__, "detail": str(exc)}


class HttpFrontDoor:
    """Request-handling policy shared by every endpoint: readiness,
    admission, typed-outcome translation, per-status counters, and the
    drain sequence. The HTTP handler class below is a thin I/O shim over
    this object, so tests can drive the policy without sockets.

    ``server``: a `ServeEngine` or `ServeFleet` (anything with
    ``submit(key=, payload=, deadline_s=, variant=)`` and ``drain()``).
    ``registry``: where the ``http_*`` counters live — pass the
    server's own ``metrics`` registry to get one merged scrape.
    """

    def __init__(self, server, *, registry=None, key_fn=None,
                 request_timeout_s=60.0, drain_timeout_s=None,
                 clock=time.monotonic):
        self._server = server
        self._key_fn = key_fn if key_fn is not None else default_bucket_key
        self._request_timeout = request_timeout_s
        self._drain_timeout = drain_timeout_s
        self._clock = clock
        self._httpd = None
        # engine submits must never block a handler thread on a full
        # queue (timeout=0 -> typed AdmissionRejected); fleet submits
        # have no timeout kwarg and never block by contract
        params = inspect.signature(server.submit).parameters
        self._submit_kwargs = {"timeout": 0} if "timeout" in params else {}
        self.ready = threading.Event()
        self._lock = concurrency.make_lock("serve.http")
        self._accepting = True  # guarded by _lock
        self._inflight = 0  # guarded by _lock
        self._idle = threading.Event()

        self.metrics = (
            registry if registry is not None else MetricsRegistry()
        )
        self._m_requests = self.metrics.counter(
            "http_requests_total", "HTTP requests received (all endpoints)"
        )
        self._m_by_code = {
            code: self.metrics.counter(
                f"http_responses_{code}_total",
                f"HTTP responses with status {code}",
            )
            for code in _KNOWN_CODES
        }
        self._m_other = self.metrics.counter(
            "http_responses_other_total",
            "HTTP responses with any other status",
        )

    # -- accounting ----------------------------------------------------

    def count_response(self, status):
        self._m_by_code.get(status, self._m_other).inc()

    def status_tally(self):
        """``{status: count}`` over every response sent — the HTTP side
        of the accounting reconciliation."""
        tally = {
            code: counter.value
            for code, counter in self._m_by_code.items()
            if counter.value
        }
        if self._m_other.value:
            tally["other"] = self._m_other.value
        return tally

    # -- request path --------------------------------------------------

    @property
    def accepting(self):
        with self._lock:
            return self._accepting

    def handle_match(self, body_bytes, headers):
        """The POST /v1/match policy: parse, admit, submit, wait,
        translate. Returns ``(status, extra_headers, body_dict)`` —
        exactly one response per request, no exceptions escape."""
        self._m_requests.inc()
        with self._lock:
            if not self._accepting:
                return 503, {}, {
                    "error": "draining",
                    "detail": "server is draining; connection not accepted",
                }
            self._inflight += 1
            self._idle.clear()
        try:
            with trace.span("http/match"):
                return self._handle_match_inner(body_bytes, headers)
        finally:
            with self._lock:
                self._inflight -= 1
                if self._inflight == 0 and not self._accepting:
                    self._idle.set()

    def _handle_match_inner(self, body_bytes, headers):
        try:
            deadline_s, variant = self._parse_headers(headers)
            body = json.loads(body_bytes.decode("utf-8"))
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            payload = decode_payload(
                body.get("payload"), body.get("dtypes")
            )
        except ValueError as exc:
            return 400, {}, {"error": "bad_request", "detail": str(exc)}
        key = self._key_fn(payload)
        try:
            fut = self._server.submit(
                key=key, payload=payload, deadline_s=deadline_s,
                variant=variant, **self._submit_kwargs,
            )
        except AdmissionRejected as exc:
            return self._with_retry(*outcome_status(exc))
        except ValueError as exc:  # unknown/unservable quality pin
            return 400, {}, {"error": "bad_request", "detail": str(exc)}
        except RuntimeError as exc:  # submit on a closed server
            return 503, {}, {"error": "draining", "detail": str(exc)}
        wait = (
            self._request_timeout
            if deadline_s is None
            else deadline_s + 5.0
        )
        try:
            result = fut.result(timeout=wait)
        except FutureTimeoutError:
            # the engine contract says every accepted future resolves;
            # this is wedge insurance for the handler thread, not a path
            # traffic should ever take
            return 500, {}, {
                "error": "wait_timeout",
                "detail": f"no resolution within {wait:.1f}s",
            }
        except BaseException as exc:
            return self._with_retry(*outcome_status(exc))
        return 200, {}, {
            "result": {
                name: np.asarray(arr).tolist()
                for name, arr in result.items()
            }
        }

    def _parse_headers(self, headers):
        deadline_s = None
        raw = headers.get("X-Deadline-Ms")
        if raw is not None:
            try:
                ms = float(raw)
            except ValueError:
                raise ValueError(
                    f"X-Deadline-Ms must be a number, got {raw!r}"
                ) from None
            if ms <= 0:
                raise ValueError(f"X-Deadline-Ms must be > 0, got {ms}")
            deadline_s = ms / 1e3
        variant = headers.get("X-Quality")
        if variant is not None and variant not in VALID_QUALITY:
            raise ValueError(
                f"X-Quality must be one of {list(VALID_QUALITY)}, "
                f"got {variant!r}"
            )
        return deadline_s, variant

    @staticmethod
    def _with_retry(status, retry_after_s, body):
        extra = {}
        if retry_after_s is not None:
            # Retry-After is integer seconds on the wire; the precise
            # hint rides in X-Retry-After-Ms for clients that can use it
            extra["Retry-After"] = str(max(1, math.ceil(retry_after_s)))
            extra["X-Retry-After-Ms"] = f"{retry_after_s * 1e3:.3f}"
            body["retry_after_s"] = retry_after_s
        return status, extra, body

    def handle_healthz(self):
        self._m_requests.inc()
        if self.ready.is_set():
            return 200, {}, {"status": "ok"}
        return 503, {}, {"status": "unready"}

    def handle_metrics(self):
        self._m_requests.inc()
        return 200, {}, self.metrics.to_prometheus()

    # -- lifecycle -----------------------------------------------------

    def attach(self, httpd):
        self._httpd = httpd

    def mark_ready(self):
        """Call after warmup: /healthz starts answering 200."""
        self.ready.set()

    def begin_drain(self, timeout=None, settle_s=2.0):
        """The SIGTERM sequence, strictly ordered:

        1. /healthz flips unready (load balancer stops routing) and new
           /v1/match requests get 503 — the LISTENER stays open;
        2. the engine/fleet drains: every in-flight request resolves
           (result or typed shed) and its handler writes the response;
        3. handler threads settle (bounded by ``settle_s``);
        4. the listener closes (``httpd.shutdown``).

        Idempotent; safe from a signal-watcher thread."""
        self.ready.clear()
        with self._lock:
            self._accepting = False
            idle = self._inflight == 0
        if idle:
            self._idle.set()  # nclint: disable=unguarded-shared-state -- Event is internally synchronized; set() outside _lock is safe because _accepting is already False, so no handler can clear() it again
        self._server.drain(
            timeout if timeout is not None else self._drain_timeout
        )
        self._idle.wait(settle_s)  # nclint: disable=unguarded-shared-state -- Event.wait MUST run outside _lock: the handler threads it waits for need the lock to record completion
        if self._httpd is not None:
            self._httpd.shutdown()


class _Handler(BaseHTTPRequestHandler):
    """Socket I/O shim over the front door. HTTP/1.0 semantics: every
    response closes its connection, so a drained server never strands a
    keep-alive socket in a handler thread."""

    front = None  # bound by make_http_server's subclass

    def log_message(self, fmt, *args):
        del fmt, args  # stdout/stderr belong to the CLI's reports

    def _respond(self, status, extra_headers, body):
        if isinstance(body, str):  # /metrics Prometheus text
            data = body.encode("utf-8")
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        else:
            data = json.dumps(body).encode("utf-8")
            ctype = "application/json"
        self.front.count_response(status)
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        for name, value in extra_headers.items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path == "/healthz":
            self._respond(*self.front.handle_healthz())
        elif self.path == "/metrics":
            self._respond(*self.front.handle_metrics())
        else:
            self._respond(404, {}, {"error": "not_found", "path": self.path})

    def do_POST(self):
        if self.path != "/v1/match":
            self._respond(404, {}, {"error": "not_found", "path": self.path})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        body = self.rfile.read(length) if length > 0 else b""
        self._respond(*self.front.handle_match(body, self.headers))


def make_http_server(front, host="127.0.0.1", port=0):
    """Bind a `ThreadingHTTPServer` to the front door; ``port=0`` picks
    an ephemeral port (read it back from ``httpd.server_address``). The
    caller runs ``httpd.serve_forever()`` (or `start_http_server`)."""

    class BoundHandler(_Handler):
        pass

    BoundHandler.front = front
    httpd = ThreadingHTTPServer((host, port), BoundHandler)
    httpd.daemon_threads = True
    front.attach(httpd)
    return httpd


def start_http_server(server, *, host="127.0.0.1", port=0, registry=None,
                      key_fn=None, request_timeout_s=60.0, ready=True):
    """In-process convenience used by tests and the load drill: build a
    front door + listener and run it on a daemon thread. Returns
    ``(front, httpd, thread)``; stop with ``front.begin_drain()`` (or
    ``httpd.shutdown()``) then ``httpd.server_close()`` and join."""
    front = HttpFrontDoor(
        server, registry=registry, key_fn=key_fn,
        request_timeout_s=request_timeout_s,
    )
    httpd = make_http_server(front, host=host, port=port)
    thread = threading.Thread(
        target=httpd.serve_forever, name="http-serve", daemon=True
    )
    thread.start()
    if ready:
        front.mark_ready()
    return front, httpd, thread
