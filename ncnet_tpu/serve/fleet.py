"""`ServeFleet`: one warmed `ServeEngine` per device, routed, supervised.

PR 6-10 built a single-replica serving story: one engine, one device,
typed outcomes, admission control, stage supervision. One process on a
TPU host has 4-8 chips; this module scales the same contract across
them without weakening it:

* **Topology** — one `ServeEngine` per device, each PINNED to its chip
  (``device=`` placement at construction; two engines in one process
  never cross-dispatch) with its own `LatencyEstimator`, its own private
  metrics registry (shared counters would merge per-replica totals into
  one meaningless sum), and ``replica_tag`` telemetry so merged span
  logs stay attributable.
* **Routing** — `serve.router.FleetRouter`: best-ETA placement with
  bucket affinity; fleet-wide admission sheds ONLY when no replica can
  meet the budget.
* **Supervision** — a per-replica `Watchdog` over the engine's dispatch
  heartbeat declares a wedged replica dead (`kill_replica`); the fleet
  then REQUEUES the dead replica's queued-but-undispatched requests onto
  survivors, while in-flight dispatches fail with a typed `ReplicaDown`
  (``dispatched=True``) — never silently. A killed replica is
  QUARANTINED (removed from routing) until `rejoin` builds a fresh
  engine on the same device and re-warms it from the fleet's recorded
  bucket specs, so ``recompiles_after_warmup == 0`` holds per replica
  even across a kill.
* **Accounting** — every accepted future resolves exactly once, and the
  fleet counters satisfy the identity (drilled in tests/test_fleet.py)::

      submitted == completed + failed + shed + deadline_exceeded
                   + requeued_then_completed

Fault points: ``serve.replica.kill`` fires on every dispatch (arm
``crash`` to kill the routed-to replica mid-load — the chaos drill);
``serve.router.route`` fires on every routing decision.
"""

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError

import jax

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.resilience.faultinject import InjectedFault
from ncnet_tpu.serve.engine import ServeEngine
from ncnet_tpu.serve.resilience import (
    DeadlineExceeded,
    ReplicaDown,
    RequestShed,
    Watchdog,
)
from ncnet_tpu.serve.router import FleetRouter, ReplicaView
from ncnet_tpu.telemetry.registry import MetricsRegistry

_SENTINEL = object()


class _Request:
    """One fleet-level request: the caller's outer future plus what a
    (re-)dispatch needs. ``requeued`` flips when a dead replica's queued
    request moves to a survivor — its eventual success then counts as
    ``requeued_then_completed``, keeping the accounting identity exact."""

    __slots__ = ("future", "raw", "key", "payload", "deadline_abs",
                 "variant", "requeued")

    def __init__(self, raw, key, payload, deadline_abs, variant=None):
        self.future = Future()
        self.raw = raw
        self.key = key
        self.payload = payload
        self.deadline_abs = deadline_abs
        self.variant = variant
        self.requeued = False


class _Replica:
    __slots__ = ("engine", "watchdog", "device")

    def __init__(self, engine, watchdog, device):
        self.engine = engine
        self.watchdog = watchdog
        self.device = device


class ServeFleet:
    """A supervised fleet of device-pinned `ServeEngine` replicas behind
    one `submit`.

    ``replicas`` defaults to one per visible device; extra replicas wrap
    around the device list (useful on the CPU-proxy mesh). Engine tuning
    kwargs (``max_batch``, ``prep_fn``, ``batch_sizes``, ...) pass
    through to every replica; ``device``/``registry``/``estimator``/
    ``replica_tag`` are fleet-owned and cannot be overridden.

    ``replica_hang_timeout`` arms one `Watchdog` per replica over the
    engine's dispatch heartbeat; a hang kills + quarantines that replica
    and survivors absorb its queued work. Leave None when latencies are
    unbounded (e.g. first-compile-in-flight setups without warmup).
    """

    def __init__(self, apply_fn, params, *, replicas=None, devices=None,
                 router=None, replica_hang_timeout=None,
                 clock=time.monotonic, registry=None, **engine_kwargs):
        for owned in ("device", "registry", "estimator", "replica_tag",
                      "shard_mesh", "clock"):
            if owned in engine_kwargs:
                raise ValueError(
                    f"{owned}= is fleet-owned, not a pass-through "
                    "engine kwarg"
                )
        if devices is None:
            devices = jax.devices()
        if replicas is None:
            replicas = len(devices)
        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        self._apply_fn = apply_fn
        self._params = params
        self._engine_kwargs = dict(engine_kwargs)
        self._router = router if router is not None else FleetRouter()
        # the rungs every replica can serve — submit() validates a
        # variant pin here so a bad pin raises synchronously instead of
        # bouncing a typed failure off whichever replica routing picked
        self._variants = (
            ("refined",) if engine_kwargs.get("refined_apply_fn") else ()
        ) + ("standard",) + (
            ("degraded",) if engine_kwargs.get("degraded_apply_fn") else ()
        )
        self._hang_timeout = replica_hang_timeout
        self._clock = clock
        self._closed = False
        # lock-order: _close_lock -> _lock -> _pending_lock
        # (never actually nested today; the declared order binds any
        # future nesting, checked by the NCNET_LOCK_AUDIT=1 drills)
        self._close_lock = concurrency.make_lock("serve.fleet.close")

        # replica table + quarantine + warm specs
        self._lock = concurrency.make_lock("serve.fleet.replicas")
        self._replicas = {}  # rid -> _Replica (healthy, routable)
        self._quarantined = {}  # rid -> device (killed, awaiting rejoin)
        self._warm_specs = {}  # key -> per-sample spec (rejoin re-warms)

        self._pending = set()
        self._pending_lock = concurrency.make_lock("serve.fleet.pending")
        self._requeue_q = queue.Queue()

        self.metrics = registry if registry is not None else MetricsRegistry()
        m = self.metrics
        self._m_submitted = m.counter(
            "fleet_requests_submitted_total",
            "requests accepted by fleet submit()",
        )
        self._m_completed = m.counter(
            "fleet_requests_completed_total",
            "requests resolved with a result (never requeued)",
        )
        self._m_failed = m.counter(
            "fleet_requests_failed_total",
            "requests resolved with a non-shed exception",
        )
        self._m_shed = m.counter(
            "fleet_requests_shed_total",
            "requests shed (fleet admission, no live replica, or drain)",
        )
        self._m_deadline = m.counter(
            "fleet_deadline_exceeded_total",
            "requests whose deadline expired before completion",
        )
        self._m_requeued = m.counter(
            "fleet_requests_requeued_total",
            "queued requests moved off a dead replica onto a survivor",
        )
        self._m_requeued_completed = m.counter(
            "fleet_requeued_completed_total",
            "requeued requests that then resolved with a result",
        )
        self._m_replicas_down = m.counter(
            "fleet_replicas_down_total",
            "replica kills (chaos, watchdog hang, or explicit)",
        )
        self._m_rejoins = m.counter(
            "fleet_rejoins_total", "quarantined replicas re-warmed back in"
        )

        # fleet-owned threads (requeue + per-replica watchdogs): close()
        # joins the whole ledger bounded and report() names stragglers.
        # Append-only; list.append is atomic under the GIL.
        self._thread_ledger = []

        for i in range(replicas):
            self._start_replica(i, devices[i % len(devices)])

        self._requeue_thread = threading.Thread(
            target=self._requeue_loop, name="fleet-requeue", daemon=True
        )
        self._thread_ledger.append(self._requeue_thread)
        self._requeue_thread.start()

    # -- replica lifecycle ---------------------------------------------

    def _start_replica(self, rid, device):
        engine = ServeEngine(
            self._apply_fn, self._params,
            device=device, replica_tag=rid, clock=self._clock,
            **self._engine_kwargs,
        )
        watchdog = None
        if self._hang_timeout is not None:
            watchdog = Watchdog(
                self._hang_timeout,
                beat_fn=lambda e=engine: e.heartbeat,
                busy_fn=lambda e=engine: e.busy,
                on_hang=lambda r=rid: self.kill_replica(
                    r, reason="dispatch heartbeat stalled"
                ),
                clock=self._clock,
            ).start()
            self._thread_ledger.append(watchdog.thread)
        with self._lock:
            self._replicas[rid] = _Replica(engine, watchdog, device)
        return engine

    def kill_replica(self, rid, reason="killed"):
        """Declare replica ``rid`` dead: quarantine it (routing stops
        immediately), then fail/requeue its pending work via
        `ServeEngine.kill`. Safe from the watchdog thread and from a
        dispatch that hit an injected fault; idempotent."""
        with self._lock:
            rep = self._replicas.pop(rid, None)
            if rep is None:
                return  # already quarantined (or never existed)
            self._quarantined[rid] = rep.device
        self._m_replicas_down.inc()
        if rep.watchdog is not None:
            rep.watchdog.stop(join_timeout=0)
        # outside the lock: kill() resolves every pending inner future,
        # and each resolution runs _on_inner_done on THIS thread
        rep.engine.kill(reason=reason)

    def rejoin(self, rid):
        """Bring a quarantined replica back: a FRESH engine on the same
        device, re-warmed over every bucket spec the fleet has seen, so
        the rejoined replica serves with zero post-warmup compiles (the
        kill took the old engine's executables with it; the fleet's
        record of `warmup` specs is the durable copy). Returns the new
        engine's compiled-program count."""
        with self._lock:
            if rid in self._replicas:
                raise ValueError(f"replica {rid!r} is already healthy")
            device = self._quarantined.pop(rid, None)
        if device is None:
            raise KeyError(f"no quarantined replica {rid!r}")
        engine = self._start_replica(rid, device)
        with self._lock:
            warm = list(self._warm_specs.items())
        n = engine.warmup(warm)
        self._m_rejoins.inc()
        return n

    def warmup(self, bucket_specs):
        """AOT-warm every replica over ``bucket_specs`` (the
        `ServeEngine.warmup` contract, fleet-wide) and RECORD the specs:
        `rejoin` re-warms a replacement replica from this record."""
        specs = list(bucket_specs)
        with self._lock:
            for key, pspec in specs:
                self._warm_specs[key] = pspec
        total = 0
        for rep in self._healthy():
            total += rep.engine.warmup(specs)
        return total

    def _healthy(self):
        with self._lock:
            return list(self._replicas.values())

    def _engine(self, rid):
        with self._lock:
            rep = self._replicas.get(rid)
        return None if rep is None else rep.engine

    def _views(self):
        with self._lock:
            items = list(self._replicas.items())
        return [
            ReplicaView(
                rid,
                estimator=rep.engine.estimator,
                queued_fn=rep.engine.queued_work,
                keys_fn=rep.engine.pending_bucket_keys,
                max_wait=rep.engine.max_wait,
                max_batch=rep.engine.max_batch,
            )
            for rid, rep in items
        ]

    # -- submit / dispatch ---------------------------------------------

    def submit(self, raw=None, *, key=None, payload=None, deadline_s=None,
               variant=None):
        """Queue one request on the best replica; returns a Future.

        The fleet analog of `ServeEngine.submit`: same raw-vs-
        key/payload convention, same typed outcomes — plus `ReplicaDown`
        (``dispatched=True``) when the replica holding a dispatched
        batch dies. ``variant`` pins the quality rung fleet-wide (the
        pin survives a requeue onto a survivor). Routing failures
        resolve the RETURNED future (typed `RequestShed`), they do not
        raise, so callers have exactly one error channel."""
        if variant is not None and variant not in self._variants:
            raise ValueError(
                f"unknown or unservable quality variant {variant!r} "
                f"(this fleet serves {list(self._variants)})"
            )
        if self._closed:  # nclint: disable=unguarded-shared-state -- benign racy read of the monotonic close flag: close() settles every pending future after the flip, so a submit that races it still resolves
            raise RuntimeError("submit on a closed ServeFleet")
        deadline_abs = (
            None if deadline_s is None else self._clock() + deadline_s
        )
        record = _Request(raw, key, payload, deadline_abs, variant)
        with self._pending_lock:
            self._pending.add(record)
        self._m_submitted.inc()
        self._route_and_dispatch(record)
        return record.future

    def _remaining(self, record):
        if record.deadline_abs is None:
            return None
        return record.deadline_abs - self._clock()

    def _route_and_dispatch(self, record):
        remaining = self._remaining(record)
        if remaining is not None and remaining <= 0:
            self._settle_exc(record, DeadlineExceeded(
                "deadline expired before placement",
                stage="route", deadline_s=0.0,
            ))
            return
        try:
            view = self._router.route(
                self._views(), key=record.key, deadline_s=remaining
            )
        except RequestShed as exc:
            self._settle_exc(record, exc)
            return
        except Exception as exc:  # noqa: BLE001 — typed resolution boundary: the outer future must resolve
            self._settle_exc(record, exc)
            return
        self._dispatch_to(view.replica, record)

    def _dispatch_to(self, rid, record):
        try:
            faultinject.fire("serve.replica.kill")
        except InjectedFault:
            # the chaos drill: the routed-to replica dies under us —
            # kill + quarantine it, then place this request on a
            # survivor (or shed typed when none remain)
            self.kill_replica(rid, reason="injected kill")
            self._route_and_dispatch(record)
            return
        engine = self._engine(rid)
        if engine is None:
            self._route_and_dispatch(record)  # raced with a kill
            return
        try:
            inner = engine.submit(
                record.raw, key=record.key, payload=record.payload,
                deadline_s=self._remaining(record),
                variant=record.variant,
            )
        except RuntimeError as exc:
            # includes AdmissionRejected; a closed engine means either a
            # kill raced our routing decision (re-route onto a survivor)
            # or the fleet is draining — close() shuts engines down but
            # leaves them routable, so re-routing there would bounce
            # between closed replicas forever: shed typed instead
            if engine.closed:
                if self._closed:  # nclint: disable=unguarded-shared-state -- benign racy read of the monotonic close flag: the drain settles every pending future after the flip
                    self._settle_exc(record, RequestShed(
                        "fleet closed during placement", reason="drain",
                    ))
                else:
                    self._route_and_dispatch(record)
            else:
                self._settle_exc(record, exc)
            return
        inner.add_done_callback(
            lambda f, r=record: self._on_inner_done(r, f)
        )

    def _on_inner_done(self, record, inner):
        exc = inner.exception()
        if exc is None:
            self._settle_result(record, inner.result())
        elif (isinstance(exc, ReplicaDown) and not exc.dispatched
              and not self._closed):  # nclint: disable=unguarded-shared-state -- benign racy read of the monotonic close flag: a stale False only requeues once more and close() sheds the record typed
            # queued-but-undispatched on a dead replica: move it to a
            # survivor. Off-thread via the requeue queue — this callback
            # runs inside the killer's kill() loop, which must not block
            # on routing or a survivor's bounded submit queue.
            if not record.requeued:
                record.requeued = True
                self._m_requeued.inc()
            self._requeue_q.put(record)
        else:
            self._settle_exc(record, exc)

    def _requeue_loop(self):
        while True:
            record = self._requeue_q.get()
            if record is _SENTINEL:
                return
            try:
                self._route_and_dispatch(record)
            except Exception as exc:  # noqa: BLE001 — last-resort: the outer future must resolve
                self._settle_exc(record, exc)

    # -- exactly-once settlement ---------------------------------------

    def _settle_result(self, record, result):
        with self._pending_lock:
            self._pending.discard(record)
        try:
            record.future.set_result(result)
        except InvalidStateError:
            return  # lost a settle race; the winner already counted
        if record.requeued:
            self._m_requeued_completed.inc()
        else:
            self._m_completed.inc()

    def _settle_exc(self, record, exc):
        with self._pending_lock:
            self._pending.discard(record)
        try:
            record.future.set_exception(exc)
        except InvalidStateError:
            return
        if isinstance(exc, DeadlineExceeded):
            self._m_deadline.inc()
        elif isinstance(exc, RequestShed):
            self._m_shed.inc()
        else:
            self._m_failed.inc()

    # -- lifecycle / introspection -------------------------------------

    @property
    def closed(self):
        return self._closed  # nclint: disable=unguarded-shared-state -- benign racy read of a monotonic flag flipped once under _close_lock; observers need freshness, not atomicity

    def replica_ids(self):
        with self._lock:
            return sorted(self._replicas)

    def quarantined_ids(self):
        with self._lock:
            return sorted(self._quarantined)

    def engines(self):
        """``{rid: engine}`` of healthy replicas — the telemetry hook
        (`TelemetrySession.add_registry(engine.metrics,
        tags={"replica": rid})` per entry)."""
        with self._lock:
            return {rid: rep.engine for rid, rep in self._replicas.items()}

    def report(self):
        """Fleet counters + per-replica `ServeEngine.report` snapshots.
        The identity ``submitted == completed + failed + shed +
        deadline_exceeded + requeued_then_completed`` holds whenever no
        request is in flight (every accepted future has resolved)."""
        with self._lock:
            healthy = {
                rid: rep.engine for rid, rep in self._replicas.items()
            }
            quarantined = sorted(self._quarantined)
        return {
            "submitted": self._m_submitted.value,
            "completed": self._m_completed.value,
            "failed": self._m_failed.value,
            "shed": self._m_shed.value,
            "deadline_exceeded": self._m_deadline.value,
            "requeued": self._m_requeued.value,
            "requeued_then_completed": self._m_requeued_completed.value,
            "replicas_down": self._m_replicas_down.value,
            "rejoins": self._m_rejoins.value,
            "healthy": sorted(healthy),
            "quarantined": quarantined,
            "last_route": self._router.last_decision,
            # ledger threads still alive after close() — empty on a live
            # fleet (workers are SUPPOSED to be running then)
            "straggler_threads": (
                sorted(
                    t.name for t in self._thread_ledger if t.is_alive()
                )
                if self._closed else []  # nclint: disable=unguarded-shared-state -- benign racy read of the monotonic close flag gating a diagnostic field
            ),
            "per_replica": {
                rid: eng.report() for rid, eng in healthy.items()
            },
        }

    def close(self, timeout=None):
        """Drain every replica; EVERY accepted future resolves before
        this returns (engine drains resolve dispatched work; anything
        still unresolved after — e.g. stranded on the requeue path —
        fails with a typed ``RequestShed(reason="drain")``).
        Idempotent."""
        with self._close_lock:
            already = self._closed
            self._closed = True
        if already:
            return
        self._requeue_q.put(_SENTINEL)
        self._requeue_thread.join(timeout=timeout)
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.watchdog is not None:
                # bounded join: close() never runs ON a watchdog thread
                # (kill_replica, which can, keeps join_timeout=0)
                rep.watchdog.stop(join_timeout=0.5)
        for rep in reps:
            rep.engine.shutdown(timeout=timeout)
        # thread-ledger sweep: whatever the joins above missed (e.g. a
        # quarantined replica's stopped-but-unjoined watchdog) gets a
        # bounded join here; survivors show in report()'s
        # straggler_threads instead of leaking silently
        ledger_deadline = self._clock() + 0.5
        for t in self._thread_ledger:
            if t is threading.current_thread():
                continue
            budget = ledger_deadline - self._clock()
            if budget > 0 and t.is_alive():
                t.join(budget)
        with self._pending_lock:
            leftovers = list(self._pending)
            self._pending.clear()
        for record in leftovers:
            self._settle_exc(record, RequestShed(
                "fleet closed before placement", reason="drain",
            ))

    def drain(self, timeout=None):
        """Alias for `close` — the name `drain_on_preemption` calls (the
        SIGTERM watcher works unchanged over a fleet)."""
        self.close(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
