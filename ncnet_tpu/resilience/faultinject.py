"""Named fault-injection points for crash/delay/corrupt testing.

Recovery code that has never seen a failure is decoration. This registry
gives the checkpoint writer, the training loop, and the data workers NAMED
points where tests (or an operator, via environment variable) can inject
the failures the recovery paths claim to survive:

  ============================  =================================================
  point                         site
  ============================  =================================================
  ``checkpoint.write``          mid-write of the checkpoint temp file (half the
                                payload is on disk, the rename has not happened)
  ``checkpoint.rename``         temp file complete + fsynced, rename pending
  ``checkpoint.bytes``          the serialized payload itself (corrupt target)
  ``step.boundary``             after each optimizer step in the training loop
  ``data.batch``                batch construction inside a loader worker
  ``dckpt.shard_write``         sharded layout: mid-write AND rename-pending of
                                each per-host shard chunk (two hits per chunk)
  ``dckpt.manifest``            sharded layout: meta + per-host manifest writes
  ``dckpt.barrier``             sharded layout: entering the cross-process
                                commit barrier (shards + manifest on disk)
  ``dckpt.commit``              sharded layout: pod-wide verification passed,
                                the atomic commit-manifest rename still pending
  ``serve.request``             per-request host prep in the serving engine
                                (`ncnet_tpu.serve`): fires on a worker thread
                                before decode/resize, so delay/crash exercises
                                slow or failed requests without stalling others
  ``serve.worker.crash``        serving prep worker, OUTSIDE the per-request
                                handler: an injected crash is a STAGE crash —
                                the supervisor must fail only the in-flight
                                request (typed `StageFailure`) and restart
  ``serve.dispatch.hang``       serving dispatch, after the in-flight batch is
                                registered and before the device call:
                                ``delay:<s>`` wedges the thread (the watchdog
                                hang drill), ``crash`` is a dispatch-stage crash
  ``serve.readout.delay``       serving readout, after a batch is popped:
                                ``delay:<s>`` models a slow D2H/convert (the
                                readout-deadline drill), ``crash`` a readout-
                                stage crash
  ``serve.replica.kill``        fleet dispatch (`ncnet_tpu.serve.fleet`): fires
                                as a request is handed to its routed-to replica;
                                ``crash`` kills THAT replica mid-load — the
                                chaos drill: queued work must requeue onto
                                survivors, in-flight batches fail typed
                                `ReplicaDown`, survivors never recompile
  ``serve.router.route``        every fleet routing decision
                                (`ncnet_tpu.serve.router`): ``delay:<s>``
                                models a slow placement path, ``crash`` fails
                                the route (the outer future resolves typed —
                                never raises into the caller)
  ``telemetry.write``           telemetry exporters (`ncnet_tpu.telemetry`):
                                before each JSONL event-log flush, and mid-write
                                of the ``.prom`` snapshot temp file — a crash
                                must leave at most a torn trailing JSONL line
  ``ackpt.handoff``             async checkpointing (`resilience.async_ckpt`):
                                on the STEP thread, before the snapshot is
                                enqueued to the writer — a kill here loses only
                                the not-yet-handed-off save
  ``ackpt.d2h``                 async writer thread, before the host gather /
                                prepare stage (the save is torn: walk-back
                                must skip it)
  ``ackpt.write``               async writer thread, before the durable write
                                (still torn; the durable layer's own points
                                nest inside the write that follows)
  ``ackpt.commit``              async writer thread, after the durable write
                                returned — the save IS committed;
                                ``latest_valid`` must land on it
  ``cluster.heartbeat``         cluster supervision (`resilience.cluster`): on
                                the heartbeat writer thread, before each beat —
                                ``kill`` is a dying host whose peers must raise
                                typed `PeerDown` within the staleness budget;
                                ``delay:<s>`` models shared-filesystem stalls
  ``cluster.stopflag``          before the durable stop flag publishes — a kill
                                here loses the drain request (peers keep
                                training; the signalled host's local exit path
                                still applies)
  ``cluster.propose``           save-cursor consensus, before this host's
                                proposal write — a kill leaves the leader
                                waiting on the round: peers must get typed
                                `PeerDown`, not a barrier hang
  ``cluster.ack``               save-cursor consensus, LEADER only, after all
                                proposals arrived and before the decision
                                write — a kill mid-decision leaves followers
                                waiting: typed `PeerDown` on every survivor
  ============================  =================================================

Actions: ``crash`` raises :class:`InjectedFault` (unwinds normally, finally
blocks run), ``kill`` calls ``os._exit(137)`` (a true preemption: no
cleanup, no atexit — what SIGKILL does to a TPU worker), ``delay:<sec>``
sleeps, ``corrupt`` flips bytes of the payload at sites that pass one.

Activation mirrors `analysis.sanitizer`: exact no-op when disabled (one
falsy-dict check per ``fire``), enabled either programmatically
(`inject` / `configure`, for in-process tests) or via the environment
variable consumed lazily on first use (for subprocess kill tests)::

    NCNET_FAULTS="checkpoint.write=kill@1,step.boundary=crash@3"

``@n`` arms the fault on the n-th hit of that point only (1-based);
without it the fault triggers on every hit.
"""

import os
import threading
import time

ENV_VAR = "NCNET_FAULTS"

ACTIONS = ("crash", "kill", "delay", "corrupt")


class InjectedFault(RuntimeError):
    """Raised by a ``crash`` action; never raised by production code paths."""


class _Fault:
    __slots__ = ("action", "arg", "at", "hits")

    def __init__(self, action, arg=None, at=None):
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r} (choose from {ACTIONS})"
            )
        self.action = action
        self.arg = arg
        self.at = at  # 1-based hit index to trigger on; None = every hit
        self.hits = 0


_lock = threading.Lock()
_faults = {}  # point name -> _Fault
_env_loaded = False


def clear():
    """Drop all injected faults and forget the env var was ever read."""
    global _env_loaded
    with _lock:
        _faults.clear()
        _env_loaded = True  # an explicit clear() beats a stale env var


def inject(point, action, arg=None, at=None):
    """Arm ``point`` with ``action`` (see module docstring); test API."""
    with _lock:
        _faults[point] = _Fault(action, arg, at)


def configure(spec):
    """Parse a ``point=action[:arg][@n],...`` spec (the env-var grammar)."""
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        point, _, rhs = item.partition("=")
        if not rhs:
            raise ValueError(
                f"malformed fault spec {item!r}: expected point=action[:arg][@n]"
            )
        rhs, _, at = rhs.partition("@")
        action, _, arg = rhs.partition(":")
        inject(
            point.strip(),
            action.strip(),
            arg=float(arg) if arg else None,
            at=int(at) if at else None,
        )


def _ensure_env_loaded():
    global _env_loaded
    with _lock:
        if _env_loaded:
            return
        _env_loaded = True
    spec = os.environ.get(ENV_VAR, "")
    if spec:
        configure(spec)


def is_enabled():
    _ensure_env_loaded()
    return bool(_faults)


def _armed(point):
    """Count a hit; return the fault iff it should trigger now."""
    _ensure_env_loaded()
    if not _faults:  # the disabled fast path: one dict truthiness check
        return None
    with _lock:
        fault = _faults.get(point)
        if fault is None:
            return None
        fault.hits += 1
        if fault.at is not None and fault.hits != fault.at:
            return None
        return fault


def fire(point, data=None):
    """Hit a named fault point; returns ``data`` (possibly corrupted).

    Exact no-op when no fault is armed: returns ``data`` unchanged after a
    single falsy-dict check, so production paths pay nothing.
    """
    fault = _armed(point)
    if fault is None:
        return data
    if fault.action == "crash":
        raise InjectedFault(f"injected crash at fault point {point!r}")
    if fault.action == "kill":
        print(f"[faultinject] hard kill at {point!r}", flush=True)
        os._exit(137)  # preemption semantics: no finally, no atexit
    if fault.action == "delay":
        time.sleep(fault.arg if fault.arg is not None else 0.1)
        return data
    # corrupt: only meaningful at sites that pass the payload through
    if data is None:
        return None
    blob = bytearray(data)
    if blob:
        # flip a spread of bits so truncation-style AND bitrot-style
        # detectors both see damage
        for off in range(0, len(blob), max(1, len(blob) // 8)):
            blob[off] ^= 0xFF
    return bytes(blob)
