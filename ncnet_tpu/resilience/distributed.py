"""Per-host sharded checkpoint saves (the orbax-style directory layout).

PR 2's durability (`resilience.durable`) is single-writer: the training
loop funnels the whole replicated state through ``jax.device_get`` on
process 0, so at pod scale one host serializes all state over DCN and
becomes the sole preemption window. Here every process durably writes only
its OWN addressable shards, and a save commits atomically for the whole
pod or not at all:

  <dir>/
    step_000000012/                       one directory per save
      arrays/
        leaf00000.s0.npy  (+ .sha256)     one .npy per shard chunk, written
        leaf00003.s0_64.npy (+ .sha256)   by exactly one process, durable
      meta.msgpack        (+ .sha256)     tiny replicated metadata (proc 0)
      manifest_proc00000.json (+ .sha256) per-host shard listing + digests
      manifest_proc00001.json (+ .sha256)
      MANIFEST.json       (+ .sha256)     COMMIT MARKER (proc 0, written
    step_000000024/...                    last, atomically renamed)
    best.json             (+ .sha256)     pointer to a committed save

Two-phase commit: (1) each process writes its shard chunks and then a
per-host manifest listing them with sha256 digests and partition specs —
all through the `durable` temp+fsync+rename discipline; (2) process 0
waits at a cross-process barrier until every host's manifest exists and
verifies, checks that the union of manifests tiles every leaf exactly,
and only then atomically publishes ``MANIFEST.json``. A save without a
verifying commit manifest does not exist as far as recovery is concerned:
`latest_valid_save` walks back past it (and past a committed save with a
missing/corrupt shard) to the newest save where EVERY manifest entry
verifies.

Shard ownership: a leaf sharded across devices is written by whichever
process holds each ``replica_id == 0`` shard (disjoint tiles, no
duplicate bytes); a fully-replicated leaf is assigned round-robin by leaf
index, so per-host I/O stays O(state / n_hosts) for the replicated
data-parallel states this repo trains today.

Restore re-shards: chunks carry their global offsets, so
`SaveReader.read(i, sharding=...)` assembles exactly the slice each local
device needs and builds the global array with
``jax.make_array_from_single_device_arrays`` — the saving and restoring
topologies (process count, mesh shape, chunk tiling) are independent.

Fault points (`resilience.faultinject`), covering every phase of the
two-phase commit: ``dckpt.shard_write`` (mid-write and rename-pending of
each shard chunk), ``dckpt.manifest`` (meta + per-host manifest writes),
``dckpt.barrier`` (entering the cross-process barrier), ``dckpt.commit``
(verification done, the commit rename still pending).

Threading contract: with async checkpointing (`resilience.async_ckpt`)
the whole save — chunk gathers, shard writes, and the commit barrier —
runs on each process's dedicated writer thread; the barrier's
file-polling wait tolerates that (no signal/main-thread dependency).
What it does NOT tolerate is hosts disagreeing about WHICH saves happen:
the training loop therefore disables coalescing for multi-process
sharded runs (deterministic backpressure instead), so every process
submits the same save sequence to its writer.

Unlike its siblings this module imports jax/numpy (it must introspect
shardings), so `resilience/__init__` does NOT import it eagerly — the
loader workers' import-light contract holds; import it explicitly.
"""

import hashlib
import io
import json
import os
import re
import shutil
import time

import numpy as np

import jax

from ncnet_tpu.resilience import durable, faultinject

STEP_DIR_RE = re.compile(r"^step_(\d{9})$")
COMMIT_NAME = "MANIFEST.json"
META_NAME = "meta.msgpack"
BEST_NAME = "best.json"
ARRAYS_SUBDIR = "arrays"
FORMAT = "dckpt-v1"


class ShardedSaveError(RuntimeError):
    """A distributed save could not complete (barrier timeout, a host's
    manifest failing verification, or incomplete leaf coverage)."""


def step_dir_name(step):
    return f"step_{int(step):09d}"


def manifest_name(process_index):
    return f"manifest_proc{int(process_index):05d}.json"


def _proc_info(process_index, process_count):
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    return int(process_index), int(process_count)


# --- shard planning ----------------------------------------------------------


def _leaf_numpy(leaf):
    """Host copy of a replicated/host leaf WITHOUT a global device_get:
    a fully-replicated jax.Array carries the whole value in each local
    shard, so the transfer is local-device -> host only."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards:
        return np.asarray(shards[0].data)
    return np.asarray(leaf)


def _shard_start_shape(shard, global_shape):
    """Normalize a shard's index (tuple of slices) to (start, shape)."""
    start, shape = [], []
    for sl, dim in zip(shard.index, global_shape):
        lo, hi, _ = sl.indices(dim)
        start.append(int(lo))
        shape.append(int(hi - lo))
    return tuple(start), tuple(shape)


def _spec_str(leaf):
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        return None
    spec = getattr(sharding, "spec", None)
    return str(spec) if spec is not None else type(sharding).__name__


def planned_chunks(leaf, leaf_index, process_index, process_count):
    """The chunks of ``leaf`` THIS process must write.

    Returns a list of ``(start, data)`` where ``start`` is the chunk's
    offset in the global array and ``data`` a host numpy array. Sharded
    leaves: the local ``replica_id == 0`` shards (disjoint tiles, each
    written by exactly one process across the pod). Replicated / host
    leaves: one full-array chunk owned by process ``leaf_index % n``.
    """
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and not sharding.is_fully_replicated:
        out = []
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            start, _ = _shard_start_shape(shard, leaf.shape)
            out.append((start, np.asarray(shard.data)))
        return out
    if leaf_index % process_count != process_index:
        return []
    arr = _leaf_numpy(leaf)
    return [((0,) * arr.ndim, arr)]


def _chunk_relpath(leaf_index, start):
    tag = "_".join(str(s) for s in start)
    return f"{ARRAYS_SUBDIR}/leaf{leaf_index:05d}.s{tag}.npy"


def _npy_bytes(arr):
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return buf.getvalue()


# --- save (collective) -------------------------------------------------------


def _wait_for(predicate, timeout, poll, what, health_check=None):
    """Poll ``predicate`` until true. ``health_check`` (the cluster
    supervisor's ``check``) runs every iteration so a dead peer raises a
    typed ``PeerDown`` within its staleness budget instead of burning
    the whole barrier timeout on a host that will never arrive."""
    deadline = time.monotonic() + timeout
    while True:
        if predicate():
            return
        if health_check is not None:
            health_check(what)
        if time.monotonic() >= deadline:
            raise ShardedSaveError(
                f"distributed checkpoint barrier timed out after {timeout}s "
                f"waiting for {what}"
            )
        time.sleep(poll)


def _verified_file(path):
    """True iff ``path`` exists and its sidecar digest verifies (a missing
    sidecar means the rename pair is still incomplete — not yet valid)."""
    return os.path.exists(path) and durable.verify_digest(path) is True


def save_sharded(
    base_dir,
    step,
    leaves,
    meta_blob,
    keep=3,
    is_best=False,
    process_index=None,
    process_count=None,
    barrier_timeout=600.0,
    poll_interval=0.05,
    health_check=None,
):
    """Collectively write one ``step_<N>/`` save; EVERY process calls this
    with the same ``leaves`` structure (list of ``(key, value)`` in a
    canonical order) and the same tiny ``meta_blob``.

    Each process durably writes only its own chunks (see `planned_chunks`)
    plus its per-host manifest; process 0 additionally writes the meta
    file and — after the barrier confirms every host's manifest verifies
    and the chunks tile every leaf — the atomically-renamed commit
    manifest. Returns the committed step directory (all processes return
    only after the commit marker is durably visible).

    ``health_check`` (e.g. ``ClusterSupervisor.check``) is called on
    every barrier poll: a peer that died before writing its manifest (or
    the commit marker) surfaces as a typed ``PeerDown`` within the
    cluster's staleness budget instead of a ``barrier_timeout`` hang.
    """
    p, n = _proc_info(process_index, process_count)
    step_dir = os.path.join(os.path.abspath(base_dir), step_dir_name(step))
    os.makedirs(os.path.join(step_dir, ARRAYS_SUBDIR), exist_ok=True)

    entries = []
    for i, (key, leaf) in enumerate(leaves):
        for start, data in planned_chunks(leaf, i, p, n):
            rel = _chunk_relpath(i, start)
            blob = _npy_bytes(data)
            durable.durable_write_bytes(
                os.path.join(step_dir, rel),
                blob,
                write_point="dckpt.shard_write",
                rename_point="dckpt.shard_write",
                bytes_point=None,
            )
            entries.append({
                "leaf": i,
                "key": str(key),
                "file": rel,
                "start": list(start),
                "shape": list(data.shape),
                "global_shape": list(getattr(leaf, "shape", data.shape)),
                "dtype": str(data.dtype),
                "spec": _spec_str(leaf),
                "sha256": hashlib.sha256(blob).hexdigest(),
            })

    # each host verifies ITS OWN chunks before advertising them: a torn or
    # bit-flipped local write is caught here, not at pod-wide commit time
    for e in entries:
        path = os.path.join(step_dir, e["file"])
        if durable.verify_digest(path) is not True:
            raise ShardedSaveError(
                f"shard {path} failed post-write verification"
            )

    if p == 0:
        durable.durable_write_bytes(
            os.path.join(step_dir, META_NAME),
            meta_blob,
            write_point="dckpt.manifest",
            rename_point="dckpt.manifest",
            bytes_point=None,
        )
    man_blob = json.dumps(
        {"format": FORMAT, "process": p, "process_count": n,
         "step": int(step), "entries": entries},
        sort_keys=True,
    ).encode("utf-8")
    durable.durable_write_bytes(
        os.path.join(step_dir, manifest_name(p)),
        man_blob,
        write_point="dckpt.manifest",
        rename_point="dckpt.manifest",
        bytes_point=None,
    )

    faultinject.fire("dckpt.barrier")
    commit_path = os.path.join(step_dir, COMMIT_NAME)
    if p != 0:
        # the commit marker IS the barrier release for non-zero processes
        _wait_for(
            lambda: _verified_file(commit_path),
            barrier_timeout, poll_interval,
            f"the commit manifest {commit_path}",
            health_check=health_check,
        )
        return step_dir

    man_paths = [os.path.join(step_dir, manifest_name(q)) for q in range(n)]
    _wait_for(
        lambda: all(_verified_file(mp) for mp in man_paths),
        barrier_timeout, poll_interval,
        f"{n} per-host manifests in {step_dir}",
        health_check=health_check,
    )
    manifests = []
    for mp in man_paths:
        with open(mp, "rb") as f:
            manifests.append(json.loads(f.read().decode("utf-8")))
    _check_coverage(leaves, manifests, step_dir)

    commit = {
        "format": FORMAT,
        "step": int(step),
        "process_count": n,
        "meta": {
            "file": META_NAME,
            "sha256": _sidecar_digest(os.path.join(step_dir, META_NAME)),
        },
        "manifests": [
            {"file": manifest_name(q),
             "sha256": _sidecar_digest(man_paths[q])}
            for q in range(n)
        ],
        "leaves": [
            {"leaf": i, "key": str(key),
             "global_shape": list(getattr(leaf, "shape", ())),
             "dtype": str(getattr(leaf, "dtype", "")),
             "spec": _spec_str(leaf)}
            for i, (key, leaf) in enumerate(leaves)
        ],
    }
    faultinject.fire("dckpt.commit")
    durable.durable_write_bytes(
        commit_path,
        json.dumps(commit, sort_keys=True).encode("utf-8"),
        write_point="dckpt.commit",
        rename_point="dckpt.commit",
        bytes_point=None,
    )

    if is_best:
        write_best_pointer(base_dir, step)
    prune_saves(base_dir, keep=keep)
    return step_dir


def _sidecar_digest(path):
    with open(durable.digest_path(path), "rb") as f:
        return f.read().strip().decode("ascii")


def _check_coverage(leaves, manifests, step_dir):
    """The union of per-host manifests must tile every leaf exactly:
    a host that silently wrote nothing (or a stale manifest from a
    different topology) must fail the commit, not the eventual restore."""
    written = {}
    for man in manifests:
        for e in man["entries"]:
            written.setdefault(e["leaf"], 0)
            written[e["leaf"]] += int(np.prod(e["shape"], dtype=np.int64))
    for i, (key, leaf) in enumerate(leaves):
        want = int(np.prod(getattr(leaf, "shape", ()), dtype=np.int64))
        got = written.get(i, 0)
        if got != want:
            raise ShardedSaveError(
                f"leaf {i} ({key}) coverage mismatch in {step_dir}: "
                f"manifests list {got} elements, global shape needs {want}"
            )


# --- best pointer + retention ------------------------------------------------


def write_best_pointer(base_dir, step):
    """O(1) ``best`` in the sharded layout: a durable pointer naming an
    already-committed save — no re-serialization of any state."""
    durable.durable_write_bytes(
        os.path.join(base_dir, BEST_NAME),
        json.dumps(
            {"step": int(step), "step_dir": step_dir_name(step)}
        ).encode("utf-8"),
        write_point="dckpt.manifest",
        rename_point="dckpt.manifest",
        bytes_point=None,
    )


def read_best_pointer(base_dir):
    """The step directory the best pointer names, or None."""
    path = os.path.join(base_dir, BEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        blob = durable.read_verified_bytes(path)
        return os.path.join(base_dir, json.loads(blob)["step_dir"])
    except Exception as e:  # a torn pointer must not break loading
        print(f"[resilience] ignoring invalid best pointer {path}: {e!r}",
              flush=True)
        return None


def save_candidates(base_dir):
    """All ``step_<N>/`` directories, newest-first (committed or not —
    validity is the walk's job, not the listing's)."""
    try:
        names = os.listdir(base_dir)
    except FileNotFoundError:
        return []
    steps = []
    for name in names:
        m = STEP_DIR_RE.match(name)
        if m and os.path.isdir(os.path.join(base_dir, name)):
            steps.append(int(m.group(1)))
    return [
        os.path.join(base_dir, step_dir_name(s))
        for s in sorted(steps, reverse=True)
    ]


def is_committed(step_dir):
    return _verified_file(os.path.join(step_dir, COMMIT_NAME))


def prune_saves(base_dir, keep=3):
    """Keep the newest ``keep`` committed saves (plus the best pointer's
    target) and drop older ones AND stale uncommitted directories from
    killed earlier saves. ``keep <= 0`` disables pruning entirely."""
    if keep <= 0:
        return
    committed = [d for d in save_candidates(base_dir) if is_committed(d)]
    if not committed:
        return
    protect = {os.path.abspath(committed[q]) for q in range(min(keep, len(committed)))}
    best = read_best_pointer(base_dir)
    if best:
        protect.add(os.path.abspath(best))
    newest = committed[0]
    for d in save_candidates(base_dir):
        if os.path.abspath(d) in protect:
            continue
        if not is_committed(d) and d >= newest:
            continue  # an in-flight newer save from a concurrent writer
        shutil.rmtree(d, ignore_errors=True)


# --- load --------------------------------------------------------------------


class SaveReader:
    """One committed save, fully digest-verified at construction.

    Construction raises (`durable.IntegrityError` / `FileNotFoundError` /
    `ShardedSaveError`) unless the commit manifest verifies, every
    per-host manifest matches its recorded digest, every listed shard
    file's bytes match the manifest's digest, and the chunks tile every
    leaf — the directory-save extension of "a save is valid only when
    every manifest entry verifies".
    """

    def __init__(self, step_dir):
        self.step_dir = os.path.abspath(step_dir)
        commit_path = os.path.join(self.step_dir, COMMIT_NAME)
        if durable.verify_digest(commit_path) is not True:
            raise durable.IntegrityError(
                f"{self.step_dir}: no verifying commit manifest "
                "(uncommitted or torn save)"
            )
        with open(commit_path, "rb") as f:
            self.commit = json.loads(f.read().decode("utf-8"))
        self.step = int(self.commit["step"])
        self._leaves = self.commit["leaves"]
        self._chunks = {i: [] for i in range(len(self._leaves))}
        for man_ref in self.commit["manifests"]:
            mp = os.path.join(self.step_dir, man_ref["file"])
            blob = self._read_checked(mp, man_ref["sha256"])
            man = json.loads(blob.decode("utf-8"))
            for e in man["entries"]:
                self._chunks[e["leaf"]].append(e)
        for i, info in enumerate(self._leaves):
            want = int(np.prod(info["global_shape"], dtype=np.int64))
            got = sum(
                int(np.prod(e["shape"], dtype=np.int64))
                for e in self._chunks[i]
            )
            if got != want:
                raise ShardedSaveError(
                    f"{self.step_dir}: leaf {i} ({info['key']}) chunks "
                    f"cover {got} of {want} elements"
                )
            for e in self._chunks[i]:
                path = os.path.join(self.step_dir, e["file"])
                if not os.path.exists(path):
                    raise FileNotFoundError(
                        f"{self.step_dir}: committed manifest lists missing "
                        f"shard {e['file']}"
                    )
        self._verify_all_chunks()

    def _read_checked(self, path, want_sha):
        with open(path, "rb") as f:
            blob = f.read()
        got = hashlib.sha256(blob).hexdigest()
        if got != want_sha:
            raise durable.IntegrityError(
                f"{path}: bytes do not match the manifest digest"
            )
        return blob

    def _verify_all_chunks(self):
        for i in self._chunks:
            for e in self._chunks[i]:
                self._read_checked(
                    os.path.join(self.step_dir, e["file"]), e["sha256"]
                )

    @property
    def n_leaves(self):
        return len(self._leaves)

    def leaf_info(self, i):
        return self._leaves[i]

    def meta_bytes(self):
        blob = durable.read_verified_bytes(
            os.path.join(self.step_dir, META_NAME)
        )
        want = self.commit["meta"]["sha256"]
        if hashlib.sha256(blob).hexdigest() != want:
            raise durable.IntegrityError(
                f"{self.step_dir}/{META_NAME} does not match the commit "
                "manifest digest"
            )
        return blob

    def _chunk_array(self, entry):
        blob = self._read_checked(
            os.path.join(self.step_dir, entry["file"]), entry["sha256"]
        )
        return np.load(io.BytesIO(blob), allow_pickle=False)

    def _assemble_region(self, i, start, shape, dtype):
        """Fill the region ``[start, start+shape)`` of leaf ``i`` from the
        chunks overlapping it — only those files are read."""
        out = np.empty(tuple(shape), dtype=dtype)
        filled = 0
        for e in self._chunks[i]:
            c_start, c_shape = e["start"], e["shape"]
            lo = [max(s, cs) for s, cs in zip(start, c_start)]
            hi = [
                min(s + d, cs + cd)
                for s, d, cs, cd in zip(start, shape, c_start, c_shape)
            ]
            if any(h <= l for l, h in zip(lo, hi)):
                continue
            chunk = self._chunk_array(e)
            src = tuple(
                slice(l - cs, h - cs) for l, h, cs in zip(lo, hi, c_start)
            )
            dst = tuple(
                slice(l - s, h - s) for l, h, s in zip(lo, hi, start)
            )
            if out.ndim == 0:  # scalar leaves: out[()] = ... deprecates
                out[...] = chunk
            else:
                out[dst] = chunk[src]
            filled += int(np.prod([h - l for l, h in zip(lo, hi)],
                                  dtype=np.int64))
        if filled != int(np.prod(shape, dtype=np.int64)):
            raise ShardedSaveError(
                f"{self.step_dir}: leaf {i} region {start}+{shape} not "
                "fully covered by saved chunks"
            )
        return out

    def read(self, i, sharding=None):
        """Leaf ``i`` as host numpy (``sharding=None``) or as a global
        ``jax.Array`` under ``sharding`` — each local device gets exactly
        the slice it needs, assembled from whatever chunk tiling the SAVING
        topology produced, then stitched with
        ``jax.make_array_from_single_device_arrays`` (the re-shard path
        for restores onto a different process count or mesh shape)."""
        info = self._leaves[i]
        gshape = tuple(info["global_shape"])
        dtype = np.dtype(info["dtype"])
        if sharding is None:
            return self._assemble_region(i, (0,) * len(gshape), gshape, dtype)
        singles = []
        for dev, idx in sharding.addressable_devices_indices_map(
            gshape
        ).items():
            start, shape = [], []
            for sl, dim in zip(idx, gshape):
                lo, hi, _ = sl.indices(dim)
                start.append(int(lo))
                shape.append(int(hi - lo))
            part = self._assemble_region(i, start, shape, dtype)
            singles.append(jax.device_put(part, dev))
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, singles
        )


def latest_valid_save(base_dir, loader):
    """Directory-save analog of `durable.latest_valid`: walk ``step_<N>/``
    saves newest-first, returning ``(loader(reader), step_dir)`` for the
    first whose EVERY manifest entry verifies AND that parses. Uncommitted
    directories (a writer killed before the commit rename) are never
    selected; a committed save with a missing or corrupt shard costs one
    fallback, not the run."""
    errors = []
    for step_dir in save_candidates(base_dir):
        try:
            return loader(SaveReader(step_dir)), step_dir
        except Exception as e:  # a torn/corrupt save must not end the walk
            errors.append(f"{step_dir}: {e!r}")
            print(
                f"[resilience] skipping invalid save {step_dir}: {e!r}",
                flush=True,
            )
    detail = "; ".join(errors) if errors else "no step_* directories exist"
    raise FileNotFoundError(f"no valid sharded save in {base_dir} ({detail})")
