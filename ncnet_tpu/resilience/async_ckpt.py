"""Async overlap-hidden checkpointing: the durable save off the step loop.

Every durable snapshot used to stall training for the full save: the
``jax.device_get`` funnel, msgpack serialization, and the temp + fsync +
rename + digest discipline all ran inline on the step thread
(PERF.md round 9 measured the sharded layout's chunk fsyncs at 1.4-2.9x
the legacy blob on one host). `AsyncCheckpointer` exploits JAX's
functional updates: the step loop hands a dedicated writer thread the
*immutable* params/opt_state tree references plus the already-host-side
cursor metadata — an O(1) handoff, no tree copy, no device sync — and
keeps training while the writer performs D2H, serialization, and the
UNCHANGED durable two-phase-commit write (legacy and sharded layouts;
per-host shard writes stay per-host, the ``MANIFEST.json`` commit rename
stays atomic).

Donation caveat (the one honest wrinkle in "no copy"): the jitted train
step donates its carried state, so the buffers behind a snapshot's refs
are invalidated when the NEXT step dispatches. Overlapped submissions
therefore snapshot through `device_snapshot` first — a device-side copy
DISPATCH (enqueued on the device stream, no host sync, no D2H); the
step thread never waits for it. Blocking submissions (sync mode,
epoch-end, the preemption final save) hand raw refs: the step thread
waits for the commit, so nothing donates underneath the writer.

Policy — at most one save in flight, one queued:

  * a *blocking* submit (``wait=True``: epoch-end / ``is_best`` / the
    preemption final save — and every submit in sync mode) waits for the
    in-flight save and then for its own commit;
  * an *overlapped* submit (mid-epoch cursor saves in async mode) never
    blocks: if the queued slot is occupied, the older queued-not-started
    snapshot is COALESCED into the newer one (newest state wins; counted
    in ``ckpt_coalesced_total``);
  * with ``coalesce=False`` (multi-process sharded runs WITHOUT a
    cluster supervisor, where a collective save skipped on one host
    would wedge the others at the commit barrier) nothing is ever
    dropped: an overlapped submit backpressures — it waits for the
    queued slot, so every process writes the same save sequence in the
    same order;
  * with a ``coalesce_arbiter`` (multi-process sharded runs WITH
    `resilience.cluster` — its ``agree_save_cursor``), skipping becomes
    the collective decision it has to be: before enqueueing, an
    overlapped submit asks the arbiter whether ANY host's queue is busy;
    if so, every host drops this snapshot at once (counted in
    ``ckpt_coalesced_total`` — coalescing regained for multi-process,
    and since the round is collective the save SETS stay identical). A
    skip drops the NEWER snapshot (the queued older one still commits);
    superseding in place would itself need consensus. Blocking submits
    bypass the arbiter (they are part of the deterministic schedule on
    every host) and backpressure instead of superseding, for the same
    divergence reason.

``flush()`` barriers at epoch end, at the `PreemptionGuard` final save
(via its second-signal flush hooks, resilience/signals.py), and at loop
exit (`close`), so shutdown semantics are unchanged. A writer-thread
failure is re-raised on the step thread at the next submit/flush/close —
training never silently outlives its durability.

Crash contract (unchanged, drilled): fault points ``ackpt.handoff``
(step thread, pre-enqueue), ``ackpt.d2h`` / ``ackpt.write`` /
``ackpt.commit`` (writer thread: before the host gather, before the
durable write, after it returns). A kill at any of them leaves
`durable.latest_valid` / `distributed.latest_valid_save` walking back to
a committed save, and async-written files are byte-identical to their
synchronous counterparts (same serialization, same writer code — only
the thread changed).

Single-producer contract: one thread (the step loop) submits; `flush`
may additionally be called from a signal handler interrupting that same
thread (it waits on per-ticket events, never holds the lock across a
wait, so the reentrant call cannot deadlock).

Unlike the rest of `ncnet_tpu.resilience` this module is NOT stdlib-only
(`device_snapshot` imports jax lazily) and is deliberately not imported
by the package ``__init__``; the training loop imports it directly.
"""

import threading
import weakref

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import default_registry

# Live-instance registry so a topology-changing restore can flush every
# active writer before reading (checkpoint.load_latest_valid_* — an
# in-flight async save otherwise races the restore's directory walk).
# lock-order: ackpt.live is never held across a flush (snapshot inside,
# flush outside), so no ordering against the per-instance _cv exists.
_live_lock = concurrency.make_lock("resilience.ackpt.live")
_live = weakref.WeakSet()  # guarded-by: _live_lock


def flush_live_checkpointers(timeout=60.0):
    """Flush every live `AsyncCheckpointer` (best-effort, never raises).

    Called by the restore paths before reading a checkpoint directory:
    a restore that overlaps an in-flight async save must not observe the
    save mid-write nor deadlock against it. Returns False if any flush
    timed out.
    """
    with _live_lock:
        live = list(_live)
    drained = True
    for ckpt in live:
        drained = ckpt.flush(timeout=timeout, reraise=False) and drained
    return drained


def device_snapshot(tree):
    """Donation-proof snapshot of a device pytree: per-leaf device-side
    copies, DISPATCHED asynchronously (no host sync, no D2H). The copies
    are fresh buffers no jitted step aliases, so the writer thread can
    gather them while the step loop keeps donating the originals.
    Non-array leaves (host scalars, None) pass through untouched —
    converting them would change the serialized bytes and break the
    async == sync byte-identity contract."""
    import jax
    import jax.numpy as jnp

    def copy_leaf(x):
        return jnp.copy(x) if isinstance(x, jax.Array) else x

    return jax.tree.map(copy_leaf, tree)


class _Ticket:
    """One handed-off snapshot: the (immutable) payload plus the two
    writer-thread callables, and the completion event the step thread
    (or a signal-handler flush) waits on."""

    __slots__ = ("data", "prepare", "write", "step", "done", "error",
                 "superseded")

    def __init__(self, data, prepare, write, step):
        self.data = data
        self.prepare = prepare
        self.write = write
        self.step = step
        self.done = threading.Event()
        self.error = None
        self.superseded = False


class AsyncCheckpointer:
    """Dedicated checkpoint writer thread with an at-most-one-in-flight,
    coalesce-or-wait handoff queue (module docstring has the policy).

    ``async_mode=False`` keeps synchronous SEMANTICS — every submit
    blocks until its save commits — but the D2H funnel + serialization +
    fsync still run on the writer thread, off the step thread (the
    satellite-1 contract: refs are snapshotted first either way).
    """

    # lock-order: _cv -> _lock
    # (_cv wraps _lock — one underlying lock, _cv the only entry point.
    # Metric updates made while holding it touch only the metric's own
    # private bare lock, so no cross-module ordering is introduced.)

    def __init__(self, async_mode=True, coalesce=True, join_timeout=60.0,
                 registry=None, coalesce_arbiter=None):
        self._async = bool(async_mode)
        self._coalesce = bool(coalesce)
        self._arbiter = coalesce_arbiter  # called on the step thread only
        self._join_timeout = join_timeout
        self._lock = concurrency.make_lock("resilience.ackpt")
        self._cv = threading.Condition(self._lock)
        self._queued = None  # guarded-by: _cv
        self._inflight = None  # guarded-by: _cv
        self._closed = False  # guarded-by: _cv
        self._failure = None  # guarded-by: _cv (first unsurfaced error)
        self._submitted = 0  # guarded-by: _cv
        self._written = 0  # guarded-by: _cv
        self._coalesced = 0  # guarded-by: _cv
        self._consensus_skips = 0  # guarded-by: _cv
        reg = registry if registry is not None else default_registry()
        self._m_inflight = reg.gauge(
            "ckpt_inflight", "checkpoint saves currently in flight (0/1)"
        )
        self._m_coalesced = reg.counter(
            "ckpt_coalesced_total",
            "queued-not-started snapshots superseded by a newer one",
        )
        self._m_inflight.set(0)
        # joined in close() under a bounded budget; report() lists it as
        # a straggler (serve-engine thread-ledger convention) if it
        # outlives that
        self._thread_ledger = [
            threading.Thread(target=self._writer_loop, name="ackpt-writer")
        ]
        self._thread_ledger[0].start()
        with _live_lock:
            _live.add(self)

    # --- step-thread side ----------------------------------------------------

    def submit(self, data, write, prepare=None, step=0, wait=False):
        """Hand one snapshot to the writer; O(1) on the step thread.

        ``write(data)`` performs the durable save; ``prepare(data)``
        (optional) runs first, also on the writer thread — the legacy
        layout's host gather lives there. ``wait=True`` (or sync mode)
        blocks until THIS snapshot commits; otherwise the call returns
        immediately, coalescing or backpressuring per policy. A pending
        writer failure (from an earlier overlapped save) re-raises here.
        """
        wait = wait or not self._async
        ticket = _Ticket(data, prepare, write, step)
        with trace.span("ckpt/handoff"):
            faultinject.fire("ackpt.handoff")
            if self._arbiter is not None and not wait:
                # collective coalescing: ask the cluster whether any
                # host's queue is busy. Reading _queued without holding
                # the lock across the (filesystem) round is safe under
                # the single-producer contract: only this thread can
                # OCCUPY the slot, so free stays free; occupied draining
                # to free mid-round just makes the skip conservative —
                # and identical on every host, since the LEADER decides
                # from the proposals. The arbiter runs outside the lock
                # (it blocks on peers and may raise a typed PeerDown).
                with self._cv:
                    self._raise_failure_locked()
                    if self._closed:
                        raise RuntimeError(
                            "AsyncCheckpointer is closed; no further snapshots"
                        )
                    busy = self._queued is not None
                if not self._arbiter(int(step), busy):
                    # every host drops this snapshot together; the queued
                    # older one still commits (oldest-wins under
                    # consensus — the docstring's freshness trade)
                    with self._cv:
                        self._consensus_skips += 1
                        self._coalesced += 1
                        self._m_coalesced.inc()
                    ticket.superseded = True
                    ticket.done.set()
                    return ticket
                # SAVE decided => every host's queue was free, ours
                # included (single producer: still free) — plain enqueue
            with self._cv:
                self._raise_failure_locked()
                if self._closed:
                    raise RuntimeError(
                        "AsyncCheckpointer is closed; no further snapshots"
                    )
                if self._queued is not None and (
                    not self._coalesce or self._arbiter is not None
                ):
                    # deterministic-collective mode: never drop a save —
                    # wait for the slot so every process writes the same
                    # sequence (multi-process sharded commit barrier).
                    # Under an arbiter this is the wait=True path: a
                    # local supersede here would diverge the save sets.
                    while self._queued is not None and self._failure is None:
                        self._cv.wait()
                    self._raise_failure_locked()
                if self._queued is not None:
                    self._queued.superseded = True
                    self._queued.done.set()
                    self._coalesced += 1
                    self._m_coalesced.inc()
                self._queued = ticket
                self._submitted += 1
                self._cv.notify_all()
            if wait:
                ticket.done.wait()
                if ticket.error is not None:
                    with self._cv:
                        if self._failure is ticket.error:
                            self._failure = None
                    raise ticket.error
        return ticket

    def flush(self, timeout=None, reraise=True):
        """Barrier: wait until no save is queued or in flight.

        Returns True when drained, False on timeout. ``reraise=True``
        surfaces a writer failure here; the `PreemptionGuard` flush hook
        passes ``reraise=False`` (a signal handler has nowhere to raise
        to — the walk-back contract covers the torn save). Waits on
        per-ticket events with the lock released, so a signal-handler
        call interrupting a step-thread flush cannot deadlock.
        """
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cv:
                ticket = self._inflight or self._queued
                if ticket is None:
                    if reraise:
                        self._raise_failure_locked()
                    return True
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
            if not ticket.done.wait(remaining):
                return False

    def close(self, reraise=True):
        """Flush outstanding saves, stop and join the writer thread.

        Idempotent. With ``reraise`` (the clean-exit path) a pending
        writer failure raises AFTER the thread is down; the exception
        path passes ``reraise=False`` so close never masks the real
        error unwinding through the loop.
        """
        self.flush(reraise=False)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        for t in self._thread_ledger:
            if t.is_alive():
                t.join(self._join_timeout)
        with _live_lock:
            _live.discard(self)
        if reraise:
            with self._cv:
                self._raise_failure_locked()

    def report(self):
        """Shutdown/telemetry summary (serve-engine report convention:
        ``straggler_threads`` is only populated once closed)."""
        with self._cv:
            stragglers = (
                sorted(t.name for t in self._thread_ledger if t.is_alive())
                if self._closed
                else []
            )
            return {
                "async_mode": self._async,
                "coalesce": self._coalesce,
                "consensus": self._arbiter is not None,
                "consensus_skips_total": self._consensus_skips,
                "submitted_total": self._submitted,
                "written_total": self._written,
                "coalesced_total": self._coalesced,
                "pending": int(self._queued is not None)
                + int(self._inflight is not None),
                "straggler_threads": stragglers,
            }

    def _raise_failure_locked(self):  # guarded-by: _cv
        err, self._failure = self._failure, None
        if err is not None:
            raise err

    # --- writer-thread side --------------------------------------------------

    def _writer_loop(self):
        while True:
            with self._cv:
                while self._queued is None and not self._closed:
                    self._cv.wait()
                ticket = self._queued
                self._queued = None
                if ticket is None:  # closed and drained
                    return
                self._inflight = ticket
                self._m_inflight.set(1)
                self._cv.notify_all()  # backpressured submitters
            err = None
            try:
                self._execute(ticket)
            except BaseException as e:  # surfaced on the step thread
                err = e
            with self._cv:
                self._inflight = None
                self._m_inflight.set(0)
                if err is not None:
                    ticket.error = err
                    if self._failure is None:
                        self._failure = err
                else:
                    self._written += 1
                self._cv.notify_all()
            ticket.done.set()

    def _execute(self, ticket):
        # the kill windows mirror the durable write's own: a hard kill at
        # d2h/write leaves the save torn (walk-back skips it); at commit
        # the save IS durable — latest_valid must land on it
        with trace.span("ckpt/write_async"):
            faultinject.fire("ackpt.d2h")
            data = ticket.data
            if ticket.prepare is not None:
                data = ticket.prepare(data)
            faultinject.fire("ackpt.write")
            ticket.write(data)
            faultinject.fire("ackpt.commit")
