"""Preemption-safety toolkit: durable writes, resume cursors, fault injection.

TPU fleets preempt routinely (large runs are economical precisely because
they tolerate being killed — PAPERS.md, the Gemma-on-TPU operational
comparison), so recovery is a feature with tests, not a hope:

  * `durable` — write-to-temp + fsync + atomic-rename file writes with a
    sidecar integrity digest, rotating retention of the last K artifacts,
    and candidate iteration for walking back past a torn/corrupt file.
  * `signals` — a `PreemptionGuard` context manager turning SIGTERM/SIGINT
    into a checkpoint-once-and-exit-cleanly flag for the training loop.
  * `faultinject` — a named-crash-point hook registry (env-var or test
    activated, exact no-op when disabled — same contract as
    `analysis.sanitizer`) that lets tests PROVE crash-at-any-point
    recovery instead of asserting it in prose.

Like `analysis`, this subpackage is import-light: the training loop and
data loader import it at instrumentation points, so it must stay
stdlib-only.
"""

from ncnet_tpu.resilience import durable, faultinject, signals

__all__ = ["durable", "faultinject", "signals"]
